"""Runtime lock-order tracer (the `-race` half of devtools).

``TracedLock`` is a drop-in for ``threading.Lock``/``RLock`` that keeps
a per-thread stack of held locks and a global acquisition-order graph
keyed by lock *role* (the stable name passed at construction, so every
``Partition._lock`` instance shares one node, like Go lock ranking).
When thread T acquires lock B while holding lock A, the edge A->B is
recorded; if the graph already proves B->...->A, two threads running
those paths concurrently can deadlock, and the tracer fails fast with
:class:`LockOrderError` instead of letting a stress test hang.

It also warns (:class:`LockHeldTooLongWarning`) when a lock is held
longer than ``VMT_LOCKTRACE_MAX_HOLD_MS`` (default 500) — the static
VMT004 rule's runtime sibling.

Production code never pays for any of this: ``make_lock``/``make_rlock``
return plain ``threading`` primitives unless ``VMT_LOCKTRACE`` is set
(``1``/``raise`` fail fast on cycles, ``warn`` only warns).

Known limitation: edges between two locks with the *same* role (e.g.
two sibling partitions locked together) are not recorded, since role
granularity cannot tell hierarchical order from a real ABBA there.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = ["LockOrderError", "LockOrderWarning", "LockHeldTooLongWarning",
           "LockGraph", "TracedLock", "make_lock", "make_rlock",
           "locktrace_enabled"]


class LockOrderError(RuntimeError):
    """A lock acquisition would complete an ABBA cycle (potential
    deadlock)."""


class LockOrderWarning(UserWarning):
    """Cycle detected while running in VMT_LOCKTRACE=warn mode."""


class LockHeldTooLongWarning(UserWarning):
    """A traced lock was held past the configured hold budget."""


_tls = threading.local()  # .held: list[_Held], shared by all traced locks

# Installed by devtools.racetrace.enable(): an object with
# acquire_inner/acquired/released used to bracket the inner lock ops with
# vector-clock joins (and scheduler-aware spin acquires). None = off.
_race_hooks = None


def _inc_counter(name: str) -> None:
    """Best-effort registry counter bump (findings are also exported as
    vm_locktrace_* self-metrics, not just warnings/exceptions)."""
    try:
        from ..utils import metrics as metricslib
        metricslib.REGISTRY.counter(name).inc()
    except ImportError:
        pass                        # registry unavailable mid-bootstrap


def _held_stack():
    stack = getattr(_tls, "held", None)
    if stack is None:
        stack = _tls.held = []
    return stack


class _Held:
    __slots__ = ("lock", "t0")

    def __init__(self, lock, t0):
        self.lock = lock
        self.t0 = t0


class LockGraph:
    """Global acquisition-order graph: edge A->B means some thread
    acquired role B while holding role A."""

    def __init__(self):
        self._mu = threading.Lock()
        self._edges: dict[str, set[str]] = {}

    def record(self, held: str, new: str):
        """Record edge held->new. Returns (added, cycle): ``added`` is
        True when the edge was not already known (the caller un-records
        it if the acquisition then fails), ``cycle`` is the cycle path
        (role names, ``[new, ..., held, new]``) if one now exists."""
        if held == new:
            return False, None  # same role: hierarchy vs ABBA unknowable
        with self._mu:
            first_time = new not in self._edges.get(held, ())
            self._edges.setdefault(held, set()).add(new)
            if not first_time:
                return False, None  # known edge, checked when first added
            return True, self._find_path(new, held)

    def remove_edge(self, held: str, new: str) -> None:
        with self._mu:
            self._edges.get(held, set()).discard(new)

    def _find_path(self, src: str, dst: str) -> list[str] | None:
        # DFS for src ->...-> dst; called with _mu held
        stack = [(src, [src])]
        seen = {src}
        while stack:
            node, path = stack.pop()
            for nxt in self._edges.get(node, ()):
                if nxt == dst:
                    return path + [dst, src]
                if nxt not in seen:
                    seen.add(nxt)
                    stack.append((nxt, path + [nxt]))
        return None

    def edges(self) -> dict[str, set[str]]:
        with self._mu:
            return {k: set(v) for k, v in self._edges.items()}

    def clear(self):
        with self._mu:
            self._edges.clear()


GLOBAL_GRAPH = LockGraph()


def _default_max_hold_ms() -> float:
    try:
        return float(os.environ.get("VMT_LOCKTRACE_MAX_HOLD_MS", "500"))
    except ValueError:
        return 500.0


def _flight_wait_ms() -> float:
    """Blocking waits on a traced lock longer than this land on the
    flight-recorder timeline as ``lock:<role>`` spans (only meaningful
    under VMT_LOCKTRACE — production locks are untraced plain locks)."""
    try:
        return float(os.environ.get("VM_FLIGHT_LOCK_WAIT_MS", "5"))
    except ValueError:
        return 5.0


class TracedLock:
    """Instrumented drop-in for ``threading.Lock``/``RLock``.

    ``name`` is the lock's *role* (stable per call site, shared by all
    instances of a class) used as the node key in the order graph.
    """

    def __init__(self, name: str, *, reentrant: bool = False,
                 graph: LockGraph | None = None, mode: str | None = None,
                 max_hold_ms: float | None = None):
        self.name = name
        self._reentrant = reentrant
        self._inner = threading.RLock() if reentrant else threading.Lock()
        self._graph = graph if graph is not None else GLOBAL_GRAPH
        env = os.environ.get("VMT_LOCKTRACE", "1")
        self._mode = mode if mode is not None else \
            ("warn" if env.lower() == "warn" else "raise")
        self._max_hold_ms = max_hold_ms if max_hold_ms is not None \
            else _default_max_hold_ms()
        # thread ident that currently owns the inner lock (+ depth for
        # RLocks); lets acquire() spot stale stack entries left behind by
        # cross-thread Lock hand-offs (acquire here, release elsewhere)
        self._owner: int | None = None
        self._owner_depth = 0

    def _check_order(self, stack):
        """Record edges held->self; returns them for rollback (a failed
        try-lock must not leave phantom edges poisoning the graph)."""
        added = []
        for held in stack:
            was_new, cycle = self._graph.record(held.lock.name, self.name)
            if was_new:
                added.append((held.lock.name, self.name))
            if cycle:
                msg = (f"lock-order cycle: acquiring '{self.name}' while "
                       f"holding '{held.lock.name}', but the reverse order "
                       f"was already observed ({' -> '.join(cycle)}); two "
                       f"threads on these paths can deadlock")
                _inc_counter("vm_locktrace_cycles_total")
                if self._mode == "warn":
                    import warnings
                    warnings.warn(msg, LockOrderWarning, stacklevel=3)
                else:
                    # the acquisition is aborted: none of its edges may
                    # outlive it, or they poison the graph with false
                    # cycles for later, legitimate acquisitions
                    for held_name, new_name in added:
                        self._graph.remove_edge(held_name, new_name)
                    raise LockOrderError(msg)
        return added

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        me = threading.get_ident()
        stack = _held_stack()
        # entries for locks this thread no longer owns are stale leftovers
        # of a cross-thread hand-off (legal for plain Lock): drop them so
        # they neither record false edges nor fake a self-deadlock
        stack[:] = [h for h in stack if h.lock._owner == me]
        already = any(h.lock is self for h in stack)
        added = []
        if not already:
            added = self._check_order(stack)
        elif not self._reentrant:
            raise LockOrderError(
                f"non-reentrant lock '{self.name}' re-acquired by the "
                f"same thread (self-deadlock)")
        hooks = _race_hooks
        t_wait = time.perf_counter()
        if hooks is not None:
            ok = hooks.acquire_inner(self._inner, blocking, timeout)
        else:
            ok = self._inner.acquire(blocking, timeout)
        waited = time.perf_counter() - t_wait
        if waited * 1e3 > _flight_wait_ms():
            # contended-lock visibility on the flight timeline: WHO was
            # stalled on WHAT while a refresh ran (import deferred — the
            # slow path only; devtools must stay import-light)
            from ..utils import flightrec
            flightrec.rec("lock:" + self.name, t_wait, waited)
        if ok:
            if hooks is not None:
                hooks.acquired(self)
            self._owner = me
            self._owner_depth += 1
            stack.append(_Held(self, time.monotonic()))
        else:
            for held_name, new_name in added:
                self._graph.remove_edge(held_name, new_name)
        return ok

    def release(self) -> None:
        stack = _held_stack()
        entry = None
        for i in range(len(stack) - 1, -1, -1):
            if stack[i].lock is self:
                entry = stack.pop(i)
                break
        # bookkeeping BEFORE the inner release: the instant
        # _inner.release() returns, a blocked acquirer may win the lock
        # and set its own ownership, which ours must not clobber
        prev = (self._owner, self._owner_depth)
        self._owner_depth = max(self._owner_depth - 1, 0)
        if self._owner_depth == 0:
            self._owner = None
        hooks = _race_hooks
        if hooks is not None:
            # publish this thread's clock into the lock BEFORE the inner
            # release makes the protected state visible to the next owner
            hooks.released(self)
        try:
            self._inner.release()
        except RuntimeError:
            self._owner, self._owner_depth = prev
            raise
        if entry is None:
            return
        if not any(h.lock is self for h in stack):  # outermost release
            held_ms = (time.monotonic() - entry.t0) * 1e3
            if held_ms > self._max_hold_ms:
                import warnings
                _inc_counter("vm_locktrace_hold_warnings_total")
                warnings.warn(
                    f"lock '{self.name}' held for {held_ms:.0f}ms "
                    f"(budget {self._max_hold_ms:.0f}ms); slow work "
                    f"inside the critical section?",
                    LockHeldTooLongWarning, stacklevel=2)

    def locked(self) -> bool:
        if self._reentrant:
            return any(h.lock is self for h in _held_stack())
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        kind = "RLock" if self._reentrant else "Lock"
        return f"<TracedLock {kind} {self.name!r}>"


# -- factory (the only thing production modules import) ----------------------

def locktrace_enabled() -> bool:
    return os.environ.get("VMT_LOCKTRACE", "") not in ("", "0")


def make_lock(name: str):
    """A ``threading.Lock`` — traced when VMT_LOCKTRACE is set or the
    racetrace sanitizer is enabled (its vector clocks synchronize at this
    seam).

    ``name`` should be the lock's role, e.g. ``"storage.Table._lock"``:
    stable per call site and shared by all instances."""
    if locktrace_enabled() or _race_hooks is not None:
        return TracedLock(name)
    return threading.Lock()


def make_rlock(name: str):
    """A ``threading.RLock`` — traced when VMT_LOCKTRACE or racetrace is
    enabled."""
    if locktrace_enabled() or _race_hooks is not None:
        return TracedLock(name, reentrant=True)
    return threading.RLock()
