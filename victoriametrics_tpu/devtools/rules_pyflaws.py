"""VMT002/VMT003 — classic Python foot-guns.

VMT002: mutable default arguments (one shared object across all calls —
the ``_ovh_get(..., _delta_memo={})`` bug class).
VMT003: bare ``except:`` (catches KeyboardInterrupt/SystemExit) and
silent ``except Exception: pass`` (swallows every error with no trace).
Narrow handlers like ``except ValueError: pass`` are idiomatic control
flow and are left alone.
"""

from __future__ import annotations

import ast

from .lint import dotted_name

_MUTABLE_CTORS = {"list", "dict", "set", "bytearray", "deque",
                  "defaultdict", "OrderedDict", "Counter"}
_BROAD_EXC = {"Exception", "BaseException"}


def _is_mutable_default(node) -> bool:
    if isinstance(node, (ast.List, ast.Dict, ast.Set)):
        return True
    if isinstance(node, ast.Call):
        name = dotted_name(node.func)
        return bool(name) and name.split(".")[-1] in _MUTABLE_CTORS
    return False


class MutableDefaultRule:
    rule_id = "VMT002"
    summary = "mutable default argument (shared across every call)"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                     ast.Lambda)):
                continue
            a = node.args
            defaults = list(a.defaults) + [d for d in a.kw_defaults if d]
            for d in defaults:
                if _is_mutable_default(d):
                    fn = getattr(node, "name", "<lambda>")
                    yield ctx.finding(
                        d, self.rule_id,
                        f"mutable default argument in {fn}(); the object "
                        f"is created once and shared by every call — use "
                        f"None + in-body init or a module-level cache")


def _handler_names(type_node) -> set[str]:
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
    out = set()
    for n in nodes:
        name = dotted_name(n)
        if name:
            out.add(name.split(".")[-1])
    return out


def _body_is_silent(body) -> bool:
    for stmt in body:
        if isinstance(stmt, ast.Pass):
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Constant) and \
                stmt.value.value is Ellipsis:
            continue
        return False
    return True


class SilentExceptRule:
    rule_id = "VMT003"
    summary = "bare 'except:' or silent 'except Exception: pass'"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if node.type is None:
                yield ctx.finding(
                    node, self.rule_id,
                    "bare 'except:' also catches KeyboardInterrupt/"
                    "SystemExit; name the exceptions (or 'except "
                    "Exception' + log at a harness boundary)")
            elif _body_is_silent(node.body) and \
                    _handler_names(node.type) & _BROAD_EXC:
                yield ctx.finding(
                    node, self.rule_id,
                    "silent 'except Exception: pass' swallows every error "
                    "with no trace; narrow the type or log it")


RULES = [MutableDefaultRule(), SilentExceptRule()]
