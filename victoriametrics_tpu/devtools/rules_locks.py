"""VMT004/VMT005 — lock discipline (static half of the race tooling).

VMT004: blocking calls (sleep, sockets, HTTP, subprocess, file opens)
made while a ``with <lock>:`` block is lexically open — the whole point
of the fine-grained locks in storage/ and parallel/ is that nothing
slow runs under them.

VMT005: per-class lock-discipline inference.  If ``self.x`` is written
under ``with self._lock:`` in one method, a bare ``self.x = ...`` write
in another method of the same class is (absent an inline justification)
a data race.  ``__init__`` and ``*_locked`` helper methods (callers
hold the lock by convention) are exempt.

Both rules treat any context-manager expression whose last attribute
looks lock-ish (``*lock*``, ``*mutex*``, ``mu``/``*_mu``) as a lock —
the project naming convention makes this reliable.
"""

from __future__ import annotations

import ast

from .lint import dotted_name

_FUNC_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

_BLOCKING_EXACT = {"time.sleep", "_time.sleep"}
_BLOCKING_PREFIXES = ("socket.", "requests.", "subprocess.",
                      "urllib.request.", "http.client.")
_BLOCKING_BUILTINS = {"open"}


def lockish_name(expr) -> str | None:
    """Dotted name of a lock-looking expression, else None."""
    name = dotted_name(expr)
    if name is None:
        return None
    last = name.split(".")[-1].lower()
    if "lock" in last or "mutex" in last or last in ("mu", "_mu") or \
            last.endswith("_mu"):
        return name
    return None


def _with_locks(node: ast.With | ast.AsyncWith) -> list[str]:
    out = []
    for item in node.items:
        name = lockish_name(item.context_expr)
        if name:
            out.append(name)
    return out


def _is_blocking_call(call: ast.Call) -> str | None:
    name = dotted_name(call.func)
    if name is None:
        return None
    if name in _BLOCKING_EXACT or name in _BLOCKING_BUILTINS or \
            name.startswith(_BLOCKING_PREFIXES):
        return name
    return None


class BlockingUnderLockRule:
    rule_id = "VMT004"
    summary = "blocking call while a 'with <lock>:' block is open"

    def check(self, ctx):
        yield from self._walk(ctx, ctx.tree, [])

    def _walk(self, ctx, node, held: list[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_SCOPES + (ast.ClassDef,)):
                # nested defs execute later, outside this lock region
                yield from self._walk(ctx, child, [])
                continue
            if isinstance(child, (ast.With, ast.AsyncWith)):
                yield from self._walk(ctx, child,
                                      held + _with_locks(child))
                continue
            if held and isinstance(child, ast.Call):
                name = _is_blocking_call(child)
                if name:
                    yield ctx.finding(
                        child, self.rule_id,
                        f"blocking call {name}() while holding "
                        f"{held[-1]}; move the slow work outside the "
                        f"critical section")
            yield from self._walk(ctx, child, held)


class _AttrWrites(ast.NodeVisitor):
    """Collect self.<attr> writes in one method, split by lock depth."""

    def __init__(self):
        self.guarded: list[tuple[str, ast.AST]] = []
        self.bare: list[tuple[str, ast.AST]] = []
        self._depth = 0

    def _record(self, target):
        if isinstance(target, ast.Attribute) and \
                isinstance(target.value, ast.Name) and \
                target.value.id == "self":
            dest = self.guarded if self._depth else self.bare
            dest.append((target.attr, target))

    def visit_Assign(self, node):
        for t in node.targets:
            self._record(t)
            if isinstance(t, ast.Tuple):
                for el in t.elts:
                    self._record(el)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._record(node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        self._record(node.target)
        self.generic_visit(node)

    def visit_With(self, node):
        locks = _with_locks(node)
        self._depth += bool(locks)
        for stmt in node.body:
            self.visit(stmt)
        self._depth -= bool(locks)

    visit_AsyncWith = visit_With

    def visit_FunctionDef(self, node):
        # nested defs run later, with or without the lock — unknowable
        # statically, so their writes count as neither guarded nor bare
        pass

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef


class LockDisciplineRule:
    rule_id = "VMT005"
    summary = "bare write to a field guarded by a lock elsewhere"

    def check(self, ctx):
        for cls in ast.walk(ctx.tree):
            if isinstance(cls, ast.ClassDef):
                yield from self._check_class(ctx, cls)

    def _check_class(self, ctx, cls: ast.ClassDef):
        per_method: dict[str, _AttrWrites] = {}
        for stmt in cls.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                w = _AttrWrites()
                for s in stmt.body:
                    w.visit(s)
                per_method[stmt.name] = w

        guarded_attrs = set()
        for name, w in per_method.items():
            if name != "__init__":
                guarded_attrs.update(a for a, _ in w.guarded)
        # the locks themselves are assigned bare in __init__ by design
        guarded_attrs = {a for a in guarded_attrs
                         if lockish_name(ast.Name(id=a)) is None}
        if not guarded_attrs:
            return

        for name, w in per_method.items():
            if name == "__init__" or name.endswith("_locked"):
                continue
            for attr, node in w.bare:
                if attr in guarded_attrs:
                    yield ctx.finding(
                        node, self.rule_id,
                        f"self.{attr} is written under a lock elsewhere "
                        f"in {cls.name} but bare here; take the lock, "
                        f"rename the method *_locked, or justify with an "
                        f"inline disable")


RULES = [BlockingUnderLockRule(), LockDisciplineRule()]
