"""VMT016 — exception-escape audit over the whole-program call graph.

The serving boundaries translate *typed* failures into *typed* wire
responses: the HTTP boundary (``httpapi/server.py::_handle``) maps
``RateLimitedError``/``SearchLimitError`` to 429 + Retry-After, the RPC
boundary (``parallel/rpc.py::_dispatch``) maps ``DeadlineExceededError``
and ``SearchLimitError`` to typed ``\\x01`` wire markers.  Everything
else falls into the anonymous ``except Exception`` arm: HTTP 500
"internal", or an unmarked RPC error frame that the client can only
re-raise as a generic ``RPCError``.

That anonymous arm is the bug this pass hunts: a *project-defined*
exception type (or a documented external raiser like ``json.loads``)
that can propagate from a serving entry point all the way to the
boundary without a typed mapping.  A ``ClusterUnavailableError`` that
surfaces as a bare 500 loses the one bit the caller needs (retry me —
this is capacity, not a bug); a ``PartialResultError`` that becomes an
anonymous error frame can no longer be degraded gracefully.

Mechanics:

- **Boundary mapped sets are scanned, not hardcoded**: the top-level
  ``except`` clauses of ``_handle`` and ``_dispatch`` are read from the
  AST, so adding a mapping at the boundary immediately retires the
  finding.  The wildcard ``except Exception`` arm contributes nothing —
  it IS the anonymous path.
- **Escape sets by fixpoint**: each function's set of statically
  raisable exception type keys is seeded from its own ``raise`` sites
  (minus types already caught by an enclosing ``try`` at the raise
  site) plus calls into :data:`callgraph.EXT_RAISERS`, then propagated
  caller-ward along ``call`` edges, filtering each hop by the ``except``
  clauses lexically enclosing the call site.  Catching is
  hierarchy-aware: ``except RPCError`` covers
  ``ClusterUnavailableError`` via ``exc_bases``, and builtin ancestry
  (``KeyError`` < ``LookupError`` < ``Exception``) is baked in.
- **Flag policy**: only project-qname types and EXT_RAISERS-origin
  builtins are reported.  Flagging every bare ``ValueError`` a
  validator raises would drown the boundary-contract signal; those
  raises are *meant* to be 4xx-ed by the handler layer, and when they
  are not, the project-typed wrappers (``QueryError``, ``ParseError``)
  are the ones this pass sees.

Findings anchor at the origin ``raise`` site (that is where the typed
mapping decision belongs — map it at the boundary, catch it en route,
or re-raise as an already-mapped type) and carry the witness chain
entry -> ... -> origin.  ``# vmt: disable=VMT016`` on the raise line is
honored for sanctioned escapes, with consumed suppressions reported so
VMT013 can flag stale ones.
"""

from __future__ import annotations

import argparse
import ast
import os
import sys

from .callgraph import (CallGraph, EXT_RAISERS, build_callgraph,
                        dotted_name, source_suppressed)
from .deadline_taint import find_entries
from .lint import Finding

RULE_ID = "VMT016"

#: (boundary kind, module rel_path, function name) — the error
#: boundaries whose top-level ``except`` clauses define the typed
#: mapping sets.  The wildcard arm is the anonymous path, not a mapping.
BOUNDARIES = (
    ("http", "victoriametrics_tpu/httpapi/server.py", "_handle"),
    ("rpc", "victoriametrics_tpu/parallel/rpc.py", "_dispatch"),
)

#: builtin exception ancestry (child -> parent), enough to make
#: ``except LookupError`` cover a ``KeyError`` and friends.  Project
#: classes use ``g.exc_bases``; the two tables chain (a project class
#: deriving ``RuntimeError`` walks into this one).
_BUILTIN_BASES = {
    "ValueError": "Exception",
    "UnicodeError": "ValueError",
    "UnicodeDecodeError": "UnicodeError",
    "UnicodeEncodeError": "UnicodeError",
    "KeyError": "LookupError",
    "IndexError": "LookupError",
    "LookupError": "Exception",
    "RuntimeError": "Exception",
    "NotImplementedError": "RuntimeError",
    "RecursionError": "RuntimeError",
    "ArithmeticError": "Exception",
    "ZeroDivisionError": "ArithmeticError",
    "OverflowError": "ArithmeticError",
    "OSError": "Exception",
    "IOError": "OSError",
    "ConnectionError": "OSError",
    "ConnectionResetError": "ConnectionError",
    "ConnectionAbortedError": "ConnectionError",
    "ConnectionRefusedError": "ConnectionError",
    "BrokenPipeError": "ConnectionError",
    "TimeoutError": "OSError",
    "FileNotFoundError": "OSError",
    "FileExistsError": "OSError",
    "PermissionError": "OSError",
    "IsADirectoryError": "OSError",
    "NotADirectoryError": "OSError",
    "InterruptedError": "OSError",
    "HTTPError": "OSError",          # urllib.error: URLError < OSError
    "URLError": "OSError",
    "AttributeError": "Exception",
    "TypeError": "Exception",
    "NameError": "Exception",
    "StopIteration": "Exception",
    "MemoryError": "Exception",
    "EOFError": "Exception",
    "AssertionError": "Exception",
    "ResourceWarning": "Exception",  # Warning < Exception
}


def catches(g: CallGraph, key: str, handler_keys) -> bool:
    """Would an ``except`` clause with ``handler_keys`` catch an
    exception of type ``key``?  Walks the ancestry — project bases via
    ``g.exc_bases`` (builtin bases stay visible there as bare names),
    builtin bases via :data:`_BUILTIN_BASES`."""
    if not handler_keys:
        return False
    if "*" in handler_keys:
        return True
    seen = set()
    stack = [key]
    while stack:
        c = stack.pop()
        if c in seen:
            continue
        seen.add(c)
        if c in handler_keys:
            return True
        if "::" in c:
            stack.extend(g.exc_bases.get(c, ()))
        elif c in _BUILTIN_BASES:
            stack.append(_BUILTIN_BASES[c])
    return False


# -- boundary mapped sets ---------------------------------------------------

def boundary_mappings(g: CallGraph) -> dict[str, dict]:
    """kind -> {"rel": .., "line": .., "mapped": frozenset(type keys)}
    scanned from the boundary functions' top-level ``except`` clauses.
    Only typed (non-wildcard) handlers count as mappings."""
    out: dict[str, dict] = {}
    for kind, rel, fname in BOUNDARIES:
        tree = g.module_trees.get(rel)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.FunctionDef) or node.name != fname:
                continue
            mapped: set[str] = set()
            for stmt in node.body:        # top-level tries only: the
                if not isinstance(stmt, ast.Try):   # nested cleanup
                    continue                        # tries are not the
                for h in stmt.handlers:             # boundary contract
                    tnode = h.type
                    if tnode is None:
                        continue
                    elts = tnode.elts if isinstance(tnode, ast.Tuple) \
                        else [tnode]
                    for t in elts:
                        dn = dotted_name(t)
                        if not dn:
                            continue
                        last = dn.rpartition(".")[2]
                        if last in ("Exception", "BaseException"):
                            continue   # the anonymous arm
                        q = g.lookup(rel, dn)
                        mapped.add(q if q in g.methods else last)
            out[kind] = {"rel": rel, "line": node.lineno, "fn": fname,
                         "mapped": frozenset(mapped)}
            break
    return out


# -- escape-set fixpoint ----------------------------------------------------

def escape_sets(g: CallGraph):
    """``esc[q]`` maps each exception type key that can propagate out of
    ``q`` to its origin ``(rel, line, origin_q, src)`` — the raise site
    (``src`` names the external raiser for EXT_RAISERS seeds, else
    ``"raise"``).  ``hop[(q, key)]`` is the callee the key arrived
    from (None when raised in ``q`` itself), for witness chains."""
    esc: dict[str, dict[str, tuple]] = {}
    hop: dict[tuple[str, str], str | None] = {}

    def seed(q, key, rel, line, src):
        if key not in esc.setdefault(q, {}):
            esc[q][key] = (rel, line, q, src)
            hop[(q, key)] = None

    for q, sites in g.raises.items():
        rel = q.partition("::")[0]
        for (key, line, caught) in sites:
            if key == "*" or catches(g, key, caught):
                continue
            seed(q, key, rel, line, "raise")
    for q, calls in g.ext_calls.items():
        rel = q.partition("::")[0]
        for (dotted, line, caught) in calls:
            key = EXT_RAISERS[dotted]
            if not catches(g, key, caught):
                seed(q, key, rel, line, f"{dotted}()")

    callers: dict[str, list[tuple]] = {}
    for q, edges in g.edges.items():
        for e in edges:
            if e.kind == "call" and e.target in g.defs:
                callers.setdefault(e.target, []).append((q, e.caught))

    work = list(esc)
    while work:
        callee = work.pop()
        ev = esc.get(callee)
        if not ev:
            continue
        for (caller, caught) in callers.get(callee, ()):
            grew = False
            for key, origin in ev.items():
                if catches(g, key, caught):
                    continue
                if key not in esc.setdefault(caller, {}):
                    esc[caller][key] = origin
                    hop[(caller, key)] = callee
                    grew = True
            if grew:
                work.append(caller)
    return esc, hop


def _chain(g: CallGraph, hop: dict, q: str, key: str) -> str:
    names = []
    cur: str | None = q
    while cur is not None:
        names.append(g.defs[cur].name if cur in g.defs else cur)
        cur = hop.get((cur, key))
    if len(names) > 5:
        names = names[:2] + ["..."] + names[-2:]
    return " -> ".join(names)


def _short(key: str) -> str:
    return key.rpartition("::")[2]


# -- the pass ---------------------------------------------------------------

def serving_entries(g: CallGraph) -> dict[str, str]:
    """The deadline-taint entries that sit behind an error boundary
    (matstream advance has no wire response to type)."""
    return {q: why for q, why in find_entries(g).items()
            if why.startswith(("http ", "rpc "))}


def run_pass(g: CallGraph | None = None, paths=None):
    """Returns (findings, used_suppressions); the latter is
    ``{rel_path: {(line, RULE_ID), ...}}`` for VMT013's bookkeeping."""
    if g is None:
        g = build_callgraph(paths or _default_paths())
    bounds = boundary_mappings(g)
    esc, hop = escape_sets(g)
    entries = serving_entries(g)

    # every raise site of (function, type): a disable on ANY of them
    # suppresses the finding (mirrors lockset's any-access-site rule —
    # which same-typed raise becomes the reported origin is a seeding
    # detail the suppression must not depend on)
    raise_sites: dict[tuple, list[tuple]] = {}
    for oq, sites in g.raises.items():
        rel = oq.partition("::")[0]
        for (key, line, _caught) in sites:
            raise_sites.setdefault((oq, key), []).append((rel, line))
    for oq, calls in g.ext_calls.items():
        rel = oq.partition("::")[0]
        for (dotted, line, _caught) in calls:
            raise_sites.setdefault((oq, EXT_RAISERS[dotted]),
                                   []).append((rel, line))

    findings: list[Finding] = []
    used: dict[str, set] = {}
    reported: set[tuple] = set()
    for q in sorted(entries, key=lambda q: entries[q]):
        why = entries[q]
        kind = why.split(None, 1)[0]
        b = bounds.get(kind)
        if b is None:
            continue
        for key, (rel, line, origin_q, src) in sorted(
                (esc.get(q) or {}).items()):
            if "::" not in key and src == "raise":
                continue   # bare builtin from project code: handler-
                           # layer 4xx territory, not a boundary gap
            if catches(g, key, b["mapped"]):
                continue
            site = (kind, key, rel, line)
            if site in reported:
                continue
            reported.add(site)
            sup = [(srel, sline) for srel, sline in
                   raise_sites.get((origin_q, key), [(rel, line)])
                   if source_suppressed(g, srel, sline, RULE_ID)]
            if sup:
                for srel, sline in sup:
                    used.setdefault(srel, set()).add((sline, RULE_ID))
                continue
            via = f" via {src}" if src != "raise" else ""
            findings.append(Finding(
                rel, line, RULE_ID,
                f"{_short(key)} raised here{via} escapes to the {kind} "
                f"boundary ({b['rel']}::{b['fn']}) as an anonymous "
                f"{'500' if kind == 'http' else 'error frame'} from "
                f"[{why}] via {_chain(g, hop, q, key)} — map it at the "
                f"boundary, catch it en route, or re-raise as a mapped "
                f"type"))
    findings.sort(key=lambda f: (f.path, f.line))
    return findings, used


def _default_paths():
    from .lint import REPO_ROOT
    return [os.path.join(REPO_ROOT, "victoriametrics_tpu")]


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m victoriametrics_tpu.devtools.errorflow",
        description="VMT016: project exception types reaching the "
                    "HTTP/RPC error boundary without a typed-status "
                    "mapping (static exception-escape audit).")
    ap.add_argument("paths", nargs="*")
    ap.add_argument("--list-boundaries", action="store_true",
                    help="print each boundary's scanned mapped set")
    ap.add_argument("--explain", metavar="TYPE_SUBSTR",
                    help="dump every serving entry a matching type "
                         "escapes from, with witness chains")
    ap.add_argument("--format", choices=("text", "sarif"), default="text")
    args = ap.parse_args(argv)

    g = build_callgraph(args.paths or _default_paths())
    if args.list_boundaries:
        for kind, b in sorted(boundary_mappings(g).items()):
            print(f"{kind}: {b['rel']}::{b['fn']} (line {b['line']})")
            for k in sorted(b["mapped"]):
                print(f"  maps {_short(k)}")
        return 0
    if args.explain:
        esc, hop = escape_sets(g)
        entries = serving_entries(g)
        for q in sorted(entries, key=lambda q: entries[q]):
            for key, (rel, line, _oq, src) in sorted(
                    (esc.get(q) or {}).items()):
                if args.explain not in key:
                    continue
                print(f"{_short(key):28s} [{entries[q]}] from {rel}:{line}"
                      f" ({src})  {_chain(g, hop, q, key)}")
        return 0
    findings, _used = run_pass(g)
    if args.format == "sarif":
        import json

        from .sarif import to_sarif
        print(json.dumps(to_sarif(
            findings, {RULE_ID: "untyped exception escape to boundary"}),
            indent=2, sort_keys=True))
        return 1 if findings else 0
    for f in findings:
        print(f)
    if findings:
        print(f"\n{len(findings)} exception-escape finding(s): add a "
              f"typed boundary mapping, catch en route, or disable with "
              f"the invariant that makes the escape sanctioned.",
              file=sys.stderr)
        return 1
    print(f"errorflow clean: {len(serving_entries(g))} entries, "
          f"{len(g.defs)} defs analyzed")
    return 0


if __name__ == "__main__":
    sys.exit(main())
