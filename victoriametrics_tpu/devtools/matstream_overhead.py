"""Materialized-stream fan-out smoke check (tools/lint.sh gate; the
matstream sibling of flight_overhead.py / profile_overhead.py).

The matstream contract is "subscribers are nearly free": one interval
with N subscribers of one expression must cost exactly ONE evaluation
(samples scanned identical to the 1-subscriber interval — the
O(distinct expressions) invariant) and the per-subscriber frame fan-out
must stay a small fraction of the evaluation itself.  The smoke builds
a tiny real store, advances one stream with 1 then with
``VM_MATSTREAM_SMOKE_SUBS`` (default 16) subscribers, and asserts:

- evals per interval == 1 in both runs (counter, not timing);
- samples scanned per interval identical (the flat-scan guard);
- fan-out wall overhead per extra subscriber under
  ``VM_MATSTREAM_SMOKE_MS`` (default 5 ms — generous: frames are built
  once and shared, so the per-subscriber cost is one bounded-queue
  put).

Run directly: ``python -m victoriametrics_tpu.devtools.
matstream_overhead`` (prints one JSON line; exit 0 = within budget,
1 = regression).  ``VMT_NO_MATSTREAM_SMOKE=1`` skips it in
tools/lint.sh.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np

STEP = 60_000
SCRAPE = 15_000
NS = 16
NN = 120
Q = "sum by (g)(rate(smoke_m[2m]))"


def _seed(s, t0: int):
    rng = np.random.default_rng(1)
    rows = []
    for i in range(NS):
        vals = np.cumsum(rng.integers(0, 30, NN)).astype(np.float64)
        rows.extend((({"__name__": "smoke_m", "i": str(i),
                       "g": f"g{i % 2}"}, t0 + j * SCRAPE, float(vals[j]))
                     for j in range(NN)))
    s.add_rows(rows)
    s.force_flush()


def _run(api, s, end: int, n_subs: int, intervals: int):
    """Advance `intervals` with `n_subs` subscribers; returns (end,
    evals, samples/interval, wall seconds)."""
    subs = [api.matstreams.subscribe(Q, STEP, 20 * STEP)
            for _ in range(n_subs)]
    for sb in subs:  # drain the cold snapshots
        sb.next_frame(timeout_s=2.0, now_ms=end)
    stream = subs[0].stream
    evals0 = stream.evals
    t0 = time.perf_counter()
    samples = []
    for r in range(intervals):
        end += STEP
        s.add_rows([
            ({"__name__": "smoke_m", "i": str(i), "g": f"g{i % 2}"},
             end - STEP + (k + 1) * SCRAPE, float(100 + r + k))
            for i in range(NS) for k in range(4)])
        assert stream.maybe_advance(end)
        samples.append(stream.last_samples_scanned)
        for sb in subs:  # every subscriber drains its copy of the frame
            f = sb.next_frame(timeout_s=2.0, now_ms=end)
            assert f is not None
    dt = time.perf_counter() - t0
    evals = stream.evals - evals0
    for sb in subs:
        sb.close()
    return end, evals, samples, dt


def main() -> int:
    fan_subs = int(os.environ.get("VM_MATSTREAM_SMOKE_SUBS", "16"))
    budget_ms = float(os.environ.get("VM_MATSTREAM_SMOKE_MS", "5"))
    intervals = 4
    from ..httpapi.prometheus_api import PrometheusAPI
    from ..query import rollup_result_cache as rrc
    from ..storage.storage import Storage
    from ..utils import fasttime
    tmp = tempfile.mkdtemp(prefix="vmtpu-matsmoke-")
    s = None
    try:
        s = Storage(tmp)
        now = fasttime.unix_ms()
        t0 = (now - (NN - 1) * SCRAPE) // STEP * STEP
        _seed(s, t0)
        end = t0 + ((NN - 1) * SCRAPE // STEP + 1) * STEP
        rrc.GLOBAL.reset()
        api = PrometheusAPI(s)
        end, evals_1, samples_1, dt_1 = _run(api, s, end, 1, intervals)
        end, evals_n, samples_n, dt_n = _run(api, s, end, fan_subs,
                                             intervals)
        per_sub_ms = max(dt_n - dt_1, 0.0) * 1e3 / (
            intervals * max(fan_subs - 1, 1))
        ok_evals = evals_1 == intervals and evals_n == intervals
        # medians: one interval may straddle a flush; the INVARIANT is
        # that scans do not grow with subscribers
        med_1 = sorted(samples_1)[len(samples_1) // 2]
        med_n = sorted(samples_n)[len(samples_n) // 2]
        ok_flat = med_n <= med_1 * 1.5
        ok_ms = per_sub_ms <= budget_ms
        print(json.dumps({
            "metric": "matstream fan-out smoke "
                      f"(1 vs {fan_subs} subscribers, {intervals} "
                      "intervals)",
            "evals_per_interval": [evals_1 / intervals,
                                   evals_n / intervals],
            "samples_per_interval_median": [med_1, med_n],
            "per_extra_subscriber_ms": round(per_sub_ms, 3),
            "budget_ms": budget_ms,
            "ok": ok_evals and ok_flat and ok_ms,
        }))
        if not ok_evals:
            print("matstream smoke: evals per interval != 1 — the "
                  "shared evaluator is gone", file=sys.stderr)
            return 1
        if not ok_flat:
            print(f"matstream smoke: samples/interval grew with "
                  f"subscribers ({med_1} -> {med_n})", file=sys.stderr)
            return 1
        if not ok_ms:
            print(f"matstream smoke: {per_sub_ms:.2f}ms per extra "
                  f"subscriber (budget {budget_ms}ms)", file=sys.stderr)
            return 1
        return 0
    finally:
        if s is not None:
            try:
                s.close()
            except OSError as e:  # already reported the real outcome
                print(f"matstream smoke: close: {e}", file=sys.stderr)
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
