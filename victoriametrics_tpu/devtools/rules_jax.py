"""VMT006 — JAX host-sync anti-patterns inside traced functions.

``block_until_ready``, ``np.asarray`` and ``.item()`` inside a function
decorated with ``jax.jit``/``pmap`` either fail at trace time or force a
device->host sync on every call, silently serializing the pipeline the
decorator was supposed to overlap.  (See /opt/skills/guides on keeping
host transfers out of compiled regions.)
"""

from __future__ import annotations

import ast

from .lint import dotted_name

_JIT_NAMES = {"jit", "pmap", "jax.jit", "jax.pmap"}
_PARTIAL_NAMES = {"partial", "functools.partial"}
_HOST_SYNC_EXACT = {"np.asarray", "numpy.asarray", "onp.asarray",
                    "jax.device_get", "jax.block_until_ready"}
_HOST_SYNC_ATTRS = {"block_until_ready", "item"}


def _is_jit_decorator(dec) -> bool:
    name = dotted_name(dec)
    if name in _JIT_NAMES:
        return True
    if isinstance(dec, ast.Call):
        fname = dotted_name(dec.func)
        if fname in _JIT_NAMES:
            return True  # @jax.jit(static_argnums=...)
        if fname in _PARTIAL_NAMES and dec.args:
            return dotted_name(dec.args[0]) in _JIT_NAMES
    return False


class JaxHostSyncRule:
    rule_id = "VMT006"
    summary = ("block_until_ready/np.asarray/.item() inside a "
               "jit/pmap-decorated function")

    def check(self, ctx):
        for fn in ast.walk(ctx.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if not any(_is_jit_decorator(d) for d in fn.decorator_list):
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                attr = node.func.attr \
                    if isinstance(node.func, ast.Attribute) else None
                if name in _HOST_SYNC_EXACT or attr in _HOST_SYNC_ATTRS:
                    what = name or f".{attr}"
                    yield ctx.finding(
                        node, self.rule_id,
                        f"{what}() inside jit/pmap function {fn.name}(); "
                        f"host syncs don't belong in traced code — hoist "
                        f"it to the caller or keep the value on device")


RULES = [JaxHostSyncRule()]
