"""VMT008/VMT009/VMT010/VMT011 — thread-lifecycle and queue discipline
(the static companions of devtools/racetrace).

VMT008: a ``threading.Thread(...)`` constructed without ``daemon=True``
in a scope that never ``join()``s anything and never sets ``.daemon`` —
such a thread outlives shutdown silently (a non-daemon thread blocks
interpreter exit; a daemonless never-joined worker leaks).

VMT009: cross-object writes to a field the lock-discipline pass (the
VMT005 inference) proved lock-guarded inside its own class.  VMT005
catches ``self.x = ...`` in the owning class; this rule catches
``other.x = ...`` from the outside, performed while no ``with <lock>:``
block is lexically open.

VMT010: a ``queue.Queue`` ``get``/``put`` carrying ``timeout=`` (or
``block=False``) inside a ``try`` whose ``queue.Empty``/``queue.Full``
handler is only ``pass`` — the timeout fires, the signal is dropped,
and starvation/backpressure becomes invisible.  Handle it: log, break,
re-check a stop flag, or count it.

VMT011: direct ``threading.Thread(...)`` construction outside
``devtools/`` and ``apps/`` — hot-path code must go through the shared
work pool (``utils/workpool``), which bounds thread count at
``cpu_count``, preserves result order, carries the racetrace
happens-before seam, and honors ``VM_SEARCH_WORKERS=1``.  Long-lived
service threads (servers, flush loops) are grandfathered via the
baseline or an inline disable with a reason.
"""

from __future__ import annotations

import ast

from .lint import dotted_name
from .rules_locks import _AttrWrites, _with_locks, lockish_name

_FUNC_SCOPES = (ast.FunctionDef, ast.AsyncFunctionDef)


def _is_thread_ctor(call: ast.Call) -> bool:
    name = dotted_name(call.func)
    return name is not None and \
        (name == "Thread" or name.endswith(".Thread"))


class UnjoinedThreadRule:
    rule_id = "VMT008"
    summary = "Thread(...) started without daemon=True or a join()"

    def check(self, ctx):
        # scopes: each function plus the module body, examined separately
        scopes = [ctx.tree] + [n for n in ast.walk(ctx.tree)
                               if isinstance(n, _FUNC_SCOPES)]
        for scope in scopes:
            yield from self._check_scope(ctx, scope)

    def _scope_nodes(self, scope):
        """Nodes belonging to this scope, not to nested functions."""
        stack = list(ast.iter_child_nodes(scope))
        while stack:
            node = stack.pop()
            if isinstance(node, _FUNC_SCOPES + (ast.Lambda,)):
                continue
            yield node
            stack.extend(ast.iter_child_nodes(node))

    def _check_scope(self, ctx, scope):
        threads = []
        joins_or_daemonizes = False
        for node in self._scope_nodes(scope):
            if isinstance(node, ast.Call):
                if _is_thread_ctor(node):
                    if any(kw.arg == "daemon" for kw in node.keywords):
                        continue        # explicit daemon choice
                    threads.append(node)
                elif isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "join" and \
                        not isinstance(node.func.value, ast.Constant):
                    # .join on a string literal is str.join, not a thread
                    joins_or_daemonizes = True
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Attribute) and t.attr == "daemon":
                        joins_or_daemonizes = True
        if joins_or_daemonizes:
            return                      # coarse: any join/daemon= in scope
        for call in threads:
            yield ctx.finding(
                call, self.rule_id,
                "Thread(...) without daemon=True in a scope with no "
                "join(); shutdown will either hang on it or leak it — "
                "pass daemon=True or join it")


class CrossObjectGuardedWriteRule:
    rule_id = "VMT009"
    summary = "write to a lock-guarded field of another object, no lock held"

    def check(self, ctx):
        guarded = self._guarded_attrs(ctx)
        if not guarded:
            return
        scopes = [n for n in ast.walk(ctx.tree)
                  if isinstance(n, _FUNC_SCOPES)
                  and not n.name.endswith("_locked")]
        for scope in scopes:
            yield from self._walk(ctx, scope, guarded, held=False)

    def _guarded_attrs(self, ctx) -> set[str]:
        """Fields some class in this file writes only under a lock — the
        same inference VMT005 runs, reused across class boundaries."""
        guarded: set[str] = set()
        for cls in ast.walk(ctx.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            for stmt in cls.body:
                if isinstance(stmt, _FUNC_SCOPES) and stmt.name != "__init__":
                    w = _AttrWrites()
                    for s in stmt.body:
                        w.visit(s)
                    guarded.update(a for a, _ in w.guarded)
        return {a for a in guarded
                if lockish_name(ast.Name(id=a)) is None}

    def _walk(self, ctx, node, guarded, held):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, _FUNC_SCOPES + (ast.Lambda, ast.ClassDef)):
                continue                # nested scopes checked separately
            if isinstance(child, (ast.With, ast.AsyncWith)):
                yield from self._walk(ctx, child, guarded,
                                      held or bool(_with_locks(child)))
                continue
            if not held and isinstance(child, (ast.Assign, ast.AugAssign)):
                targets = child.targets if isinstance(child, ast.Assign) \
                    else [child.target]
                for t in targets:
                    if isinstance(t, ast.Attribute) and \
                            t.attr in guarded and \
                            not (isinstance(t.value, ast.Name) and
                                 t.value.id == "self"):
                        yield ctx.finding(
                            t, self.rule_id,
                            f".{t.attr} is lock-guarded inside its own "
                            f"class but written here from outside with no "
                            f"lock held; go through a method that takes "
                            f"the owner's lock")
            yield from self._walk(ctx, child, guarded, held)


_QUEUE_EXCS = {"Empty", "Full"}


def _has_timeout_queue_op(try_body) -> bool:
    for stmt in try_body:
        for node in ast.walk(stmt):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("get", "put")):
                continue
            for kw in node.keywords:
                if kw.arg == "timeout":
                    return True
                if kw.arg == "block" and \
                        isinstance(kw.value, ast.Constant) and \
                        kw.value.value is False:
                    return True
    return False


def _body_is_pass(body) -> bool:
    return all(isinstance(s, ast.Pass) or
               (isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant)
                and s.value.value is Ellipsis)
               for s in body)


class SwallowedQueueTimeoutRule:
    rule_id = "VMT010"
    summary = "queue get/put timeout whose Empty/Full is silently swallowed"

    def check(self, ctx):
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Try):
                continue
            if not _has_timeout_queue_op(node.body):
                continue
            for handler in node.handlers:
                if handler.type is None:
                    continue            # bare except is VMT003's business
                names = set()
                nodes = handler.type.elts \
                    if isinstance(handler.type, ast.Tuple) \
                    else [handler.type]
                for n in nodes:
                    dn = dotted_name(n)
                    if dn:
                        names.add(dn.split(".")[-1])
                if names & _QUEUE_EXCS and _body_is_pass(handler.body):
                    yield ctx.finding(
                        handler, self.rule_id,
                        "queue timeout expired and its Empty/Full was "
                        "swallowed with 'pass'; starvation becomes "
                        "invisible — log it, break, or re-check the stop "
                        "flag explicitly")


class DirectThreadRule:
    rule_id = "VMT011"
    summary = "threading.Thread(...) outside devtools//apps/ (use workpool)"

    #: path fragments where direct Thread construction is legitimate:
    #: dev tooling (schedulers, harnesses) and app entry points (servers)
    _EXEMPT = ("devtools/", "apps/")

    def check(self, ctx):
        rel = ctx.rel_path.replace("\\", "/")
        if any(frag in rel for frag in self._EXEMPT):
            return
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.Call) and _is_thread_ctor(node):
                yield ctx.finding(
                    node, self.rule_id,
                    "direct threading.Thread(...) on a non-devtools/apps "
                    "path; hot-path fan-out must go through "
                    "utils.workpool.POOL (bounded, ordered, racetrace-"
                    "aware, VM_SEARCH_WORKERS-gated) — long-lived service "
                    "threads need a '# vmt: disable=VMT011' with a reason "
                    "or a baseline entry")


RULES = [UnjoinedThreadRule(), CrossObjectGuardedWriteRule(),
         SwallowedQueueTimeoutRule(), DirectThreadRule()]
