"""Fleet-batched device serving (ROADMAP item 3): every active stream
as ONE mesh program per interval.

The matstream registry (query/matstream.py) already enumerates every
active (expression, grid) pair; the device plane used to pay one kernel
launch — and on a cold shape one XLA compile — PER query shape per
interval anyway.  This module batches all device-resident streams of one
bucket shape into a single fused launch: their packed (S, N) tile planes
gain a leading stream axis ([B, S, N], named ``fleet_*`` in the
partition-rule table so the batch axis shards over the mesh's STREAM
axis), suffix ingest lands in one donated batched append
(ops.device_rollup.fleet_append_tile), and one
``fleet_rollup_aggregate`` launch computes every stream's [G, T]
aggregate — 40 subscriptions cost one compile and one launch instead of
40.

Lifecycle of a stream through the fleet:

1. **adoption** — a MatStream advance left a per-stream rolling window
   resident (the wcache ``("roll-aggr", ...)`` entry).  The next
   interval's prepass pulls a host copy of that window, CROPS it to the
   stream's fetch bound, drops the per-stream entry (its buffers may be
   donated away any time by a concurrent eval of the same selector —
   the pull races that loudly and skips adoption for an interval), and
   packs the copy into the stream slot of a bucket.
2. **bucketing** — buckets are shape classes: (func, step, lookback,
   S_b, N_b, T_b, G_b, dtype) with every dimension rounded UP a small
   geometric ladder ({1, 1.5}·2^k, floor ``VM_FLEET_LADDER_MIN``), so
   series churn and grid drift re-land in an existing compiled shape
   instead of retriggering XLA.  Padded rows carry counts == 0 /
   ts == TS_PAD, padded grid columns are sliced off on the host, padded
   group rows aggregate to NaN and are discarded — the masks the
   per-stream kernels already honor.
3. **interval prepass** — MatStream._advance calls :func:`prepass`
   before evaluating: every due member advances (slice-fetch mirroring
   ``advance_rolling``'s guards; any violated guard EVICTS the member —
   the stream's own eval then rebuilds per-stream state, re-adoptable
   next interval), staged suffixes apply in one donated batched append
   per bucket, and one fused launch per bucket computes all due
   members' aggregates.  The [B, G, T] result is pulled once and
   sliced per stream into a result table.
4. **serving** — the stream's evaluation reaches
   eval._try_device_fused_aggr, which consults :func:`take` FIRST: a
   grid/version-matched result row answers the query with zero storage
   reads and zero launches.  The shared launch cost is split per stream
   by rows-share (``device:execute`` / ``device:upload`` laps +
   uploaded-byte shares, consumed once so the per-stream rows sum
   exactly to the launch total).

Bucket planes keep an authoritative HOST mirror (numpy) alongside the
device planes: appends/compactions apply to both (same arithmetic), so
membership churn re-uploads from the mirror instead of pulling [B, S, N]
back over the link.

``VM_DEVICE_FLEET=0`` disables the plane entirely — the per-stream
rolling path then serves every stream individually: the loud escape
hatch AND the bit-equality oracle (tests/test_device_fleet.py diffs the
two at rtol=1e-12).
"""

from __future__ import annotations

import os
import time as _time

import numpy as np

from ..devtools.locktrace import make_lock
from ..ops.rollup_np import RollupConfig
from ..utils import costacc, flightrec
from ..utils import metrics as metricslib

_LAUNCHES = metricslib.REGISTRY.counter("vm_device_fleet_launches_total")
#: incremented by the number of due streams each launch served: the
#: ratio to _LAUNCHES is the amortization factor
_STREAMS = metricslib.REGISTRY.counter(
    "vm_device_fleet_streams_per_launch_total")
_ADOPTIONS = metricslib.REGISTRY.counter("vm_device_fleet_adoptions_total")
_EVICTIONS = metricslib.REGISTRY.counter("vm_device_fleet_evictions_total")
_SERVED = metricslib.REGISTRY.counter("vm_device_fleet_served_total")


def enabled() -> bool:
    """Fleet batching on?  VM_DEVICE_FLEET=0 falls back to the
    per-stream rolling path — the escape hatch and equality oracle."""
    return os.environ.get("VM_DEVICE_FLEET", "1") != "0"


def ladder_min() -> int:
    try:
        return max(int(os.environ.get("VM_FLEET_LADDER_MIN", "8")), 1)
    except ValueError:
        return 8


def max_members() -> int:
    try:
        return max(int(os.environ.get("VM_FLEET_MAX", "256")), 1)
    except ValueError:
        return 256


def bucket_up(n: int, minimum: int | None = None) -> int:
    """Smallest ladder value >= n from the geometric ladder
    {1, 1.5} * 2^k scaled from `minimum` (default VM_FLEET_LADDER_MIN):
    m, 1.5m, 2m, 3m, 4m, 6m, ... — at most 50% padding waste, and churn
    within a rung never changes the compiled shape.  Rungs are computed
    directly (m<<k / 3m<<k>>1), NOT by cumulative floored multiplies: a
    running ``b = b*3//2`` stalls forever at b=1, so a floor of 1 (the
    1-device mesh, or VM_FLEET_LADDER_MIN=1) would hang the caller."""
    m = max(minimum if minimum is not None else ladder_min(), 1)
    b, j = m, 0
    while b < n:
        j += 1
        b = m << (j // 2) if j % 2 == 0 else (3 * m << (j // 2)) >> 1
    return b


class FleetMember:
    """One adopted stream: identity + grid parameters + host-side series
    bookkeeping.  The sample data itself lives in the bucket's planes at
    ``slot``."""

    __slots__ = (
        "skey", "stream_key", "me", "tenant", "max_series",
        "func", "aggr", "step", "duration", "window", "lookback",
        "lookback_delta", "offset", "drop_stale",
        "S", "G", "T", "group_keys", "gids", "v0",
        "base_ms", "lo_ms", "hi_ms", "version", "structural",
        "counts", "row_of_raw", "segments", "bucket", "slot",
    )

    def samples_in_range(self, fetch_lo: int) -> int:
        return sum(n for _, seg_hi, n in self.segments if seg_hi >= fetch_lo)


class FleetBucket:
    """One compiled shape class: members' planes stacked on a leading
    stream axis, device arrays + authoritative host mirrors."""

    __slots__ = ("key", "func", "step", "lookback", "dtype",
                 "B_pad", "S_b", "N_b", "T_b", "G_b", "cfg",
                 "members", "ts_h", "vals_h", "counts_h", "gids_h",
                 "v0_h", "aggr_h", "dev", "dirty", "compiles",
                 "last_up_bytes", "last_up_wall")

    def __init__(self, key, n_stream: int):
        (self.func, self.step, self.lookback,
         self.S_b, self.N_b, self.T_b, self.G_b, self.dtype) = key
        self.key = key
        self.B_pad = 0
        self.members: list[FleetMember] = []
        self.dev = None
        self.dirty = True
        self.compiles = 0
        self.last_up_bytes = 0
        self.last_up_wall = 0.0
        from ..ops.device_rollup import normalized_cfg
        self.cfg = normalized_cfg(self.func, RollupConfig(
            start=0, end=(self.T_b - 1) * self.step, step=self.step,
            window=self.lookback))
        self._alloc(n_stream)

    def _alloc(self, n_stream: int, b_need: int = 1) -> None:
        """(Re)allocate mirrors for at least `b_need` stream slots
        (ladder-bucketed, rounded to the mesh stream-axis size)."""
        from ..ops.device_rollup import TS_PAD
        b = bucket_up(max(b_need, 1))
        b = -(-b // n_stream) * n_stream
        if b <= self.B_pad:
            return
        old = self.B_pad
        ts = np.full((b, self.S_b, self.N_b), TS_PAD, dtype=np.int32)
        vals = np.zeros((b, self.S_b, self.N_b), dtype=self.dtype)
        counts = np.zeros((b, self.S_b), dtype=np.int32)
        gids = np.zeros((b, self.S_b), dtype=np.int32)
        v0 = np.zeros((b, self.S_b),
                      dtype=np.float32 if self.dtype == "float32"
                      else np.float64)
        aggr = np.zeros(b, dtype=np.int32)
        if old:
            ts[:old] = self.ts_h
            vals[:old] = self.vals_h
            counts[:old] = self.counts_h
            gids[:old] = self.gids_h
            v0[:old] = self.v0_h
            aggr[:old] = self.aggr_h
        self.ts_h, self.vals_h, self.counts_h = ts, vals, counts
        self.gids_h, self.v0_h, self.aggr_h = gids, v0, aggr
        self.B_pad = b
        self.dirty = True


class FleetResult:
    """One served interval of one member, consumed by :func:`take`.
    Cost shares are consumed ONCE (zeroed on first take) so repeated
    evals in an interval never double-charge the launch."""

    __slots__ = ("start", "end", "step", "version", "structural",
                 "lookback_delta", "rows", "group_keys", "samples",
                 "exec_share_s", "up_share_s", "up_share_b")


class FleetPlane:
    """Per-engine fleet state.  One coarse lock: prepass (adoption,
    advance, append, launch) and take() serialize on it; it never
    acquires stream or registry locks, and the wcache/storage locks it
    reaches into never call back — no cycle."""

    def __init__(self, engine):
        self.engine = engine
        self._lock = make_lock("query.FleetPlane._lock")
        self._members: dict = {}      # skey -> FleetMember
        self._buckets: dict = {}      # bucket key -> FleetBucket
        self._results: dict = {}      # skey -> FleetResult
        self._memo: dict = {}         # stream key -> shape info | False
        # skey -> remaining full-eval retries after an eviction: adoption
        # needs a per-shape device window in the window cache, but the
        # serving layer only rebuilds one when device_window_ready says
        # so — which it never would again after the eviction dropped both
        # the member and the wcache entry.  The retry budget routes a few
        # refreshes back through the full device eval (the loud cold
        # rebuild); one success re-registers the window and the next
        # prepass re-adopts.
        self._rebuild_retry: dict = {}
        self.launches = 0
        self.served = 0
        self.adoptions = 0
        self.evictions = 0
        self.compiles = 0
        self.last_decline = ""
        self._mesh = None
        if engine.mesh is not None:
            from ..parallel.mesh import make_fleet_mesh
            self._mesh = make_fleet_mesh(
                list(engine.mesh.devices.flatten()))

    def n_stream(self) -> int:
        if self._mesh is None:
            return 1
        from ..parallel.partition import AXIS_STREAM, axis_multiple
        return axis_multiple(self._mesh, AXIS_STREAM)

    def has(self, skey) -> bool:
        with self._lock:
            return skey in self._members

    def wants_rebuild(self, skey) -> bool:
        """Consume one post-eviction retry: True routes this refresh
        through the full device eval so the per-shape window (and with it
        the adoption path) can come back."""
        with self._lock:
            n = self._rebuild_retry.get(skey)
            if n is None:
                return False
            if n <= 1:
                self._rebuild_retry.pop(skey, None)
            else:
                self._rebuild_retry[skey] = n - 1
            return True

    def stats(self) -> dict:
        with self._lock:
            return {"members": len(self._members),
                    "buckets": len(self._buckets),
                    "launches": self.launches, "served": self.served,
                    "adoptions": self.adoptions,
                    "evictions": self.evictions,
                    "compiles": self.compiles}

    # -- stream-shape analysis (memoized per stream identity) -------------

    def _analyze(self, api, st):
        key = (st.tenant, st.q, st.step, st.duration)
        info = self._memo.get(key)
        if info is not None:
            return info or None
        info = self._analyze_uncached(api, st)
        self._memo[key] = info if info is not None else False
        return info

    def _analyze_uncached(self, api, st):
        from ..ops import rollup_np
        from ..ops.device_rollup import FLEET_AGGR_CODES
        from .eval import _device_aggr_shape, _device_roll_keys
        from .exec import parse_cached
        from .metricsql.ast import AggrFuncExpr
        e = parse_cached(st.q)
        if not isinstance(e, AggrFuncExpr):
            return None
        shape = _device_aggr_shape(e)
        if shape is None:
            return None
        phi, func, rarg = shape
        # quantile's dense [G, M, T] scatter doesn't batch; per-stream
        # residency still serves it
        if phi is not None or e.name not in FLEET_AGGR_CODES or \
                func not in rollup_np.CORE_SUPPORTED:
            return None
        window = rarg.window.value_ms(st.step) if rarg.window is not None \
            else 0
        offset = rarg.offset.value_ms(st.step) if rarg.offset is not None \
            else 0
        ec = api._ec(0, st.duration, st.step, st.tenant)
        skey, _ = _device_roll_keys(ec, e, func, rarg, phi, window)
        if skey is None:
            return None
        lookback = window if window > 0 else (
            ec.lookback_delta if func == "default_rollup" else st.step)
        return {"skey": skey, "func": func, "aggr": e.name,
                "aggr_code": FLEET_AGGR_CODES[e.name], "window": window,
                "offset": offset, "lookback": lookback,
                "lookback_delta": ec.lookback_delta,
                "drop_stale": func not in ("default_rollup",
                                           "stale_samples_over_time"),
                "me": rarg.expr, "max_series": ec.max_series}

    # -- the per-interval batch scheduler ---------------------------------

    def run(self, api, now_ms: int) -> int:
        """Advance + launch every due member; adopt newly-resident
        streams.  Returns the number of fused launches."""
        with self._lock:
            return self._run_locked(api, now_ms)

    def _run_locked(self, api, now_ms: int) -> int:
        reg = getattr(api, "matstreams", None)
        if reg is None:
            return 0
        ver = getattr(api.storage, "data_version", None)
        if ver is None or \
                getattr(api.storage, "structural_version", None) is None:
            return 0
        work: list[tuple[FleetMember, int]] = []   # (member, query end)
        for st in reg.streams():
            if not st.due(now_ms):
                continue
            info = self._analyze(api, st)
            if info is None:
                continue
            end_q = (now_ms // st.step) * st.step
            m = self._members.get(info["skey"])
            if m is None:
                m = self._adopt(api, st, info, end_q)
                if m is None:
                    continue
            r = self._results.get(m.skey)
            if r is not None and r.end == end_q - m.offset and \
                    r.version == ver:
                continue  # this interval already served by a prior pump
            work.append((m, end_q))
        if not work:
            return 0
        t_pack = _time.perf_counter()
        staged: dict = {}   # bucket -> list[(member, cols, rows_idx)]
        due: dict = {}      # bucket -> list[(member, end_q)]
        for m, end_q in work:
            verdict = self._advance_member(api, m, end_q)
            if verdict == "evict":
                self._evict(m, self.last_decline)
                continue
            if verdict == "skip":
                continue
            if isinstance(verdict, tuple):
                staged.setdefault(m.bucket, []).append((m,) + verdict)
            due.setdefault(m.bucket, []).append((m, end_q))
        touched = set(staged) | {b for b in self._buckets.values()
                                 if b.dirty and b.members}
        for b in touched:
            if b.dirty:
                self._stage_to_mirror(b, staged.get(b, ()))
                self._upload(b)
            else:
                self._stage_to_mirror(b, staged.get(b, ()))
                self._append_device(b, staged.get(b, ()))
        flightrec.rec("device:fleet_pack", t_pack,
                      _time.perf_counter() - t_pack,
                      arg=f"{len(work)} streams, {len(touched)} buckets")
        n = 0
        for b, mems in due.items():
            if b.members and b.dev is not None:
                self._launch(api, b, mems)
                n += 1
        return n

    # -- adoption ---------------------------------------------------------

    def _adopt(self, api, st, info, end_q):
        from ..models.tile_cache import timed_transfer
        from ..ops.device_rollup import TS_PAD
        from .tpu_engine import RollingTile, tile_capacity
        eng = self.engine
        wcache = eng.window_cache()
        stv = wcache.peek(info["skey"])
        if stv is None:
            return None  # not yet device-resident; the stream's own
            #              eval builds the per-stream window first
        rt, gids_dev, group_keys, qx, _rb = stv
        if qx is not None or not isinstance(rt, RollingTile):
            return None
        v0i = rt.tiles[3]
        if v0i is not None and v0i.wide_range:
            return None  # f32-unsafe dynamic range: per-stream path only
        storage = api.storage
        # the member inherits the tile's version watermark; the advance
        # pass right after adoption runs the same late-data/deletes
        # guards advance_rolling would, so version drift since the tile
        # was built is NOT an adoption blocker — structural drift is
        # (the tile's series set may no longer match storage)
        if getattr(storage, "data_version", None) is None or \
                getattr(storage, "structural_version", None) != \
                rt.structural or getattr(storage, "dedup_interval_ms", 0):
            return None
        if len(self._members) >= max_members():
            return None
        S = len(rt.counts_host)
        start_g = end_q - st.duration - info["offset"]
        fetch_lo = start_g - info["lookback"] - info["lookback_delta"]
        if rt.lo_ms > fetch_lo:
            return None
        try:
            # the pull races concurrent donated appends by OTHER shapes
            # sharing this selector's RollingTile: a donated-away buffer
            # raises here and adoption just waits an interval
            N = int(rt.tiles[0].shape[1])
            nbytes = S * N * (4 + np.dtype(eng.value_dtype).itemsize)
            ts_full, vals_full = timed_transfer(
                "device:download", nbytes,
                lambda: (np.asarray(rt.tiles[0][:S], dtype=np.int32),
                         np.asarray(rt.tiles[1][:S])))
        except Exception as e:  # noqa: BLE001 — donation race, loud skip
            flightrec.instant("fleet:adopt_race", arg=repr(e)[:120])
            return None
        counts = np.asarray(rt.counts_host, dtype=np.int32).copy()
        # crop to this stream's fetch bound and REBASE the origin there:
        # samples older than fetch_lo can never contribute to a
        # fixed-shape stream again, and the crop bounds the bucket's
        # column dimension at ~window size.  cutoff_rel may be NEGATIVE
        # (cold tiles anchor base_ms at the grid start, with the
        # lookback prefix at negative relative timestamps) — then
        # nothing drops and the rebase just shifts every ts up
        cutoff_rel = fetch_lo - rt.base_ms
        k = np.arange(ts_full.shape[1])[None, :]
        valid = k < counts[:, None]
        drop = ((ts_full < cutoff_rel) & valid).sum(axis=1).astype(np.int32)
        counts = counts - drop
        idx = np.clip(drop[:, None] + k, 0, ts_full.shape[1] - 1)
        ts_full = np.take_along_axis(
            ts_full.astype(np.int64), idx, axis=1) - cutoff_rel
        vals_full = np.take_along_axis(vals_full, idx, axis=1)
        base_ms = fetch_lo
        live = k < counts[:, None]
        ts_full = np.where(live, ts_full, TS_PAD).astype(np.int32)
        vals_full = np.where(live, vals_full, 0)
        n_need = int(counts.max()) if S else 1
        m = FleetMember()
        m.skey = info["skey"]
        m.stream_key = (st.tenant, st.q, st.step, st.duration)
        m.me = info["me"]
        m.tenant = st.tenant
        m.max_series = info["max_series"]
        m.func = info["func"]
        m.aggr = info["aggr"]
        m.step = st.step
        m.duration = st.duration
        m.window = info["window"]
        m.lookback = info["lookback"]
        m.lookback_delta = info["lookback_delta"]
        m.offset = info["offset"]
        m.drop_stale = info["drop_stale"]
        m.S = S
        m.G = len(group_keys)
        m.T = st.duration // st.step + 1
        m.group_keys = list(group_keys)
        m.gids = np.asarray(gids_dev, dtype=np.int32)[:S]
        m.v0 = None if v0i is None else \
            np.asarray(v0i.offsets[:S], dtype=np.float64)
        m.base_ms = base_ms
        m.lo_ms = max(rt.lo_ms, base_ms)
        m.hi_ms = rt.hi_ms
        m.version = rt.version
        m.structural = rt.structural
        m.counts = counts.astype(np.int64)
        m.row_of_raw = dict(rt.row_of_raw)
        m.segments = [(max(lo, base_ms), hi, nn)
                      for lo, hi, nn in rt.segments if hi >= base_ms]
        key = (m.func, m.step, m.lookback, bucket_up(S),
               bucket_up(tile_capacity(n_need), 64), bucket_up(m.T),
               bucket_up(m.G), str(np.dtype(self.engine.value_dtype)))
        b = self._buckets.get(key)
        if b is None:
            b = self._buckets[key] = FleetBucket(key, self.n_stream())
        b._alloc(self.n_stream(), len(b.members) + 1)
        m.bucket = b
        m.slot = len(b.members)
        b.members.append(m)
        self._fill_slot(b, m, ts_full, vals_full)
        b.dirty = True
        self._members[m.skey] = m
        # the per-stream entry's buffers stay referenced by the shared
        # roll-tile entry; dropping the SHAPE entry routes this stream's
        # evals to the fleet (take + device_window_ready) from now on
        wcache.invalidate(m.skey)
        self._rebuild_retry.pop(m.skey, None)
        self.adoptions += 1
        _ADOPTIONS.inc()
        flightrec.instant("fleet:adopt", arg=str(m.skey[1])[:120])
        return m

    def _fill_slot(self, b: FleetBucket, m: FleetMember,
                   ts: np.ndarray, vals: np.ndarray) -> None:
        from ..ops.device_rollup import TS_PAD
        S = ts.shape[0]
        # live columns all sit left of counts.max() <= N_b after the
        # adoption crop; the tail beyond the bucket's width is pure pad
        N = min(ts.shape[1], b.N_b)
        ts = ts[:, :N]
        vals = vals[:, :N]
        sl = m.slot
        b.ts_h[sl] = TS_PAD
        b.vals_h[sl] = 0
        b.counts_h[sl] = 0
        b.gids_h[sl] = 0
        b.v0_h[sl] = 0
        b.ts_h[sl, :S, :N] = ts
        b.vals_h[sl, :S, :N] = vals.astype(b.vals_h.dtype)
        b.counts_h[sl, :S] = m.counts
        b.gids_h[sl, :S] = m.gids
        if m.v0 is not None:
            b.v0_h[sl, :S] = m.v0
        from ..ops.device_rollup import FLEET_AGGR_CODES
        b.aggr_h[sl] = FLEET_AGGR_CODES[m.aggr]

    # -- advance (mirrors advance_rolling's guard set) --------------------

    def _advance_member(self, api, m: FleetMember, end_q: int):
        """Returns "ok" (nothing to append), "skip" (decline this
        interval, keep the member), "evict", or (cols, rows_idx) staged
        append columns."""
        def no(reason: str) -> str:
            self.last_decline = reason
            return "evict"

        storage = api.storage
        start_g = end_q - m.duration - m.offset
        end_g = end_q - m.offset
        fetch_lo = start_g - m.lookback - m.lookback_delta
        ver = getattr(storage, "data_version", None)
        if ver is None or \
                getattr(storage, "structural_version", None) != m.structural:
            return no("deletes/retention changed visible data")
        if getattr(storage, "dedup_interval_ms", 0):
            return no("dedup interval set")
        if m.lo_ms > fetch_lo:
            return no("member history does not reach the lookback")
        if start_g < m.base_ms:
            return no("query starts before the member's rebase origin")
        if end_g - m.base_ms >= 2**31 - 1:
            if not self._compact(m.bucket, {m.slot: fetch_lo}) or \
                    end_g - m.base_ms >= 2**31 - 1:
                return no("int32 rebase exhausted")
        if ver != m.version:
            try:
                lo_new = storage.min_appended_since(m.version)
            except LookupError:
                return no("append log trimmed past member version")
            if lo_new is not None and lo_new <= m.hi_ms:
                return no("late data landed inside the covered range")
        staged = "ok"
        if end_g > m.hi_ms:
            from .eval import filters_from_metric_expr
            filters = filters_from_metric_expr(m.me, storage)
            if hasattr(storage, "reset_partial"):
                storage.reset_partial()
            try:
                cols = storage.search_columns(filters, m.hi_ms + 1, end_g,
                                              max_series=m.max_series,
                                              tenant=m.tenant)
            except Exception:  # noqa: BLE001 — limits etc: per-stream path
                return no("slice fetch failed")
            if getattr(storage, "last_partial", False):
                # never commit a partial interval; retry next interval
                # (the member keeps its committed coverage)
                self.last_decline = "partial slice fetch"
                return "skip"
            if m.drop_stale:
                cols.drop_stale_nans()
            if cols.n_series:
                staged = self._stage_append(m, cols, fetch_lo)
                if isinstance(staged, str):
                    return no(staged) if staged != "ok" else staged
                m.segments.append((m.hi_ms + 1, end_g, cols.n_samples))
            m.hi_ms = end_g
        m.version = ver
        return staged

    def _stage_append(self, m: FleetMember, cols, fetch_lo: int):
        """Validate + index one fetched slice for the batched append.
        Returns (cols, rows_idx) or a decline reason string."""
        from .tpu_engine import F32_SAFE_RANGE
        rows_idx = np.empty(cols.n_series, dtype=np.int64)
        for i, rn in enumerate(cols.raw_names):
            r = m.row_of_raw.get(rn)
            if r is None:
                return "new series appeared"
            rows_idx[i] = r
        new_n = m.counts[rows_idx] + cols.counts
        if int(new_n.max()) > m.bucket.N_b:
            if not self._compact(m.bucket, {m.slot: fetch_lo}):
                return "column headroom exhausted"
            new_n = m.counts[rows_idx] + cols.counts
            if int(new_n.max()) > m.bucket.N_b:
                return "column headroom exhausted"
        if m.v0 is not None:
            vals_in = cols.vals - m.v0[rows_idx][:, None]
            live = np.arange(cols.ts.shape[1])[None, :] < \
                cols.counts[:, None]
            sub = vals_in[live]
            finite = sub[np.isfinite(sub)]
            if finite.size and \
                    float(np.abs(finite).max()) >= F32_SAFE_RANGE:
                return "append exceeds the f32-safe rebased range"
        return (cols, rows_idx)

    # -- packing: mirrors + device ----------------------------------------

    def _stage_to_mirror(self, b: FleetBucket, staged) -> None:
        """Apply staged appends to the bucket's host mirrors (the same
        scatter the donated device append performs)."""
        for m, cols, rows_idx in staged:
            K = cols.ts.shape[1]
            kk = np.arange(K)[None, :]
            live = kk < cols.counts[:, None]
            r_i, k_i = np.nonzero(live)
            rows = rows_idx[r_i]
            col = m.counts[rows] + k_i
            rel = (cols.ts - m.base_ms).astype(np.int64)
            vals_in = cols.vals
            if m.v0 is not None:
                vals_in = vals_in - m.v0[rows_idx][:, None]
            b.ts_h[m.slot, rows, col] = rel[r_i, k_i].astype(np.int32)
            b.vals_h[m.slot, rows, col] = \
                vals_in[r_i, k_i].astype(b.vals_h.dtype)
            new_n = m.counts[rows_idx] + cols.counts
            m.counts[rows_idx] = new_n
            b.counts_h[m.slot, rows_idx] = new_n.astype(np.int32)

    def _put(self, name: str, a: np.ndarray, pad_value=0):
        from ..models.tile_cache import chunked_device_put
        from ..parallel.partition import shard_put
        if self._mesh is not None:
            return shard_put(self._mesh, name, a, pad_value)
        return chunked_device_put(np.asarray(a))

    def _upload(self, b: FleetBucket) -> None:
        """Full mirror -> device upload (adoption, eviction repack).

        The mirrors are uploaded as PRIVATE COPIES: the CPU backend
        zero-copies 64-byte-aligned numpy arrays into device buffers
        (alignment is allocator luck, so it engages nondeterministically),
        and the mirrors are mutated in place by _stage_to_mirror every
        interval — an aliased upload would mutate the "device" tile
        underneath later launches, and the donated append would scribble
        its output back into the mirror."""
        from ..ops.device_rollup import TS_PAD
        t0 = _time.perf_counter()
        b.dev = {
            "ts": self._put("fleet_ts", b.ts_h.copy(), TS_PAD),
            "vals": self._put("fleet_values", b.vals_h.copy()),
            "counts": self._put("fleet_counts", b.counts_h.copy()),
            "gids": self._put("fleet_gids", b.gids_h.copy()),
            "v0": self._put("fleet_v0", b.v0_h.copy()),
            "aggr": self._put("fleet_aggr", b.aggr_h.copy()),
        }
        b.last_up_wall = _time.perf_counter() - t0
        b.last_up_bytes = (b.ts_h.nbytes + b.vals_h.nbytes +
                           b.counts_h.nbytes + b.gids_h.nbytes +
                           b.v0_h.nbytes + b.aggr_h.nbytes)
        b.dirty = False

    def _append_device(self, b: FleetBucket, staged) -> None:
        """One donated batched append for every staged slice of this
        bucket (no-op rows for members with nothing staged)."""
        if not staged:
            b.last_up_bytes = 0
            b.last_up_wall = 0.0
            return
        from ..ops.device_rollup import fleet_append_tile
        from .tpu_engine import timed_kernel_call
        t0 = _time.perf_counter()
        K = max(int(c.ts.shape[1]) for _, c, _ in staged)
        K_pad = (K + 7) // 8 * 8
        new_ts = np.zeros((b.B_pad, b.S_b, K_pad), dtype=np.int32)
        new_vals = np.zeros((b.B_pad, b.S_b, K_pad), dtype=b.vals_h.dtype)
        new_counts = np.zeros((b.B_pad, b.S_b), dtype=np.int32)
        for m, cols, rows_idx in staged:
            Kc = cols.ts.shape[1]
            vals_in = cols.vals
            if m.v0 is not None:
                vals_in = vals_in - m.v0[rows_idx][:, None]
            new_ts[m.slot, rows_idx, :Kc] = \
                (cols.ts - m.base_ms).astype(np.int32)
            new_vals[m.slot, rows_idx, :Kc] = \
                vals_in.astype(b.vals_h.dtype)
            new_counts[m.slot, rows_idx] = cols.counts
        ts_d = self._put("fleet_ts", new_ts)
        vals_d = self._put("fleet_values", new_vals)
        counts_d = self._put("fleet_counts", new_counts)
        dev = b.dev
        out = timed_kernel_call("fleet_append_tile", fleet_append_tile,
                                dev["ts"], dev["vals"], dev["counts"],
                                ts_d, vals_d, counts_d)
        dev["ts"], dev["vals"], dev["counts"] = out
        b.last_up_wall = _time.perf_counter() - t0
        b.last_up_bytes = (new_ts.nbytes + new_vals.nbytes +
                           new_counts.nbytes)

    def _compact(self, b: FleetBucket, cutoffs: dict) -> bool:
        """Window-slide compaction for the slots in `cutoffs` ({slot:
        absolute cutoff}): mirrors AND device planes (one donated
        batched launch) drop samples older than each member's cutoff
        and rebase its origin there."""
        from ..ops.device_rollup import TS_PAD
        cut_rel = np.zeros(b.B_pad, dtype=np.int64)
        todo = []
        for m in b.members:
            c = cutoffs.get(m.slot)
            if c is None:
                continue
            rel = c - m.base_ms
            if rel <= 0:
                return False  # nothing would move
            if rel >= 2**31 - 1:
                return False  # stale beyond the int32 frame: evict path
            cut_rel[m.slot] = rel
            todo.append((m, c, rel))
        if not todo:
            return False
        # host mirrors (authoritative): per-slot crop, same semantics as
        # _compact_tile_body (drop ts < cutoff, shift left, rebase)
        k = np.arange(b.N_b)[None, :]
        for m, cutoff_abs, rel in todo:
            ts = b.ts_h[m.slot].astype(np.int64)
            counts = b.counts_h[m.slot].astype(np.int64)
            valid = k < counts[:, None]
            drop = ((ts < rel) & valid).sum(axis=1)
            new_counts = counts - drop
            idx = np.clip(drop[:, None] + k, 0, b.N_b - 1)
            ts2 = np.take_along_axis(ts, idx, axis=1) - rel
            v2 = np.take_along_axis(b.vals_h[m.slot], idx, axis=1)
            live = k < new_counts[:, None]
            b.ts_h[m.slot] = np.where(live, ts2, TS_PAD).astype(np.int32)
            b.vals_h[m.slot] = np.where(live, v2, 0)
            b.counts_h[m.slot] = new_counts.astype(np.int32)
            m.counts = new_counts[:m.S].copy()
            m.base_ms = cutoff_abs
            m.lo_ms = max(m.lo_ms, cutoff_abs)
            m.segments = [(max(lo, cutoff_abs), hi, nn)
                          for lo, hi, nn in m.segments if hi >= cutoff_abs]
        if b.dev is not None and not b.dirty:
            from ..models.tile_cache import count_window_compaction
            from ..ops.device_rollup import fleet_compact_tile
            from .tpu_engine import timed_kernel_call
            cut = cut_rel.astype(np.int32)
            cut_d = self._put("fleet_shift", cut)
            out = timed_kernel_call("fleet_compact_tile",
                                    fleet_compact_tile, b.dev["ts"],
                                    b.dev["vals"], b.dev["counts"],
                                    cut_d, cut_d)
            b.dev["ts"], b.dev["vals"], b.dev["counts"] = out
            count_window_compaction()
        return True

    # -- eviction ---------------------------------------------------------

    def _evict(self, m: FleetMember, reason: str) -> None:
        b = m.bucket
        self._members.pop(m.skey, None)
        self._results.pop(m.skey, None)
        self._rebuild_retry[m.skey] = 4
        last = b.members[-1]
        if last is not m:
            # swap-remove: the last slot's planes move into the hole
            b.ts_h[m.slot] = b.ts_h[last.slot]
            b.vals_h[m.slot] = b.vals_h[last.slot]
            b.counts_h[m.slot] = b.counts_h[last.slot]
            b.gids_h[m.slot] = b.gids_h[last.slot]
            b.v0_h[m.slot] = b.v0_h[last.slot]
            b.aggr_h[m.slot] = b.aggr_h[last.slot]
            b.members[m.slot] = last
            last.slot = m.slot
        b.members.pop()
        from ..ops.device_rollup import TS_PAD
        sl = len(b.members)
        b.ts_h[sl] = TS_PAD
        b.vals_h[sl] = 0
        b.counts_h[sl] = 0
        b.gids_h[sl] = 0
        b.v0_h[sl] = 0
        b.aggr_h[sl] = 0
        b.dirty = True
        if not b.members:
            self._buckets.pop(b.key, None)
        self.evictions += 1
        _EVICTIONS.inc()
        flightrec.instant("fleet:evict",
                          arg=f"{reason}: {str(m.skey[1])[:100]}")

    # -- the fused launch -------------------------------------------------

    def _launch(self, api, b: FleetBucket, due) -> None:
        from ..ops.device_rollup import fleet_rollup_aggregate_tile
        from .tpu_engine import _pull_host, backend_compiles, \
            timed_kernel_call
        shift = np.zeros(b.B_pad, dtype=np.int32)
        min_ts = np.zeros(b.B_pad, dtype=np.int32)
        for m, end_q in due:
            start_g = end_q - m.duration - m.offset
            shift[m.slot] = start_g - m.base_ms
            min_ts[m.slot] = -(m.lookback + m.lookback_delta)
        t0 = _time.perf_counter()
        shift_d = self._put("fleet_shift", shift)
        mints_d = self._put("fleet_min_ts", min_ts)
        dev = b.dev
        compiles0 = backend_compiles()
        if self._mesh is not None:
            from ..parallel.mesh import cached_fleet_rollup_aggregate
            fn = cached_fleet_rollup_aggregate(self._mesh, b.func, b.cfg,
                                               b.G_b)
            out = timed_kernel_call("fleet_rollup_aggregate", fn,
                                    dev["ts"], dev["vals"], dev["counts"],
                                    dev["gids"], dev["aggr"], shift_d,
                                    mints_d, dev["v0"])
        else:
            out = timed_kernel_call("fleet_rollup_aggregate",
                                    fleet_rollup_aggregate_tile, b.func,
                                    b.cfg, b.G_b, dev["ts"], dev["vals"],
                                    dev["counts"], dev["gids"],
                                    dev["aggr"], shift_d, mints_d,
                                    dev["v0"])
        # REAL XLA compiles only (monitoring event), NOT jit-cache entry
        # growth: donation churn creates cpp fastpath entries that resolve
        # in the Python trace cache without compiling anything
        grew = backend_compiles() - compiles0
        if grew > 0:
            b.compiles += grew
            self.compiles += grew
        out_h = _pull_host(out)
        wall = _time.perf_counter() - t0
        ver = getattr(api.storage, "data_version", None)
        structural = getattr(api.storage, "structural_version", None)
        # rows-share split of the shared launch: the LAST member takes
        # the exact remainder so per-stream shares sum to the total
        total_S = sum(m.S for m, _ in due) or 1
        acc_w = acc_uw = 0.0
        acc_b = 0
        n_streams_in_bucket = len(b.members)
        for i, (m, end_q) in enumerate(due):
            start_g = end_q - m.duration - m.offset
            r = FleetResult()
            r.start = start_g
            r.end = end_q - m.offset
            r.step = m.step
            r.version = ver
            r.structural = structural
            r.lookback_delta = m.lookback_delta
            r.rows = np.asarray(out_h[m.slot, :m.G, :m.T],
                                dtype=np.float64).copy()
            r.group_keys = m.group_keys
            fetch_lo = start_g - m.lookback - m.lookback_delta
            r.samples = m.samples_in_range(fetch_lo)
            if i + 1 == len(due):
                r.exec_share_s = wall - acc_w
                r.up_share_s = b.last_up_wall - acc_uw
                r.up_share_b = b.last_up_bytes - acc_b
            else:
                frac = m.S / total_S
                r.exec_share_s = wall * frac
                r.up_share_s = b.last_up_wall * frac
                r.up_share_b = int(b.last_up_bytes * frac)
            acc_w += r.exec_share_s
            acc_uw += r.up_share_s
            acc_b += r.up_share_b
            self._results[m.skey] = r
        b.last_up_bytes = 0
        b.last_up_wall = 0.0
        self.launches += 1
        _LAUNCHES.inc()
        _STREAMS.inc(len(due))
        flightrec.rec(
            "device:fleet_launch", t0, wall,
            arg=f"{len(due)}/{n_streams_in_bucket} streams "
                f"[B={b.B_pad},S={b.S_b},N={b.N_b},G={b.G_b},T={b.T_b}]")


# -- module-level seams ------------------------------------------------------


def prepass(api, now_ms: int) -> int:
    """Interval-aligned batch scheduler hook (MatStream._advance calls
    this before evaluating).  Never raises: a fleet failure falls back
    to the per-stream paths for the interval, loudly."""
    eng = getattr(api, "tpu", None)
    if eng is None or not enabled():
        return 0
    from ..models.tile_cache import device_resident_enabled
    if not device_resident_enabled():
        return 0
    try:
        return eng.fleet().run(api, now_ms)
    except Exception as e:  # noqa: BLE001 — serving must survive
        flightrec.instant("fleet:error", arg=repr(e)[:160])
        import sys
        print(f"vmtpu: fleet prepass failed (per-stream fallback): {e!r}",
              file=sys.stderr)
        return 0


def resident(engine, skey) -> bool:
    """True when the fleet holds a member for this rolling-state key, OR
    the key was recently evicted and should run one full device eval to
    rebuild its per-shape window so the fleet can re-adopt it
    (device_window_ready's fleet extension)."""
    if engine is None or not enabled():
        return False
    plane = engine._fleet
    return plane is not None and \
        (plane.has(skey) or plane.wants_rebuild(skey))


def take(ec, skey):
    """Serve one eval from the fleet's result table: (rows [G, T],
    group_keys) on a grid/version-matched result, else None (the eval
    falls through to the per-stream paths).  Counts samples, checks the
    deadline, and laps this stream's share of the shared launch into
    the query's cost tracker (consume-once)."""
    eng = ec.tpu
    if eng is None or not enabled():
        return None
    plane = eng._fleet
    if plane is None:
        return None
    from ..models.tile_cache import device_resident_enabled
    if not device_resident_enabled():
        return None
    with plane._lock:
        r = plane._results.get(skey)
        m = plane._members.get(skey)
        if r is None or m is None:
            return None
        if (r.start, r.end, r.step) != (ec.start - m.offset,
                                        ec.end - m.offset, ec.step):
            return None
        if r.version != getattr(ec.storage, "data_version", None) or \
                r.structural != getattr(ec.storage, "structural_version",
                                        None) or \
                r.lookback_delta != ec.lookback_delta:
            return None
        rows, group_keys, samples = r.rows, r.group_keys, r.samples
        exec_s, up_s, up_b = r.exec_share_s, r.up_share_s, r.up_share_b
        r.exec_share_s = r.up_share_s = 0.0
        r.up_share_b = 0
        plane.served += 1
    ec.check_deadline()
    ec.count_samples(samples)
    tr = costacc.current()
    if tr is not None:
        if exec_s:
            tr.lap("device:execute", exec_s, 0.0)
        if up_s or up_b:
            tr.lap("device:upload", up_s, 0.0)
            tr.add_device(up=up_b)
    _SERVED.inc()
    return rows, group_keys
