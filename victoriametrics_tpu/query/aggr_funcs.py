"""Aggregate functions (reference app/vmselect/promql/aggr.go:20-58, 37
functions + MetricsQL extras).

Each aggregate takes the stacked values matrix [S, T] of one group (NaN =
absent) plus optional scalar/string args, and returns either one row [T]
(simple aggregates) or a list of (extra_labels, row) for multi-output
aggregates (quantiles, count_values) or per-series selections (topk family,
limitk, outliers) which return masks instead.
"""

from __future__ import annotations

import numpy as np

nan = np.nan

with np.errstate(all="ignore"):
    pass


def _nan_all(m: np.ndarray) -> np.ndarray:
    return np.isnan(m).all(axis=0)


def _guard(fn):
    def wrapped(m, *args):
        with np.errstate(all="ignore"):
            out = fn(m, *args)
        out = np.asarray(out, dtype=np.float64)
        out[_nan_all(m)] = nan
        return out
    return wrapped


@_guard
def a_sum(m):
    return np.nansum(m, axis=0)


@_guard
def a_min(m):
    return np.nanmin(m, axis=0)


@_guard
def a_max(m):
    return np.nanmax(m, axis=0)


@_guard
def a_avg(m):
    return np.nanmean(m, axis=0)


@_guard
def a_count(m):
    return (~np.isnan(m)).sum(axis=0).astype(np.float64)


@_guard
def a_stddev(m):
    return np.nanstd(m, axis=0)


@_guard
def a_stdvar(m):
    return np.nanvar(m, axis=0)


@_guard
def a_group(m):
    return np.ones(m.shape[1])


@_guard
def a_median(m):
    return np.nanmedian(m, axis=0)


@_guard
def a_sum2(m):
    return np.nansum(m * m, axis=0)


@_guard
def a_geomean(m):
    cnt = (~np.isnan(m)).sum(axis=0)
    return np.exp(np.nansum(np.log(m), axis=0) / np.maximum(cnt, 1))


@_guard
def a_distinct(m):
    out = np.zeros(m.shape[1])
    for j in range(m.shape[1]):
        col = m[:, j]
        out[j] = np.unique(col[~np.isnan(col)]).size
    return out


@_guard
def a_mode(m):
    out = np.full(m.shape[1], nan)
    for j in range(m.shape[1]):
        col = m[:, j]
        col = col[~np.isnan(col)]
        if col.size:
            vals, counts = np.unique(col, return_counts=True)
            out[j] = vals[np.argmax(counts)]
    return out


def a_quantile(m, phi: float):
    if np.isnan(phi):
        return np.full(m.shape[1], nan)
    with np.errstate(all="ignore"):
        out = np.full(m.shape[1], nan)
        ok = ~_nan_all(m)
        if ok.any():
            out[ok] = np.nanquantile(m[:, ok], min(max(phi, 0), 1), axis=0)
        if phi < 0:
            out[ok] = -np.inf
        if phi > 1:
            out[ok] = np.inf
    return out


def _guard_matrix(fn):
    # matrix-shaped results: all-NaN COLUMNS go NaN (per-axis, not per-row)
    def wrapped(m, *args):
        with np.errstate(all="ignore"):
            out = np.asarray(fn(m, *args), dtype=np.float64)
        out[:, _nan_all(m)] = nan
        return out
    return wrapped


@_guard_matrix
def a_zscore(m):
    mean = np.nanmean(m, axis=0)
    sd = np.nanstd(m, axis=0)
    return (m - mean) / np.where(sd > 0, sd, nan)   # returns matrix!


@_guard_matrix
def a_share(m):
    # aggr.go:462 aggrFuncShare: negative points are EXCLUDED from the sum
    # and their own share is NaN
    ok = ~np.isnan(m) & (m >= 0)
    s = np.where(ok, m, 0.0).sum(axis=0)
    return np.where(ok, m / s, nan)                 # returns matrix!

SIMPLE = {
    "sum": a_sum, "min": a_min, "max": a_max, "avg": a_avg,
    "count": a_count, "stddev": a_stddev, "stdvar": a_stdvar,
    "group": a_group, "median": a_median, "sum2": a_sum2,
    "geomean": a_geomean, "distinct": a_distinct, "mode": a_mode,
}

# matrix-preserving aggregates: output one series per input series
PER_SERIES = {"zscore": a_zscore, "share": a_share}


def series_rank_metric(kind: str, m: np.ndarray) -> np.ndarray:
    """Whole-series statistic for topk_*/bottomk_* selection."""
    with np.errstate(all="ignore"):
        if kind == "avg":
            return np.nanmean(m, axis=1)
        if kind == "min":
            return np.nanmin(m, axis=1)
        if kind == "max":
            return np.nanmax(m, axis=1)
        if kind == "median":
            return np.nanmedian(m, axis=1)
        if kind == "last":
            out = np.full(m.shape[0], nan)
            for i in range(m.shape[0]):
                row = m[i]
                ok = np.flatnonzero(~np.isnan(row))
                if ok.size:
                    out[i] = row[ok[-1]]
            return out
    raise ValueError(f"unknown rank kind {kind}")


def topk_mask_per_ts(m: np.ndarray, k: int, bottom: bool) -> np.ndarray:
    """Prometheus-style per-timestamp topk: mask [S, T] of kept samples."""
    S, T = m.shape
    k = max(int(k), 0)
    mask = np.zeros((S, T), dtype=bool)
    if k == 0:
        return mask
    # ties keep the LOWEST series index (deterministic, and identical to
    # jax.lax.top_k so the device selection path agrees bit-for-bit)
    key = np.where(np.isnan(m), -np.inf if not bottom else np.inf, m)
    order = np.argsort(key if bottom else -key, axis=0, kind="stable")
    sel = order[:k]
    for j in range(T):
        mask[sel[:, j], j] = True
    mask &= ~np.isnan(m)
    return mask
