"""Canonical sample-value formatting shared by the HTTP responders and
count_values-style label generation (strconv.AppendFloat 'g' analog)."""

from __future__ import annotations

import math


def fmt_value(v: float) -> str:
    v = float(v)  # numpy scalars repr as np.float64(...) otherwise
    if math.isnan(v):
        return "NaN"
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    return repr(v)
