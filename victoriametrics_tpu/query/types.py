"""Query-engine core types.

Timeseries: one output series on the shared (start..end, step) grid; NaN
marks absent points (the reference's netstorage.Result shape after rollup).
EvalConfig: the per-query static context threaded through the evaluator
(eval.go evalConfig analog).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from ..storage.metric_name import MetricName


@dataclasses.dataclass
class Timeseries:
    metric_name: MetricName
    values: np.ndarray  # float64 [T], NaN = absent
    # memoized metric_name.marshal() — set ONLY by producers that know the
    # name will not be mutated downstream (the rollup result cache); may go
    # stale if metric_name is edited, so consumers must treat it as a hint
    raw: bytes | None = None

    def copy_shallow_labels(self) -> "Timeseries":
        mn = MetricName(self.metric_name.metric_group,
                        list(self.metric_name.labels))
        return Timeseries(mn, self.values)


@dataclasses.dataclass
class EvalConfig:
    start: int                 # unix ms, first output timestamp
    end: int                   # unix ms, last output timestamp (inclusive)
    step: int                  # ms
    storage: object = None     # duck-typed: search_series(filters, lo, hi)
    lookback_delta: int = 300_000   # instant-vector staleness window
    max_points_per_series: int = 50_000_000
    max_series: int = 1_000_000
    max_samples_per_query: int = 1_000_000_000  # -search.maxSamplesPerQuery
    max_memory_per_query: int = 0               # -search.maxMemoryPerQuery
    deadline: float = 0.0      # time.monotonic() cutoff; 0 = none
    round_digits: int = 100
    tenant: tuple = (0, 0)     # (accountID, projectID), lib/auth.Token analog
    disable_cache: bool = False  # nocache=1 / -search.disableCache
    # internal: the tail child of an eval-cache partial hit must not read or
    # write the eval rollup cache under its parent's key, but MAY still use
    # the device tile reuse paths (unlike user-facing disable_cache)
    no_eval_cache: bool = False
    # internal: disable the device ROLLING/aux tile-reuse shortcuts while
    # keeping fresh device compute. Set by the HTTP result cache's suffix
    # eval: its VARIABLE-LENGTH suffix grids don't fit the constant-shape
    # sliding advance the resident-window reuse is designed for (the
    # RingBlock declines them), and layering the two tail-merges would
    # double-count coverage. Device engines normally never reach the
    # suffix path for rolling-capable shapes — the serving layer routes
    # them through the resident window first (device_window_ready);
    # this flag covers the remaining fallback evals. Both patterns are
    # pinned by tests/test_served_device_path.py.
    no_device_roll: bool = False
    tracer: object = None      # querytracer.Tracer | NOP (set in __post_init__)
    tpu: object = None         # TPUEngine when the device path is enabled
    _grid: np.ndarray | None = None
    _samples_scanned: list | None = None  # shared per-query accumulator
    _partial: list | None = None          # per-query partial-result flag
    # per-query partial-RESOLUTION flag: some fetch was served from a
    # downsampled tier coarser than the query's step allows (raw dropped
    # by retention) — degraded loudly, never silently wrong
    _partial_res: list | None = None
    _cost: object | None = None  # shared per-query CostTracker

    def __post_init__(self):
        if self.tracer is None:
            from ..utils import querytracer
            self.tracer = querytracer.NOP
        if self._samples_scanned is None:
            # created HERE (not lazily) so child() configs made before the
            # first fetch still share one per-query accumulator
            self._samples_scanned = [0]
        if self._partial is None:
            self._partial = [False]
        if self._partial_res is None:
            self._partial_res = [False]
        if self._cost is None:
            # one CostTracker per query, shared by children exactly like
            # the samples accumulator (utils/costacc: the per-query
            # resource-cost plane behind /api/v1/status/usage)
            from ..utils.costacc import CostTracker
            self._cost = CostTracker()
        if self.step <= 0:
            raise ValueError("step must be positive")
        if self.end < self.start:
            raise ValueError("end < start")
        npoints = (self.end - self.start) // self.step + 1
        if npoints > self.max_points_per_series:
            raise ValueError(f"too many output points: {npoints}")

    def timestamps(self) -> np.ndarray:
        if self._grid is None:
            self._grid = np.arange(self.start, self.end + 1, self.step,
                                   dtype=np.int64)
        return self._grid

    @property
    def n_points(self) -> int:
        return self.timestamps().size

    def child(self, **kw) -> "EvalConfig":
        d = dict(start=self.start, end=self.end, step=self.step,
                 storage=self.storage, lookback_delta=self.lookback_delta,
                 max_points_per_series=self.max_points_per_series,
                 max_series=self.max_series, round_digits=self.round_digits,
                 max_samples_per_query=self.max_samples_per_query,
                 max_memory_per_query=self.max_memory_per_query,
                 deadline=self.deadline, tenant=self.tenant,
                 disable_cache=self.disable_cache,
                 no_eval_cache=self.no_eval_cache,
                 no_device_roll=self.no_device_roll,
                 tracer=self.tracer, tpu=self.tpu,
                 _samples_scanned=self._samples_scanned,
                 _partial=self._partial, _partial_res=self._partial_res,
                 _cost=self._cost)
        d.update(kw)
        return EvalConfig(**d)

    def check_deadline(self):
        if self.deadline:
            import time as _t
            if _t.monotonic() > self.deadline:
                from .limits import QueryLimitError
                raise QueryLimitError(
                    "query exceeds -search.maxQueryDuration; increase the "
                    "flag or reduce the query scope")

    @property
    def samples_scanned(self) -> int:
        """Samples fetched so far across all selectors of this query
        (shared accumulator — children report into the parent). The
        O(new-samples) serving regression guard asserts on this."""
        return int(self._samples_scanned[0])

    @property
    def cost(self):
        """The query's shared CostTracker (utils/costacc)."""
        return self._cost

    def count_samples(self, n: int):
        """Accumulate scanned samples across all selectors of one query
        (the -search.maxSamplesPerQuery scope, eval.go seriesFetched).
        Negative n rolls back a fetch whose work was abandoned (e.g. the
        fused device path declining after its fetch)."""
        acc = self._samples_scanned
        acc[0] += n
        self._cost.add_samples(n)
        if acc[0] > self.max_samples_per_query:
            from .limits import QueryLimitError
            raise QueryLimitError(
                f"cannot select more than -search.maxSamplesPerQuery="
                f"{self.max_samples_per_query} samples; the query scans "
                f"{acc[0]} samples so far; possible solutions: to increase "
                f"the -search.maxSamplesPerQuery, to reduce the time range "
                f"or the number of matching series")


def new_series(values: np.ndarray, group: bytes = b"",
               labels: list | None = None) -> Timeseries:
    return Timeseries(MetricName(group, list(labels or [])),
                      np.asarray(values, dtype=np.float64))


def const_series(ec: EvalConfig, v: float) -> Timeseries:
    return new_series(np.full(ec.n_points, v, dtype=np.float64))
