"""Materialized query streams + subscription push (ROADMAP item 2: the
cross-query amortization plane).

Every distinct range expression registers here as ONE materialized
stream, keyed by its canonical expression text (the same canonical form
the rollup-result cache keys on).  One evaluator per stream advances the
expression's ring-cache entry O(new samples) per interval — regardless
of how many dashboards subscribe — and every subscriber receives the
suffix DELTA of the window instead of re-issuing ``query_range``:
storage reads per interval are O(distinct expressions), not
O(subscribers).

Frames (JSON dicts, also the SSE payloads of ``/api/v1/watch``):

- ``snapshot`` — the full current window, exactly the polled
  ``query_range`` result shape (``result`` entries of ``{"metric": ...,
  "values": [[t_seconds, value_string], ...]}`` with NaN points
  omitted).  Sent on (re)subscribe and whenever delta semantics cannot
  be guaranteed.
- ``delta`` — the window advanced: the client drops every stored point
  with ``t < startMs`` or ``t >= newStartMs`` and inserts the frame's
  points.  ``newStartMs`` is computed by DIFFING the fresh evaluation
  against the committed state, so replace-region semantics hold even
  when the volatile tail (OFFSET_MS) was recomputed — reassembled state
  is bit-equal to a poll by construction.
- ``error`` — the advance failed (deadline, shed load, ...); loud, and
  the next good frame is a resync snapshot.

Decline contract (mirrors the device-residency plane of PR 11): a
PARTIAL interval (storage node down mid-fan-out) is never committed —
subscribers get a partial-flagged snapshot, ``vm_matstream_declines_
total`` ticks, and the next clean advance resyncs.  Slow subscribers
are bounded: each subscription holds a small frame queue
(``VM_MATSTREAM_QUEUE``); overflow drops the backlog and enqueues one
resync snapshot (drop-and-resync, never unbounded memory).

No background threads: subscribers PUMP their stream cooperatively —
``next_frame`` advances the stream when its interval is due (first
caller wins the advance lock; everyone else gets the fanned frame), so
an idle stream costs nothing and the deterministic scheduler sees plain
lock/queue seams.

``VM_MATSTREAM=0`` disables the plane (``/api/v1/watch`` answers 503,
``subscribe`` raises, the vmalert shared-instant memo degrades to
per-rule evaluation) — the escape hatch AND the equality oracle: pushed
frames must reassemble bit-equal to the polled path.
"""

from __future__ import annotations

import math
import os
import queue
import threading
import time as _time
import weakref

import numpy as np

from ..devtools.locktrace import make_lock
from ..utils import costacc, fasttime, flightrec
from ..utils import metrics as metricslib
from .format_value import fmt_value

_instances: "weakref.WeakSet[MatStreamRegistry]" = weakref.WeakSet()

metricslib.REGISTRY.gauge(
    "vm_matstream_streams",
    callback=lambda: sum(r.stream_count() for r in list(_instances)))
metricslib.REGISTRY.gauge(
    "vm_matstream_subscribers",
    callback=lambda: sum(r.subscriber_count() for r in list(_instances)))
_FRAMES = metricslib.REGISTRY.counter("vm_matstream_frames_sent_total")
#: evaluations SAVED by sharing: (subscribers - 1) per fanned frame plus
#: every shared-instant memo hit (vmalert rules sharing one expression)
_REUSE = metricslib.REGISTRY.counter("vm_matstream_fanout_reuse_total")
_DECLINES = metricslib.REGISTRY.counter("vm_matstream_declines_total")
_DROPS = metricslib.REGISTRY.counter("vm_matstream_dropped_frames_total")
_EVALS = metricslib.REGISTRY.counter("vm_matstream_evals_total")
#: reconnect/resume accounting: a hit replays only the missed suffix
#: frames; a miss (unknown/too-old token) degrades LOUDLY to a full
#: resync snapshot
_RESUMES = metricslib.REGISTRY.counter("vm_matstream_resumes_total")
_RESUME_MISSES = metricslib.REGISTRY.counter(
    "vm_matstream_resume_misses_total")


def enabled() -> bool:
    return os.environ.get("VM_MATSTREAM", "1") != "0"


def queue_limit() -> int:
    try:
        return max(int(os.environ.get("VM_MATSTREAM_QUEUE", "8")), 1)
    except ValueError:
        return 8


def max_streams() -> int:
    try:
        return max(int(os.environ.get("VM_MATSTREAM_MAX", "256")), 1)
    except ValueError:
        return 256


class MatStreamDisabled(RuntimeError):
    pass


class MatStreamLimitError(RuntimeError):
    pass


class _State:
    """One committed evaluation of the stream's window."""

    __slots__ = ("start", "end", "step", "raws", "metas", "vals", "idx")

    def __init__(self, start, end, step, raws, metas, vals):
        self.start = start
        self.end = end
        self.step = step
        self.raws = raws            # list[bytes]
        self.metas = metas          # list[dict], parallel
        self.vals = vals            # (S, T) float64, owned copy
        self.idx = {r: s for s, r in enumerate(raws)}


def _series_entries(state: _State, from_ts: int) -> list[dict]:
    """``query_range``-shaped result entries for points >= from_ts (NaN
    omitted, series with no surviving points omitted) — the polled
    response serialization, bit for bit."""
    i0 = max(0, (from_ts - state.start + state.step - 1) // state.step)
    if from_ts <= state.start:
        i0 = 0
    grid = (np.arange(state.start + i0 * state.step, state.end + 1,
                      state.step, dtype=np.int64) / 1e3)
    out = []
    for s, meta in enumerate(state.metas):
        v = state.vals[s, i0:]
        pts = [[float(t), fmt_value(x)] for t, x in zip(grid, v)
               if not math.isnan(x)]
        if pts:
            out.append({"metric": meta, "values": pts})
    return out


def _diff_new_start(old: _State | None, new: _State) -> int:
    """First timestamp whose content differs between the committed state
    and the fresh evaluation — everything >= it goes into the delta
    frame (replace-region semantics).  Clamped so the fresh columns past
    the old coverage always count."""
    if old is None or old.step != new.step or \
            (new.start - old.start) % new.step != 0:
        return new.start
    step = new.step
    ov_lo = max(old.start, new.start)
    ov_hi = min(old.end, new.end)
    if ov_hi < ov_lo:
        return new.start
    fresh = min(ov_hi + step, old.end + step)
    o0 = (ov_lo - old.start) // step
    n0 = (ov_lo - new.start) // step
    T = (ov_hi - ov_lo) // step + 1
    changed = np.zeros(T, dtype=bool)
    common_o: list[int] = []
    common_n: list[int] = []
    for raw, nrow in new.idx.items():
        orow = old.idx.get(raw)
        if orow is None:
            # appeared: every non-NaN point of the new row is a change
            changed |= ~np.isnan(new.vals[nrow, n0:n0 + T])
        else:
            common_o.append(orow)
            common_n.append(nrow)
    for raw, orow in old.idx.items():
        if raw not in new.idx:
            # vanished: every point the old row HAD must be dropped
            changed |= ~np.isnan(old.vals[orow, o0:o0 + T])
    if common_o:
        a = old.vals[np.asarray(common_o)][:, o0:o0 + T]
        b = new.vals[np.asarray(common_n)][:, n0:n0 + T]
        neq = ~((a == b) | (np.isnan(a) & np.isnan(b)))
        changed |= neq.any(axis=0)
    nz = np.flatnonzero(changed)
    first = ov_lo + int(nz[0]) * step if nz.size else fresh
    return min(first, fresh)


class Subscription:
    """One subscriber's bounded frame queue.  ``next_frame`` is the only
    consumer API; producers run under the stream lock."""

    def __init__(self, stream: "MatStream"):
        self.stream = stream
        self.q: "queue.Queue[dict]" = queue.Queue(maxsize=queue_limit())
        #: next frame must be a full snapshot (cold subscribe, overflow
        #: resync, after an error/partial decline).  Written only under
        #: stream._lock.
        self.need_snapshot = True
        self.dropped = 0
        self.closed = False

    def next_frame(self, timeout_s: float = 30.0,
                   now_ms: int | None = None) -> dict | None:
        """Pop the next frame, cooperatively advancing the stream when
        its interval is due.  ``None`` on timeout (caller heartbeats) or
        when closed.  Tests pass a pinned ``now_ms`` for determinism;
        live callers leave it None (wall clock, re-read per wait)."""
        deadline = _time.monotonic() + max(timeout_s, 0.0)
        while True:
            try:
                return self.q.get_nowait()
            except queue.Empty:
                pass
            if self.closed:
                return None
            now = now_ms if now_ms is not None else fasttime.unix_ms()
            if self.stream.maybe_advance(now):
                continue
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return None
            # wake early enough to pump the next interval on time
            wait = min(remaining, max(self.stream.step / 4e3, 0.05), 1.0)
            try:
                return self.q.get(timeout=wait)
            except queue.Empty:
                continue

    def close(self) -> None:
        self.stream._unsubscribe(self)


_EPOCH_COUNTER = __import__("itertools").count(1)


class MatStream:
    """One materialized expression: canonical query text + (step,
    window, tenant), its committed window state, and its subscribers."""

    def __init__(self, registry: "MatStreamRegistry", q: str, step: int,
                 duration: int, tenant: tuple):
        self.registry = registry
        self.q = q                  # canonical expression text
        self.step = step
        self.duration = duration
        self.tenant = tenant
        self._lock = make_lock("query.MatStream._lock")
        self._advance_lock = make_lock("query.MatStream._advance_lock")
        self._state: _State | None = None
        self._subs: list[Subscription] = []
        #: resume-token namespace: a token from another stream
        #: incarnation (evicted + re-created, process restart) must
        #: never replay against this one's seq space
        self.epoch = f"{fasttime.unix_ms():x}.{next(_EPOCH_COUNTER):x}"
        #: the last few fanned frames, (seq, frame), for reconnect
        #: resume (bounded by VM_MATSTREAM_QUEUE like subscriber queues)
        self._recent: list[tuple[int, dict]] = []
        #: instant-share verdict (see MatStreamRegistry.instant_vector):
        #: None = unvalidated, True = the committed tail column is
        #: bit-equal to a legacy instant eval at the same ts, False =
        #: proven divergent for this expression/step — never share.
        #: A True verdict is REVALIDATED every Nth share (the Nth call
        #: pays the legacy eval and re-compares), bounding how long a
        #: workload change — e.g. late-arriving samples inside the
        #: window — could serve diverging shares
        self.instant_share: bool | None = None
        self._share_hits = 0
        self.seq = 0
        self.evals = 0
        self.declines = 0
        self.frames_sent = 0
        self.last_samples_scanned = 0
        self.last_error = ""
        self._cost_totals: dict = {}
        self.created_at = fasttime.unix_seconds()

    # -- subscriber management (under self._lock) -------------------------

    def subscribe(self, resume: str | None = None) -> Subscription:
        """``resume`` is a token from a previous subscription's frames
        (``Last-Event-ID``/``resume=``): when it names THIS stream
        incarnation and every frame after it is still retained, the
        subscriber receives only the missed suffix frames; anything
        else — foreign epoch, too-old seq, malformed — degrades loudly
        to a full resync snapshot (vm_matstream_resume_misses_total)."""
        sub = Subscription(self)
        with self._lock:
            self._subs.append(sub)
            if resume:
                if self._try_resume(sub, resume):
                    _RESUMES.inc()
                    return sub
                _RESUME_MISSES.inc()
                flightrec.instant("matstream:resume_miss",
                                  arg=self.q[:120])
                if self._state is not None:
                    self._offer(sub, None,
                                [self._snapshot_frame(resync=True)])
                    sub.need_snapshot = False
                return sub
            if self._state is not None:
                # cold subscribe replays the CURRENT window from the
                # committed state — no evaluation, no storage read
                self._offer(sub, None, [self._snapshot_frame()])
                sub.need_snapshot = False
        return sub

    def _try_resume(self, sub: Subscription, token: str) -> bool:
        """Replay the missed suffix frames for a valid token (under
        self._lock).  Valid = same epoch AND every seq in (token_seq,
        self.seq] still retained — the client's reassembled state at
        token_seq is then a correct base for the retained deltas."""
        epoch, _, seq_s = token.rpartition(":")
        if epoch != self.epoch or not seq_s.isdigit():
            return False
        seq = int(seq_s)
        if seq > self.seq:
            return False
        # a token naming a PARTIAL snapshot frame means the client's
        # window holds the uncommitted partial values (the one fanned
        # frame that mutates client state away from the committed
        # line) — deltas diffed against the committed state would
        # leave its prefix silently divergent, so resync instead
        at = next((f for s, f in self._recent if s == seq), None)
        if at is not None and at.get("partial"):
            return False
        if seq == self.seq:
            sub.need_snapshot = False  # nothing missed: deltas continue
            return True
        missed = [f for s, f in self._recent if s > seq]
        if len(missed) != self.seq - seq:
            return False  # gap: retained ring no longer covers the token
        if any(f.get("type") != "delta" for f in missed):
            # the missed suffix crosses a decline (error frame or
            # partial snapshot): live subscribers were resynced with a
            # FRESH snapshot after it, but the retained ring holds the
            # raw delta that was diffed against the COMMITTED state —
            # replaying it onto a client that applied the partial
            # values would leave a silently divergent prefix.  Degrade
            # to the snapshot+resync path instead.
            return False
        sub.need_snapshot = False
        self._offer(sub, self._snapshot_frame, missed)
        return True

    def resume_token(self, frame: dict) -> str:
        """The SSE event id for one frame of this stream."""
        with self._lock:
            return f"{self.epoch}:{frame.get('seq', self.seq)}"

    def _unsubscribe(self, sub: Subscription) -> None:
        with self._lock:
            sub.closed = True
            try:
                self._subs.remove(sub)
            except ValueError:
                pass

    def _snapshot_frame(self, partial: bool = False,
                        resync: bool = False) -> dict:
        st = self._state
        f = {"type": "snapshot", "seq": self.seq, "query": self.q,
             "startMs": st.start, "endMs": st.end, "stepMs": st.step,
             "result": _series_entries(st, st.start)}
        if partial:
            f["partial"] = True
        if resync:
            f["resync"] = True
        return f

    def _offer(self, sub: Subscription, snapshot_fn, frames: list[dict]):
        """Enqueue frames for one subscriber; bounded queue overflow
        drops the backlog and resyncs with one snapshot."""
        for f in frames:
            if sub.need_snapshot and f.get("type") == "delta":
                if snapshot_fn is None:
                    continue
                f = snapshot_fn()
                sub.need_snapshot = False
            try:
                sub.q.put_nowait(f)
                self.frames_sent += 1
                _FRAMES.inc()
            except queue.Full:
                # drop-and-resync: clear the backlog, then enqueue ONE
                # resync snapshot (the queue is empty now, so this
                # cannot overflow) — a slow subscriber catches up from
                # the current window instead of replaying stale deltas
                n = 0
                while True:
                    try:
                        sub.q.get_nowait()
                        n += 1
                    except queue.Empty:
                        break
                sub.dropped += n + 1
                _DROPS.inc(n + 1)
                sub.need_snapshot = True
                flightrec.instant("matstream:drop", arg=self.q[:120])
                if snapshot_fn is not None:
                    try:
                        sub.q.put_nowait(self._mark_resync(snapshot_fn()))
                        sub.need_snapshot = False
                        self.frames_sent += 1
                        _FRAMES.inc()
                    except queue.Full:  # pragma: no cover — just drained
                        pass

    @staticmethod
    def _mark_resync(frame: dict) -> dict:
        f = dict(frame)
        f["resync"] = True
        return f

    def _fanout(self, frames: list[dict], snapshot_fn, resync_all: bool):
        # retain for reconnect resume BEFORE fanning (a subscriber that
        # drops mid-fan can resume into the frame it just missed)
        for f in frames:
            self._recent.append((self.seq, f))
        del self._recent[:-queue_limit()]
        subs = self._subs
        for sub in subs:
            if resync_all:
                sub.need_snapshot = True
            self._offer(sub, snapshot_fn, frames)
        if len(subs) > 1 and frames:
            _REUSE.inc(len(subs) - 1)

    # -- the evaluator -----------------------------------------------------

    def due(self, now_ms: int) -> bool:
        end = (now_ms // self.step) * self.step
        # racy-by-design fast path: _state is only rebound while BOTH
        # _advance_lock and _lock are held, and maybe_advance re-checks
        # due() after taking _advance_lock — a stale ref here costs one
        # redundant check, never a double advance
        st = self._state  # vmt: disable=VMT015
        return st is None or end > st.end

    def maybe_advance(self, now_ms: int) -> bool:
        """Advance to the interval `now_ms` falls in, if due and nobody
        else is already evaluating.  Returns True when THIS call
        advanced (frames were fanned out)."""
        if not self.due(now_ms):
            return False
        if not self._advance_lock.acquire(False):
            return False
        try:
            if not self.due(now_ms):
                return False
            self._advance(now_ms)
            return True
        finally:
            self._advance_lock.release()

    def _advance(self, now_ms: int) -> None:
        """One shared evaluation -> one frame -> every subscriber.
        Runs under _advance_lock."""
        end = (now_ms // self.step) * self.step
        start = end - self.duration
        api = self.registry.api
        # fleet prepass: ONE fused mesh launch serves every due
        # device-resident stream this interval; the eval below then hits
        # the fleet's result table instead of launching its own kernel.
        # The first due stream of the interval pays the (single) launch
        # for the whole fleet; the rest find fresh results and no-op.
        from . import fleet as _fleet
        _fleet.prepass(api, now_ms)
        t0 = _time.perf_counter()
        ec = api._ec(start, end, self.step, self.tenant)
        if hasattr(api.storage, "reset_partial"):
            api.storage.reset_partial()
        err: Exception | None = None
        rows: list = []
        try:
            with api.gate:
                rows = api._exec_range_cached(ec, self.q, now_ms)
        except Exception as e:  # noqa: BLE001 — fanned as an error frame
            err = e
        _EVALS.inc()
        partial = bool(getattr(api.storage, "last_partial", False))
        dur = _time.perf_counter() - t0
        flightrec.rec("matstream:advance", t0, dur, arg=self.q[:200])
        summary = ec._cost.summary()
        costacc.record_usage(self.tenant, ec._cost, summary=summary)
        with self._lock:
            self._fold_cost(summary)
            # stats land under _lock so usage_row's locked reads never
            # tear against the advance (the advance itself is already
            # serialized by _advance_lock)
            self.evals += 1
            self.last_samples_scanned = ec.samples_scanned
            self.seq += 1
            if err is not None:
                # loud: the failure reaches every subscriber, and the
                # next good advance resyncs from a snapshot
                self.last_error = str(err)
                self.declines += 1
                _DECLINES.inc()
                flightrec.instant("matstream:decline", arg=str(err)[:120])
                self._fanout([{"type": "error", "seq": self.seq,
                               "query": self.q, "error": str(err)}],
                             None, resync_all=True)
                return
            self.last_error = ""
            new_state = self._build_state(ec, rows)
            if partial:
                # decline: never commit a partial interval — serve it
                # loudly as a partial snapshot and resync when clean
                # (the rebuild-path contract of PR 11)
                self.declines += 1
                _DECLINES.inc()
                flightrec.instant("matstream:decline", arg="partial")
                prev, self._state = self._state, new_state
                frame = self._snapshot_frame(partial=True)
                self._state = prev
                self._fanout([frame], None, resync_all=True)
                return
            old = self._state
            self._state = new_state
            new_start = _diff_new_start(old, new_state)
            frame = {"type": "delta", "seq": self.seq, "query": self.q,
                     "startMs": new_state.start, "endMs": new_state.end,
                     "stepMs": new_state.step, "newStartMs": new_start,
                     "result": _series_entries(new_state, new_start)}
            self._fanout([frame], self._snapshot_frame,
                         resync_all=False)

    def _build_state(self, ec, rows) -> _State:
        T = ec.n_points
        raws, metas = [], []
        vals = np.full((len(rows), T), np.nan)
        for s, r in enumerate(rows):
            raws.append(r.raw if r.raw is not None
                        else r.metric_name.marshal())
            metas.append(r.metric_name.to_dict())
            v = r.values
            # rows from the cached executor are window-exact; be
            # defensive about short rows anyway (suffix producers)
            vals[s, T - min(v.size, T):] = v[-T:]
        return _State(ec.start, ec.end, ec.step, raws, metas, vals)

    def _fold_cost(self, summary: dict) -> None:
        t = self._cost_totals
        for k in ("samplesScanned", "bytesRead", "cpuMs", "deviceBytes",
                  "rpcBytes"):
            t[k] = t.get(k, 0) + summary.get(k, 0)
        # this stream's rows-share of the fused fleet launch (query.fleet
        # laps the split into the eval's tracker on take()): the shares
        # across streams sum to the launch totals, so usage rows stay an
        # exact decomposition of device wall time
        by = summary.get("wallMsByPhase") or {}
        for row, phase in (("deviceExecMs", "device:execute"),
                           ("deviceUploadMs", "device:upload")):
            t[row] = round(t.get(row, 0) + by.get(phase, 0.0), 3)

    # -- introspection -----------------------------------------------------

    def instant_rows_from_state(self, ts_ms: int) -> list[dict] | None:
        """Datasource-shaped rows derived from the committed window's
        LAST column — the shared-instant candidate for rule groups
        evaluating this stream's expression at exactly the committed
        end (None otherwise).  Value formatting mirrors instant_vector
        (float(fmt_value(v))), so a validated share is bit-equal to the
        legacy poll path."""
        with self._lock:
            st = self._state
            if st is None or st.end != ts_ms:
                return None
            out = []
            for s, meta in enumerate(st.metas):
                v = st.vals[s, -1]
                if math.isnan(v):
                    continue
                out.append({"metric": meta, "value": float(fmt_value(v)),
                            "ts": ts_ms / 1e3})
            return out

    def usage_row(self) -> dict:
        with self._lock:
            row = {"query": self.q, "tenant": f"{self.tenant[0]}:"
                   f"{self.tenant[1]}", "stepMs": self.step,
                   "windowMs": self.duration,
                   "subscribers": len(self._subs), "evals": self.evals,
                   "framesSent": self.frames_sent,
                   "declines": self.declines,
                   "lastSamplesScanned": self.last_samples_scanned}
            row.update({k: (round(v, 3) if isinstance(v, float) else v)
                        for k, v in self._cost_totals.items()})
            if self.last_error:
                row["lastError"] = self.last_error
            return row

    def subscriber_count(self) -> int:
        with self._lock:
            return len(self._subs)


class MatStreamRegistry:
    """Per-serving-instance stream table + the shared-instant memo the
    colocated vmalert rule engine routes through."""

    _INSTANT_MEMO_MAX = 512
    #: every Nth validated share re-runs the legacy eval and
    #: re-compares (see MatStream.instant_share)
    _SHARE_REVALIDATE_N = 16

    def __init__(self, api):
        # the owning PrometheusAPI (cached range executor + gate + _ec);
        # plain backref — the API owns the registry for its lifetime
        self.api = api
        self._lock = make_lock("query.MatStreamRegistry._lock")
        self._streams: dict[tuple, MatStream] = {}
        from collections import OrderedDict
        self._instant_memo: "OrderedDict[tuple, list]" = OrderedDict()
        self.instant_evals = 0
        self.instant_reuse = 0
        _instances.add(self)

    # -- range streams -----------------------------------------------------

    def canonical(self, q: str) -> str:
        """Canonical expression text — the stream identity AND the text
        handed to the cached executor, so spelling variants of one
        expression share a single stream and ring-cache entry."""
        from .exec import parse_cached
        return str(parse_cached(q))

    def subscribe(self, q: str, step: int, duration: int,
                  tenant: tuple = (0, 0),
                  resume: str | None = None) -> Subscription:
        if not enabled():
            raise MatStreamDisabled(
                "materialized streams disabled (VM_MATSTREAM=0)")
        canonical = self.canonical(q)
        if step <= 0:
            raise ValueError("step must be positive")
        duration = max(-(-int(duration) // step) * step, step)
        key = (tenant, canonical, step, duration)
        with self._lock:
            st = self._streams.get(key)
            if st is None:
                if len(self._streams) >= max_streams():
                    self._evict_locked()
                if len(self._streams) >= max_streams():
                    raise MatStreamLimitError(
                        f"too many materialized streams "
                        f"({max_streams()}); raise VM_MATSTREAM_MAX or "
                        f"unsubscribe idle watchers")
                st = MatStream(self, canonical, step, duration, tenant)
                self._streams[key] = st
            # subscribe WHILE holding the registry lock (registry ->
            # stream lock order, nested nowhere else): releasing first
            # would let a concurrent at-capacity subscribe evict this
            # still-subscriber-less stream and orphan the subscription
            # (two live streams for one key = duplicate evaluations)
            return st.subscribe(resume=resume)

    def _evict_locked(self) -> None:
        """Drop the oldest subscriber-less stream (its warm state is
        re-creatable from the ring cache)."""
        for key, st in list(self._streams.items()):
            if st.subscriber_count() == 0:
                del self._streams[key]
                return

    def advance_due(self, now_ms: int | None = None) -> int:
        """Advance every due stream once (bench/test driver; HTTP
        subscribers normally pump their own streams).  Returns how many
        streams advanced."""
        now = now_ms if now_ms is not None else fasttime.unix_ms()
        n = 0
        for st in self.streams():
            if st.maybe_advance(now):
                n += 1
        return n

    def streams(self) -> list[MatStream]:
        with self._lock:
            return list(self._streams.values())

    def stream_count(self) -> int:
        with self._lock:
            return len(self._streams)

    def subscriber_count(self) -> int:
        return sum(s.subscriber_count() for s in self.streams())

    def usage_rows(self) -> list[dict]:
        rows = [s.usage_row() for s in self.streams()]
        rows.sort(key=lambda r: -r.get("cpuMs", 0))
        return rows

    def instant_stats(self) -> dict:
        with self._lock:
            return {"evals": self.instant_evals,
                    "reuse": self.instant_reuse}

    # -- shared instant evaluation (vmalert rule groups) -------------------

    def _instant_candidate(self, tenant, canonical, ts_ms):
        """A RANGE stream over the same (tenant, expression) whose
        committed window ends exactly at ts_ms — its tail column is the
        shared-instant candidate (None, None when no stream/state
        lines up or sharing is proven divergent)."""
        with self._lock:
            streams = [st for k, st in self._streams.items()
                       if k[0] == tenant and k[1] == canonical]
        for st in streams:
            if st.instant_share is False:
                continue
            rows = st.instant_rows_from_state(ts_ms)
            if rows is not None:
                return st, rows
        return None, None

    def instant_vector(self, q: str, ts_ms: int,
                       tenant: tuple = (0, 0)) -> list[dict]:
        """One instant evaluation per distinct (expression, timestamp),
        fanned to every caller — recording/alerting rules sharing a
        selector pay one fetch+rollup.  Returns datasource-shaped rows
        (``{"metric", "value", "ts"}``), identical to the legacy HTTP
        poll path by construction (same executor, same value
        formatting).  With VM_MATSTREAM=0 the memo is bypassed: every
        caller evaluates itself (the legacy behavior, the oracle).

        Rule groups and RANGE streams over ONE expression also share:
        when a stream's committed window ends exactly at ts_ms, its
        tail column serves the instant — after a one-time
        validate-then-trust check (the first such call still runs the
        legacy eval and compares bit-for-bit; a divergent expression —
        e.g. one whose default rollup window depends on the grid step —
        pins ``instant_share=False`` and never shares again).  A
        validated hit costs zero evaluations and zero storage reads."""
        share = enabled()
        canonical = self.canonical(q)
        key = (tenant, canonical, ts_ms)
        cand_stream = cand_rows = None
        if share:
            with self._lock:
                hit = self._instant_memo.get(key)
                if hit is not None:
                    self._instant_memo.move_to_end(key)
                    self.instant_reuse += 1
                    _REUSE.inc()
                    return hit
            cand_stream, cand_rows = self._instant_candidate(
                tenant, canonical, ts_ms)
            if cand_stream is not None and cand_stream.instant_share:
                cand_stream._share_hits += 1
                if cand_stream._share_hits % self._SHARE_REVALIDATE_N:
                    _REUSE.inc()
                    flightrec.instant("matstream:instant_share",
                                      arg=canonical[:120])
                    with self._lock:
                        self.instant_reuse += 1
                        self._instant_memo[key] = cand_rows
                        while len(self._instant_memo) > \
                                self._INSTANT_MEMO_MAX:
                            self._instant_memo.popitem(last=False)
                    return cand_rows
                # every Nth share falls through to the legacy eval and
                # re-compares below — a workload change (late samples
                # inside the window) is caught within N shares
                cand_stream.instant_share = None
        from .exec import exec_query
        api = self.api
        ec = api._ec(ts_ms, ts_ms, 300_000, tenant)
        if hasattr(api.storage, "reset_partial"):
            api.storage.reset_partial()
        t0 = _time.perf_counter()
        with api.gate:
            rows = exec_query(ec, canonical)
        flightrec.rec("matstream:instant", t0,
                      _time.perf_counter() - t0, arg=canonical[:200])
        with self._lock:
            # under _lock like the instant_reuse increments above: the
            # memo is shared by every instant caller (HTTP, rule groups,
            # the SLO pump), so the miss counter races without it
            self.instant_evals += 1
        _EVALS.inc()
        costacc.record_usage(tenant, ec._cost)
        out = []
        for r in rows:
            v = r.values[-1]
            if math.isnan(v):
                continue
            # float(fmt_value(v)) mirrors the HTTP responder exactly:
            # the legacy datasource parses the formatted string
            out.append({"metric": r.metric_name.to_dict(),
                        "value": float(fmt_value(v)), "ts": ts_ms / 1e3})
        if cand_stream is not None and cand_stream.instant_share is None:
            # validate-then-trust: this legacy eval ran anyway — record
            # whether the stream's tail column matches it bit-for-bit
            # (order-insensitive: rules treat the result as a vector)
            import json as _json

            def _k(rows):
                return sorted(_json.dumps(r, sort_keys=True)
                              for r in rows)
            cand_stream.instant_share = _k(cand_rows) == _k(out)
        if share:
            with self._lock:
                self._instant_memo[key] = out
                while len(self._instant_memo) > self._INSTANT_MEMO_MAX:
                    self._instant_memo.popitem(last=False)
        return out


_ENC_LOCK = make_lock("query.matstream._ENC_LOCK")
_ENC_RING: list = []          # [(frame dict, encoded bytes)] newest last
_ENC_RING_MAX = 16


def encode_frame(frame: dict) -> bytes:
    """JSON-encode one frame ONCE process-wide: frames are shared dicts
    fanned to every subscriber, so N watchers of one stream must not
    pay N serializations of the same (possibly window-sized) payload.
    Identity-keyed ring memo, bounded to the last few frames (streams
    produce one frame per interval; anything older has been sent)."""
    import json as _json
    with _ENC_LOCK:
        for fr, b in _ENC_RING:
            if fr is frame:
                return b
    b = _json.dumps(frame).encode()
    with _ENC_LOCK:
        _ENC_RING.append((frame, b))
        while len(_ENC_RING) > _ENC_RING_MAX:
            _ENC_RING.pop(0)
    return b


class StreamClient:
    """Client-side frame reassembly (tests + tools/watch.sh): applies
    snapshot/delta frames and yields the polled ``query_range`` result
    shape — the bit-equality oracle's comparator."""

    def __init__(self):
        self._series: dict[str, dict] = {}   # key -> {"metric", pts}
        self.window: tuple | None = None
        self.partial = False
        self.errors: list[str] = []

    @staticmethod
    def _key(metric: dict) -> str:
        import json as _json
        return _json.dumps(metric, sort_keys=True)

    def apply(self, frame: dict) -> None:
        t = frame.get("type")
        if t == "error":
            self.errors.append(frame.get("error", ""))
            return
        if t == "snapshot":
            self._series = {}
            for ent in frame["result"]:
                self._series[self._key(ent["metric"])] = {
                    "metric": ent["metric"],
                    "pts": {p[0]: p[1] for p in ent["values"]}}
            self.window = (frame["startMs"], frame["endMs"],
                           frame["stepMs"])
            self.partial = bool(frame.get("partial"))
            return
        if t != "delta":
            raise ValueError(f"unknown frame type {t!r}")
        start_s = frame["startMs"] / 1e3
        ns_s = frame["newStartMs"] / 1e3
        for ent in self._series.values():
            ent["pts"] = {ts: v for ts, v in ent["pts"].items()
                          if start_s <= ts < ns_s}
        for ent in frame["result"]:
            k = self._key(ent["metric"])
            cur = self._series.get(k)
            if cur is None:
                cur = self._series[k] = {"metric": ent["metric"],
                                         "pts": {}}
            for ts, v in ent["values"]:
                cur["pts"][ts] = v
        self._series = {k: e for k, e in self._series.items() if e["pts"]}
        self.window = (frame["startMs"], frame["endMs"], frame["stepMs"])
        self.partial = False

    def result(self) -> list[dict]:
        out = []
        for k in sorted(self._series):
            e = self._series[k]
            out.append({"metric": e["metric"],
                        "values": [[ts, e["pts"][ts]]
                                   for ts in sorted(e["pts"])]})
        return out
