"""Per-query resource limits and memory admission (reference
app/vmselect/promql/eval.go:1776-1885 rollupMemoryLimiter,
app/vmselect/promql/memory_limiter.go, -search.max* flag family).

A query is admitted only if its estimated rollup working set fits the
shared budget (25% of allowed memory, like getRollupMemoryLimiter);
estimates use the reference's formula: series*1000 + points*16 bytes.
"""

from __future__ import annotations

import threading

from ..utils import memory


class QueryLimitError(ValueError):
    """Raised when a query exceeds a -search.max* limit (HTTP 422)."""


class MemoryLimiter:
    """memory_limiter.go analog: admit/release byte reservations."""

    def __init__(self, max_size: int):
        self.max_size = max_size
        self.usage = 0
        self._lock = threading.Lock()

    def get(self, n: int) -> bool:
        with self._lock:
            if n <= self.max_size - self.usage:
                self.usage += n
                return True
            return False

    def put(self, n: int) -> None:
        with self._lock:
            if n > self.usage:
                raise ValueError("BUG: releasing more than acquired")
            self.usage -= n


_rollup_limiter: MemoryLimiter | None = None
_rollup_lock = threading.Lock()


def rollup_memory_limiter() -> MemoryLimiter:
    global _rollup_limiter
    with _rollup_lock:
        if _rollup_limiter is None:
            _rollup_limiter = MemoryLimiter(memory.allowed() // 4)
        return _rollup_limiter


def estimate_rollup_memory(n_series: int, points_per_series: int) -> int:
    """eval.go:1839 rollupMemorySize: series overhead + 16B per point."""
    return n_series * 1000 + n_series * points_per_series * 16


class _Admission:
    """Context manager holding a rollup-memory reservation."""

    def __init__(self, limiter: MemoryLimiter, size: int):
        self.limiter = limiter
        self.size = size

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.limiter.put(self.size)
        return False


def admit_rollup(query: str, n_series: int, points_per_series: int,
                 max_memory_per_query: int = 0) -> _Admission:
    """Raise QueryLimitError if the estimated working set does not fit;
    otherwise reserve it until the context exits (eval.go:1842-1866)."""
    size = estimate_rollup_memory(n_series, points_per_series)
    if max_memory_per_query > 0 and size > max_memory_per_query:
        raise QueryLimitError(
            f"not enough memory for processing {query!r}, which selects "
            f"{n_series} time series with {points_per_series} points in "
            f"each according to -search.maxMemoryPerQuery="
            f"{max_memory_per_query}; requested memory: {size} bytes; "
            f"possible solutions: reduce the number of matching series, "
            f"increase the step query arg, raise -search.maxMemoryPerQuery")
    lim = rollup_memory_limiter()
    if not lim.get(size):
        raise QueryLimitError(
            f"not enough memory for processing {query!r}, which selects "
            f"{n_series} time series with {points_per_series} points in "
            f"each; total available memory for concurrent requests: "
            f"{lim.max_size} bytes; requested memory: {size} bytes; "
            f"possible solutions: reduce the number of matching series, "
            f"increase the step query arg, use a node with more RAM, "
            f"increase -memory.allowedPercent")
    return _Admission(lim, size)
