"""VictoriaMetrics-native histogram bucketing (reference
vendor/github.com/VictoriaMetrics/metrics/histogram.go:12-30,215-230).

Log-spaced buckets: 18 per decade over [1e-9, 1e18), multiplier
10^(1/18); vmrange labels are "%.3e...%.3e" bounds, with "0...1.000e-09"
and "1.000e+18...+Inf" catch-alls. Shared by the histogram_over_time
rollup and the histogram() aggregate.
"""

from __future__ import annotations

import math

E10_MIN = -9
E10_MAX = 18
BUCKETS_PER_DECIMAL = 18
BUCKETS_COUNT = (E10_MAX - E10_MIN) * BUCKETS_PER_DECIMAL

_ranges: list[str] | None = None


def _bucket_ranges() -> list[str]:
    global _ranges
    if _ranges is None:
        out = []
        v = 10.0 ** E10_MIN
        start = f"{v:.3e}"
        for _ in range(BUCKETS_COUNT):
            v *= 10 ** (1.0 / BUCKETS_PER_DECIMAL)
            end = f"{v:.3e}"
            out.append(start + "..." + end)
            start = end
        # benign double-compute: the bucket table is a pure constant,
        # racing fills store equal lists
        _ranges = out  # vmt: disable=VMT015
    return _ranges


LOWER_RANGE = f"0...{10.0 ** E10_MIN:.3e}"
UPPER_RANGE = f"{10.0 ** E10_MAX:.3e}...+Inf"


def vmrange_for(v: float) -> str | None:
    """The vmrange label for one value; None for NaN / negative (which the
    reference histogram skips)."""
    if math.isnan(v) or v < 0:
        return None
    if v == 0:
        return LOWER_RANGE
    if math.isinf(v):
        # +Inf lands in the upper catch-all like the reference (the
        # log10 path below would overflow int())
        return UPPER_RANGE
    idx = (math.log10(v) - E10_MIN) * BUCKETS_PER_DECIMAL
    if idx < 0:
        return LOWER_RANGE
    i = int(idx)
    if idx == float(i) and i > 0:
        # exact 10^n boundaries belong to the lower bucket (le semantics);
        # applied BEFORE the upper-overflow check so exactly 1e18 lands in
        # the last finite bucket like the reference
        i -= 1
    if i >= BUCKETS_COUNT:
        return UPPER_RANGE
    return _bucket_ranges()[i]


def histogram_counts(values) -> dict[str, int]:
    """Non-zero vmrange -> count for a batch of values."""
    out: dict[str, int] = {}
    for v in values:
        r = vmrange_for(float(v))
        if r is not None:
            out[r] = out.get(r, 0) + 1
    return out
