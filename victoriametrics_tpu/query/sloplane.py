"""SLO plane: burn-rate objectives over self-scraped telemetry, with
incident auto-diagnosis and the health roll-up verdict.

The self-scrape collector (utils/selfscrape.py) turns the process's own
``vm_*`` counters into ordinary TSDB series; this module closes the loop
by *watching* them.  Declarative :class:`SLOSpec`\\ s describe service
level indicators as MetricsQL expression templates (``{w}`` is the
window placeholder); the :class:`SLOEngine` evaluates every distinct
(expression, window) pair ONCE per round through the matstream shared
instant-eval memo — multi-window multi-burn-rate alerting
(Google SRE workbook ch. 5: a fast 5m/1h pair pages, a slow 30m/6h
pair warns) stays FLAT in SLO count: N objectives over one indicator
cost one eval per distinct window per interval.

A burn-rate breach (both windows of a pair over threshold) freezes a
bounded incident record: flight-recorder capture id, truncated profiler
snapshot, top queries, per-tenant cost, and the health verdict at the
moment of breach — every diagnosis surface linked under one incident id
in a fixed-size ring (``/api/v1/status/incidents``).

Health (``/api/v1/status/health``): :func:`local_health` folds registry
backpressure gauges, quarantine, readonly state and SLO status into a
verdict ``ok|degraded|critical`` with machine-readable reasons;
:func:`cluster_health` (vmselect) additionally fans the ``health_v1``
RPC and merges node liveness / ring-reroute state, naming the nodes.

Env knobs: ``VM_SLO_WINDOWS`` (``short:long:threshold`` pairs, default
``5m:1h:14.4,30m:6h:6``), ``VM_SLO_PERIOD`` (error-budget period,
default ``24h``), ``VM_SLO_EVAL_INTERVAL`` (seconds, default 15),
``VM_SLO_INCIDENTS`` (ring size, default 16).
"""

from __future__ import annotations

import os
import threading

from ..devtools.locktrace import make_lock
from ..utils import costacc, fasttime, flightrec, logger, profiler
from ..utils import metrics as metricslib

DEFAULT_WINDOWS = "5m:1h:14.4,30m:6h:6"
DEFAULT_PERIOD = "24h"
DEFAULT_EVAL_INTERVAL_S = 15.0
DEFAULT_INCIDENT_RING = 16

#: one tick per UNIQUE (expr, window) matstream eval the engine issued —
#: the flat-in-SLO-count acceptance counter
_EVALS = metricslib.REGISTRY.counter("vm_slo_evals_total")
_ROUNDS = metricslib.REGISTRY.counter("vm_slo_eval_rounds_total")


def _dur_s(s: str, default: float) -> float:
    try:
        from .metricsql.parser import parse_duration_ms
        ms, _ = parse_duration_ms(str(s).strip())
        return ms / 1e3
    except Exception:  # noqa: BLE001 — bad knob value, fall back
        return default


def parse_windows(raw: str | None) -> list[tuple[str, str, float]]:
    """``"5m:1h:14.4,30m:6h:6"`` -> ``[(short, long, threshold), ...]``.
    The first pair is the fast (paging) pair; the rest warn."""
    raw = raw if raw is not None else \
        os.environ.get("VM_SLO_WINDOWS", DEFAULT_WINDOWS)
    out: list[tuple[str, str, float]] = []
    for part in str(raw).split(","):
        part = part.strip()
        if not part:
            continue
        bits = part.split(":")
        if len(bits) != 3:
            logger.errorf("sloplane: bad window pair %r (want "
                          "short:long:threshold), skipped", part)
            continue
        try:
            out.append((bits[0].strip(), bits[1].strip(),
                        float(bits[2])))
        except ValueError:
            logger.errorf("sloplane: bad burn threshold in %r, skipped",
                          part)
    return out or parse_windows(DEFAULT_WINDOWS)


def _scalar(rows) -> float:
    return sum(r["value"] for r in rows) if rows else 0.0


def ratio_fold(vals: dict) -> tuple[float, float]:
    """Default SLI fold: ``bad``/``total`` event counts from the two
    eponymous expression keys."""
    return (max(0.0, _scalar(vals.get("bad"))),
            max(0.0, _scalar(vals.get("total"))))


def latency_fold(threshold_s: float):
    """SLI fold over vmrange histogram buckets: an event is *good* when
    its bucket's upper bound is within ``threshold_s``.  Expects keys
    ``total`` (the ``_count`` increase) and ``buckets`` (the ``_bucket``
    increase grouped by ``vmrange``)."""
    def fold(vals: dict) -> tuple[float, float]:
        total = max(0.0, _scalar(vals.get("total")))
        good = 0.0
        for r in (vals.get("buckets") or ()):
            rng = r.get("metric", {}).get("vmrange", "")
            parts = rng.split("...")
            if len(parts) != 2:
                continue
            try:
                upper = float(parts[1])
            except ValueError:
                continue
            if upper <= threshold_s * (1 + 1e-9):
                good += max(0.0, r["value"])
        # bucket sums can drift past _count within one scrape (the
        # registry snapshot is not atomic across series) — clamp
        return max(0.0, total - good), total
    return fold


class SLOSpec:
    """One declarative objective: named indicator expressions (templated
    on ``{w}``), an objective percentage, and a fold turning the
    per-window results into (bad_events, total_events)."""

    def __init__(self, name: str, objective: float, exprs: dict,
                 fold=None, description: str = ""):
        self.name = name
        self.objective = float(objective)
        #: allowed error fraction; burn rate = error_ratio / budget
        self.budget = max(1e-9, 1.0 - self.objective / 100.0)
        self.exprs = dict(exprs)
        self.fold = fold or ratio_fold
        self.description = description


#: the plane's own diagnosis/admin endpoints are NOT serving-path SLIs:
#: counting them would make the plane's own eval pumps and health
#: fan-outs burn the very SLOs they diagnose (a reflexive feedback loop)
_SERVING_PATHS = '{{path!~"/api/v1/status/.*|/internal/.*"}}'


def default_specs() -> list[SLOSpec]:
    """The stock objectives over the self-scraped plane.  All sum
    across ``path``/``instance`` so one spec covers every role that
    self-scrapes into the same storage."""
    return [
        SLOSpec(
            "http-availability", 99.9,
            {"bad": "sum(increase(vm_http_request_errors_total"
                    f"{_SERVING_PATHS}[{{w}}]))",
             "total": "sum(increase(vm_http_requests_total"
                      f"{_SERVING_PATHS}[{{w}}]))"},
            description="HTTP 5xx ratio over serving API paths"),
        SLOSpec(
            "http-latency", 99.0,
            {"total": "sum(increase(vm_request_duration_seconds_count"
                      f"{_SERVING_PATHS}[{{w}}]))",
             "buckets": "sum(increase(vm_request_duration_seconds_bucket"
                        f"{_SERVING_PATHS}[{{w}}]))"
                        " by (vmrange)"},
            fold=latency_fold(1.0),
            description="serving requests answered under 1s"),
        SLOSpec(
            "ingest-durability", 99.99,
            {"bad": "sum(increase(vm_ingest_spill_errors_total[{w}]))",
             "total": "sum(increase(vm_rows_inserted_total[{w}]))"},
            description="ingested rows never lost to spill errors"),
        SLOSpec(
            "search-admission", 99.9,
            {"bad":
                "sum(increase(vm_search_requests_rejected_total[{w}]))",
             "total":
                "sum(increase(vm_search_queries_total[{w}]))"
                " + sum(increase("
                "vm_search_requests_rejected_total[{w}]))"},
            description="queries admitted without queue-depth rejection"),
    ]


class IncidentRing:
    """Bounded ring of incident records; newest kept, oldest evicted."""

    def __init__(self, cap: int):
        self.cap = max(1, int(cap))
        self._items: list[dict] = []
        self._next_id = 1
        self._opened = metricslib.REGISTRY.counter  # per-slo counters
        self._lock = threading.Lock()

    def open(self, rec: dict) -> dict:
        with self._lock:
            rec["id"] = self._next_id
            self._next_id += 1
            self._items.append(rec)
            if len(self._items) > self.cap:
                self._items = self._items[-self.cap:]
        self._opened(metricslib.format_name(
            "vm_incidents_total", {"slo": rec["slo"]})).inc()
        return rec

    def resolve(self, slo: str, now_ms: int) -> dict | None:
        with self._lock:
            for rec in reversed(self._items):
                if rec["slo"] == slo and rec.get("resolvedMs") is None:
                    rec["resolvedMs"] = now_ms
                    return rec
        return None

    def open_incident(self, slo: str) -> dict | None:
        with self._lock:
            for rec in reversed(self._items):
                if rec["slo"] == slo and rec.get("resolvedMs") is None:
                    return rec
        return None

    def get(self, incident_id: int) -> dict | None:
        with self._lock:
            for rec in self._items:
                if rec["id"] == incident_id:
                    return rec
        return None

    def list(self) -> list[dict]:
        """Newest-first summaries (the heavy diagnosis blobs stay behind
        ``?id=``)."""
        with self._lock:
            items = list(self._items)
        out = []
        for rec in reversed(items):
            out.append({
                "id": rec["id"], "slo": rec["slo"],
                "severity": rec.get("severity"),
                "startedMs": rec.get("startedMs"),
                "resolvedMs": rec.get("resolvedMs"),
                "burn": rec.get("burn"),
                "flightCaptureId": rec.get("flightCaptureId"),
                "hasProfile": rec.get("profile") is not None,
                "verdict": (rec.get("health") or {}).get("verdict"),
            })
        return out


class SLOEngine:
    """Evaluates every spec's burn rates each interval, maintains the
    exported gauges, and drives incident open/resolve transitions.

    Pumped externally — ``maybe_eval`` rides the self-scrape
    ``on_tick`` (so burn rates follow the freshest sample) and the
    ``/api/v1/status/slo?pump=1`` seam forces a round for tests."""

    def __init__(self, api, specs: list[SLOSpec] | None = None,
                 windows: list[tuple[str, str, float]] | None = None,
                 interval_s: float | None = None,
                 period: str | None = None, role: str = ""):
        self.api = api
        self.role = role
        self.specs = specs if specs is not None else default_specs()
        self.windows = windows if windows is not None else parse_windows(None)
        if interval_s is None:
            try:
                interval_s = float(os.environ.get(
                    "VM_SLO_EVAL_INTERVAL", DEFAULT_EVAL_INTERVAL_S))
            except ValueError:
                interval_s = DEFAULT_EVAL_INTERVAL_S
        self.interval_s = max(0.05, interval_s)
        self.period = period or os.environ.get("VM_SLO_PERIOD",
                                               DEFAULT_PERIOD)
        self.period_s = _dur_s(self.period, _dur_s(DEFAULT_PERIOD, 86400))
        try:
            ring_cap = int(os.environ.get("VM_SLO_INCIDENTS",
                                          DEFAULT_INCIDENT_RING))
        except ValueError:
            ring_cap = DEFAULT_INCIDENT_RING
        self.incidents = IncidentRing(ring_cap)
        self.eval_rounds = 0
        self.expr_evals = 0
        self.exprs_last_round = 0
        self.last_eval_ms = 0
        #: spec name -> {"burn": {w: rate}, "budgetRemaining": f,
        #: "firing": [pair], "noData": bool, "severity": str|None}
        self._state: dict[str, dict] = {}
        self._gauges: dict[str, metricslib.Gauge] = {}
        # one lock for ALL engine state (counters, gauge memo, _state,
        # last_eval_ms): maybe_eval rides the self-scrape tick AND the
        # ?pump=1 HTTP seam, so rounds race unless every access takes it
        self._lock = make_lock("query.SLOEngine._lock")

    # -- evaluation --------------------------------------------------------

    def _all_windows(self) -> list[str]:
        seen: dict[str, None] = {}
        for s, long_w, _thr in self.windows:
            seen.setdefault(s)
            seen.setdefault(long_w)
        seen.setdefault(self.period)
        return list(seen)

    def _eval_expr(self, expr: str, ts_ms: int):
        try:
            rows = self.api.matstreams.instant_vector(expr, ts_ms, (0, 0))
        except Exception as e:  # noqa: BLE001 — storage trouble != crash
            logger.errorf("sloplane: eval failed for %s: %s", expr, e)
            return None
        return rows

    def maybe_eval(self, now_ms: int | None = None,
                   force: bool = False) -> bool:
        """One eval round if ``interval_s`` has elapsed (or forced).
        Returns whether a round ran."""
        if now_ms is None:
            now_ms = fasttime.unix_ms()
        with self._lock:
            if not force and \
                    now_ms - self.last_eval_ms < self.interval_s * 1e3:
                return False
            self.last_eval_ms = now_ms
        try:
            self._eval_round(now_ms)
        except Exception as e:  # noqa: BLE001 — keep the pump alive
            logger.errorf("sloplane: eval round failed: %s", e)
        return True

    def _eval_round(self, now_ms: int):
        # 1) collect the distinct (expr, window) set across ALL specs —
        # identical indicators shared by several objectives dedupe here
        # (and again in the matstream memo for concurrent callers)
        windows = self._all_windows()
        needed: dict[str, None] = {}
        for spec in self.specs:
            for tmpl in spec.exprs.values():
                for w in windows:
                    needed.setdefault(tmpl.format(w=w))
        results: dict[str, list | None] = {}
        for expr in needed:
            results[expr] = self._eval_expr(expr, now_ms)
            with self._lock:
                self.expr_evals += 1
            _EVALS.inc()
        with self._lock:
            self.exprs_last_round = len(needed)
            self.eval_rounds += 1
        _ROUNDS.inc()

        # 2) fold per spec per window, update gauges + firing state
        for spec in self.specs:
            burn: dict[str, float] = {}
            no_data = False
            for w in windows:
                vals = {}
                missing = False
                for key, tmpl in spec.exprs.items():
                    rows = results.get(tmpl.format(w=w))
                    if rows is None:
                        missing = True
                    vals[key] = rows or []
                if missing:
                    no_data = True
                bad, total = spec.fold(vals)
                if total <= 0:
                    ratio = 1.0 if bad > 0 else 0.0
                else:
                    ratio = min(1.0, bad / total)
                burn[w] = ratio / spec.budget
            firing = []
            for i, (short_w, long_w, thr) in enumerate(self.windows):
                if burn.get(short_w, 0.0) >= thr and \
                        burn.get(long_w, 0.0) >= thr:
                    firing.append({
                        "short": short_w, "long": long_w,
                        "threshold": thr,
                        "severity": "page" if i == 0 else "warn"})
            budget_remaining = max(0.0, 1.0 - burn.get(self.period, 0.0))
            state = {
                "burn": burn, "firing": firing, "noData": no_data,
                "budgetRemaining": budget_remaining,
                "severity": firing[0]["severity"] if firing else None,
            }
            self._export(spec, state)
            # publish the state BEFORE the transition: an incident
            # frozen by _transition snapshots health via firing(),
            # which must already see this round's burn
            with self._lock:
                self._state[spec.name] = state
            self._transition(spec, state, now_ms)

    def _gauge(self, base: str, labels: dict) -> metricslib.Gauge:
        name = metricslib.format_name(base, labels)
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = metricslib.REGISTRY.gauge(name)
                self._gauges[name] = g
            return g

    def _export(self, spec: SLOSpec, state: dict):
        for w, rate in state["burn"].items():
            self._gauge("vm_slo_burn_rate",
                        {"slo": spec.name, "window": w}).set(rate)
        self._gauge("vm_slo_error_budget_remaining",
                    {"slo": spec.name}).set(state["budgetRemaining"])

    # -- incident lifecycle ------------------------------------------------

    def _transition(self, spec: SLOSpec, state: dict, now_ms: int):
        open_rec = self.incidents.open_incident(spec.name)
        if state["firing"] and open_rec is None:
            self._freeze_incident(spec, state, now_ms)
        elif not state["firing"] and open_rec is not None:
            self.incidents.resolve(spec.name, now_ms)
            logger.infof("sloplane: incident %d (%s) resolved",
                         open_rec["id"], spec.name)

    def _freeze_incident(self, spec: SLOSpec, state: dict, now_ms: int):
        """Burn breach -> one bounded record holding every diagnosis
        surface, each captured best-effort (a dead profiler must not
        lose the flight trace)."""
        rec = {
            "slo": spec.name, "severity": state["severity"],
            "objective": spec.objective,
            "description": spec.description,
            "startedMs": now_ms, "resolvedMs": None,
            "burn": dict(state["burn"]), "firing": state["firing"],
            "flightCaptureId": None, "profile": None,
            "topQueries": None, "tenantUsage": None, "health": None,
        }
        if flightrec.enabled():
            try:
                cap = flightrec.RECORDER.capture(
                    "slo_burn", meta={"slo": spec.name},
                    defer_build=True)
                if cap:
                    rec["flightCaptureId"] = cap.get("id")
                    flightrec.note_capture(cap["id"])
            except Exception as e:  # noqa: BLE001
                logger.errorf("sloplane: flight capture failed: %s", e)
        try:
            if profiler.PROFILER.ensure_started():
                snap = profiler.PROFILER.snapshot()
                # keep the record bounded: top stacks only
                if isinstance(snap.get("stacks"), list):
                    snap["stacks"] = snap["stacks"][:50]
                rec["profile"] = snap
        except Exception as e:  # noqa: BLE001
            logger.errorf("sloplane: profiler snapshot failed: %s", e)
        api = self.api
        try:
            if getattr(api, "qstats", None) is not None:
                rec["topQueries"] = api.qstats.tops(5)
        except Exception as e:  # noqa: BLE001
            logger.errorf("sloplane: top-queries snapshot failed: %s", e)
        try:
            rec["tenantUsage"] = costacc.TENANT_USAGE.snapshot()[:20]
        except Exception as e:  # noqa: BLE001
            logger.errorf("sloplane: tenant-usage snapshot failed: %s", e)
        try:
            rec["health"] = health_for_api(api, engine=self,
                                           role=self.role)
        except Exception as e:  # noqa: BLE001
            logger.errorf("sloplane: health snapshot failed: %s", e)
        self.incidents.open(rec)
        logger.warnf(
            "sloplane: incident opened for %s (severity %s, burn %s)",
            spec.name, state["severity"],
            {w: round(r, 2) for w, r in state["burn"].items()})

    # -- reporting ---------------------------------------------------------

    def firing(self) -> list[tuple[str, dict]]:
        with self._lock:
            return [(name, st) for name, st in self._state.items()
                    if st["firing"]]

    def status(self) -> dict:
        with self._lock:
            state = {k: dict(v) for k, v in self._state.items()}
            counters = {"evalRounds": self.eval_rounds,
                        "exprEvals": self.expr_evals,
                        "exprsPerRound": self.exprs_last_round,
                        "lastEvalMs": self.last_eval_ms}
        slos = []
        for spec in self.specs:
            st = state.get(spec.name, {})
            open_rec = self.incidents.open_incident(spec.name)
            slos.append({
                "slo": spec.name, "objective": spec.objective,
                "description": spec.description,
                "burn": st.get("burn", {}),
                "budgetRemaining": st.get("budgetRemaining"),
                "firing": st.get("firing", []),
                "noData": st.get("noData", True),
                "severity": st.get("severity"),
                "openIncidentId":
                    open_rec["id"] if open_rec else None,
            })
        return {
            "status": "success",
            "intervalSeconds": self.interval_s,
            "windows": [{"short": s, "long": lw, "threshold": t}
                        for s, lw, t in self.windows],
            "period": self.period,
            **counters,
            "slos": slos,
        }


# -- health roll-up --------------------------------------------------------

_SEV_RANK = {"ok": 0, "degraded": 1, "critical": 2}

#: merge-queue depth beyond which the node reports merge backpressure
MERGE_PENDING_DEGRADED = 32
#: work-queue backlog factor (queue depth > factor * workers)
QUEUE_BACKLOG_FACTOR = 8


def _metric_value(name: str) -> float | None:
    m = metricslib.REGISTRY._metrics.get(name)
    if m is None or not hasattr(m, "get"):
        return None
    try:
        # a registry Gauge/Counter read, not a queue drain
        v = float(m.get())  # vmt: disable=VMT012
    except Exception:  # noqa: BLE001
        return None
    return None if v != v else v


def _verdict(reasons: list[dict]) -> str:
    worst = "ok"
    for r in reasons:
        sev = r.get("severity", "degraded")
        if _SEV_RANK.get(sev, 0) > _SEV_RANK[worst]:
            worst = sev
    return worst


def local_health(storage=None, engine: SLOEngine | None = None,
                 role: str = "") -> dict:
    """This process's own verdict: quarantine + readonly + backpressure
    gauges + SLO firing state, folded to ``ok|degraded|critical`` with
    machine-readable ``{code, severity, detail}`` reasons."""
    from ..utils import buildinfo
    reasons: list[dict] = []
    quarantined = 0
    if storage is not None:
        rep = None
        try:
            if hasattr(storage, "quarantine_report"):
                rep = storage.quarantine_report()
        except Exception:  # noqa: BLE001 — health must always answer
            rep = None
        if rep:
            quarantined = len(rep)
            reasons.append({
                "code": "quarantined_parts", "severity": "degraded",
                "detail": f"{quarantined} part(s) quarantined; results "
                          "partial until restored"})
        if getattr(storage, "readonly", False) or \
                getattr(storage, "_readonly", False):
            reasons.append({
                "code": "readonly", "severity": "degraded",
                "detail": "storage is read-only"})
    pending = _metric_value("vm_merge_pending")
    if pending is not None and pending > MERGE_PENDING_DEGRADED:
        reasons.append({
            "code": "merge_backpressure", "severity": "degraded",
            "detail": f"{int(pending)} merges pending "
                      f"(> {MERGE_PENDING_DEGRADED})"})
    depth = _metric_value("vm_workpool_queue_depth")
    workers = _metric_value("vm_workpool_workers")
    if depth is not None and workers:
        if depth > QUEUE_BACKLOG_FACTOR * workers:
            reasons.append({
                "code": "work_queue_backlog", "severity": "degraded",
                "detail": f"{int(depth)} queued tasks over "
                          f"{int(workers)} workers"})
    if engine is not None:
        for name, st in engine.firing():
            sev = "critical" if st["severity"] == "page" else "degraded"
            reasons.append({
                "code": "slo_burn", "severity": sev, "slo": name,
                "detail": f"SLO {name} burning at "
                          + ", ".join(f"{w}={r:.1f}x"
                                      for w, r in st["burn"].items())})
    out = {
        "status": "success",
        "verdict": _verdict(reasons),
        "role": role,
        "version": buildinfo.version(),
        "uptimeSeconds": round(metricslib.uptime_seconds(), 3),
        "reasons": reasons,
        "stats": {
            "quarantinedParts": quarantined,
            "mergePending": pending,
            "workQueueDepth": depth,
        },
    }
    if engine is not None:
        out["slo"] = {
            "firing": [name for name, _ in engine.firing()],
            "evalRounds": engine.eval_rounds,
        }
    return out


def cluster_health(cluster, engine: SLOEngine | None = None,
                   role: str = "vmselect", fan: bool = True) -> dict:
    """The vmselect roll-up: this process's local verdict + per-node
    ``health_v1`` reports + liveness/draining/ring state from
    ``cluster_status()``, merged into one verdict that NAMES the nodes
    behind every degradation.  ``fan=False`` (vminsert: no select
    channel to the nodes) keeps the liveness/ring merge but skips the
    health_v1 fan-out — missing reports are then expected, not a
    degradation."""
    out = local_health(storage=None, engine=engine, role=role)
    reasons = out["reasons"]
    try:
        cs = cluster.cluster_status()
    except Exception:  # noqa: BLE001
        cs = {"nodes": []}
    reports: dict = {}
    if fan:
        try:
            reports = {r.get("node"): r
                       for r in cluster.health_report()}
        except Exception:  # noqa: BLE001
            reports = {}
    nodes_out = []
    down = 0
    for n in cs.get("nodes", []):
        name = n.get("name")
        rep = reports.get(name)
        node_verdict = (rep or {}).get("verdict", "unknown")
        if not n.get("healthy", True):
            down += 1
            reasons.append({
                "code": "node_down", "severity": "degraded",
                "node": name,
                "detail": f"storage node {name} is not responding"})
        elif fan and rep is None:
            reasons.append({
                "code": "node_unreachable", "severity": "degraded",
                "node": name,
                "detail": f"no health_v1 report from {name}"})
        elif node_verdict in ("degraded", "critical"):
            codes = ",".join(r.get("code", "?")
                             for r in rep.get("reasons", [])) or "?"
            reasons.append({
                "code": "node_degraded", "severity": "degraded",
                "node": name,
                "detail": f"storage node {name} reports "
                          f"{node_verdict}: {codes}"})
        if n.get("draining"):
            reasons.append({
                "code": "node_draining", "severity": "ok",
                "node": name,
                "detail": f"storage node {name} is draining "
                          "(planned; excluded from new writes)"})
        nodes_out.append({
            "name": name,
            "healthy": bool(n.get("healthy", True)),
            "draining": bool(n.get("draining")),
            "verdict": node_verdict,
            "reasons": (rep or {}).get("reasons", []),
        })
    total = len(cs.get("nodes", []))
    if total and down >= total:
        reasons.append({
            "code": "all_nodes_down", "severity": "critical",
            "detail": "every storage node is unreachable"})
    out["nodes"] = nodes_out
    out["ring"] = {
        "filterActive": bool(cs.get("ringFilter")),
        "rerouteActive": down > 0,
    }
    out["verdict"] = _verdict(reasons)
    return out


def health_for_api(api, engine: SLOEngine | None = None,
                   role: str = "") -> dict:
    """Dispatch on the API's storage: ClusterStorage (has
    ``cluster_status``) rolls the nodes up; plain Storage answers
    locally.  A vminsert merges liveness but cannot fan health_v1
    (insert-only channels)."""
    storage = getattr(api, "storage", None)
    if storage is not None and hasattr(storage, "cluster_status"):
        return cluster_health(storage, engine=engine,
                              role=role or "vmselect",
                              fan=role != "vminsert")
    return local_health(storage=storage, engine=engine,
                        role=role or "vmsingle")
