"""Rollup result cache (reference app/vmselect/promql/
rollup_result_cache.go:39-364): caches range-query results keyed by
(query, step) so repeated/refreshing queries only compute the new tail,
merging cached prefixes with freshly computed suffixes.

Entries store ONE (S, T) float64 block per query on the entry's own
step-aligned grid plus parallel raw-name/MetricName lists; hits, merges
and puts are whole-block NumPy ops — no per-series marshal/unmarshal on
the steady-state path (that churn used to cost more than the tail fetch
itself). A hit requires the request grid to be phase-aligned with the
cached grid — the HTTP layer aligns start/end to the step (AdjustStartEnd
analog) so this always holds for dashboard refreshes. Backfill older than
the cached window resets the cache (ResetRollupResultCacheIfNeeded
analog).

Ring entries (VM_RESULT_CACHE_RING, default on): each entry's block lives
inside a larger buffer with reserved headroom columns/rows, and the entry
window is a (col_off, n_cols) view into it.  A rolling dashboard refresh
then merges IN PLACE: the fresh suffix columns are scattered into the
buffer, the start offset advances, and ``merge()`` returns read-only
zero-copy views over the buffer instead of reallocating a fresh (S, T)
block per refresh (the O(S*T) copy that used to dominate steady-state
serving).  When the window slides past the buffer's right edge the live
columns are compacted into a NEW buffer (amortized one column per
refresh); the old buffer is left intact so earlier hits' views stay
valid.  Contract: rows returned by an in-place ``merge()`` are read-only
views that stay stable for their whole lifetime — the entry keeps
weakrefs to the views it handed out, and a later merge that would
overwrite still-referenced columns (a concurrent refresh of the same key
racing an in-flight response serialization) compacts into a fresh buffer
instead of writing through the aliased one.  Sequential steady-state
refreshes drop the previous response before the next merge, so the
liveness check costs nothing there.  ``VM_RESULT_CACHE_RING=0`` restores
the full rebuild path exactly (the equality oracle).

The cache is bounded by BYTES as well as entries: ``max_bytes`` (env
``VM_RESULT_CACHE_MAX_BYTES``, default 1/8 of physical RAM — the
reference's cache sizing) LRU-evicts whole entries; the most recently
used entry is never evicted, so one over-budget entry degrades to a
bounded single-entry cache instead of thrashing.
"""

from __future__ import annotations

import itertools
import os
import threading
import time as _time
import weakref

import numpy as np

from ..storage.metric_name import MetricName
from ..utils import costacc as _costacc
from ..utils import flightrec as _flightrec
from ..utils import metrics as metricslib
from .types import EvalConfig, Timeseries

_instances: "weakref.WeakSet[RollupResultCache]" = weakref.WeakSet()
_CACHE_REQUESTS = metricslib.REGISTRY.counter(
    'vm_cache_requests_total{type="promql/rollupResult"}')
_CACHE_MISSES = metricslib.REGISTRY.counter(
    'vm_cache_misses_total{type="promql/rollupResult"}')
metricslib.REGISTRY.gauge(
    'vm_cache_entries{type="promql/rollupResult"}',
    callback=lambda: sum(c.entry_count() for c in list(_instances)))
metricslib.REGISTRY.gauge(
    'vm_cache_size_bytes{type="promql/rollupResult"}',
    callback=lambda: sum(c.size_bytes() for c in list(_instances)))
metricslib.REGISTRY.gauge(
    'vm_cache_max_size_bytes{type="promql/rollupResult"}',
    callback=lambda: sum(c.max_bytes for c in list(_instances)))
# steady-state merge health: wall time spent stitching prefix+suffix, and
# how many merges extended the entry in place vs rebuilt a fresh block
_MERGE_SECONDS = metricslib.REGISTRY.float_counter(
    "vm_rollup_cache_merge_seconds_total")
_INPLACE = metricslib.REGISTRY.counter("vm_rollup_cache_inplace_total")
_REBUILD = metricslib.REGISTRY.counter("vm_rollup_cache_rebuild_total")
# puts that skipped the per-series identity rebuild because the raw-name
# list was unchanged (distinct from _INPLACE: this also ticks on the
# ring-off oracle path, where every merge still rebuilds)
_PUT_REUSE = metricslib.REGISTRY.counter(
    "vm_rollup_cache_put_identity_reused_total")

# Cached series tails are clipped back by this much: the freshest points may
# still change (late samples within the flush window) — cacheTimestampOffset.
OFFSET_MS = 5 * 60_000

# ring-entry headroom: spare suffix columns consumed ~1 per rolling refresh
# (compaction copies the live window once every COL_HEADROOM refreshes) and
# spare row slots for series appearing mid-window
COL_HEADROOM = 64
ROW_HEADROOM = 8


def ring_enabled() -> bool:
    """Ring (in-place merge) entries on?  VM_RESULT_CACHE_RING=0 restores
    the rebuild-every-merge path exactly — the equality oracle."""
    return os.environ.get("VM_RESULT_CACHE_RING", "1") != "0"


def _default_max_bytes() -> int:
    """~1/8 of physical RAM (the reference's cache sizing); floor keeps
    tiny containers serviceable."""
    try:
        total = os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (ValueError, OSError, AttributeError):
        total = 8 << 30
    return max(total // 8, 64 << 20)


_storage_tokens = itertools.count(1)


def next_storage_token() -> int:
    """Unique per-storage-instance token for cache keys: id() could be
    reused after GC, silently serving another storage's entries."""
    return next(_storage_tokens)


def _copy_name(mn: MetricName) -> MetricName:
    return MetricName(mn.metric_group, list(mn.labels))


def _raw_of(ts: Timeseries, trust_raw: bool) -> bytes:
    """Series identity for cache keying. `trust_raw=True` is ONLY safe for
    rows the caller just built and has not exposed to any code that could
    mutate metric_name in place (the eval-level rollup path): transforms,
    binops and multi-output rollups edit labels in place, leaving ts.raw
    stale — distinct output series then collide on one raw and merge()
    stitches them wrongly. Post-transform callers (the HTTP-level cache)
    must pass trust_raw=False and pay the marshal."""
    if trust_raw and ts.raw is not None:
        return ts.raw
    return ts.metric_name.marshal()


class _Entry:
    """One cached block.  The live window is buf[:n_rows,
    col_off:col_off+n_cols] on the step grid anchored at c_start; rows
    beyond n_rows and columns outside the window are headroom/scratch.
    raws/names/idx are treated copy-on-append: mutations REBIND the lists
    so CacheHit snapshots stay stable."""

    __slots__ = ("c_start", "c_end", "step", "raws", "names", "idx",
                 "buf", "n_rows", "col_off", "gen", "served", "out_refs")

    def __init__(self, c_start, c_end, step, raws, names, buf, n_rows,
                 col_off):
        self.c_start = c_start
        self.c_end = c_end
        self.step = step
        self.raws = raws      # list[bytes], parallel to buf rows
        self.names = names    # list[MetricName], parallel to buf rows
        self.idx = {r: s for s, r in enumerate(raws)}
        self.buf = buf        # (row_cap, col_cap) float64
        self.n_rows = n_rows
        self.col_off = col_off
        self.gen = 0          # bumped on every mutation (hit validation)
        self.served = None    # (start, end, gen) stamp of an in-place merge
        self.out_refs = ()    # weakrefs to row views the last merge handed out

    @property
    def n_cols(self) -> int:
        return (self.c_end - self.c_start) // self.step + 1

    @property
    def vals(self) -> np.ndarray:
        """The live (S, n) window view."""
        return self.buf[:self.n_rows,
                        self.col_off:self.col_off + self.n_cols]

    def size_bytes(self) -> int:
        return self.buf.nbytes


def _new_entry(c_start: int, c_end: int, step: int, raws, names,
               vals: np.ndarray) -> _Entry:
    """Build an entry from a dense (S, n) block, reserving ring headroom
    when enabled (plain exact-size block otherwise)."""
    S, n = vals.shape
    if not ring_enabled():
        return _Entry(c_start, c_end, step, raws, names, vals, S, 0)
    rh = max(ROW_HEADROOM, S // 64)
    buf = np.empty((S + rh, n + COL_HEADROOM))
    buf[:S, :n] = vals
    return _Entry(c_start, c_end, step, raws, names, buf, S, 0)


class CacheHit:
    """A cache hit covering [ec.start, cov_end].  Snapshots the entry
    state at get() time (view + raw/name list refs + generation): the
    snapshot stays valid across later in-place merges because those only
    write columns beyond the then-final coverage, append rows beyond the
    snapshot, rebind (not mutate) the lists, and compact into fresh
    buffers."""

    __slots__ = ("entry", "key", "i0", "n", "gen", "view", "raws", "names")

    def __init__(self, entry: _Entry, key, i0: int, n: int):
        self.entry = entry
        self.key = key
        self.i0 = i0
        self.n = n
        self.gen = entry.gen
        v = entry.buf[:entry.n_rows,
                      entry.col_off + i0:entry.col_off + i0 + n].view()
        v.setflags(write=False)
        self.view = v
        self.raws = entry.raws
        self.names = entry.names

    def rows(self) -> list[Timeseries]:
        """Materialize as Timeseries (full-hit path). One block copy; the
        per-row views are handed out with fresh MetricName copies so
        caller mutation can't corrupt the entry."""
        vals = self.view.copy()
        return [Timeseries(_copy_name(self.names[s]), vals[s],
                           raw=self.raws[s])
                for s in range(len(self.raws))]


class RingBlock:
    """Fixed-row rolling (G, T) block with column headroom — the ring-cache
    entry machinery (headroom buffer + in-place column scatter + offset
    advance + weakref-guarded compaction) reused by the device plane for
    the host-side copy of the device-resident [G, T] aggregate.  Rows are
    groups (fixed identity, no churn), so this is `_Entry`/`merge()`
    stripped to its column mechanics: a rolling refresh writes only the
    freshly computed tail columns, re-serves the rest as zero-copy
    read-only row views, and compacts into a fresh buffer when headroom
    runs out or a still-alive earlier response aliases the buffer (the
    views-stable contract)."""

    __slots__ = ("buf", "G", "T", "col_off", "start", "end", "step",
                 "window", "out_refs")

    def __init__(self, out, start: int, end: int, step: int, window: int):
        out = np.asarray(out, dtype=np.float64)
        self.G, self.T = out.shape
        self.step = step
        self.window = window
        self.start = start
        self.end = end
        self.col_off = 0
        self.buf = np.empty((self.G, self.T + COL_HEADROOM))
        self.buf[:, :self.T] = out
        self.out_refs: tuple = ()

    def reset(self, out, start: int, end: int, step: int,
              window: int) -> None:
        """Reinitialize around a freshly computed full block (shape
        change, or an advance the sliding pattern doesn't cover).  The
        old buffer is left intact for any still-alive views."""
        self.__init__(out, start, end, step, window)

    def rows(self) -> list[np.ndarray]:
        """Read-only per-row views of the live window, remembered (by
        weakref) so a later in-place advance never writes through a row
        still held by an in-flight response."""
        win = self.buf[:, self.col_off:self.col_off + self.T].view()
        win.setflags(write=False)
        rows = [win[g] for g in range(self.G)]
        refs = [r for r in self.out_refs if r() is not None]
        refs.extend(weakref.ref(v) for v in rows)
        self.out_refs = tuple(refs)
        return rows

    def try_advance(self, start: int, end: int, step: int,
                    window: int) -> int | None:
        """Number of fresh tail columns needed to advance the window to
        [start, end] in the designed constant-shape sliding pattern
        (0 = pure re-serve), or None when the shape doesn't fit and the
        caller must recompute + reset().  Variable-length grids (suffix
        evals, narrowed ranges) deliberately don't fit — reused columns
        keep the estimates they were computed under, which is only the
        documented contract for the sliding-dashboard advance."""
        if step != self.step or window != self.window:
            return None
        if start < self.start or end < self.end:
            return None
        if (start - self.start) % step or \
                (start - self.start) != (end - self.end):
            return None
        if (end - start) // step + 1 != self.T:
            return None
        n_new = (end - self.end) // step
        if n_new >= self.T:
            return None  # disjoint windows: nothing reusable
        return n_new

    def commit(self, start: int, end: int, tail) -> list[np.ndarray]:
        """Advance in place per a successful try_advance: scatter the
        (G, n_new) tail columns, move the window offset, return fresh
        read-only row views.  When handed-out rows are still alive or the
        headroom is exhausted, the live columns compact into a FRESH
        buffer so earlier responses' views stay intact."""
        n_new = (end - self.end) // self.step
        shift = (start - self.start) // self.step
        col_off = self.col_off + shift
        alive = any(r() is not None for r in self.out_refs)
        if alive or col_off + self.T > self.buf.shape[1]:
            nb = np.empty((self.G, self.T + COL_HEADROOM))
            keep = self.T - n_new
            if keep:
                nb[:, :keep] = self.buf[
                    :, self.col_off + shift:self.col_off + self.T]
            self.buf = nb
            col_off = 0
            self.out_refs = ()
        if n_new:
            self.buf[:, col_off + self.T - n_new:col_off + self.T] = tail
        self.col_off = col_off
        self.start = start
        self.end = end
        return self.rows()


class RollupResultCache:
    def __init__(self, max_entries: int = 4096,
                 max_bytes: int | None = None):
        from collections import OrderedDict
        self._lock = threading.Lock()
        self._cache: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self.max_entries = max_entries
        if max_bytes is None:
            try:
                max_bytes = int(os.environ.get(
                    "VM_RESULT_CACHE_MAX_BYTES", "0"))
            except ValueError:
                max_bytes = 0
        if max_bytes <= 0:
            max_bytes = _default_max_bytes()
        self.max_bytes = max_bytes
        self._bytes = 0
        # per-instance thread-safe counters (the global vm_cache_* metrics
        # above aggregate over every live cache)
        self._hits = metricslib.Counter("hits")
        self._misses = metricslib.Counter("misses")
        _instances.add(self)

    @property
    def hits(self) -> int:
        return self._hits.get()

    @property
    def misses(self) -> int:
        return self._misses.get()

    def _key(self, ec: EvalConfig, q: str) -> tuple:
        # tenant MUST be part of the key (a shared entry would leak across
        # tenants), and so must the storage instance (one process can host
        # several storages: tests, embedded setups)
        token = getattr(ec.storage, "cache_token", None)
        return (token if token is not None else id(ec.storage),
                ec.tenant, q, ec.step)

    def _evict_locked(self) -> None:
        """LRU-evict until under both bounds; the most recently used entry
        survives even when alone over max_bytes (bounded either way)."""
        while (len(self._cache) > self.max_entries or
               self._bytes > self.max_bytes) and len(self._cache) > 1:
            _, old = self._cache.popitem(last=False)
            self._bytes -= old.size_bytes()

    def get(self, ec: EvalConfig, q: str, now_ms: int
            ) -> tuple[CacheHit | None, int]:
        """Returns (hit covering [ec.start, cov_end], first timestamp
        still to compute). (None, ec.start) on miss."""
        _CACHE_REQUESTS.inc()
        with self._lock:
            key = self._key(ec, q)
            e = self._cache.get(key)
            if e is None or e.c_start > ec.start or e.c_end < ec.start or \
                    (ec.start - e.c_start) % ec.step != 0:
                self._misses.inc()
                _CACHE_MISSES.inc()
                return None, ec.start
            self._cache.move_to_end(key)
            self._hits.inc()
            cov_end = min(e.c_end, ec.end)
            i0 = (ec.start - e.c_start) // ec.step
            n = (cov_end - ec.start) // ec.step + 1
            hit = CacheHit(e, key, i0, n)
        return hit, ec.start + n * ec.step

    def put(self, ec: EvalConfig, q: str, rows: list[Timeseries],
            now_ms: int, trust_raw: bool = True) -> None:
        t0 = _time.perf_counter()
        _costacc.restamp()
        try:
            self._put(ec, q, rows, now_ms, trust_raw)
        finally:
            _costacc.lap("cache:put", _time.perf_counter() - t0)

    def _put(self, ec: EvalConfig, q: str, rows: list[Timeseries],
             now_ms: int, trust_raw: bool = True) -> None:
        # don't cache the volatile tail
        cov_end_limit = now_ms - OFFSET_MS
        cov_end = ec.start + (
            (min(ec.end, cov_end_limit) - ec.start) // ec.step) * ec.step
        if cov_end < ec.start:
            return
        # NOTE: empty result sets ARE cached (zero-row entry) — a panel
        # over a dead selector must refresh tail-only, not re-scan the
        # full range every 30s
        n = (cov_end - ec.start) // ec.step + 1
        key = self._key(ec, q)
        ring = ring_enabled()
        if ring:
            with self._lock:
                e = self._cache.get(key)
                if e is not None and \
                        e.served == (ec.start, ec.end, e.gen):
                    # an in-place merge already finalized this entry for
                    # exactly this window (including the volatile-tail
                    # trim) — the put is a pure no-op
                    e.served = None
                    self._cache.move_to_end(key)
                    return
        # collapse duplicate identities (last row wins, matching the old
        # dict-keyed entries): keeping both would desync merge()'s
        # raw->row index and freeze one row's tail forever
        by_raw: dict[bytes, int] = {}
        for s, ts in enumerate(rows):
            by_raw[_raw_of(ts, trust_raw)] = s
        raws = list(by_raw.keys())
        sel = list(by_raw.values())
        vals = np.empty((len(raws), n))
        for j, s in enumerate(sel):
            v = rows[s].values
            vals[j, :] = v[:n] if v.size >= n else np.pad(
                v, (0, n - v.size), constant_values=np.nan)
        with self._lock:
            old = self._cache.get(key)
            # identity unchanged since the last put of this key: reuse
            # the existing (already-copied) MetricName list instead of
            # re-copying S names per steady-state refresh (entry lists
            # are rebound, never mutated, so sharing them is safe)
            names_src = old.names if old is not None and \
                old.raws == raws else None
        if names_src is not None:
            _PUT_REUSE.inc()
        else:
            names_src = [_copy_name(rows[s].metric_name) for s in sel]
        # the O(S*T) buffer allocation + copy happens OUTSIDE the cache
        # lock: a large first-eval put must not stall every other key's
        # get/merge behind a multi-hundred-MB memcpy
        e = _new_entry(ec.start, cov_end, ec.step, raws, names_src, vals)
        with self._lock:
            old = self._cache.get(key)
            if old is not None:
                self._bytes -= old.size_bytes()
            self._cache[key] = e
            self._bytes += e.size_bytes()
            self._cache.move_to_end(key)
            self._evict_locked()

    def merge(self, hit: CacheHit, fresh: list[Timeseries],
              ec: EvalConfig, new_start: int, trust_raw: bool = True,
              now_ms: int | None = None) -> list[Timeseries]:
        """Stitch the cached prefix block with freshly computed suffix
        rows.  Ring path: the suffix columns are written into the entry
        buffer in place, the entry window advances, and the returned rows
        are read-only zero-copy views (valid until the next merge of the
        same key).  Fallback/oracle path: block-at-a-time rebuild — the
        cached prefix is one 2D copy; only the (small) fresh suffix is
        touched per series."""
        t0 = _time.perf_counter()
        kind = "rebuild"
        try:
            # partial results must NEVER be committed: the in-place path
            # mutates the live entry before the caller's put() guard runs,
            # so the guard is applied here — a partial suffix takes the
            # pure rebuild path (served, never cached; same contract as
            # the skipped put)
            partial = ec._partial[0] or \
                getattr(ec.storage, "last_partial", False)
            if ring_enabled() and not partial:
                rows = self._merge_inplace(hit, fresh, ec, new_start,
                                           trust_raw, now_ms)
                if rows is not None:
                    _INPLACE.inc()
                    kind = "inplace"
                    return rows
            _REBUILD.inc()
            return self._merge_rebuild(hit, fresh, ec, new_start,
                                       trust_raw)
        finally:
            now = _time.perf_counter()
            _MERGE_SECONDS.inc(now - t0)
            # the inplace-vs-rebuild DECISION on the flight timeline: a
            # rebuild where inplace was expected is itself a latency clue
            _flightrec.rec("rcache:" + kind, t0, now - t0)
            _costacc.lap("cache:merge", now - t0)

    def _merge_inplace(self, hit: CacheHit, fresh: list[Timeseries],
                       ec: EvalConfig, new_start: int, trust_raw: bool,
                       now_ms: int | None):
        """Extend hit's entry in place for a rolling refresh; None when
        the shape doesn't fit (caller rebuilds).  Preconditions checked
        under the lock: the hit must still describe the live entry (same
        object, same generation — no concurrent merge/put/reset raced us),
        the hit must have covered the full cached tail, and every fresh
        row must be suffix-exact."""
        step = ec.step
        T = ec.n_points
        n_prefix = (new_start - ec.start) // step
        n_suffix = T - n_prefix
        if n_suffix <= 0 or n_prefix < 0:
            return None
        for ts in fresh:
            if ts.values.size != n_suffix:
                return None
        fresh_raws = [_raw_of(ts, trust_raw) for ts in fresh]
        if len(set(fresh_raws)) != len(fresh_raws):
            return None  # duplicate identities: rebuild's last-wins rules
        if now_ms is None:
            from ..utils import fasttime
            now_ms = fasttime.unix_ms()
        cov_end = ec.start + (
            (min(ec.end, now_ms - OFFSET_MS) - ec.start) // step) * step
        # the buffer writes run under the cache-wide lock: the scatter is
        # O(S * new columns) (the steady-state merge is exactly the new
        # work) and the compaction copy is amortized to one column per
        # refresh, but a concurrent get()/put() of ANOTHER key does wait
        # out the write.  A per-entry lock would shrink that window;
        # deliberately not done until it shows up in merge_seconds.
        with self._lock:
            e = self._cache.get(hit.key)
            if e is not hit.entry or e.gen != hit.gen:
                return None
            if new_start != e.c_end + step or ec.start < e.c_start or \
                    (ec.start - e.c_start) % step != 0:
                return None
            # advance: drop columns before the new window start
            col_off = e.col_off + (ec.start - e.c_start) // step
            new_raws = []
            new_names = []
            seen = e.idx
            for ts, raw in zip(fresh, fresh_raws):
                if raw not in seen:
                    new_raws.append(raw)
                    new_names.append(_copy_name(ts.metric_name))
            n_rows = e.n_rows + len(new_raws)
            buf = e.buf
            # rows handed out by the previous merge of this key still
            # alive (a concurrent refresh racing an in-flight response
            # serialization): writing the suffix through the shared
            # buffer would tear those rows mid-read, so compact into a
            # fresh buffer instead — the old one stays intact for them
            views_alive = any(r() is not None for r in e.out_refs)
            if views_alive or col_off + T > buf.shape[1] or \
                    n_rows > buf.shape[0]:
                # compact into a FRESH buffer (never memmove: earlier
                # hits' views into the old buffer must stay intact).
                # Dead rows — series whose entire remaining prefix is NaN
                # and that get no fresh data this merge — are dropped
                # here, so series churn cannot grow a hot entry without
                # bound (the rebuild path's all-NaN pruning, amortized to
                # once per COL_HEADROOM refreshes)
                pref = buf[:e.n_rows, col_off:col_off + n_prefix]
                keep = ~np.isnan(pref).all(axis=1)
                for raw in fresh_raws:
                    r = e.idx.get(raw)
                    if r is not None:
                        keep[r] = True
                if bool(keep.all()):
                    kept_src = None
                else:
                    kept_src = np.flatnonzero(keep)
                    # copy-on-write rebind: hit snapshots keep their lists
                    e.raws = [e.raws[i] for i in kept_src]
                    e.names = [e.names[i] for i in kept_src]
                    e.idx = {r: s for s, r in enumerate(e.raws)}
                    e.n_rows = int(kept_src.size)
                n_rows = e.n_rows + len(new_raws)
                nb = np.empty((n_rows + max(ROW_HEADROOM, n_rows // 64),
                               T + COL_HEADROOM))
                nb[:e.n_rows, :n_prefix] = \
                    pref if kept_src is None else pref[kept_src]
                self._bytes += nb.nbytes - buf.nbytes
                e.buf = buf = nb
                col_off = 0
            e.col_off = col_off
            e.c_start = ec.start
            if new_raws:
                # copy-on-append: rebind so hit snapshots keep their lists
                r0 = e.n_rows
                e.raws = e.raws + new_raws
                e.names = e.names + new_names
                for j, raw in enumerate(new_raws):
                    e.idx[raw] = r0 + j
                buf[r0:n_rows, col_off:col_off + n_prefix] = np.nan
                e.n_rows = n_rows
            span = slice(col_off + n_prefix, col_off + T)
            buf[:n_rows, span] = np.nan
            if fresh:
                rows_idx = np.fromiter((e.idx[r] for r in fresh_raws),
                                       np.int64, len(fresh))
                buf[rows_idx, span] = [ts.values for ts in fresh]
            e.gen += 1
            if cov_end < ec.start:
                # nothing final in the window (deep volatile tail): the
                # merged result is served but the entry can't cover it
                self._bytes -= e.size_bytes()
                del self._cache[hit.key]
            else:
                e.c_end = cov_end
                e.served = (ec.start, ec.end, e.gen)
                self._cache.move_to_end(hit.key)
                self._evict_locked()
            win = buf[:n_rows, col_off:col_off + T].view()
            win.setflags(write=False)
            # remember the handed-out row views: the next merge of this
            # key must not write through the buffer while any are alive
            row_views = [win[s] for s in range(n_rows)]
            e.out_refs = [weakref.ref(v) for v in row_views]
            raws = e.raws
            names = e.names
        return [Timeseries(_copy_name(names[s]), row_views[s], raw=raws[s])
                for s in range(len(raws))]

    def _merge_rebuild(self, hit: CacheHit, fresh: list[Timeseries],
                       ec: EvalConfig, new_start: int,
                       trust_raw: bool) -> list[Timeseries]:
        T = ec.n_points
        n_prefix = min((new_start - ec.start) // ec.step, hit.n)
        S_c = len(hit.raws)
        idx = {raw: s for s, raw in enumerate(hit.raws)}
        fresh_raws = [_raw_of(ts, trust_raw) for ts in fresh]
        raws = list(hit.raws)
        names = [_copy_name(nm) for nm in hit.names]
        for ts, raw in zip(fresh, fresh_raws):
            if raw not in idx:  # dedupe: two fresh rows may share a raw
                idx[raw] = len(raws)
                raws.append(raw)
                names.append(_copy_name(ts.metric_name))
        S = len(raws)
        vals = np.full((S, T), np.nan)
        vals[:S_c, :n_prefix] = hit.view[:, :n_prefix]
        for ts, raw in zip(fresh, fresh_raws):
            s = idx[raw]
            v = ts.values
            m = v.size
            vals[s, T - m:] = v if m <= T else v[-T:]
        return [Timeseries(names[s], vals[s], raw=raws[s])
                for s in range(S)]

    def entry_count(self) -> int:
        # locked: a /metrics scrape must not iterate under concurrent
        # put()/evict mutation
        with self._lock:
            return len(self._cache)

    def size_bytes(self) -> int:
        with self._lock:
            return self._bytes

    def reset(self):
        with self._lock:
            self._cache.clear()
            self._bytes = 0

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._cache), "hits": self.hits,
                    "misses": self.misses, "bytes": self._bytes,
                    "max_bytes": self.max_bytes}


GLOBAL = RollupResultCache()
