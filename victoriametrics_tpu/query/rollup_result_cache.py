"""Rollup result cache (reference app/vmselect/promql/
rollup_result_cache.go:39-364): caches range-query results keyed by
(query, step) so repeated/refreshing queries only compute the new tail,
merging cached prefixes with freshly computed suffixes.

Entries store ONE (S, T) float64 block per query on the entry's own
step-aligned grid plus parallel raw-name/MetricName lists; hits, merges
and puts are whole-block NumPy ops — no per-series marshal/unmarshal on
the steady-state path (that churn used to cost more than the tail fetch
itself). A hit requires the request grid to be phase-aligned with the
cached grid — the HTTP layer aligns start/end to the step (AdjustStartEnd
analog) so this always holds for dashboard refreshes. Backfill older than
the cached window resets the cache (ResetRollupResultCacheIfNeeded
analog)."""

from __future__ import annotations

import itertools
import threading
import weakref

import numpy as np

from ..storage.metric_name import MetricName
from ..utils import metrics as metricslib
from .types import EvalConfig, Timeseries

_instances: "weakref.WeakSet[RollupResultCache]" = weakref.WeakSet()
_CACHE_REQUESTS = metricslib.REGISTRY.counter(
    'vm_cache_requests_total{type="promql/rollupResult"}')
_CACHE_MISSES = metricslib.REGISTRY.counter(
    'vm_cache_misses_total{type="promql/rollupResult"}')
metricslib.REGISTRY.gauge(
    'vm_cache_entries{type="promql/rollupResult"}',
    callback=lambda: sum(c.entry_count() for c in list(_instances)))
metricslib.REGISTRY.gauge(
    'vm_cache_size_bytes{type="promql/rollupResult"}',
    callback=lambda: sum(c.size_bytes() for c in list(_instances)))

# Cached series tails are clipped back by this much: the freshest points may
# still change (late samples within the flush window) — cacheTimestampOffset.
OFFSET_MS = 5 * 60_000


_storage_tokens = itertools.count(1)


def next_storage_token() -> int:
    """Unique per-storage-instance token for cache keys: id() could be
    reused after GC, silently serving another storage's entries."""
    return next(_storage_tokens)


def _copy_name(mn: MetricName) -> MetricName:
    return MetricName(mn.metric_group, list(mn.labels))


def _raw_of(ts: Timeseries, trust_raw: bool) -> bytes:
    """Series identity for cache keying. `trust_raw=True` is ONLY safe for
    rows the caller just built and has not exposed to any code that could
    mutate metric_name in place (the eval-level rollup path): transforms,
    binops and multi-output rollups edit labels in place, leaving ts.raw
    stale — distinct output series then collide on one raw and merge()
    stitches them wrongly. Post-transform callers (the HTTP-level cache)
    must pass trust_raw=False and pay the marshal."""
    if trust_raw and ts.raw is not None:
        return ts.raw
    return ts.metric_name.marshal()


class _Entry:
    __slots__ = ("c_start", "c_end", "raws", "names", "vals")

    def __init__(self, c_start, c_end, raws, names, vals):
        self.c_start = c_start
        self.c_end = c_end
        self.raws = raws      # list[bytes], parallel to vals rows
        self.names = names    # list[MetricName], parallel to vals rows
        self.vals = vals      # (S, n) float64 on the entry grid


class CacheHit:
    """A cache hit covering [ec.start, cov_end] — a zero-copy view into
    the entry block until rows()/merge materialize it."""

    __slots__ = ("entry", "i0", "n")

    def __init__(self, entry: _Entry, i0: int, n: int):
        self.entry = entry
        self.i0 = i0
        self.n = n

    def rows(self) -> list[Timeseries]:
        """Materialize as Timeseries (full-hit path). One block copy; the
        per-row views are handed out with fresh MetricName copies so
        caller mutation can't corrupt the entry."""
        e = self.entry
        vals = e.vals[:, self.i0:self.i0 + self.n].copy()
        return [Timeseries(_copy_name(e.names[s]), vals[s], raw=e.raws[s])
                for s in range(len(e.raws))]


class RollupResultCache:
    def __init__(self, max_entries: int = 4096):
        from collections import OrderedDict
        self._lock = threading.Lock()
        self._cache: "OrderedDict[tuple, _Entry]" = OrderedDict()
        self.max_entries = max_entries
        # per-instance thread-safe counters (the global vm_cache_* metrics
        # above aggregate over every live cache)
        self._hits = metricslib.Counter("hits")
        self._misses = metricslib.Counter("misses")
        _instances.add(self)

    @property
    def hits(self) -> int:
        return self._hits.get()

    @property
    def misses(self) -> int:
        return self._misses.get()

    def _key(self, ec: EvalConfig, q: str) -> tuple:
        # tenant MUST be part of the key (a shared entry would leak across
        # tenants), and so must the storage instance (one process can host
        # several storages: tests, embedded setups)
        token = getattr(ec.storage, "cache_token", None)
        return (token if token is not None else id(ec.storage),
                ec.tenant, q, ec.step)

    def get(self, ec: EvalConfig, q: str, now_ms: int
            ) -> tuple[CacheHit | None, int]:
        """Returns (hit covering [ec.start, cov_end], first timestamp
        still to compute). (None, ec.start) on miss."""
        _CACHE_REQUESTS.inc()
        with self._lock:
            key = self._key(ec, q)
            e = self._cache.get(key)
            if e is None or e.c_start > ec.start or e.c_end < ec.start or \
                    (ec.start - e.c_start) % ec.step != 0:
                self._misses.inc()
                _CACHE_MISSES.inc()
                return None, ec.start
            self._cache.move_to_end(key)
            self._hits.inc()
        cov_end = min(e.c_end, ec.end)
        i0 = (ec.start - e.c_start) // ec.step
        n = (cov_end - ec.start) // ec.step + 1
        return CacheHit(e, i0, n), ec.start + n * ec.step

    def put(self, ec: EvalConfig, q: str, rows: list[Timeseries],
            now_ms: int, trust_raw: bool = True) -> None:
        # don't cache the volatile tail
        cov_end_limit = now_ms - OFFSET_MS
        cov_end = ec.start + (
            (min(ec.end, cov_end_limit) - ec.start) // ec.step) * ec.step
        if cov_end < ec.start:
            return
        # NOTE: empty result sets ARE cached (zero-row entry) — a panel
        # over a dead selector must refresh tail-only, not re-scan the
        # full range every 30s
        n = (cov_end - ec.start) // ec.step + 1
        # collapse duplicate identities (last row wins, matching the old
        # dict-keyed entries): keeping both would desync merge()'s
        # raw->row index and freeze one row's tail forever
        by_raw: dict[bytes, int] = {}
        for s, ts in enumerate(rows):
            by_raw[_raw_of(ts, trust_raw)] = s
        raws = list(by_raw.keys())
        vals = np.empty((len(raws), n))
        names = []
        for j, (raw, s) in enumerate(by_raw.items()):
            v = rows[s].values
            vals[j, :] = v[:n] if v.size >= n else np.pad(
                v, (0, n - v.size), constant_values=np.nan)
            names.append(_copy_name(rows[s].metric_name))
        e = _Entry(ec.start, cov_end, raws, names, vals)
        with self._lock:
            key = self._key(ec, q)
            self._cache[key] = e
            self._cache.move_to_end(key)
            while len(self._cache) > self.max_entries:
                self._cache.popitem(last=False)  # LRU, not clear-all

    def merge(self, hit: CacheHit, fresh: list[Timeseries],
              ec: EvalConfig, new_start: int,
              trust_raw: bool = True) -> list[Timeseries]:
        """Stitch the cached prefix block with freshly computed suffix
        rows. Block-at-a-time: the cached prefix is one 2D copy; only the
        (small) fresh suffix is touched per series."""
        T = ec.n_points
        e = hit.entry
        n_prefix = min((new_start - ec.start) // ec.step, hit.n)
        S_c = len(e.raws)
        idx = {raw: s for s, raw in enumerate(e.raws)}
        fresh_raws = [_raw_of(ts, trust_raw) for ts in fresh]
        raws = list(e.raws)
        names = [_copy_name(nm) for nm in e.names]
        for ts, raw in zip(fresh, fresh_raws):
            if raw not in idx:  # dedupe: two fresh rows may share a raw
                idx[raw] = len(raws)
                raws.append(raw)
                names.append(_copy_name(ts.metric_name))
        S = len(raws)
        vals = np.full((S, T), np.nan)
        vals[:S_c, :n_prefix] = e.vals[:, hit.i0:hit.i0 + n_prefix]
        for ts, raw in zip(fresh, fresh_raws):
            s = idx[raw]
            v = ts.values
            m = v.size
            vals[s, T - m:] = v if m <= T else v[-T:]
        return [Timeseries(names[s], vals[s], raw=raws[s])
                for s in range(S)]

    def entry_count(self) -> int:
        # locked: a /metrics scrape must not iterate under concurrent
        # put()/evict mutation
        with self._lock:
            return len(self._cache)

    def size_bytes(self) -> int:
        with self._lock:
            return sum(e.vals.nbytes for e in self._cache.values())

    def reset(self):
        with self._lock:
            self._cache.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._cache), "hits": self.hits,
                    "misses": self.misses}


GLOBAL = RollupResultCache()
