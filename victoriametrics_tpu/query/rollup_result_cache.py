"""Rollup result cache (reference app/vmselect/promql/
rollup_result_cache.go:39-364): caches range-query results keyed by
(query, step) so repeated/refreshing queries only compute the new tail,
merging cached prefixes with freshly computed suffixes.

Entries store per-series NumPy value arrays on the entry's own step-aligned
grid; hits are served with slices (no per-point Python work). A hit requires
the request grid to be phase-aligned with the cached grid — the HTTP layer
aligns start/end to the step (AdjustStartEnd analog) so this always holds
for dashboard refreshes. Backfill older than the cached window resets the
cache (ResetRollupResultCacheIfNeeded analog)."""

from __future__ import annotations

import itertools
import threading

import numpy as np

from ..storage.metric_name import MetricName
from .types import EvalConfig, Timeseries

# Cached series tails are clipped back by this much: the freshest points may
# still change (late samples within the flush window) — cacheTimestampOffset.
OFFSET_MS = 5 * 60_000


_storage_tokens = itertools.count(1)


def next_storage_token() -> int:
    """Unique per-storage-instance token for cache keys: id() could be
    reused after GC, silently serving another storage's entries."""
    return next(_storage_tokens)


class RollupResultCache:
    def __init__(self, max_entries: int = 4096):
        from collections import OrderedDict
        self._lock = threading.Lock()
        # key -> (c_start, c_end, {metric_name_raw: values ndarray})
        self._cache: "OrderedDict[tuple, tuple[int, int, dict]]" = \
            OrderedDict()
        self.max_entries = max_entries
        self.hits = 0
        self.misses = 0

    def _key(self, ec: EvalConfig, q: str) -> tuple:
        # tenant MUST be part of the key (a shared entry would leak across
        # tenants), and so must the storage instance (one process can host
        # several storages: tests, embedded setups)
        token = getattr(ec.storage, "cache_token", None)
        return (token if token is not None else id(ec.storage),
                ec.tenant, q, ec.step)

    def get(self, ec: EvalConfig, q: str, now_ms: int
            ) -> tuple[list[Timeseries] | None, int]:
        """Returns (cached series on [ec.start, cov_end], first timestamp
        still to compute). (None, ec.start) on miss."""
        with self._lock:
            key = self._key(ec, q)
            e = self._cache.get(key)
            if e is None or e[0] > ec.start or e[1] < ec.start or \
                    (ec.start - e[0]) % ec.step != 0:
                self.misses += 1
                return None, ec.start
            self._cache.move_to_end(key)
            self.hits += 1
            c_start, c_end, series = e
        cov_end = min(c_end, ec.end)
        i0 = (ec.start - c_start) // ec.step
        n = (cov_end - ec.start) // ec.step + 1
        out = [Timeseries(MetricName.unmarshal(raw),
                          vals[i0:i0 + n].copy())
               for raw, vals in series.items()]
        return out, ec.start + n * ec.step

    def put(self, ec: EvalConfig, q: str, rows: list[Timeseries],
            now_ms: int) -> None:
        # don't cache the volatile tail
        cov_end_limit = now_ms - OFFSET_MS
        cov_end = ec.start + (
            (min(ec.end, cov_end_limit) - ec.start) // ec.step) * ec.step
        if cov_end < ec.start:
            return
        n = (cov_end - ec.start) // ec.step + 1
        series = {ts.metric_name.marshal(): ts.values[:n].copy()
                  for ts in rows}
        with self._lock:
            key = self._key(ec, q)
            self._cache[key] = (ec.start, cov_end, series)
            self._cache.move_to_end(key)
            while len(self._cache) > self.max_entries:
                self._cache.popitem(last=False)  # LRU, not clear-all

    def merge(self, cached: list[Timeseries], fresh: list[Timeseries],
              ec: EvalConfig, new_start: int) -> list[Timeseries]:
        """Stitch cached prefix rows with freshly computed suffix rows."""
        T = ec.n_points
        n_prefix = (new_start - ec.start) // ec.step
        by_name: dict[bytes, np.ndarray] = {}
        for ts in cached:
            vals = np.full(T, np.nan)
            m = min(ts.values.size, n_prefix)
            vals[:m] = ts.values[:m]
            by_name[ts.metric_name.marshal()] = vals
        for ts in fresh:
            raw = ts.metric_name.marshal()
            vals = by_name.get(raw)
            if vals is None:
                vals = np.full(T, np.nan)
                by_name[raw] = vals
            m = ts.values.size
            vals[T - m:] = ts.values if m <= T else ts.values[-T:]
        return [Timeseries(MetricName.unmarshal(raw), vals)
                for raw, vals in by_name.items()]

    def reset(self):
        with self._lock:
            self._cache.clear()

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._cache), "hits": self.hits,
                    "misses": self.misses}


GLOBAL = RollupResultCache()
