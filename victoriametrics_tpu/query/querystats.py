"""Per-query runtime stats (reference app/vmselect/promql/active_queries.go
+ lib/querystats): the in-flight query registry behind
``/api/v1/status/active_queries`` and the last-N query-stats ring behind
``/api/v1/status/top_queries``.

Both register themselves with the self-metrics registry
(``vm_active_queries``, ``vm_search_queries_total``) so ``/metrics``
sees them too.
"""

from __future__ import annotations

import collections
import threading
import weakref

from ..utils import fasttime
from ..utils import metrics as metricslib

_active_instances: "weakref.WeakSet[ActiveQueries]" = weakref.WeakSet()

metricslib.REGISTRY.gauge(
    "vm_active_queries",
    callback=lambda: sum(len(a) for a in list(_active_instances)))


class ActiveQueries:
    """In-flight query registry (app/vmselect/promql/active_queries.go)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._live: dict[int, dict] = {}
        _active_instances.add(self)

    def register(self, query: str, start, end, step) -> int:
        with self._lock:
            self._next += 1
            qid = self._next
            self._live[qid] = {"qid": qid, "query": query, "start": start,
                               "end": end, "step": step,
                               "t": fasttime.unix_seconds()}
            return qid

    def unregister(self, qid: int):
        with self._lock:
            self._live.pop(qid, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._live)

    def snapshot(self) -> list[dict]:
        with self._lock:
            now = fasttime.unix_seconds()
            return [{**q, "duration": f"{now - q['t']:.3f}s"}
                    for q in self._live.values()]


class QueryStats:
    """Top-queries stats ring (reference lib/querystats: the last
    ``max_records`` query executions, aggregated at read time within
    ``max_lifetime_s``).  A bounded deque — old entries age out instead of
    freezing the table once an entry cap is hit."""

    def __init__(self, max_records: int = 20_000,
                 max_lifetime_s: float = 300.0):
        self._lock = threading.Lock()
        # ring of (query, time_range_s rounded, duration_s, unix_s)
        self._ring: collections.deque = collections.deque(
            maxlen=max_records)
        self.max_lifetime_s = max_lifetime_s
        self._queries_total = metricslib.REGISTRY.counter(
            "vm_search_queries_total")

    def record(self, query: str, time_range_s: float, duration_s: float):
        self._queries_total.inc()
        with self._lock:
            self._ring.append((query, round(time_range_s), duration_s,
                               fasttime.unix_seconds()))

    def _aggregate(self) -> list[dict]:
        cutoff = fasttime.unix_seconds() - self.max_lifetime_s
        acc: dict[tuple, list] = {}
        with self._lock:
            records = list(self._ring)
        for q, tr, d, at in records:
            if at < cutoff:
                continue
            e = acc.get((q, tr))
            if e is None:
                e = acc[(q, tr)] = [0, 0.0]
            e[0] += 1
            e[1] += d
        return [{"query": q, "timeRangeSeconds": tr, "count": c,
                 "sumDurationSeconds": round(d, 6),
                 "avgDurationSeconds": round(d / c, 6)}
                for (q, tr), (c, d) in acc.items()]

    _SORTERS = {"count": lambda x: -x["count"],
                "sumDuration": lambda x: -x["sumDurationSeconds"],
                "avgDuration": lambda x: -x["avgDurationSeconds"]}

    def top(self, n: int, key: str) -> list[dict]:
        items = self._aggregate()
        items.sort(key=self._SORTERS.get(key, self._SORTERS["count"]))
        return items[:n]

    def tops(self, n: int) -> dict[str, list[dict]]:
        """All three top-N orderings from ONE aggregation pass over the
        ring (the /top_queries endpoint serves all three at once)."""
        items = self._aggregate()
        out = {}
        for key, sorter in self._SORTERS.items():
            out[key] = sorted(items, key=sorter)[:n]
        return out
