"""Per-query runtime stats (reference app/vmselect/promql/active_queries.go
+ lib/querystats): the in-flight query registry behind
``/api/v1/status/active_queries``, the last-N query-stats ring behind
``/api/v1/status/top_queries``, and the slow-query log behind
``/api/v1/status/slow_queries`` (the vmselect
``-search.logSlowQueryDuration`` behavior, kept queryable instead of
only logged).

All register themselves with the self-metrics registry
(``vm_active_queries``, ``vm_search_queries_total``,
``vm_slow_queries_total``) so ``/metrics`` sees them too.
"""

from __future__ import annotations

import collections
import os
import threading
import weakref

from ..utils import fasttime
from ..utils import metrics as metricslib

_active_instances: "weakref.WeakSet[ActiveQueries]" = weakref.WeakSet()

metricslib.REGISTRY.gauge(
    "vm_active_queries",
    callback=lambda: sum(len(a) for a in list(_active_instances)))


class ActiveQueries:
    """In-flight query registry (app/vmselect/promql/active_queries.go)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._next = 0
        self._live: dict[int, dict] = {}
        _active_instances.add(self)

    def register(self, query: str, start, end, step) -> int:
        with self._lock:
            self._next += 1
            qid = self._next
            self._live[qid] = {"qid": qid, "query": query, "start": start,
                               "end": end, "step": step,
                               "t": fasttime.unix_seconds()}
            return qid

    def unregister(self, qid: int):
        with self._lock:
            self._live.pop(qid, None)

    def __len__(self) -> int:
        with self._lock:
            return len(self._live)

    def snapshot(self) -> list[dict]:
        with self._lock:
            now = fasttime.unix_seconds()
            return [{**q, "duration": f"{now - q['t']:.3f}s"}
                    for q in self._live.values()]


class QueryStats:
    """Top-queries stats ring (reference lib/querystats: the last
    ``max_records`` query executions, aggregated at read time within
    ``max_lifetime_s``).  A bounded deque — old entries age out instead of
    freezing the table once an entry cap is hit."""

    #: per-record cost columns carried into the aggregation (the
    #: utils/costacc CostTracker summary keys), summed per (query,
    #: time-range) group — the most EXPENSIVE queries, not just the
    #: slowest, become findable
    _COST_FIELDS = ("samplesScanned", "bytesRead", "cpuMs",
                    "deviceBytes", "rpcBytes")

    def __init__(self, max_records: int = 20_000,
                 max_lifetime_s: float = 300.0):
        self._lock = threading.Lock()
        # ring of (query, time_range_s rounded, duration_s, cost dict,
        # unix_s)
        self._ring: collections.deque = collections.deque(
            maxlen=max_records)
        self.max_lifetime_s = max_lifetime_s
        self._queries_total = metricslib.REGISTRY.counter(
            "vm_search_queries_total")

    def record(self, query: str, time_range_s: float, duration_s: float,
               cost: dict | None = None):
        self._queries_total.inc()
        with self._lock:
            self._ring.append((query, round(time_range_s), duration_s,
                               cost, fasttime.unix_seconds()))

    def _aggregate(self) -> list[dict]:
        cutoff = fasttime.unix_seconds() - self.max_lifetime_s
        acc: dict[tuple, list] = {}
        with self._lock:
            records = list(self._ring)
        nf = len(self._COST_FIELDS)
        for q, tr, d, cost, at in records:
            if at < cutoff:
                continue
            e = acc.get((q, tr))
            if e is None:
                e = acc[(q, tr)] = [0, 0.0] + [0] * nf
            e[0] += 1
            e[1] += d
            if cost:
                for i, f in enumerate(self._COST_FIELDS):
                    e[2 + i] += cost.get(f, 0)
        out = []
        for (q, tr), e in acc.items():
            c, d = e[0], e[1]
            rec = {"query": q, "timeRangeSeconds": tr, "count": c,
                   "sumDurationSeconds": round(d, 6),
                   "avgDurationSeconds": round(d / c, 6)}
            for i, f in enumerate(self._COST_FIELDS):
                key = "sum" + f[0].upper() + f[1:]
                rec[key] = round(e[2 + i], 3) if f == "cpuMs" \
                    else int(e[2 + i])
            out.append(rec)
        return out

    _SORTERS = {"count": lambda x: -x["count"],
                "sumDuration": lambda x: -x["sumDurationSeconds"],
                "avgDuration": lambda x: -x["avgDurationSeconds"],
                # cumulative-cost orderings: CPU burned and samples
                # scanned are the two cluster-cost currencies
                "sumCpuMs": lambda x: -x["sumCpuMs"],
                "sumSamplesScanned": lambda x: -x["sumSamplesScanned"]}

    def top(self, n: int, key: str) -> list[dict]:
        items = self._aggregate()
        items.sort(key=self._SORTERS.get(key, self._SORTERS["count"]))
        return items[:n]

    def tops(self, n: int) -> dict[str, list[dict]]:
        """All three top-N orderings from ONE aggregation pass over the
        ring (the /top_queries endpoint serves all three at once)."""
        items = self._aggregate()
        out = {}
        for key, sorter in self._SORTERS.items():
            out[key] = sorted(items, key=sorter)[:n]
        return out


def slow_query_threshold_ms() -> float:
    """``VM_SLOW_QUERY_MS``: queries slower than this are retained in
    the slow-query log (default 5000, the reference's
    -search.logSlowQueryDuration=5s; <=0 disables)."""
    try:
        return float(os.environ.get("VM_SLOW_QUERY_MS", "5000"))
    except ValueError:
        return 5000.0


#: spans that CONTAIN other phase spans of the same flight ctx — the
#: whole refresh and the pool's per-task wrapper.  Reported under
#: ``containerSpansMs``, not ``phaseSplitMs``.
_CONTAINER_SPANS = frozenset({"serve:refresh", "pool:task"})


class SlowQueryLog:
    """Bounded ring of the slowest-query evidence: each record carries
    the query, its window, the measured duration, the PER-PHASE split
    reassembled from the flight recorder's cross-thread events for that
    query's context, and — when the refresh tripped a flight capture —
    the capture id, so ``/api/v1/status/slow_queries`` links straight to
    the timeline that explains the latency."""

    def __init__(self, max_records: int = 200,
                 threshold_ms: float | None = None):
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max_records)
        self._threshold_ms = threshold_ms
        self._slow_total = metricslib.REGISTRY.counter(
            "vm_slow_queries_total")
        self._rejected_total = metricslib.REGISTRY.counter(
            "vm_rejected_queries_total")

    def threshold_ms(self) -> float:
        """Pinned at construction when given, else re-read from the env
        per call (tests and operators flip it without a restart)."""
        if self._threshold_ms is not None:
            return self._threshold_ms
        return slow_query_threshold_ms()

    def maybe_record(self, query: str, start: int, end: int, step: int,
                     tenant, duration_s: float, ctx: int = 0,
                     capture_id: int | None = None,
                     cost: dict | None = None) -> bool:
        """Record when duration exceeds the threshold; returns whether it
        did.  `ctx` is the query's flight context (0 = none): the
        per-phase split is summed from the ring events carrying it —
        including spans recorded on pool workers.  `cost` is the query's
        CostTracker summary (samplesScanned/bytesRead/cpuMs/...), so a
        slow record says what the query COST, not just how long it
        took."""
        th = self.threshold_ms()
        if th <= 0 or duration_s * 1e3 < th:
            return False
        self._slow_total.inc()
        phases = {}
        containers = {}
        if ctx:
            from ..utils import flightrec
            for name, sec in sorted(flightrec.phase_split(ctx).items()):
                # container spans (the whole refresh, the pool's
                # per-task wrapper) NEST the leaf phases for the same
                # ctx: kept out of phaseSplitMs so the split holds
                # disjoint phases that sum to ~wall time instead of
                # double-counting every contained window
                if name in _CONTAINER_SPANS:
                    containers[name] = round(sec * 1e3, 3)
                else:
                    phases[name] = round(sec * 1e3, 3)
        rec = {"query": query, "start": start, "end": end, "step": step,
               "tenant": f"{tenant[0]}:{tenant[1]}" if tenant else "0:0",
               "durationSeconds": round(duration_s, 6),
               "time": fasttime.unix_seconds(),
               "phaseSplitMs": phases}
        if containers:
            rec["containerSpansMs"] = containers
        if capture_id is not None:
            rec["flightCaptureId"] = capture_id
        if cost is not None:
            rec["cost"] = cost
        with self._lock:
            self._ring.append(rec)
        return True

    def record_rejected(self, query: str, start: int, end: int, step: int,
                        tenant, reason: str = "") -> None:
        """Shed-load visibility: a query REJECTED by admission control
        (TenantGate 429) enters the ring unconditionally — it never ran,
        so the duration threshold does not apply — marked
        ``rejected: true`` with the gate's reason.  Keeps shed load from
        vanishing out of the slow-query evidence trail (the gate's
        ``gate:rejected`` flight instant is the capture-side half).
        Counts ``vm_rejected_queries_total`` — NOT the slow counter: a
        shed query never ran, and a 429 storm must not trip alerts on
        ``vm_slow_queries_total``."""
        self._rejected_total.inc()
        rec = {"query": query, "start": start, "end": end, "step": step,
               "tenant": f"{tenant[0]}:{tenant[1]}" if tenant else "0:0",
               "durationSeconds": 0.0,
               "time": fasttime.unix_seconds(),
               "rejected": True,
               "reason": reason,
               "phaseSplitMs": {}}
        with self._lock:
            self._ring.append(rec)

    def snapshot(self) -> list[dict]:
        """Records, newest first."""
        with self._lock:
            return list(reversed(self._ring))
