"""Transform functions (reference app/vmselect/promql/transform.go:23-140,
113 functions; the heavily-used subset here, expanding over rounds).

A transform takes already-evaluated args (lists of Timeseries, floats, or
strings) plus the EvalConfig, and returns a list of Timeseries.
"""

from __future__ import annotations

import math
import re

import numpy as np

from ..storage.metric_name import MetricName
from .types import EvalConfig, Timeseries, const_series, new_series

nan = np.nan


# -- helpers -----------------------------------------------------------------

def _map_values(series: list[Timeseries], fn, keep_name=False) -> list[Timeseries]:
    out = []
    for ts in series:
        with np.errstate(all="ignore"):
            vals = np.asarray(fn(ts.values), dtype=np.float64)
        mn = MetricName(ts.metric_name.metric_group if keep_name else b"",
                        list(ts.metric_name.labels))
        out.append(Timeseries(mn, vals))
    return out


def _elementwise(fn):
    def tf(ec, args):
        return _map_values(args[0], fn)
    return tf


def _scalar_arg(args, i, default=None) -> float:
    a = args[i] if i < len(args) else default
    if isinstance(a, list):
        if len(a) != 1:
            raise ValueError("expected scalar arg")
        return float(a[0].values[0])
    return float(a)


def _string_arg(args, i) -> str:
    if not isinstance(args[i], str):
        raise ValueError("expected string arg")
    return args[i]


# -- math --------------------------------------------------------------------

MATH = {
    "abs": np.abs, "ceil": np.ceil, "floor": np.floor, "exp": np.exp,
    "ln": np.log, "log2": np.log2, "log10": np.log10, "sqrt": np.sqrt,
    "sgn": np.sign, "acos": np.arccos, "acosh": np.arccosh,
    "asin": np.arcsin, "asinh": np.arcsinh, "atan": np.arctan,
    "atanh": np.arctanh, "cos": np.cos, "cosh": np.cosh, "sin": np.sin,
    "sinh": np.sinh, "tan": np.tan, "tanh": np.tanh,
    "deg": np.degrees, "rad": np.radians,
}


def _arg_values(args, i, default=None):
    """Per-point parameter: a 1-series arg yields its value ARRAY (so
    clamp_min(q, time()) etc vary per step); plain floats broadcast."""
    a = args[i] if i < len(args) else default
    if isinstance(a, list):
        if len(a) != 1:
            raise ValueError("expected scalar arg")
        return a[0].values
    return float(a)


def _vm_round(v: np.ndarray, nearest) -> np.ndarray:
    """transform.go:2337 transformRound, replicated float-for-float: add a
    signed half, subtract fmod, then TRUNCATE at the nearest's decimal
    precision. The truncation step is observable (e.g. round(0.28948, 0.01)
    = 0.28 because 0.29*100 = 28.999... truncates to 28), so np.round is not
    equivalent."""
    n = np.asarray(nearest, dtype=np.float64)
    # decimal.FromFloat(n) exponent -> p10 (per distinct nearest value)
    def p10_of(x):
        from ..ops.decimal import float_to_decimal
        if not np.isfinite(x) or x == 0:
            return 1.0
        _, e = float_to_decimal(np.array([x]))
        return 10.0 ** (-e)
    if n.ndim == 0:
        p10 = p10_of(float(n))
    else:
        p10 = np.array([p10_of(float(x)) for x in n])
    with np.errstate(all="ignore"):
        w = v + 0.5 * np.copysign(n, v)
        w = w - np.fmod(w, n)
        w = np.trunc(w * p10)
        out = w / p10
    return np.where(np.isnan(v), nan, out)


def tf_round(ec, args):
    nearest = _arg_values(args, 1, 1.0)
    return _map_values(args[0], lambda v: _vm_round(v, nearest),
                       keep_name=True)


def tf_clamp(ec, args):
    lo, hi = _arg_values(args, 1), _arg_values(args, 2)
    return _map_values(args[0], lambda v: np.clip(v, lo, hi), keep_name=True)


def tf_clamp_min(ec, args):
    lo = _arg_values(args, 1)
    return _map_values(args[0], lambda v: np.maximum(v, lo), keep_name=True)


def tf_clamp_max(ec, args):
    hi = _arg_values(args, 1)
    return _map_values(args[0], lambda v: np.minimum(v, hi), keep_name=True)


# -- time --------------------------------------------------------------------

def tf_time(ec, args):
    return [new_series(ec.timestamps() / 1e3)]


def tf_now(ec, args):
    from ..utils import fasttime
    return [const_series(ec, fasttime.unix_seconds())]


def tf_step(ec, args):
    return [const_series(ec, ec.step / 1e3)]


def tf_start(ec, args):
    return [const_series(ec, ec.start / 1e3)]


def tf_end(ec, args):
    return [const_series(ec, ec.end / 1e3)]


def _dt_transform(extract):
    def tf(ec, args):
        series = args[0] if args else [new_series(ec.timestamps() / 1e3)]
        import datetime

        def fn(v):
            out = np.full(v.size, nan)
            ok = ~np.isnan(v)
            for i in np.flatnonzero(ok):
                dt = datetime.datetime.fromtimestamp(
                    v[i], tz=datetime.timezone.utc)
                out[i] = extract(dt)
            return out
        return _map_values(series, fn)
    return tf


DT_FUNCS = {
    "minute": _dt_transform(lambda d: d.minute),
    "hour": _dt_transform(lambda d: d.hour),
    "day_of_month": _dt_transform(lambda d: d.day),
    "day_of_week": _dt_transform(lambda d: d.isoweekday() % 7),
    "day_of_year": _dt_transform(lambda d: d.timetuple().tm_yday),
    "days_in_month": _dt_transform(
        lambda d: __import__("calendar").monthrange(d.year, d.month)[1]),
    "month": _dt_transform(lambda d: d.month),
    "year": _dt_transform(lambda d: d.year),
}


# -- series shaping ------------------------------------------------------------

def tf_scalar(ec, args):
    if args and isinstance(args[0], str):
        # scalar("-12.34"): numeric strings become scalars (reference
        # transformScalar string fast path)
        try:
            return [const_series(ec, float(args[0]))]
        except ValueError:
            return [const_series(ec, nan)]
    series = args[0]
    if len(series) != 1:
        return [const_series(ec, nan)]
    return [new_series(series[0].values.copy())]


def tf_vector(ec, args):
    if isinstance(args[0], (int, float)):
        return [const_series(ec, float(args[0]))]
    return list(args[0])


def _is_scalar_series(series) -> bool:
    return (len(series) == 1 and not series[0].metric_name.metric_group
            and not series[0].metric_name.labels)


def tf_union(ec, args):
    series_args = [a for a in args if isinstance(a, list)]
    if series_args and all(_is_scalar_series(a) for a in series_args):
        # (v1, ..., vN) of scalars keeps every element — needed for
        # `q == (v1,...,vN)` lists (transform.go:1731)
        return [a[0] for a in series_args]
    seen = set()
    out = []
    for series in series_args:
        for ts in series:
            key = ts.metric_name.marshal()
            if key not in seen:
                seen.add(key)
                out.append(ts)
    return out


def tf_sort(ec, args, desc=False, by_last=False):
    import functools
    series = list(args[0])

    def cmp(x, y):
        a, b = x.values, y.values
        n = a.size - 1
        while n >= 0:
            if not math.isnan(a[n]):
                if math.isnan(b[n]):
                    return 1   # a after b ("not less")
                if a[n] != b[n]:
                    break
            elif not math.isnan(b[n]):
                return -1
            n -= 1
        if n < 0:
            return 0
        if desc:
            return -1 if b[n] < a[n] else 1
        return -1 if a[n] < b[n] else 1
    series.sort(key=functools.cmp_to_key(cmp))
    return series


_NAT_CHUNK = re.compile(r"[0-9]+|[^0-9]+")


def _natural_key(v: bytes):
    """Natural-order sort key matching lib/stringsutil LessNatural: decimal
    digit runs compare numerically and sort before non-digit chunks."""
    out = []
    for m in _NAT_CHUNK.finditer(v.decode("utf-8", "surrogateescape")):
        c = m.group(0)
        if c[0] in "0123456789":
            out.append((0, int(c), ""))
        else:
            out.append((1, 0, c))
    return out


def tf_sort_by_label(ec, args, desc=False, numeric=False):
    series = list(args[0])
    labels = [a for a in args[1:] if isinstance(a, str)]

    def key(ts):
        out = []
        for lab in labels:
            v = ts.metric_name.get_label(lab.encode()) or b""
            out.append(_natural_key(v) if numeric else v)
        return out
    series.sort(key=key, reverse=desc)
    return series


def tf_limit_offset(ec, args):
    limit = int(_scalar_arg(args, 0))
    offset = int(_scalar_arg(args, 1))
    # transform.go:2290: empty (all-NaN) series are dropped BEFORE the
    # offset is applied
    rows = [ts for ts in args[2] if not np.isnan(ts.values).all()]
    return rows[offset:offset + limit]


def tf_absent(ec, args):
    series = args[0]
    if not series:
        return [const_series(ec, 1.0)]
    m = np.vstack([ts.values for ts in series])
    absent = np.isnan(m).all(axis=0)
    return [new_series(np.where(absent, 1.0, nan))]


def tf_drop_common_labels(ec, args):
    series = [t.copy_shallow_labels() for ts in args for t in ts]
    if not series:
        return series
    common = dict(series[0].metric_name.labels)
    common[b"__name__"] = series[0].metric_name.metric_group
    for ts in series[1:]:
        d = dict(ts.metric_name.labels)
        d[b"__name__"] = ts.metric_name.metric_group
        for k in list(common):
            if d.get(k) != common[k]:
                del common[k]
    for ts in series:
        if b"__name__" in common:
            ts.metric_name.metric_group = b""
        ts.metric_name.labels = [
            (k, v) for k, v in ts.metric_name.labels if k not in common]
        ts.raw = None  # in-place name edit: memoized marshal is stale
    return series


# -- running / range over the output grid -------------------------------------

def _running(fn_acc):
    def tf(ec, args):
        out = []
        for ts in args[0]:
            v = ts.values
            ok = ~np.isnan(v)
            acc = fn_acc(np.where(ok, v, 0), ok)
            acc[~ok.cumsum().astype(bool)] = nan
            out.append(Timeseries(MetricName(b"", list(ts.metric_name.labels)),
                                  acc))
        return out
    return tf


def _racc_sum(v, ok):
    return np.cumsum(v)


def _racc_avg(v, ok):
    with np.errstate(all="ignore"):
        return np.cumsum(v) / np.maximum(np.cumsum(ok), 1)


def _racc_min(v, ok):
    x = np.where(ok, v, np.inf)
    return np.minimum.accumulate(x)


def _racc_max(v, ok):
    x = np.where(ok, v, -np.inf)
    return np.maximum.accumulate(x)


def _range_apply(stat):
    def tf(ec, args):
        out = []
        for ts in args[0]:
            with np.errstate(all="ignore"):
                s = stat(ts.values)
            out.append(Timeseries(MetricName(b"", list(ts.metric_name.labels)),
                                  np.full(ts.values.size, s)))
        return out
    return tf


def tf_range_quantile(ec, args):
    phi = _scalar_arg(args, 0)
    out = []
    for ts in args[1]:
        with np.errstate(all="ignore"):
            s = np.nanquantile(ts.values, min(max(phi, 0), 1)) \
                if not np.isnan(ts.values).all() else nan
        out.append(Timeseries(MetricName(b"", list(ts.metric_name.labels)),
                              np.full(ts.values.size, s)))
    return out


def tf_range_normalize(ec, args):
    """transform.go:1347 transformRangeNormalize: (v-min)/(max-min) per
    series; all-NaN series (infinite spread) dropped; KEEPS metric names
    (it's in transformFuncsKeepMetricName); a zero spread yields 0/0=NaN."""
    out = []
    for series in args:
        for ts in series:
            with np.errstate(all="ignore"):
                ok = ~np.isnan(ts.values)
                if not ok.any():
                    continue
                lo, hi = np.min(ts.values[ok]), np.max(ts.values[ok])
                v = (ts.values - lo) / (hi - lo)
            out.append(Timeseries(MetricName(ts.metric_name.metric_group,
                                             list(ts.metric_name.labels)), v))
    return out


# -- gap filling ----------------------------------------------------------------

def tf_interpolate(ec, args):
    out = []
    for ts in args[0]:
        v = ts.values.copy()
        ok = ~np.isnan(v)
        if ok.any() and not ok.all():
            idx = np.arange(v.size)
            filled = np.interp(idx, idx[ok], v[ok])
            # only interior gaps: leading/trailing NaNs stay NaN
            # (transform.go:1268 skips leading/trailing)
            first, last = idx[ok][0], idx[ok][-1]
            inside = (idx >= first) & (idx <= last)
            v = np.where(inside, filled, nan)
        out.append(Timeseries(ts.metric_name, v))
    return out


def tf_keep_last_value(ec, args):
    out = []
    for ts in args[0]:
        v = ts.values.copy()
        ok = ~np.isnan(v)
        if ok.any():
            last = np.maximum.accumulate(np.where(ok, np.arange(v.size), -1))
            filled = np.where(last >= 0, v[np.maximum(last, 0)], nan)
            v = filled
        out.append(Timeseries(ts.metric_name, v))
    return out


def tf_keep_next_value(ec, args):
    out = []
    for ts in args[0]:
        v = ts.values[::-1].copy()
        ok = ~np.isnan(v)
        if ok.any():
            last = np.maximum.accumulate(np.where(ok, np.arange(v.size), -1))
            v = np.where(last >= 0, v[np.maximum(last, 0)], nan)
        out.append(Timeseries(ts.metric_name, v[::-1]))
    return out


def tf_remove_resets(ec, args):
    from ..ops.rollup_np import remove_counter_resets

    def fn(v):
        ok = ~np.isnan(v)
        if not ok.any():
            return v
        filled = v[ok]
        fixed = remove_counter_resets(filled)
        out = v.copy()
        out[ok] = fixed
        return out
    return _map_values(args[0], fn)


# -- label manipulation ---------------------------------------------------------

def _get_label(mn: MetricName, key: bytes):
    if key == b"__name__":
        return mn.metric_group or None
    return mn.get_label(key)


def _set_label(mn: MetricName, key: bytes, value: bytes):
    if key == b"__name__":
        mn.metric_group = value
        return
    mn.labels = [(k, v) for k, v in mn.labels if k != key]
    if value:
        mn.labels.append((key, value))
        mn.sort_labels()


def tf_label_set(ec, args):
    series = [t.copy_shallow_labels() for t in args[0]]
    pairs = args[1:]
    for i in range(0, len(pairs) - 1, 2):
        k, v = _string_arg(pairs, i).encode(), _string_arg(pairs, i + 1).encode()
        for ts in series:
            _set_label(ts.metric_name, k, v)
    return series


def tf_label_del(ec, args):
    series = [t.copy_shallow_labels() for t in args[0]]
    keys = [a.encode() for a in args[1:] if isinstance(a, str)]
    for ts in series:
        for k in keys:
            _set_label(ts.metric_name, k, b"")
    return series


def tf_label_keep(ec, args):
    series = [t.copy_shallow_labels() for t in args[0]]
    keep = {a.encode() for a in args[1:] if isinstance(a, str)}
    for ts in series:
        if b"__name__" not in keep:
            ts.metric_name.metric_group = b""
        ts.metric_name.labels = [
            (k, v) for k, v in ts.metric_name.labels if k in keep]
    return series


def tf_label_copy(ec, args, move=False):
    series = [t.copy_shallow_labels() for t in args[0]]
    pairs = args[1:]
    for i in range(0, len(pairs) - 1, 2):
        src = _string_arg(pairs, i).encode()
        dst = _string_arg(pairs, i + 1).encode()
        for ts in series:
            v = _get_label(ts.metric_name, src)
            if v:
                _set_label(ts.metric_name, dst, v)
                if move and src != dst:
                    _set_label(ts.metric_name, src, b"")
    return series


def tf_label_replace(ec, args):
    series = [t.copy_shallow_labels() for t in args[0]]
    dst, repl, src, regex = (_string_arg(args, 1), _string_arg(args, 2),
                             _string_arg(args, 3), _string_arg(args, 4))
    try:
        rx = re.compile("(?:" + regex + ")\\Z")
    except re.error as e:
        raise ValueError(f"label_replace: bad regex: {e}")
    for ts in series:
        v = (_get_label(ts.metric_name, src.encode()) or b"").decode(
            "utf-8", "replace")
        m = rx.match(v)
        if m:
            # $1 / ${1} expand to the group, or "" when the group does not
            # exist (Go regexp.Expand semantics — no error)
            def _grp(gm):
                gi = gm.group(1) or gm.group(2)
                try:
                    return m.group(int(gi)) or ""
                except (IndexError, ValueError):
                    return ""
            new = re.sub(r"\$(?:\{(\w+)\}|(\d+))", _grp, repl)
            _set_label(ts.metric_name, dst.encode(), new.encode())
    return series


def tf_label_join(ec, args):
    series = [t.copy_shallow_labels() for t in args[0]]
    dst = _string_arg(args, 1).encode()
    sep = _string_arg(args, 2).encode()
    srcs = [a.encode() for a in args[3:] if isinstance(a, str)]
    for ts in series:
        parts = [(_get_label(ts.metric_name, s) or b"") for s in srcs]
        _set_label(ts.metric_name, dst, sep.join(parts))
    return series


def tf_label_value(ec, args):
    series = [t.copy_shallow_labels() for t in args[0]]
    key = _string_arg(args, 1).encode()
    out = []
    for ts in series:
        v = _get_label(ts.metric_name, key)
        try:
            x = float(v) if v is not None else nan
        except ValueError:
            x = nan
        out.append(Timeseries(ts.metric_name,
                              np.where(np.isnan(ts.values), nan, x)))
    return out


def tf_label_transform(ec, args):
    series = [t.copy_shallow_labels() for t in args[0]]
    key = _string_arg(args, 1).encode()
    regex = _string_arg(args, 2)
    repl = _string_arg(args, 3)
    rx = re.compile(regex)
    for ts in series:
        v = (ts.metric_name.get_label(key) or b"").decode("utf-8", "replace")
        _set_label(ts.metric_name, key,
                   rx.sub(repl.replace("$", "\\"), v).encode())
    return series


def tf_label_map(ec, args):
    series = [t.copy_shallow_labels() for t in args[0]]
    key = _string_arg(args, 1).encode()
    mapping = {}
    rest = args[2:]
    for i in range(0, len(rest) - 1, 2):
        mapping[_string_arg(rest, i).encode()] = _string_arg(rest, i + 1).encode()
    for ts in series:
        v = ts.metric_name.get_label(key) or b""
        if v in mapping:
            _set_label(ts.metric_name, key, mapping[v])
    return series


def _label_case(upper: bool):
    def tf(ec, args):
        series = [t.copy_shallow_labels() for t in args[0]]
        keys = [a.encode() for a in args[1:] if isinstance(a, str)]
        for ts in series:
            for k in keys:
                v = ts.metric_name.get_label(k)
                if v:
                    s = v.decode("utf-8", "replace")
                    _set_label(ts.metric_name, k,
                               (s.upper() if upper else s.lower()).encode())
        return series
    return tf


def tf_label_match(ec, args, negate=False):
    series = args[0]
    key = _string_arg(args, 1).encode()
    rx = re.compile("(?:" + _string_arg(args, 2) + ")\\Z")
    out = []
    for ts in series:
        v = (ts.metric_name.get_label(key) or b"").decode("utf-8", "replace")
        if bool(rx.match(v)) != negate:
            out.append(ts)
    return out


def tf_labels_equal(ec, args):
    series = args[0]
    keys = [a.encode() for a in args[1:] if isinstance(a, str)]
    out = []
    for ts in series:
        vals = {ts.metric_name.get_label(k) for k in keys}
        if len(vals) == 1:
            out.append(ts)
    return out


# -- histogram_quantile --------------------------------------------------------

def _group_buckets(series: list[Timeseries]):
    """Group bucket series by labels-minus-le; returns
    [(labels_key, MetricName_without_le, [(le, values)])]."""
    groups: dict[bytes, tuple[MetricName, list]] = {}
    for ts in series:
        le = ts.metric_name.get_label(b"le")
        if le is None:
            continue
        try:
            le_f = float(le)
        except ValueError:
            continue
        mn = MetricName(b"", [(k, v) for k, v in ts.metric_name.labels
                              if k != b"le"])
        key = mn.marshal()
        if key not in groups:
            groups[key] = (mn, [])
        groups[key][1].append((le_f, ts.values))
    return groups


def _merge_same_le(buckets):
    """transform.go:1151 mergeSameLE: buckets with identical numeric le are
    SUMMED (le="5" and le="5.0" are the same bucket from different scrapes)."""
    out = []
    for le, v in buckets:
        if out and out[-1][0] == le:
            out[-1] = (le, out[-1][1] + v)
        else:
            out.append((le, v))
    return out


def tf_histogram_quantile(ec, args):
    phis = _arg_values(args, 0)
    series = _vmrange_to_le(list(args[1]))
    bounds_label = args[2].encode() if len(args) > 2 and \
        isinstance(args[2], str) else None
    out = []
    for key, (mn, buckets) in _group_buckets(series).items():
        buckets.sort(key=lambda b: b[0])
        buckets = _merge_same_le(buckets)
        les = np.array([b[0] for b in buckets])
        m = np.vstack([b[1] for b in buckets])  # [B, T] cumulative counts
        with np.errstate(all="ignore"):
            vals = _hist_quantile_cols(phis, les, m)
        if bounds_label:
            # lower/upper bucket-edge bound series (prometheus issue 5706)
            lo = np.full(vals.shape, nan)
            hi = np.full(vals.shape, nan)
            fin = np.isfinite(vals)
            if fin.any():
                for j in np.flatnonzero(fin):
                    i = int(np.searchsorted(les, vals[j], side="left"))
                    lo[j] = les[i - 1] if i > 0 else 0.0
                    hi[j] = les[min(i, les.size - 1)]
            for tag, bvals in ((b"lower", lo), (b"upper", hi)):
                b = MetricName(mn.metric_group,
                               [(k, v) for k, v in mn.labels
                                if k != bounds_label] +
                               [(bounds_label, tag)])
                b.sort_labels()
                out.append(Timeseries(b, bvals))
        out.append(Timeseries(mn, vals))
    return out


def _hist_quantile_cols(phi, les: np.ndarray, m: np.ndarray) -> np.ndarray:
    T = m.shape[1]
    phi_arr = np.broadcast_to(np.asarray(phi, dtype=np.float64), (T,))
    out = np.full(T, nan)
    if not np.isfinite(les[-1]) and les.size < 2:
        return out
    for j in range(T):
        phi = float(phi_arr[j])
        counts = m[:, j]
        if np.isnan(counts).all():
            continue
        counts = np.nan_to_num(counts)
        # enforce monotonicity (float jitter)
        counts = np.maximum.accumulate(counts)
        total = counts[-1]
        if total == 0:
            continue
        if phi < 0:
            out[j] = -np.inf
            continue
        if phi > 1:
            out[j] = np.inf
            continue
        rank = phi * total
        idx = int(np.searchsorted(counts, rank, side="left"))
        idx = min(idx, les.size - 1)
        if not np.isfinite(les[idx]):
            # +Inf bucket: return the upper bound of the previous bucket
            out[j] = les[idx - 1] if idx > 0 else nan
            continue
        lo = les[idx - 1] if idx > 0 else 0.0
        c_lo = counts[idx - 1] if idx > 0 else 0.0
        c_hi = counts[idx]
        if c_hi <= c_lo:
            out[j] = les[idx]
            continue
        out[j] = lo + (les[idx] - lo) * (rank - c_lo) / (c_hi - c_lo)
    return out


def tf_histogram_avg(ec, args):
    """transform.go:812 transformHistogramAvg + :876 avgForLeTimeseries:
    vmrange buckets are converted to le= first; the +Inf bucket is SKIPPED
    entirely (it does not advance lePrev/vPrev); weights are adjacent
    cumulative diffs and a zero total weight yields NaN."""
    out = []
    series = _vmrange_to_le(list(args[0]))
    for key, (mn, buckets) in _group_buckets(series).items():
        buckets.sort(key=lambda b: b[0])
        buckets = _merge_same_le(buckets)
        fin = [(le, v) for le, v in buckets if np.isfinite(le)]
        if not fin:
            out.append(Timeseries(mn, np.full(
                buckets[0][1].size if buckets else 0, nan)))
            continue
        les = np.array([b[0] for b in fin])
        m = np.nan_to_num(np.vstack([b[1] for b in fin]))
        mids = (les + np.concatenate([[0.0], les[:-1]])) / 2
        d = np.diff(np.vstack([np.zeros(m.shape[1]), m]), axis=0)
        with np.errstate(all="ignore"):
            tot = d.sum(axis=0)
            avg = np.where(tot != 0, (d * mids[:, None]).sum(axis=0) / tot,
                           nan)
        out.append(Timeseries(mn, avg))
    return out


def tf_prometheus_buckets(ec, args):
    """vmrange buckets (histogram_over_time / histogram()) -> cumulative
    Prometheus le= buckets (transform.go:490)."""
    return _vmrange_to_le(list(args[0]))


def tf_buckets_limit(ec, args):
    """Reduce per-group bucket count by merging the buckets with the
    fewest hits, always keeping the first and last (transform.go:386)."""
    limit = int(_scalar_arg(args, 0))
    if limit <= 0:
        return []
    if limit < 3:
        limit = 3  # preserve first/last for min/max accuracy
    tss = _vmrange_to_le(list(args[1]))
    groups: dict[bytes, list] = {}
    for ts in tss:
        le_b = ts.metric_name.get_label(b"le")
        if not le_b:
            continue
        try:
            le = float(le_b)
        except ValueError:
            continue
        mn = MetricName(ts.metric_name.metric_group,
                        [(k, v) for k, v in ts.metric_name.labels
                         if k != b"le"])
        groups.setdefault(mn.marshal(), []).append([le, 0.0, ts])
    out = []
    for grp in groups.values():
        if len(grp) <= limit:
            out.extend(x[2] for x in grp)
            continue
        grp.sort(key=lambda x: x[0])
        prev = np.zeros(grp[0][2].values.size)
        for x in grp:
            vals = np.nan_to_num(x[2].values)
            x[1] = float((vals - prev).sum())
            prev = vals
        while len(grp) > limit:
            best = 1
            best_hits = grp[1][1] + grp[2][1]
            for i in range(1, len(grp) - 2):
                h = grp[i][1] + grp[i + 1][1]
                if h < best_hits:
                    best, best_hits = i, h
            grp[best + 1][1] += grp[best][1]
            del grp[best]
        out.extend(x[2] for x in grp)
    return out


# -- misc ----------------------------------------------------------------------

def tf_pi(ec, args):
    return [const_series(ec, math.pi)]


def tf_e(ec, args):
    return [const_series(ec, math.e)]


def _go_rand_series(ec, args, draw_attr):
    """Seeded rand draws replicate Go's math/rand stream bit-for-bit
    (transform.go:2653 newTransformRand + gorand.py); unseeded calls are
    time-seeded like the reference and just use numpy."""
    if args:
        from .gorand import GoRand
        r = GoRand(int(_scalar_arg(args, 0, 0)))
        draw = getattr(r, draw_attr)
        return [new_series(np.array([draw() for _ in range(ec.n_points)]))]
    rng = np.random.default_rng()
    fallback = {"float64": rng.random,
                "norm_float64": rng.standard_normal,
                "exp_float64": lambda n: rng.exponential(size=n)}
    return [new_series(np.asarray(fallback[draw_attr](ec.n_points),
                                  dtype=np.float64))]


def tf_rand(ec, args):
    return _go_rand_series(ec, args, "float64")


def tf_rand_normal(ec, args):
    return _go_rand_series(ec, args, "norm_float64")


def tf_rand_exponential(ec, args):
    return _go_rand_series(ec, args, "exp_float64")


def tf_smooth_exponential(ec, args):
    sf = min(max(_scalar_arg(args, 1), 0.0), 1.0)
    out = []
    for ts in args[0]:
        v = ts.values
        acc = v.copy()
        prev = nan
        for i in range(v.size):
            if np.isnan(v[i]):
                acc[i] = prev
            elif np.isnan(prev):
                acc[i] = v[i]
                prev = v[i]
            else:
                prev = sf * v[i] + (1 - sf) * prev
                acc[i] = prev
        out.append(Timeseries(MetricName(b"", list(ts.metric_name.labels)), acc))
    return out


def tf_bitmap_and(ec, args):
    mask = int(_scalar_arg(args, 1))
    return _map_values(args[0], lambda v: np.where(
        np.isnan(v), nan, (v.astype(np.int64) & mask).astype(np.float64)))


def tf_bitmap_or(ec, args):
    mask = int(_scalar_arg(args, 1))
    return _map_values(args[0], lambda v: np.where(
        np.isnan(v), nan, (v.astype(np.int64) | mask).astype(np.float64)))


def tf_bitmap_xor(ec, args):
    mask = int(_scalar_arg(args, 1))
    return _map_values(args[0], lambda v: np.where(
        np.isnan(v), nan, (v.astype(np.int64) ^ mask).astype(np.float64)))


TRANSFORM_FUNCS: dict = {}
TRANSFORM_FUNCS.update({name: _elementwise(fn) for name, fn in MATH.items()})
TRANSFORM_FUNCS.update(DT_FUNCS)
TRANSFORM_FUNCS.update({
    "round": tf_round, "clamp": tf_clamp, "clamp_min": tf_clamp_min,
    "clamp_max": tf_clamp_max,
    "time": tf_time, "now": tf_now, "step": tf_step, "start": tf_start,
    "end": tf_end, "pi": tf_pi, "e": tf_e,
    "rand": tf_rand, "rand_normal": tf_rand_normal,
    "rand_exponential": tf_rand_exponential,
    "scalar": tf_scalar, "vector": tf_vector, "union": tf_union,
    "sort": lambda ec, a: tf_sort(ec, a),
    "sort_desc": lambda ec, a: tf_sort(ec, a, desc=True),
    "sort_by_label": lambda ec, a: tf_sort_by_label(ec, a),
    "sort_by_label_desc": lambda ec, a: tf_sort_by_label(ec, a, desc=True),
    "sort_by_label_numeric": lambda ec, a: tf_sort_by_label(ec, a, numeric=True),
    "sort_by_label_numeric_desc":
        lambda ec, a: tf_sort_by_label(ec, a, desc=True, numeric=True),
    "limit_offset": tf_limit_offset, "absent": tf_absent,
    "drop_common_labels": tf_drop_common_labels,
    "running_sum": _running(_racc_sum), "running_avg": _running(_racc_avg),
    "running_min": _running(_racc_min), "running_max": _running(_racc_max),
    "range_sum": _range_apply(np.nansum), "range_avg": _range_apply(np.nanmean),
    "range_min": _range_apply(np.nanmin), "range_max": _range_apply(np.nanmax),
    "range_first": _range_apply(
        lambda v: v[np.flatnonzero(~np.isnan(v))[0]]
        if (~np.isnan(v)).any() else nan),
    "range_last": _range_apply(
        lambda v: v[np.flatnonzero(~np.isnan(v))[-1]]
        if (~np.isnan(v)).any() else nan),
    "range_stddev": _range_apply(np.nanstd),
    "range_stdvar": _range_apply(np.nanvar),
    "range_median": _range_apply(np.nanmedian),
    "range_quantile": tf_range_quantile,
    "range_normalize": tf_range_normalize,
    "interpolate": tf_interpolate,
    "keep_last_value": tf_keep_last_value,
    "keep_next_value": tf_keep_next_value,
    "remove_resets": tf_remove_resets,
    "label_set": tf_label_set, "label_del": tf_label_del,
    "label_keep": tf_label_keep,
    "label_copy": lambda ec, a: tf_label_copy(ec, a),
    "label_move": lambda ec, a: tf_label_copy(ec, a, move=True),
    "label_replace": tf_label_replace, "label_join": tf_label_join,
    "label_value": tf_label_value, "label_transform": tf_label_transform,
    "label_map": tf_label_map,
    "label_lowercase": _label_case(False),
    "label_uppercase": _label_case(True),
    "label_match": lambda ec, a: tf_label_match(ec, a),
    "label_mismatch": lambda ec, a: tf_label_match(ec, a, negate=True),
    "labels_equal": tf_labels_equal,
    "histogram_quantile": tf_histogram_quantile,
    "histogram_avg": tf_histogram_avg,
    "prometheus_buckets": tf_prometheus_buckets,
    "buckets_limit": tf_buckets_limit,
    "smooth_exponential": tf_smooth_exponential,
    "bitmap_and": tf_bitmap_and, "bitmap_or": tf_bitmap_or,
    "bitmap_xor": tf_bitmap_xor,
    "sgn": _elementwise(np.sign),
})

# args that must NOT be auto-evaluated to series (string positions are
# detected at eval time via StringExpr)


# -- vmrange histograms + round-2 parity tail ---------------------------------

def _vmrange_to_le(series: list[Timeseries]) -> list[Timeseries]:
    """Convert VM-native vmrange buckets into cumulative Prometheus le=
    buckets (transform.go:494 vmrangeBucketsToLE); le-labeled series pass
    through unchanged."""
    out = []
    groups: dict[bytes, tuple[MetricName, list]] = {}
    for ts in series:
        vr = ts.metric_name.get_label(b"vmrange")
        if not vr:
            if ts.metric_name.get_label(b"le"):
                out.append(ts)
            continue
        sep = vr.find(b"...")
        if sep < 0:
            continue
        try:
            start = float(vr[:sep])
            end = float(vr[sep + 3:])
        except ValueError:
            continue
        mn = MetricName(ts.metric_name.metric_group,
                        [(k, v) for k, v in ts.metric_name.labels
                         if k not in (b"le", b"vmrange")])
        key = mn.marshal()
        if key not in groups:
            groups[key] = (mn, [])
        groups[key][1].append((start, end, vr[:sep], vr[sep + 3:], ts))
    for key, (mn, xss) in groups.items():
        xss.sort(key=lambda x: x[1])
        T = xss[0][4].values.size

        def bucket(le_bytes, vals):
            b = MetricName(mn.metric_group,
                           list(mn.labels) + [(b"le", le_bytes)])
            b.sort_labels()
            return Timeseries(b, vals)

        new: list[tuple[float, bytes, np.ndarray]] = []
        seen_le: dict[bytes, np.ndarray] = {}
        prev_end = 0.0  # reference xsPrev zero-value: start==0 fills nothing
        prev_end_s = None
        nonzero = [x for x in xss
                   if np.nansum(np.nan_to_num(x[4].values)) > 0]
        for start, end, start_s, end_s, ts in nonzero:
            if start != prev_end and start_s not in seen_le:
                z = np.zeros(T)
                seen_le[start_s] = z
                new.append((start, start_s, z))
            vals = ts.values.copy()
            prev = seen_le.get(end_s)
            if prev is not None:
                # duplicate end: merge when non-overlapping, else DROP the
                # later bucket (transform.go:598 discards the merge result;
                # an overlapping duplicate like 0...0.25 over 0...0.2 +
                # 0.2...0.25 must not be double-counted)
                from .binary_op import merge_values_non_overlapping
                merge_values_non_overlapping(prev, vals)
            else:
                seen_le[end_s] = vals
                new.append((end, end_s, vals))
            prev_end, prev_end_s = end, end_s
        if new and prev_end_s is not None and np.isfinite(prev_end):
            new.append((np.inf, b"+Inf", np.zeros(T)))
        if not new:
            continue
        # cumulative counts across ascending le: NaN and non-positive points
        # contribute nothing (transform.go:616)
        acc = np.zeros(T)
        for le, le_s, vals in new:
            acc = acc + np.where(np.isnan(vals) | (vals <= 0), 0.0, vals)
            out.append(bucket(le_s, acc.copy()))
    return out


def _le_share(le_req: float, les: np.ndarray, counts: np.ndarray,
              j: int) -> tuple[float, float, float]:
    """(q, lower, upper) share of counts at or below le_req
    (transform.go:661)."""
    if np.isnan(le_req) or les.size == 0:
        return nan, nan, nan
    if le_req < 0:
        return 0.0, 0.0, 0.0
    if np.isinf(le_req):
        return 1.0, 1.0, 1.0
    v_prev = 0.0
    le_prev = 0.0
    v_last = counts[-1, j]
    if v_last == 0 or np.isnan(v_last):
        return nan, nan, nan
    for b in range(les.size):
        v = counts[b, j]
        le = les[b]
        if le_req >= le:
            v_prev, le_prev = v, le
            continue
        lower = v_prev / v_last
        if np.isinf(le):
            return lower, lower, 1.0
        if le_prev == le_req:
            return lower, lower, lower
        upper = v / v_last
        q = lower + (v - v_prev) / v_last * (le_req - le_prev) / (le - le_prev)
        return q, lower, upper
    return 1.0, 1.0, 1.0


def _grouped_le_matrix(series):
    """[(MetricName-without-le, les asc, counts [B, T] monotone)]"""
    out = []
    for key, (mn, buckets) in _group_buckets(_vmrange_to_le(series)).items():
        buckets.sort(key=lambda b: b[0])
        les = np.array([b[0] for b in buckets])
        m = np.nan_to_num(np.vstack([b[1] for b in buckets]))
        m = np.maximum.accumulate(m, axis=0)  # fix broken buckets
        out.append((mn, les, m))
    return out


def tf_histogram_share(ec, args):
    le_req = _arg_values(args, 0)
    bounds_label = args[2].encode() if len(args) > 2 and \
        isinstance(args[2], str) else None
    out = []
    for mn, les, m in _grouped_le_matrix(args[1]):
        T = m.shape[1]
        le_arr = np.broadcast_to(np.asarray(le_req, dtype=np.float64),
                                 (T,))
        q = np.full(T, nan)
        lo = np.full(T, nan)
        hi = np.full(T, nan)
        for j in range(T):
            q[j], lo[j], hi[j] = _le_share(float(le_arr[j]), les, m, j)
        out.append(Timeseries(mn, q))
        if bounds_label:
            for tag, vals in ((b"lower", lo), (b"upper", hi)):
                b = MetricName(mn.metric_group,
                               [(k, v) for k, v in mn.labels
                                if k != bounds_label] +
                               [(bounds_label, tag)])
                b.sort_labels()
                out.append(Timeseries(b, vals))
    return out


def tf_histogram_fraction(ec, args):
    lower, upper = _arg_values(args, 0), _arg_values(args, 1)
    if np.isscalar(lower) and np.isscalar(upper) and lower >= upper:
        raise ValueError("histogram_fraction: lower le must be < upper le")
    out = []
    for mn, les, m in _grouped_le_matrix(args[2]):
        T = m.shape[1]
        lo_arr = np.broadcast_to(np.asarray(lower, dtype=np.float64), (T,))
        up_arr = np.broadcast_to(np.asarray(upper, dtype=np.float64), (T,))
        vals = np.full(T, nan)
        for j in range(T):
            up, _, _ = _le_share(float(up_arr[j]), les, m, j)
            dn, _, _ = _le_share(float(lo_arr[j]), les, m, j)
            vals[j] = up - dn
        out.append(Timeseries(mn, vals))
    return out


def _hist_stdvar_cols(les: np.ndarray, m: np.ndarray) -> np.ndarray:
    """stdvar over le-bucket midpoints (transform.go:900)."""
    T = m.shape[1]
    out = np.full(T, nan)
    for j in range(T):
        le_prev = v_prev = 0.0
        s = s2 = wtot = 0.0
        for b in range(les.size):
            if np.isinf(les[b]):
                continue
            n = (les[b] + le_prev) / 2
            w = m[b, j] - v_prev
            s += n * w
            s2 += n * n * w
            wtot += w
            le_prev, v_prev = les[b], m[b, j]
        if wtot == 0:
            continue
        avg = s / wtot
        out[j] = max(s2 / wtot - avg * avg, 0.0)
    return out


def tf_histogram_stdvar(ec, args):
    return [Timeseries(mn, _hist_stdvar_cols(les, m))
            for mn, les, m in _grouped_le_matrix(args[0])]


def tf_histogram_stddev(ec, args):
    return [Timeseries(mn, np.sqrt(_hist_stdvar_cols(les, m)))
            for mn, les, m in _grouped_le_matrix(args[0])]


def tf_histogram_quantiles(ec, args):
    dst_label = _string_arg(args, 0).encode()
    phis = [_scalar_arg(args, i) for i in range(1, len(args) - 1)]
    series = args[-1]
    out = []
    for phi in phis:
        rows = tf_histogram_quantile(ec, [phi, list(series)])
        for ts in rows:
            mn = MetricName(ts.metric_name.metric_group,
                            [(k, v) for k, v in ts.metric_name.labels
                             if k != dst_label] +
                            [(dst_label, repr(phi).encode())])
            mn.sort_labels()
            out.append(Timeseries(mn, ts.values))
    return out


def tf_drop_empty_series(ec, args):
    return [ts for ts in args[0] if not np.isnan(ts.values).all()]


def tf_label_graphite_group(ec, args):
    group_ids = [int(_scalar_arg(args, i)) for i in range(1, len(args))]
    out = []
    for ts in args[0]:
        groups = ts.metric_name.metric_group.split(b".")
        parts = [groups[g] if 0 <= g < len(groups) else b""
                 for g in group_ids]
        mn = MetricName(b".".join(parts), list(ts.metric_name.labels))
        out.append(Timeseries(mn, ts.values))
    return out


def tf_range_zscore(ec, args):
    out = []
    with np.errstate(all="ignore"):
        for ts in args[0]:
            sd = np.nanstd(ts.values)
            out.append(Timeseries(ts.metric_name,
                                  (ts.values - np.nanmean(ts.values)) / sd))
    return out


def tf_range_trim_zscore(ec, args):
    z = abs(_scalar_arg(args, 0))
    out = []
    with np.errstate(all="ignore"):
        for ts in args[1]:
            sd = np.nanstd(ts.values)
            avg = np.nanmean(ts.values)
            vals = np.where(np.abs(ts.values - avg) / sd > z, nan, ts.values)
            out.append(Timeseries(ts.metric_name, vals))
    return out


def tf_range_trim_outliers(ec, args):
    k = _scalar_arg(args, 0)
    out = []
    with np.errstate(all="ignore"):
        for ts in args[1]:
            med = np.nanmedian(ts.values)
            mad = np.nanmedian(np.abs(ts.values - med))
            vals = np.where(np.abs(ts.values - med) > k * mad, nan,
                            ts.values)
            out.append(Timeseries(ts.metric_name, vals))
    return out


def tf_range_trim_spikes(ec, args):
    phi = _scalar_arg(args, 0) / 2.0
    out = []
    with np.errstate(all="ignore"):
        for ts in args[1]:
            ok = ts.values[~np.isnan(ts.values)]
            if ok.size == 0:
                out.append(ts)
                continue
            v_min, v_max = np.quantile(ok, [phi, 1 - phi])
            vals = np.where((ts.values > v_max) | (ts.values < v_min), nan,
                            ts.values)
            out.append(Timeseries(ts.metric_name, vals))
    return out


def tf_range_mad(ec, args):
    out = []
    with np.errstate(all="ignore"):
        for ts in args[0]:
            med = np.nanmedian(ts.values)
            mad = np.nanmedian(np.abs(ts.values - med))
            out.append(Timeseries(ts.metric_name,
                                  np.full(ts.values.size, mad)))
    return out


def tf_range_linear_regression(ec, args):
    grid = None
    out = []
    for ts in args[0]:
        if grid is None:
            grid = ec.timestamps()
        t_s = (grid - grid[0]) / 1e3
        ok = ~np.isnan(ts.values)
        if ok.sum() < 1:
            out.append(ts)
            continue
        if ok.sum() == 1:
            out.append(Timeseries(ts.metric_name,
                                  np.full(grid.size, ts.values[ok][0])))
            continue
        k, v0 = np.polyfit(t_s[ok], ts.values[ok], 1)
        out.append(Timeseries(ts.metric_name, v0 + k * t_s))
    return out


def tf_timezone_offset(ec, args):
    import zoneinfo
    import datetime as _dt
    tz_name = _string_arg(args, 0)
    try:
        tz = zoneinfo.ZoneInfo(tz_name)
    except (zoneinfo.ZoneInfoNotFoundError, ValueError) as e:
        raise ValueError(f"cannot load timezone {tz_name!r}: {e}")
    grid = ec.timestamps()
    vals = np.array([
        _dt.datetime.fromtimestamp(t / 1e3, tz).utcoffset().total_seconds()
        for t in grid])
    return [Timeseries(MetricName(b""), vals)]


TRANSFORM_FUNCS.update({
    "drop_empty_series": tf_drop_empty_series,
    "histogram_share": tf_histogram_share,
    "histogram_fraction": tf_histogram_fraction,
    "histogram_stddev": tf_histogram_stddev,
    "histogram_stdvar": tf_histogram_stdvar,
    "histogram_quantiles": tf_histogram_quantiles,
    "label_graphite_group": tf_label_graphite_group,
    "range_zscore": tf_range_zscore,
    "range_trim_zscore": tf_range_trim_zscore,
    "range_trim_outliers": tf_range_trim_outliers,
    "range_trim_spikes": tf_range_trim_spikes,
    "range_mad": tf_range_mad,
    "range_linear_regression": tf_range_linear_regression,
    "timezone_offset": tf_timezone_offset,
})
