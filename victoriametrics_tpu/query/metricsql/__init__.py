from .ast import (AggrFuncExpr, BinaryOpExpr, DurationExpr, FuncExpr,
                  MetricExpr, NumberExpr, RollupExpr, StringExpr)
from .parser import parse, ParseError

__all__ = ["parse", "ParseError", "AggrFuncExpr", "BinaryOpExpr",
           "DurationExpr", "FuncExpr", "MetricExpr", "NumberExpr",
           "RollupExpr", "StringExpr"]
