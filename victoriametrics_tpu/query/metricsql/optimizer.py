"""Common-filter pushdown optimizer (reference metricsql optimizer.go:16
``Optimize``): adds missing label filters to both sides of binary
operations — ``foo{a="x"} + bar`` becomes ``foo{a="x"} + bar{a="x"}`` —
so every selector under a binary op fetches only the series that can
survive the label-matched join.  A storage-traffic reduction that feeds
the shared-selector materialization plane: fewer series fetched per
distinct expression means cheaper streams for everyone subscribed.

Soundness rules mirror the reference:

- pushdown applies per binary op, using the COMMON label filters of the
  op's result (``getCommonLabelFilters``): the union of both sides'
  filters for label-matched ops, the left side only for
  ``unless``/``ifnot``/``default`` (the right side never shapes the
  result's series set), the intersection for ``or`` (either side alone
  may produce a result series);
- ``on (...)`` / ``ignoring (...)`` modifiers trim the pushed filters to
  labels that actually participate in the match; ``group_left``/
  ``group_right`` keep only the "one" side's filters;
- aggregations propagate filters through ``by (...)``/``without (...)``
  the same way; a modifier-less aggregation blocks propagation (its
  output drops all labels);
- ``__name__`` filters never push (they name the OTHER metric);
- label-manipulating transforms (``label_set``, ``label_replace``, ...)
  and series-shape functions (``absent*``, ``scalar``, ``vector``, ...)
  block propagation through themselves.

``optimize()`` deep-copies before mutating — parse results may share
nodes (WITH-template expansion).  ``VM_MQL_OPTIMIZE=0`` disables the
pass at the ``parse_cached`` seam (escape hatch AND equality oracle:
optimized and unoptimized evaluations must return identical rows).
"""

from __future__ import annotations

import copy

from .ast import (AggrFuncExpr, BinaryOpExpr, Expr, FuncExpr, LabelFilter,
                  MetricExpr, RollupExpr)

#: transforms that rewrite labels: filters must not cross them in either
#: direction (a filter valid on the output may not hold on the input)
_LABEL_MANIPULATION_FUNCS = frozenset((
    "alias", "drop_common_labels", "label_copy", "label_del",
    "label_graphite_group", "label_join", "label_keep", "label_lowercase",
    "label_map", "label_match", "label_mismatch", "label_move",
    "label_replace", "label_set", "label_transform", "label_uppercase",
    "label_value",
))

#: transforms whose output series set is unrelated to any selector arg
_OPAQUE_TRANSFORMS = frozenset((
    "", "absent", "scalar", "union", "vector", "range_normalize",
    "end", "now", "pi", "ru", "start", "step", "time",
    "count_values_over_time",
))


def _is_rollup_func(name: str) -> bool:
    from ..rollup_funcs import GENERIC_FUNCS, MULTI_FUNCS, ORACLE_FUNCS
    return (name in ORACLE_FUNCS or name in GENERIC_FUNCS
            or name in MULTI_FUNCS)


def _func_arg_idx(name: str, nargs: int) -> int:
    """Index of the series arg filters may cross, or -1 (reference
    ``getFuncArgIdxForOptimization``)."""
    name = name.lower()
    if _is_rollup_func(name):
        if name == "absent_over_time":
            return -1
        if name in ("quantile_over_time", "aggr_over_time",
                    "hoeffding_bound_lower", "hoeffding_bound_upper"):
            return 1
        if name == "quantiles_over_time":
            return nargs - 1
        return 0
    if name in _LABEL_MANIPULATION_FUNCS or name in _OPAQUE_TRANSFORMS:
        return -1
    if name == "limit_offset":
        return 2
    if name in ("buckets_limit", "histogram_quantile", "histogram_share",
                "range_quantile"):
        return 1
    if name == "histogram_quantiles":
        return nargs - 1
    return 0


_LAST_ARG_AGGRS = frozenset((
    "bottomk", "bottomk_avg", "bottomk_max", "bottomk_median",
    "bottomk_min", "bottomk_last", "limitk", "outliers_iqr", "outliersk",
    "quantile", "topk", "topk_avg", "topk_max", "topk_median", "topk_min",
    "topk_last",
))


def _aggr_arg_idx(name: str, nargs: int) -> int:
    """Index of an aggregation's series arg (reference
    ``getAggrArgIdxForOptimization``): scalar-first aggrs take the last
    arg; ``count_values`` relabels and blocks propagation."""
    name = name.lower()
    if name in _LAST_ARG_AGGRS:
        return nargs - 1
    if name == "count_values":
        return -1
    return 0


def _series_arg(e) -> Expr | None:
    if isinstance(e, AggrFuncExpr):
        idx = _aggr_arg_idx(e.name, len(e.args))
    else:
        idx = _func_arg_idx(e.name, len(e.args))
    if idx < 0 or idx >= len(e.args):
        return None
    return e.args[idx]


def _fkey(f: LabelFilter) -> tuple:
    return (f.label, f.value, f.is_negative, f.is_regexp)


def _intersect(a: list[LabelFilter], b: list[LabelFilter]):
    keys = {_fkey(f) for f in b}
    return [f for f in a if _fkey(f) in keys]


def _union(a: list[LabelFilter], b: list[LabelFilter]):
    out = list(a)
    keys = {_fkey(f) for f in a}
    for f in b:
        if _fkey(f) not in keys:
            keys.add(_fkey(f))
            out.append(f)
    return out


def _trim_on(lfs: list[LabelFilter], labels: list[str]):
    keep = set(labels)
    return [f for f in lfs if f.label in keep]


def _trim_ignoring(lfs: list[LabelFilter], labels: list[str]):
    drop = set(labels)
    return [f for f in lfs if f.label not in drop]


def _trim_by_group_modifier(lfs, be: BinaryOpExpr):
    op = be.group_modifier.op.lower()
    if op == "on":
        return _trim_on(lfs, be.group_modifier.args)
    if op == "ignoring":
        return _trim_ignoring(lfs, be.group_modifier.args)
    return lfs


def _trim_by_aggr_modifier(lfs, ae: AggrFuncExpr):
    if ae.without:
        return _trim_ignoring(lfs, ae.grouping)
    if ae.grouping:
        return _trim_on(lfs, ae.grouping)
    # modifier-less aggregation: every label is dropped from the output
    return []


def _common_filters(e: Expr) -> list[LabelFilter]:
    """Label filters every output series of `e` is known to satisfy
    (``__name__`` excluded)."""
    if isinstance(e, MetricExpr):
        sets = e.filter_sets()
        lfs = [f for f in sets[0] if f.label != "__name__"]
        for fs in sets[1:]:
            lfs = _intersect(lfs, [f for f in fs if f.label != "__name__"])
        return lfs
    if isinstance(e, RollupExpr):
        return _common_filters(e.expr)
    if isinstance(e, AggrFuncExpr):
        arg = _series_arg(e)
        if arg is None:
            return []
        return _trim_by_aggr_modifier(_common_filters(arg), e)
    if isinstance(e, FuncExpr):
        arg = _series_arg(e)
        if arg is None:
            return []
        return _common_filters(arg)
    if isinstance(e, BinaryOpExpr):
        left = _common_filters(e.left)
        right = _common_filters(e.right)
        op = e.op.lower()
        if op == "or":
            lfs = _intersect(left, right)
        elif op in ("unless", "ifnot", "default"):
            lfs = left if not e.join_modifier.op else []
        else:
            jm = e.join_modifier.op.lower()
            if jm == "group_left":
                lfs = left
            elif jm == "group_right":
                lfs = right
            else:
                lfs = _union(left, right)
        return _trim_by_group_modifier(lfs, e)
    return []


def _sort_filters(fs: list[LabelFilter]) -> list[LabelFilter]:
    """Canonical order for a mutated set: the literal name filter stays
    first (the parser puts it there and ``__str__``/name-resolution rely
    on it), everything else sorts by (label, value, op)."""
    head: list[LabelFilter] = []
    rest = fs
    if fs and fs[0].label == "__name__":
        head, rest = fs[:1], fs[1:]
    return head + sorted(
        rest, key=lambda f: (f.label, f.value, f.is_negative, f.is_regexp))


def _pushdown(e: Expr, lfs: list[LabelFilter]) -> None:
    if not lfs:
        return
    if isinstance(e, MetricExpr):
        sets = [e.label_filters] + e.or_sets if e.or_sets \
            else [e.label_filters]
        new_sets = []
        for fs in sets:
            have = {_fkey(f) for f in fs}
            add = [copy.copy(f) for f in lfs if _fkey(f) not in have]
            new_sets.append(_sort_filters(fs + add) if add else fs)
        e.label_filters = new_sets[0]
        if e.or_sets:
            e.or_sets = new_sets[1:]
        return
    if isinstance(e, RollupExpr):
        _pushdown(e.expr, lfs)
        return
    if isinstance(e, AggrFuncExpr):
        lfs = _trim_by_aggr_modifier(lfs, e)
        arg = _series_arg(e)
        if arg is not None:
            _pushdown(arg, lfs)
        return
    if isinstance(e, FuncExpr):
        arg = _series_arg(e)
        if arg is not None:
            _pushdown(arg, lfs)
        return
    if isinstance(e, BinaryOpExpr):
        # both sides take the filters for EVERY op: the asymmetry lives
        # entirely in _common_filters (what may be claimed of the
        # result).  Pushing result filters into the subtractive side of
        # unless/ifnot/default is sound — a right-side series only
        # matters where its labels match a surviving left-side series,
        # which satisfies the filters by construction.
        lfs = _trim_by_group_modifier(lfs, e)
        _pushdown(e.left, lfs)
        _pushdown(e.right, lfs)
        return


def _optimize_inplace(e: Expr) -> None:
    if isinstance(e, RollupExpr):
        _optimize_inplace(e.expr)
        return
    if isinstance(e, (FuncExpr, AggrFuncExpr)):
        for a in e.args:
            _optimize_inplace(a)
        return
    if isinstance(e, BinaryOpExpr):
        _optimize_inplace(e.left)
        _optimize_inplace(e.right)
        lfs = _common_filters(e)
        _pushdown(e, lfs)
        return


def _can_optimize(e: Expr) -> bool:
    if isinstance(e, BinaryOpExpr):
        return True
    if isinstance(e, RollupExpr):
        return _can_optimize(e.expr)
    if isinstance(e, (FuncExpr, AggrFuncExpr)):
        return any(_can_optimize(a) for a in e.args)
    return False


def optimize(e: Expr) -> Expr:
    """Returns `e` with common label filters pushed across binary ops;
    the input AST is never mutated (a deep copy is optimized in place —
    parse results may share nodes via WITH-template expansion)."""
    if not _can_optimize(e):
        return e
    out = copy.deepcopy(e)
    _optimize_inplace(out)
    return out
