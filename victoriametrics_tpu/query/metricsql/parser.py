"""MetricsQL lexer + recursive-descent parser.

Grammar semantics follow the vendored metricsql package (parser.go:15,
lexer.go): full PromQL plus the MetricsQL extensions used in practice —
`default`/`if`/`ifnot` binary ops, duration literals as scalars, step-based
durations (`5i`), numeric suffixes (Ki/Mi/...), bare-number windows
(seconds), `keep_metric_names`, `limit N` on aggregates, WITH-expression
templates, `@` modifier, subqueries `[1h:5m]`.
"""

from __future__ import annotations

import re

from .ast import (AggrFuncExpr, BinaryOpExpr, DurationExpr, Expr, FuncExpr,
                  LabelFilter, MetricExpr, ModifierExpr, NumberExpr,
                  RollupExpr, StringExpr, WithExpr)


class ParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Lexer
# ---------------------------------------------------------------------------

# no leading ":" — it would swallow the subquery separator in "[1h:1m]"
_IDENT_RE = re.compile(r"[a-zA-Z_][a-zA-Z0-9_:.]*")
_DURATION_RE = re.compile(
    r"(?:\d+(?:\.\d+)?(?:[mM][sS]|[smhdwyiSMHDWYI]))+")
# Numeric size suffixes are uppercase only (K/M/G/T, Ki/Mi/...): lowercase
# m/s/h/d/w/y are duration units and must stay distinct ("5m" = 5 minutes).
_NUMBER_RE = re.compile(
    r"0[xX][0-9a-fA-F]+|0[bB][01]+|0[oO][0-7]+"
    r"|(?:\d[\d_]*(?:\.[\d_]*)?|\.\d[\d_]*)(?:[eE][+-]?\d+)?"
    r"(?:[KMGT]i?B?)?")
_OPS = ["==", "!=", ">=", "<=", "=~", "!~", "+", "-", "*", "/", "%", "^",
        ">", "<", "=", "(", ")", "{", "}", "[", "]", ",", "@", ":"]

_SUFFIX = {"K": 1e3, "Ki": 1024.0, "M": 1e6, "Mi": 1024.0 ** 2,
           "G": 1e9, "Gi": 1024.0 ** 3, "T": 1e12, "Ti": 1024.0 ** 4}

_DUR_UNIT_MS = {"ms": 1.0, "s": 1e3, "m": 60e3, "h": 3600e3, "d": 86400e3,
                "w": 7 * 86400e3, "y": 365 * 86400e3}


class Token:
    __slots__ = ("kind", "text", "pos")

    def __init__(self, kind: str, text: str, pos: int):
        self.kind = kind      # ident|number|duration|string|op|eof
        self.text = text
        self.pos = pos

    def __repr__(self):
        return f"Token({self.kind}, {self.text!r})"


def tokenize(q: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(q)
    while i < n:
        c = q[i]
        if c in " \t\r\n":
            i += 1
            continue
        if c == "#":
            while i < n and q[i] != "\n":
                i += 1
            continue
        if c in "\"'":
            j = i + 1
            buf = []
            while j < n and q[j] != c:
                if q[j] == "\\" and j + 1 < n:
                    esc = q[j + 1]
                    buf.append({"n": "\n", "t": "\t", "r": "\r",
                                "\\": "\\", '"': '"', "'": "'"}.get(esc, "\\" + esc))
                    j += 2
                else:
                    buf.append(q[j])
                    j += 1
            if j >= n:
                raise ParseError(f"unterminated string at {i}")
            toks.append(Token("string", "".join(buf), i))
            i = j + 1
            continue
        if c.isdigit() or (c == "." and i + 1 < n and q[i + 1].isdigit()):
            m = _DURATION_RE.match(q, i)
            # duration wins only if it consumes more than the bare number
            nm = _NUMBER_RE.match(q, i)
            if m and (not nm or m.end() > nm.end()):
                toks.append(Token("duration", m.group(0), i))
                i = m.end()
                continue
            if nm:
                toks.append(Token("number", nm.group(0), i))
                i = nm.end()
                continue
        im = _IDENT_RE.match(q, i)
        if im:
            toks.append(Token("ident", im.group(0), i))
            i = im.end()
            continue
        for op in _OPS:
            if q.startswith(op, i):
                toks.append(Token("op", op, i))
                i += len(op)
                break
        else:
            raise ParseError(f"unexpected character {c!r} at {i}")
    toks.append(Token("eof", "", n))
    return toks


def parse_number(text: str) -> float:
    text = text.replace("_", "")
    low = text.lower()
    if low.startswith("0x"):
        return float(int(text, 16))
    if low.startswith("0b"):
        return float(int(text, 2))
    if low.startswith("0o"):
        return float(int(text, 8))
    if text.endswith("B"):
        text = text[:-1]
    for suf in ("Ki", "Mi", "Gi", "Ti"):
        if text.endswith(suf):
            return float(text[:-2]) * _SUFFIX[suf]
    if text and text[-1] in "KMGT":
        return float(text[:-1]) * _SUFFIX[text[-1]]
    return float(text)


def parse_duration_ms(text: str) -> tuple[float, bool]:
    """Returns (ms, step_based). Units are case-insensitive except the
    number/size ambiguity handled by the lexer."""
    if text.endswith(("i", "I")) and not text.lower().endswith("mi"):
        # step-based like 5i (possibly fractional)
        return float(text[:-1]), True
    total = 0.0
    for num, unit in re.findall(r"(\d+(?:\.\d+)?)(ms|s|m|h|d|w|y)",
                                text.lower()):
        total += float(num) * _DUR_UNIT_MS[unit]
    return total, False


# ---------------------------------------------------------------------------
# Parser
# ---------------------------------------------------------------------------

AGGR_FUNC_NAMES = frozenset("""
sum min max avg stddev stdvar count count_values bottomk topk quantile
quantiles group median mode limitk distinct sum2 geomean histogram any
topk_min topk_max topk_avg topk_median topk_last bottomk_min bottomk_max
bottomk_avg bottomk_median bottomk_last outliersk outliers_mad outliers_iqr
zscore share mad iqr
""".split())

_RIGHT_ASSOC = {"^"}

# precedence levels, low to high
_BINOPS = [
    {"or", "default", "if", "ifnot"},
    {"and", "unless"},
    {"==", "!=", ">", "<", ">=", "<="},
    {"+", "-"},
    {"*", "/", "%", "atan2"},
    {"^"},
]
_ALL_BINOPS = set().union(*_BINOPS)


class Parser:
    def __init__(self, q: str):
        self.toks = tokenize(q)
        self.i = 0
        self.with_scopes: list[dict[str, tuple[list[str], Expr]]] = [
            _default_with_scope()]

    # -- token helpers -------------------------------------------------

    @property
    def tok(self) -> Token:
        return self.toks[self.i]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect_op(self, op: str):
        t = self.next()
        if t.kind != "op" or t.text != op:
            raise ParseError(f"expected {op!r}, got {t.text!r} at {t.pos}")

    def at_op(self, *ops) -> bool:
        return self.tok.kind == "op" and self.tok.text in ops

    def at_keyword(self, *kws) -> bool:
        return self.tok.kind == "ident" and self.tok.text.lower() in kws

    # -- entry ----------------------------------------------------------

    def parse(self) -> Expr:
        e = self.parse_expr(0)
        if self.tok.kind != "eof":
            raise ParseError(f"unexpected {self.tok.text!r} at {self.tok.pos}")
        return e

    def parse_expr(self, level: int = 0) -> Expr:
        if level >= len(_BINOPS):
            return self.parse_unary()
        left = self.parse_expr(level + 1)
        while True:
            op = None
            if self.at_op(*_BINOPS[level]):
                op = self.next().text
            elif self.tok.kind == "ident" and \
                    self.tok.text.lower() in _BINOPS[level]:
                op = self.next().text.lower()
            if op is None:
                return left
            be = BinaryOpExpr(op=op, left=left)
            if self.at_keyword("bool"):
                self.next()
                be.bool_modifier = True
            if self.at_keyword("on", "ignoring"):
                be.group_modifier = ModifierExpr(self.next().text.lower(),
                                                 self.parse_ident_list())
            if self.at_keyword("group_left", "group_right"):
                kw = self.next().text.lower()
                args = []
                if self.at_op("("):
                    args = self.parse_ident_list(allow_star=True)
                be.join_modifier = ModifierExpr(kw, args)
                if self.at_keyword("prefix"):
                    # group_left(...) prefix "p": copied join tags get the
                    # prefix (Go parser.go:393 JoinModifierPrefix)
                    self.next()
                    t = self.next()
                    if t.kind != "string":
                        raise ParseError(
                            f"prefix needs a string at {t.pos}")
                    be.join_modifier.prefix = t.text
            if op in _RIGHT_ASSOC:
                be.right = self.parse_expr(level)  # right-assoc
            else:
                be.right = self.parse_expr(level + 1)
            # keep_metric_names after the right operand attaches to the
            # BINOP (Go metricsql parser.go:410); a real function call
            # consumes its own flag before we get here (parser.go:1210)
            if self.at_keyword("keep_metric_names"):
                self.next()
                be.keep_metric_names = True
            left = be
        # unreachable

    def parse_unary(self) -> Expr:
        if self.at_op("-"):
            self.next()
            # unary minus binds looser than ^: -4^0.5 == -(4^0.5)
            arg = self.parse_expr(len(_BINOPS) - 1)
            if isinstance(arg, NumberExpr):
                return NumberExpr(-arg.value)
            e = BinaryOpExpr(op="*", left=NumberExpr(-1.0), right=arg)
            return self.parse_postfix(e)
        if self.at_op("+"):
            self.next()
            return self.parse_unary()
        return self.parse_postfix(self.parse_primary())

    # -- postfix: [window[:step]], offset, @, keep_metric_names ----------

    def parse_postfix(self, e: Expr) -> Expr:
        if self.at_keyword("keep_metric_names"):
            # a real function call owns its flag (Go parser.go:1210); a
            # parenthesized binop too (parser.go:602); anything else
            # leaves the token for the enclosing binop (parser.go:410)
            parens = getattr(e, "_parens", False)
            if isinstance(e, FuncExpr) and not parens:
                self.next()
                e.keep_metric_names = True
            elif isinstance(e, BinaryOpExpr) and parens:
                self.next()
                e.keep_metric_names = True
            else:
                return e
        if self.at_op("[", "@") or self.at_keyword("offset"):
            return self._parse_rollup_suffix(e)
        return e

    def _parse_rollup_suffix(self, e: Expr) -> RollupExpr:
        """Go parser.go:1783 parseRollupExpr: a fixed SEQUENCE (not a loop) —
        optional [window[:step]], then optional `@`, then optional offset,
        then optionally a second `@` spot (duplicate `@` is an error). A
        suffix in any other order is left unconsumed and errors upstream."""
        re_ = RollupExpr(expr=e)
        if self.at_op("["):
            self.next()
            window = step = None
            inherit = False
            if not self.at_op(":"):
                window = self.parse_duration_token()
            if self.at_op(":"):
                self.next()
                if self.at_op("]"):
                    inherit = True
                else:
                    step = self.parse_duration_token()
            self.expect_op("]")
            re_.window, re_.step, re_.inherit_step = window, step, inherit
            if not (self.at_op("@") or self.at_keyword("offset")):
                return re_
        if self.at_op("@"):
            self.next()
            re_.at = self._parse_at_expr()
        if self.at_keyword("offset"):
            self.next()
            neg = False
            if self.at_op("-"):
                self.next()
                neg = True
            d = self.parse_duration_token()
            if neg:
                d = DurationExpr(-d.ms, d.step_based, "-" + d.text)
            re_.offset = d
        if self.at_op("@"):
            if re_.at is not None:
                raise ParseError("duplicate `@` token")
            self.next()
            re_.at = self._parse_at_expr()
        return re_

    def _parse_at_expr(self) -> Expr:
        # the at-expression takes no rollup suffixes: a trailing
        # `offset`/`[...]` binds to the OUTER rollup, so
        # `time() @ end() offset 10m` is (time() @ end()) offset 10m
        # (metricsql parser.go parseSingleExprWithoutRollupSuffix)
        if self.at_op("-"):
            self.next()
            prim = self.parse_primary()
            return (NumberExpr(-prim.value)
                    if isinstance(prim, NumberExpr) else
                    BinaryOpExpr(op="*", left=NumberExpr(-1.0), right=prim))
        return self.parse_primary()

    def parse_duration_token(self) -> DurationExpr:
        t = self.next()
        if t.kind == "duration":
            ms, step_based = parse_duration_ms(t.text)
            return DurationExpr(ms, step_based, t.text)
        if t.kind == "number":
            # bare number = seconds (MetricsQL extension)
            return DurationExpr(parse_number(t.text) * 1e3, False, t.text)
        if t.kind == "ident":
            # WITH-bound duration name
            resolved = self._resolve_with(t.text)
            if isinstance(resolved, DurationExpr):
                return resolved
            if isinstance(resolved, NumberExpr):
                return DurationExpr(resolved.value * 1e3, False, "")
        raise ParseError(f"expected duration, got {t.text!r} at {t.pos}")

    # -- primaries --------------------------------------------------------

    def parse_primary(self) -> Expr:
        t = self.tok
        if t.kind == "number":
            self.next()
            return NumberExpr(parse_number(t.text))
        if t.kind == "duration":
            self.next()
            ms, step_based = parse_duration_ms(t.text)
            return DurationExpr(ms, step_based, t.text)
        if t.kind == "string":
            self.next()
            return StringExpr(t.text)
        if t.kind == "op" and t.text == "(":
            self.next()
            if self.at_op(")"):
                # `()` is an empty union (exec_test.go `()` case)
                self.next()
                return FuncExpr(name="union", args=[])
            e = self.parse_expr(0)
            if self.at_op(","):
                # (e1, e2, ...) is union(e1, e2, ...) in MetricsQL
                exprs = [e]
                while self.at_op(","):
                    self.next()
                    if self.at_op(")"):
                        break
                    exprs.append(self.parse_expr(0))
                self.expect_op(")")
                u = FuncExpr(name="union", args=exprs)
                u._parens = True
                return u
            self.expect_op(")")
            e._parens = True
            return e
        if t.kind == "op" and t.text == "{":
            sets = self.parse_label_filters()
            return MetricExpr(label_filters=sets[0], or_sets=sets[1:])
        if t.kind == "ident":
            return self.parse_ident_expr()
        raise ParseError(f"unexpected token {t.text!r} at {t.pos}")

    def parse_ident_expr(self) -> Expr:
        name = self.next().text
        low = name.lower()
        if low in ("nan",):
            return NumberExpr(float("nan"))
        if low in ("inf", "+inf"):
            return NumberExpr(float("inf"))
        if low == "with" and self.at_op("("):
            return self.parse_with_expr()

        # WITH-template reference?
        w = self._lookup_with(name)
        if w is not None:
            params, body = w
            if params:
                # function-like template
                self.expect_op("(")
                args = [self.parse_expr(0)]
                while self.at_op(","):
                    self.next()
                    args.append(self.parse_expr(0))
                self.expect_op(")")
                return _substitute(body, dict(zip(params, args)))
            return _clone(body)

        if self.at_op("("):
            if low in AGGR_FUNC_NAMES:
                ae = AggrFuncExpr(name=low)
                ae.args = self.parse_arg_list()
                self.parse_aggr_modifiers(ae)
                return ae
            fe = FuncExpr(name=low)
            fe.args = self.parse_arg_list()
            return fe
        if self.at_keyword("by", "without") and low in AGGR_FUNC_NAMES:
            # sum by (x) (q) form
            ae = AggrFuncExpr(name=low)
            self.parse_aggr_modifiers(ae)
            ae.args = self.parse_arg_list()
            # allow trailing modifiers too (limit)
            self.parse_aggr_modifiers(ae, allow_grouping=False)
            return ae
        # plain metric selector; the name distributes over every OR'd
        # filter set: foo{a="b" or c="d"} == {__name__="foo",a="b"} union
        # {__name__="foo",c="d"} (metricsql parser.go)
        if self.at_op("{"):
            sets = self.parse_label_filters()
            return MetricExpr(
                label_filters=[LabelFilter("__name__", name)] + sets[0],
                or_sets=[[LabelFilter("__name__", name)] + fs
                         for fs in sets[1:]])
        return MetricExpr(label_filters=[LabelFilter("__name__", name)])

    def parse_arg_list(self) -> list[Expr]:
        self.expect_op("(")
        args: list[Expr] = []
        if self.at_op(")"):
            self.next()
            return args
        args.append(self.parse_expr(0))
        while self.at_op(","):
            self.next()
            if self.at_op(")"):
                break
            args.append(self.parse_expr(0))
        self.expect_op(")")
        return args

    def parse_aggr_modifiers(self, ae: AggrFuncExpr, allow_grouping=True):
        while True:
            if allow_grouping and self.at_keyword("by", "without"):
                kw = self.next().text.lower()
                ae.grouping = self.parse_ident_list()
                ae.without = kw == "without"
            elif self.at_keyword("limit"):
                self.next()
                t = self.next()
                if t.kind != "number":
                    raise ParseError(f"expected number after limit at {t.pos}")
                ae.limit = int(parse_number(t.text))
            else:
                return

    def parse_ident_list(self, allow_star: bool = False) -> list[str]:
        self.expect_op("(")
        if allow_star and self.at_op("*"):
            # `*` is valid only in group_left(*)/group_right(*) and only as
            # the SOLE element: copy ALL tags from the one side
            # (Go parser.go parseIdentList allowStar, metric_name.go:318)
            self.next()
            self.expect_op(")")
            return ["*"]
        out = []
        while not self.at_op(")"):
            t = self.next()
            if t.kind not in ("ident", "string"):
                raise ParseError(f"expected label name at {t.pos}")
            out.append(t.text)
            if self.at_op(","):
                self.next()
        self.expect_op(")")
        return out

    def parse_label_filters(self) -> list[list[LabelFilter]]:
        """{f, f or f, f} -> list of OR'd filter sets (>= 1): the
        selector-level `or` (reference metricsql parser.go labelFilterss)
        separates complete filter sets; a series matches when ANY set
        matches.  A label literally named `or` still parses ({or="x"}):
        the keyword is only a separator BETWEEN filters."""
        self.expect_op("{")
        sets: list[list[LabelFilter]] = [[]]
        while not self.at_op("}"):
            t = self.next()
            if t.kind not in ("ident", "string"):
                raise ParseError(f"expected label name at {t.pos}")
            label = t.text
            op_t = self.next()
            if op_t.kind != "op" or op_t.text not in ("=", "!=", "=~", "!~"):
                raise ParseError(f"expected label op at {op_t.pos}")
            v = self.next()
            if v.kind != "string":
                # allow WITH-bound string/number
                if v.kind == "ident":
                    r = self._resolve_with(v.text)
                    if isinstance(r, StringExpr):
                        v = Token("string", r.value, v.pos)
                    else:
                        raise ParseError(f"expected string at {v.pos}")
                else:
                    raise ParseError(f"expected string at {v.pos}")
            sets[-1].append(LabelFilter(label, v.text,
                                        is_negative=op_t.text in ("!=", "!~"),
                                        is_regexp=op_t.text in ("=~", "!~")))
            if self.at_op(","):
                self.next()
            elif self.at_keyword("or"):
                kw = self.next()
                if self.at_op("}"):
                    raise ParseError(
                        f"missing label filters after `or` at {kw.pos}")
                sets.append([])
        self.expect_op("}")
        return sets

    # -- WITH templates ----------------------------------------------------

    def parse_with_expr(self) -> Expr:
        self.expect_op("(")
        scope: dict[str, tuple[list[str], Expr]] = {}
        self.with_scopes.append(scope)
        try:
            while not self.at_op(")"):
                nt = self.next()
                if nt.kind != "ident":
                    raise ParseError(f"expected WITH name at {nt.pos}")
                params: list[str] = []
                if self.at_op("("):
                    params = self.parse_ident_list()
                self.expect_op("=")
                body = self.parse_expr(0)
                scope[nt.text] = (params, body)
                if self.at_op(","):
                    self.next()
            self.expect_op(")")
            body = self.parse_expr(0)
        finally:
            self.with_scopes.pop()
        return body

    def _lookup_with(self, name: str):
        for scope in reversed(self.with_scopes):
            if name in scope:
                return scope[name]
        return None

    def _resolve_with(self, name: str) -> Expr | None:
        w = self._lookup_with(name)
        if w is None:
            return None
        params, body = w
        if params:
            return None
        return body


def _clone(e: Expr) -> Expr:
    import copy
    return copy.deepcopy(e)


_DEFAULT_WITH_SOURCES = {
    # builtin WITH templates (metricsql parser.go:56-71)
    "ru": (["freev", "maxv"],
           "clamp_min(maxv - clamp_min(freev, 0), 0) / "
           "clamp_min(maxv, 0) * 100"),
    "ttf": (["freev"],
            "smooth_exponential(clamp_max(clamp_max(-freev, 0) / "
            "clamp_max(deriv_fast(freev), 0), 365*24*3600), "
            "clamp_max(step()/300, 1))"),
    "range_median": (["q"], "range_quantile(0.5, q)"),
    "alias": (["q", "name"], 'label_set(q, "__name__", name)'),
}
_default_with: dict | None = None


def _default_with_scope() -> dict:
    global _default_with
    if _default_with is None:
        _default_with = {}  # set first: template bodies may reference others
        for name, (params, src) in _DEFAULT_WITH_SOURCES.items():
            _default_with[name] = (params, Parser(src).parse_expr(0))
    return _default_with


def _substitute(e: Expr, bindings: dict[str, Expr]) -> Expr:
    """Replace bare metric selectors whose name is a template param."""
    import copy
    e = copy.deepcopy(e)

    def walk(x):
        if isinstance(x, MetricExpr):
            nm = x.metric_name
            if nm in bindings and len(x.label_filters) == 1:
                return copy.deepcopy(bindings[nm])
            return x
        for field in getattr(x, "__dataclass_fields__", {}):
            v = getattr(x, field)
            if isinstance(v, Expr):
                setattr(x, field, walk(v))
            elif isinstance(v, list):
                setattr(x, field, [walk(a) if isinstance(a, Expr) else a
                                   for a in v])
        return x

    return walk(e)


def parse(q: str) -> Expr:
    """Parse a MetricsQL query into an AST (metricsql.Parse analog)."""
    if not q or not q.strip():
        raise ParseError("empty query")
    return Parser(q).parse()
