"""MetricsQL AST (semantics of the vendored metricsql package's Expr types,
parser.go:1877-2299 — re-designed as plain Python dataclasses).

All expressions render back to canonical query strings via str(); the
canonical form is also the rollup-result-cache key.
"""

from __future__ import annotations

import dataclasses


class Expr:
    def __str__(self) -> str:  # pragma: no cover - overridden
        raise NotImplementedError


@dataclasses.dataclass
class NumberExpr(Expr):
    value: float

    def __str__(self):
        v = self.value
        if v != v:
            return "NaN"
        if v in (float("inf"), float("-inf")):
            return "+Inf" if v > 0 else "-Inf"
        if v == int(v) and abs(v) < 1e15:
            return str(int(v))
        return repr(v)


@dataclasses.dataclass
class StringExpr(Expr):
    value: str

    def __str__(self):
        return '"' + self.value.replace("\\", "\\\\").replace('"', '\\"') + '"'


@dataclasses.dataclass
class DurationExpr(Expr):
    """Duration in milliseconds; step-relative if `step_based` (e.g. "5i")."""
    ms: float
    step_based: bool = False
    text: str = ""

    def value_ms(self, step_ms: int) -> int:
        return int(self.ms * step_ms) if self.step_based else int(self.ms)

    def __str__(self):
        return self.text or f"{int(self.ms)}ms"


@dataclasses.dataclass
class LabelFilter:
    label: str          # "__name__" for the metric name
    value: str
    is_negative: bool = False
    is_regexp: bool = False

    def op(self) -> str:
        return {(False, False): "=", (True, False): "!=",
                (False, True): "=~", (True, True): "!~"}[
            (self.is_negative, self.is_regexp)]

    def __str__(self):
        v = self.value.replace("\\", "\\\\").replace('"', '\\"')
        return f'{self.label}{self.op()}"{v}"'


@dataclasses.dataclass
class MetricExpr(Expr):
    label_filters: list[LabelFilter] = dataclasses.field(default_factory=list)
    # additional OR'd filter sets: `{a="b" or c="d"}` parses into
    # label_filters=[a="b"], or_sets=[[c="d"]] — the reference metricsql's
    # labelFilterss union (selectors match series satisfying ANY set)
    or_sets: list[list[LabelFilter]] = dataclasses.field(
        default_factory=list)

    @property
    def metric_name(self) -> str | None:
        for f in self.label_filters:
            if f.label == "__name__" and not f.is_negative and not f.is_regexp:
                return f.value
        return None

    def filter_sets(self) -> list[list[LabelFilter]]:
        """All OR'd filter sets (always >= 1; single-set selectors return
        [label_filters])."""
        if not self.or_sets:
            return [self.label_filters]
        return [self.label_filters] + self.or_sets

    def is_empty(self) -> bool:
        return not self.label_filters and not self.or_sets

    @staticmethod
    def _literal_name(fs: list[LabelFilter]) -> str | None:
        if fs and fs[0].label == "__name__" and not fs[0].is_negative \
                and not fs[0].is_regexp:
            return fs[0].value
        return None

    def __str__(self):
        sets = self.filter_sets()
        if len(sets) > 1:
            # shared leading literal name renders once: foo{a="b" or c="d"}
            # — but only when every set keeps at least one more filter (a
            # name-only set would render a dangling ` or ` that can't
            # re-parse; such selectors take the general form below)
            name = self._literal_name(sets[0])
            if name is not None and all(
                    self._literal_name(fs) == name and len(fs) > 1
                    for fs in sets):
                body = " or ".join(
                    ", ".join(str(f) for f in fs[1:]) for fs in sets)
                return name + "{" + body + "}"
            return "{" + " or ".join(
                ", ".join(str(f) for f in fs) for fs in sets) + "}"
        name = self.metric_name
        rest = [f for f in self.label_filters
                if not (f.label == "__name__" and not f.is_negative
                        and not f.is_regexp and f.value == name)]
        body = ", ".join(str(f) for f in rest)
        if name is not None:
            return name + (f"{{{body}}}" if body else "")
        return f"{{{body}}}"


@dataclasses.dataclass
class RollupExpr(Expr):
    """expr[window:step] offset o @ at, e.g. m[5m] or (q)[1h:5m] offset 1d."""
    expr: Expr
    window: DurationExpr | None = None
    step: DurationExpr | None = None      # subquery step
    offset: DurationExpr | None = None
    at: Expr | None = None
    inherit_step: bool = False            # trailing ":" as in q[1h:]

    def needs_subquery(self) -> bool:
        return self.step is not None or self.inherit_step or not isinstance(
            self.expr, MetricExpr)

    def __str__(self):
        s = str(self.expr)
        if not isinstance(self.expr, (MetricExpr, FuncExpr)) and not (
                isinstance(self.expr, RollupExpr)):
            s = f"({s})"
        if self.window is not None or self.step is not None or self.inherit_step:
            w = str(self.window) if self.window is not None else ""
            if self.step is not None:
                s += f"[{w}:{self.step}]"
            elif self.inherit_step:
                s += f"[{w}:]"
            else:
                s += f"[{w}]"
        if self.offset is not None:
            s += f" offset {self.offset}"
        if self.at is not None:
            s += f" @ ({self.at})"
        return s


@dataclasses.dataclass
class FuncExpr(Expr):
    name: str
    args: list[Expr] = dataclasses.field(default_factory=list)
    keep_metric_names: bool = False

    def __str__(self):
        s = f"{self.name}({', '.join(str(a) for a in self.args)})"
        if self.keep_metric_names:
            s += " keep_metric_names"
        return s


@dataclasses.dataclass
class AggrFuncExpr(Expr):
    name: str
    args: list[Expr] = dataclasses.field(default_factory=list)
    grouping: list[str] = dataclasses.field(default_factory=list)
    without: bool = False
    limit: int = 0

    def __str__(self):
        s = f"{self.name}({', '.join(str(a) for a in self.args)})"
        if self.grouping or self.without:
            kw = "without" if self.without else "by"
            s += f" {kw} ({', '.join(self.grouping)})"
        if self.limit:
            s += f" limit {self.limit}"
        return s


@dataclasses.dataclass
class ModifierExpr:
    op: str = ""                      # on | ignoring | group_left | group_right
    args: list[str] = dataclasses.field(default_factory=list)
    prefix: str = ""                  # group_left(...) prefix "p" join prefix


@dataclasses.dataclass
class BinaryOpExpr(Expr):
    op: str
    left: Expr = None
    right: Expr = None
    bool_modifier: bool = False
    group_modifier: ModifierExpr = dataclasses.field(default_factory=ModifierExpr)
    join_modifier: ModifierExpr = dataclasses.field(default_factory=ModifierExpr)
    join_modifier_prefix: str | None = None
    keep_metric_names: bool = False

    def __str__(self):
        parts = [self._wrap(self.left), self.op]
        if self.bool_modifier:
            parts.append("bool")
        if self.group_modifier.op:
            parts.append(
                f"{self.group_modifier.op} ({', '.join(self.group_modifier.args)})")
        if self.join_modifier.op:
            jm = f"{self.join_modifier.op} ({', '.join(self.join_modifier.args)})"
            parts.append(jm)
        parts.append(self._wrap(self.right))
        return " ".join(parts)

    def _wrap(self, e: Expr) -> str:
        if isinstance(e, BinaryOpExpr):
            return f"({e})"
        return str(e)


@dataclasses.dataclass
class WithExpr(Expr):
    """WITH (a = expr, ...) body — expanded away at parse time; kept only for
    error reporting."""
    was: list
    expr: Expr

    def __str__(self):
        return str(self.expr)
