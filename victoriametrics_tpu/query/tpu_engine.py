"""TPU query backend: routes supported rollups onto the device kernels
(the -search.tpuBackend analog; see models/rollup_pipeline.py).

try_rollup_tpu returns per-series rollup rows for ORACLE funcs, or None to
fall back to the host path. Series are packed into padded tiles; tiles are
cached in HBM keyed by the series-set fingerprint so repeated queries skip
the transfer (the reference's blockcache-hot behavior).
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

from ..ops import rollup_np
from ..ops.rollup_np import RollupConfig
from ..utils import metrics as metricslib

# (kernel, phase) -> histogram handle; keeps name formatting and the
# registry lock off the per-dispatch path (same memo pattern as rpc.py)
_kernel_hist_memo: dict = {}


def _kernel_histogram(kernel: str, phase: str):
    key = (kernel, phase)
    h = _kernel_hist_memo.get(key)
    if h is None:
        # benign double-create: REGISTRY.histogram dedups by name, so
        # two racing fills store the same object
        h = _kernel_hist_memo[key] = metricslib.REGISTRY.histogram(  # vmt: disable=VMT015
            metricslib.format_name("vm_tpu_kernel_duration_seconds",
                                   {"kernel": kernel, "phase": phase}))
    return h


def timed_kernel_call(kernel: str, jit_fn, *args, **kw):
    """Run a jitted kernel recording its wall time into
    vm_tpu_kernel_duration_seconds, split compile vs. execute: a call
    that grew the jit cache (jax's _cache_size) paid a trace+compile,
    everything else is pure dispatch/execute.  The split is the first
    thing to look at when p99 spikes — a 'compile' sample on a steady
    workload means a shape/dtype churned a cached kernel."""
    import jax

    from ..utils import flightrec as _flightrec
    cache_size = getattr(jit_fn, "_cache_size", None)
    before = cache_size() if callable(cache_size) else None
    t0 = time.perf_counter()
    out = jit_fn(*args, **kw)
    # async dispatch returns immediately; without this sync the histogram
    # would record dispatch overhead, not the kernel (callers convert the
    # result to numpy right after, so no extra blocking is introduced)
    jax.block_until_ready(out)
    dt = time.perf_counter() - t0
    phase = "execute"
    if before is not None and cache_size() > before:
        phase = "compile"
    _kernel_histogram(kernel, phase).update(dt)
    # a device-leg flight capture attributes transfer vs compile vs
    # execute: uploads are spanned at the put seams, this is the rest
    _flightrec.rec(f"device:{phase}", t0, dt, arg=kernel)
    # cost plane: device kernel wall into the query's tracker (CPU 0 —
    # the work ran on the accelerator, not this thread)
    from ..utils import costacc as _costacc
    _tr = _costacc.current()
    if _tr is not None:
        _tr.lap(f"device:{phase}", dt, 0.0)
    return out


def _pull_host(out, dtype=np.float64) -> np.ndarray:
    """D2H pull of a kernel result with byte accounting + flight span —
    the one seam where device results cross back to the host."""
    from ..models.tile_cache import timed_transfer
    nbytes = int(np.prod(out.shape)) * np.dtype(dtype).itemsize
    return timed_transfer("device:download", nbytes,
                          lambda: np.asarray(out, dtype=dtype))

# -- the f32 tile design ------------------------------------------------
# Real TPUs have no native float64 (it is emulated, or silently truncated
# without x64), so device tiles there are float32 holding REBASED values
# v - v0, where v0 is the series' first uploaded value. The rebase happens
# in exact integer mantissa space on device (delta planes reconstruct from
# zero instead of the first mantissa), so a counter at 1e9 + small
# increments keeps FULL precision in its deltas — the one f32 rounding is
# the final scale multiply, bounding the error at ~2^-23 of the REBASED
# magnitude (window dynamic range), not of the absolute value.
#   F32_DIRECT funcs are shift-invariant (rate(v - v0) == rate(v)): they
#     run unchanged. Counter-reset classification needs the absolute base,
#     so kernels take v0 for the threshold compare (see
#     device_rollup._remove_counter_resets; post-reset precision degrades
#     to plain-f32 of the reset magnitude).
#   F32_AFFINE funcs satisfy f(v) = f(v - v0) + v0: the [S, T] device
#     output gets a host-side float64 addback per series (NaN gaps stay
#     NaN). Only valid where per-series outputs come back (not fused
#     cross-series aggregation, where group members have different v0).
#   Everything else (sum_over_time needs n*v0; cross-series selection on
#     absolute values) falls back to the f64 host path.
# The host evaluator stays float64 — the golden conformance corpus pins
# those numerics; tests/test_f32_tiles.py bounds device-vs-host error
# differentially. Precedent for lossy device numerics: the storage codec
# itself quantizes (lib/encoding/nearest_delta.go:15 precisionBits).
F32_DIRECT = frozenset({
    "count_over_time", "present_over_time", "stddev_over_time",
    "stdvar_over_time", "changes", "delta", "idelta", "increase",
    "increase_pure", "rate", "irate", "deriv", "deriv_fast", "lag",
    "lifetime", "scrape_interval", "timestamp", "tfirst_over_time",
    "tlast_over_time",
})
F32_AFFINE = frozenset({
    "min_over_time", "max_over_time", "avg_over_time", "first_over_time",
    "last_over_time", "default_rollup",
})


class V0Info:
    """Host-side companion of an f32 tile: per-series rebase offsets
    (float64 — the affine addback and append rebasing must not round
    through f32) plus the wide-range flag.

    `wide_range` is True when any series' REBASED magnitude |v - v0|
    reaches 2^24 (f32's exact-integer limit) — e.g. a large-base counter
    that resets mid-tile, or one that grows >16M within the window. The
    rebase guarantees nothing there: every value-dependent func would see
    ulp(|v - v0|)-sized noise, so they all fall back to the f64 host path
    for such tiles (per-series patching is possible future work).
    Value-free funcs (counts, timestamps) still run."""

    __slots__ = ("offsets", "wide_range")

    def __init__(self, offsets: np.ndarray, wide_range: bool):
        self.offsets = offsets
        self.wide_range = wide_range

    def __getitem__(self, i):
        return self.offsets[i]


# funcs whose output never reads sample VALUES: immune to f32 value error
VALUE_FREE_FUNCS = frozenset({
    "count_over_time", "present_over_time", "lag", "lifetime",
    "scrape_interval", "timestamp", "tfirst_over_time", "tlast_over_time",
})
# rebased-magnitude bound above which f32 value math is unsafe
F32_SAFE_RANGE = float(1 << 24)


def is_tpu_platform(platform: str | None) -> bool:
    """True for real TPU hardware platform names. The axon tunnel plugin
    in some images reports its own platform name rather than "tpu"."""
    return platform in ("tpu", "axon")


def auto_value_dtype():
    """float32 tiles on real TPU hardware; float64 elsewhere (CPU XLA has
    native f64 — the conformance dtype)."""
    try:
        import jax
        plat = jax.default_backend()
    except Exception:
        return np.float64
    return np.float32 if is_tpu_platform(plat) else np.float64


_CACHE_DIR_SET = False
_COMPILE_EVENTS_SET = False
# REAL XLA backend compiles (the monitoring event fires only when XLA
# actually builds an executable — jit tracing-cache hits and cpp-fastpath
# misses that resolve in the Python cache do NOT tick this), and
# persistent-compile-cache hits (a warm process deserializes instead of
# compiling).  The fleet's ≤-compiles-per-bucket guard and the
# compile-cache smoke both read these; jit _cache_size growth is NOT a
# compile signal (donation/placement churn grows it without compiling).
_BACKEND_COMPILES = metricslib.REGISTRY.counter(
    "vm_device_backend_compiles_total")
_COMPILE_CACHE_HITS = metricslib.REGISTRY.counter(
    "vm_device_fleet_compile_cache_hits_total")


def _register_compile_listeners():
    global _COMPILE_EVENTS_SET
    if _COMPILE_EVENTS_SET:
        return
    try:
        import threading

        from jax._src import monitoring  # no public seam for these events

        # backend_compile_duration fires on persistent-cache HITS too (the
        # event wraps compile-or-retrieve); the hit event precedes it in
        # the same call stack, so a thread-local pending flag swallows the
        # duration event a retrieval (not a real compile) produced.
        pending_hit = threading.local()

        def _on_dur(name, dur_s, **kw):
            if name == "/jax/core/compile/backend_compile_duration":
                if getattr(pending_hit, "n", 0) > 0:
                    pending_hit.n -= 1
                else:
                    _BACKEND_COMPILES.inc()

        def _on_event(name, **kw):
            if name == "/jax/compilation_cache/cache_hits":
                pending_hit.n = getattr(pending_hit, "n", 0) + 1
                _COMPILE_CACHE_HITS.inc()

        monitoring.register_event_duration_secs_listener(_on_dur)
        monitoring.register_event_listener(_on_event)
        _COMPILE_EVENTS_SET = True
    except Exception as e:  # pragma: no cover - jax internals drift
        import sys
        print(f"vmtpu: compile-event telemetry unavailable: {e!r}",
              file=sys.stderr)


def backend_compiles() -> int:
    """Count of REAL XLA compiles this process has paid so far."""
    return int(_BACKEND_COMPILES.get())


def compile_cache_hits() -> int:
    """Count of persistent-compile-cache hits (compiles NOT paid)."""
    return int(_COMPILE_CACHE_HITS.get())


def enable_compilation_cache():
    """Point XLA's persistent compilation cache at a durable directory so
    the fused-kernel compiles (~minutes cold on CPU-XLA) are paid once per
    machine, not once per process. The reference's first query doesn't pay
    a compile (docs/victoriametrics/README.md: p99 < 1s); with the cache
    warm, neither does ours. Idempotent; loud (not silent) on failure.
    ``VM_COMPILE_CACHE_DIR`` names the directory (``VM_JAX_CACHE_DIR``
    kept as the historical alias)."""
    global _CACHE_DIR_SET
    _register_compile_listeners()
    if _CACHE_DIR_SET:
        return
    import jax
    cache_dir = os.environ.get("VM_COMPILE_CACHE_DIR") or os.environ.get(
        "VM_JAX_CACHE_DIR",
        os.path.join(os.path.expanduser("~"), ".cache", "vmtpu-jax"))
    try:
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # fused rollup kernels are small but slow to compile: cache all
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        _CACHE_DIR_SET = True
    except Exception as e:  # pragma: no cover - config drift
        import sys
        print(f"vmtpu: persistent compilation cache unavailable: {e!r}",
              file=sys.stderr)


def jax_cache_refused() -> bool:
    """True when jax's persistent compilation cache cannot serve this
    backend (plugin runtimes its support matrix blacklists) — the
    own-format executable cache below takes over there."""
    if os.environ.get("VM_OWN_EXEC_CACHE") == "1":
        return True  # forced: lets CPU CI exercise the fallback format
    try:
        import jax
        from jax._src import compilation_cache as cc
        return not cc.is_cache_used(jax.devices()[0].client)
    except Exception:
        return True


class OwnExecutableCache:
    """Own-format persistent executable cache for backends whose runtime
    jax's compilation cache refuses to serve: whole compiled executables
    (jax.experimental.serialize_executable payloads + in/out treedefs)
    keyed by a fingerprint of the LOWERED program text — the StableHLO
    module embeds avals, shardings and donation, so any shape/layout/
    partitioning change keys a different entry.  Entries are atomic
    single files under <dir>/vmtpu-exec; a corrupt or version-skewed
    entry deserializes loudly into a miss, never a wrong executable."""

    def __init__(self, root: str):
        self.root = os.path.join(root, "vmtpu-exec")
        os.makedirs(self.root, exist_ok=True)

    def fingerprint(self, name: str, lowered) -> str:
        import hashlib

        import jax
        h = hashlib.sha256()
        h.update(jax.__version__.encode())
        h.update(jax.default_backend().encode())
        h.update(name.encode())
        h.update(lowered.as_text().encode())
        return h.hexdigest()

    def _path(self, key: str) -> str:
        return os.path.join(self.root, key + ".vmexec")

    def load(self, key: str):
        import pickle

        from jax.experimental import serialize_executable as se
        try:
            with open(self._path(key), "rb") as f:
                payload, in_tree, out_tree = pickle.load(f)
            return se.deserialize_and_load(payload, in_tree, out_tree)
        except FileNotFoundError:
            return None
        except Exception as e:  # corrupt / jaxlib-skewed entry: a miss
            import sys
            print(f"vmtpu: exec-cache entry {key[:12]} unreadable "
                  f"({e!r}); recompiling", file=sys.stderr)
            try:
                os.unlink(self._path(key))
            except OSError:
                pass
            return None

    def store(self, key: str, compiled) -> None:
        import pickle

        from jax.experimental import serialize_executable as se
        try:
            blob = pickle.dumps(se.serialize(compiled))
            tmp = self._path(key) + f".tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, self._path(key))
        except Exception as e:  # pragma: no cover - serialization refusal
            import sys
            print(f"vmtpu: executable not cacheable ({e!r})",
                  file=sys.stderr)


_OWN_EXEC_CACHE: tuple | None = None


def own_executable_cache() -> OwnExecutableCache | None:
    """The process's own-format executable cache, or None when jax's
    native persistent cache already covers this backend (the common
    case) or no cache directory is writable."""
    global _OWN_EXEC_CACHE
    if _OWN_EXEC_CACHE is None:
        cache = None
        if jax_cache_refused():
            cache_dir = os.environ.get("VM_COMPILE_CACHE_DIR") or \
                os.environ.get("VM_JAX_CACHE_DIR") or os.path.join(
                    os.path.expanduser("~"), ".cache", "vmtpu-jax")
            try:
                cache = OwnExecutableCache(cache_dir)
            except OSError as e:
                import sys
                print(f"vmtpu: own-format exec cache unavailable: {e!r}",
                      file=sys.stderr)
        _OWN_EXEC_CACHE = (cache,)
    return _OWN_EXEC_CACHE[0]


def with_executable_cache(jit_fn, name: str):
    """Wrap a jit callable with the own-format executable cache when the
    backend refuses jax's persistent cache; identity otherwise.  The
    wrapper AOT-lowers on first call, serves the compiled executable from
    disk on fingerprint hit (ticking the compile-cache-hit counter), and
    serializes after a cold compile."""
    cache = own_executable_cache()
    if cache is None:
        return jit_fn
    state: dict = {}

    def call(*args):
        # AOT executables are shape-monomorphic; callers reuse one jit fn
        # across bucket growth, so key the compiled program by signature
        sig = tuple((tuple(a.shape), str(a.dtype)) for a in args)
        fn = state.get(sig)
        if fn is None:
            lowered = jit_fn.lower(*args)
            key = cache.fingerprint(name, lowered)
            fn = cache.load(key)
            if fn is None:
                fn = lowered.compile()
                cache.store(key, fn)
            else:
                _COMPILE_CACHE_HITS.inc()
            state[sig] = fn
        return fn(*args)

    return call


@dataclasses.dataclass
class TPUEngine:
    cache_bytes: int = 2 << 30
    value_dtype: object = None  # None = auto (f32 on TPU, f64 elsewhere)
    min_series: int = 64        # below this the host path wins
    mesh: object = None         # jax.sharding.Mesh; series axis sharding
    last_roll_decline: str = ""  # why the last rolling advance fell back
    _cache: object = None
    _aux: object = None
    _wcache: object = None      # DeviceWindowCache (resident windows)
    _fleet: object = None       # query.fleet.FleetPlane (batched streams)

    def __post_init__(self):
        enable_compilation_cache()
        if self.value_dtype is None:
            self.value_dtype = auto_value_dtype()

    def is_f32(self) -> bool:
        return np.dtype(self.value_dtype) == np.float32

    def func_mode(self, func: str, per_series: bool):
        """How this engine's dtype can run `func`: "direct", "addback"
        (per-series host f64 + v0), or None (host fallback)."""
        if not self.is_f32():
            return "direct"
        if func in F32_DIRECT:
            return "direct"
        if per_series and func in F32_AFFINE:
            return "addback"
        return None

    def cache(self):
        if self._cache is None:
            from ..models.tile_cache import TileCache
            self._cache = TileCache(self.cache_bytes)
        return self._cache

    def window_cache(self):
        """Device-resident rolling windows (models.tile_cache
        .DeviceWindowCache): the state that makes a rolling refresh
        upload only its tail columns."""
        if self._wcache is None:
            from ..models.tile_cache import DeviceWindowCache
            self._wcache = DeviceWindowCache()
        return self._wcache

    def fleet(self):
        """Fleet-batched stream plane (query.fleet.FleetPlane): every
        device-resident matstream packed on one leading stream axis and
        served by ONE fused mesh launch per interval."""
        if self._fleet is None:
            from .fleet import FleetPlane
            self._fleet = FleetPlane(self)
        return self._fleet

    def series_shards(self) -> int:
        """Size of the mesh's series axis (1 = single-device engine)."""
        if self.mesh is None:
            return 1
        from ..parallel.mesh import AXIS_SERIES
        return self.mesh.shape[AXIS_SERIES]


def auto_mesh():
    """Series-axis mesh over every visible device, or None single-chip.
    The serving apps call this at startup: the same engine then answers
    identically on 1 chip and on a pod slice (the reference's
    vmselect-over-N-vmstorage scatter-gather, netstorage.go:374, becomes a
    mesh psum)."""
    try:
        import jax
        devs = jax.devices()
    except Exception:
        return None
    if len(devs) < 2:
        return None
    from ..parallel.mesh import make_mesh
    return make_mesh(n_series=len(devs), n_time=1, devices=devs)


def _fingerprint(series, start_ms: int) -> tuple:
    import xxhash
    h = xxhash.xxh64()
    for sd in series:
        raw = getattr(sd, "raw_name", None)
        h.update(raw if raw is not None else sd.metric_name.marshal())
        h.update(np.int64(sd.timestamps.size).tobytes())
        if sd.timestamps.size:
            h.update(sd.timestamps[-1].tobytes())
    return ("tile", h.intdigest(), start_ms)


def try_rollup_tpu(engine: TPUEngine, func: str, series, cfg: RollupConfig,
                   args: tuple, cache_key=None):
    """Returns list of per-series value rows, or None for host fallback."""
    if func not in rollup_np.CORE_SUPPORTED:
        return None  # device kernels cover the core set; host batch the rest
    mode = engine.func_mode(func, per_series=True)
    if mode is None:
        return None  # f32 tiles cannot run this func; host f64 path
    if args:
        return None
    if len(series) < engine.min_series:
        return None
    span = cfg.end - cfg.start + cfg.lookback
    if span >= 2**31 - 1:
        return None  # needs chunking; host path handles it
    try:
        import jax
        import jax.numpy as jnp

        from ..ops.device_rollup import pack_series, rollup_tile
    except Exception:
        return None

    key = cache_key or _fingerprint(series, cfg.start)
    cache = engine.cache()
    tiles = cache.get(key)
    if tiles is None:
        tiles = _upload_tiles(engine, series, cfg)
        # retain the DECODED device tiles (not the planes): hot queries then
        # run straight on HBM-resident data
        cache.put_device(key, tiles)
    from ..ops.device_rollup import MIN_TS_NONE, normalized_cfg
    if _counter_unsafe(engine, func, tiles):
        return None
    ts_t, v_t, counts, v0 = tiles
    out = timed_kernel_call("rollup_tile", rollup_tile, func, ts_t, v_t,
                            counts, normalized_cfg(func, cfg), MIN_TS_NONE,
                            _v0_dev(engine, v0))
    # mesh tiles are row-padded; only the live rows come back
    rows = _pull_host(out)[:len(series)]
    if mode == "addback":
        rows = rows + v0[:len(series), None]  # NaN gaps stay NaN
    return list(rows)


TOPK_RANK_KINDS = frozenset({"max", "min", "avg", "median", "last"})


def try_topk_rollup_tpu(engine: TPUEngine, name: str, k: float, func: str,
                        series, cfg: RollupConfig, cache_key=None):
    """Fused topk/bottomk family on device: the [S, T] rollup stays in HBM;
    selection (per-timestamp top-k, or whole-series rank for the
    topk_<kind> variants) runs on device and only winner indices + the k
    selected rows cross the link (aggr.go:793 getRangeTopKTimeseries /
    topk per-ts; critical on tunneled links where D2H dominates).

    Returns a list of (orig_series_index, values_row) — the caller attaches
    names — or None for host fallback."""
    if func not in rollup_np.CORE_SUPPORTED:
        return None
    # selection compares values ACROSS series: rebased rows with different
    # v0 are not comparable, so f32 tiles only run shift-invariant funcs
    if engine.func_mode(func, per_series=False) != "direct":
        return None
    if len(series) < engine.min_series:
        return None
    span = cfg.end - cfg.start + cfg.lookback
    if span >= 2**31 - 1:
        return None
    bottom = name.startswith("bottomk")
    if name in ("topk", "bottomk"):
        kind = None
    else:
        kind = name.split("_", 1)[1]
        if kind not in TOPK_RANK_KINDS:
            return None
    try:
        import jax.numpy as jnp

        from ..ops.device_rollup import (normalized_cfg, rank_tile,
                                         take_rows, topk_select_tile)
    except Exception:
        return None
    k_i = max(int(k), 0)
    if k_i == 0:
        return []
    key = cache_key or _fingerprint(series, cfg.start)
    cache = engine.cache()
    tiles = cache.get(key)
    if tiles is None:
        tiles = _upload_tiles(engine, series, cfg)
        cache.put_device(key, tiles)
    if _counter_unsafe(engine, func, tiles):
        return None
    ts_t, v_t, counts, v0 = tiles
    v0d = _v0_dev(engine, v0)
    ncfg = normalized_cfg(func, cfg)
    if kind is None:
        k_eff = min(k_i, int(ts_t.shape[0]))
        rolled, idx, sel_nan = topk_select_tile(
            func, ts_t, v_t, counts, ncfg, k_eff, bottom, v0=v0d)
        idx_h = np.asarray(idx)
        valid = ~np.asarray(sel_nan)
        # padded tile rows roll to all-NaN and can never be selected valid
        sel = np.unique(idx_h[valid])
        sel = sel[sel < len(series)]
        if sel.size == 0:
            return []
        rows_sel = _pull_host(take_rows(rolled, jnp.asarray(sel)))
        # rebuild the kept-sample mask for the selected rows
        t_pos, j_pos = np.nonzero(valid)
        s_pos = idx_h[t_pos, j_pos]
        keep = s_pos < len(series)
        row_of = np.searchsorted(sel, s_pos[keep])
        mask = np.zeros((sel.size, rows_sel.shape[1]), dtype=bool)
        mask[row_of, t_pos[keep]] = True
        out = []
        for j, i in enumerate(sel):
            vals = np.where(mask[j], rows_sel[j], np.nan)
            if not np.isnan(vals).all():
                out.append((int(i), vals))
        return out
    rolled, rank = rank_tile(func, kind, ts_t, v_t, counts, ncfg, v0=v0d)
    rank_h = np.asarray(rank, dtype=np.float64)[:len(series)]
    # ordering replicates _eval_topk_family exactly (stable sorts, ties
    # favor later series)
    rank_h = np.where(np.isnan(rank_h),
                      np.inf if bottom else -np.inf, rank_h)
    if bottom:
        order = np.argsort(-rank_h, kind="stable")
    else:
        order = np.argsort(rank_h, kind="stable")
    sel = order[-min(k_i, len(series)):]  # rank order, ties favor later
    rows_sel = _pull_host(take_rows(rolled, jnp.asarray(sel)))
    return [(int(i), rows_sel[j]) for j, i in enumerate(sel)]


FUSED_AGGRS = frozenset({"sum", "count", "avg", "min", "max", "stddev",
                         "stdvar", "group"})


def try_aggr_rollup_tpu(engine: TPUEngine, aggr: str, func: str, series,
                        gids, num_groups: int, cfg: RollupConfig,
                        cache_key=None):
    """Fused aggr(rollup(selector)) on device: per-series rollup + segment
    aggregation run in one kernel, so only the [G, T] aggregate crosses the
    device->host link (the incrementalAggrFuncCallbacks analog,
    eval.go:1055; critical on tunneled links where D2H dominates).
    Returns an [G, T] float64 array or None for host fallback."""
    if aggr not in FUSED_AGGRS or func not in rollup_np.CORE_SUPPORTED:
        return None
    # group members have different v0, so f32 tiles only run
    # shift-invariant funcs fused (the affine addback is per-series)
    if engine.func_mode(func, per_series=False) != "direct":
        return None
    if len(series) < engine.min_series:
        return None
    span = cfg.end - cfg.start + cfg.lookback
    if span >= 2**31 - 1:
        return None
    try:
        import jax.numpy as jnp

        from ..ops.device_rollup import rollup_aggregate_tile
    except Exception:
        return None
    key = cache_key or _fingerprint(series, cfg.start)
    cache = engine.cache()
    tiles = cache.get(key)
    if tiles is None:
        tiles = _upload_tiles(engine, series, cfg)
        cache.put_device(key, tiles)
    if _counter_unsafe(engine, func, tiles):
        return None
    return _dispatch_fused(engine, aggr, func, tiles, jnp.asarray(gids),
                           num_groups, cfg)


def warmup(engine: TPUEngine, funcs=("rate", "increase", "default_rollup"),
           aggrs=("sum",)) -> int:
    """Pre-compile the hot fused/per-series kernels on a small canonical
    shape so the first real query pays neither jit-infrastructure init nor
    the kernel compile (which also seeds the persistent compilation cache,
    enable_compilation_cache). Serving apps call this from a daemon thread
    at startup; returns the number of kernels exercised. Never raises —
    warmup failure must not take the server down."""
    import time as _time

    from ..storage.metric_name import MetricName
    from ..storage.storage import SeriesData
    n_runs = 0
    try:
        S, N = max(int(engine.min_series), 64), 128
        from ..utils import fasttime
        start = (fasttime.unix_ms() - N * 15_000) // 60_000 * 60_000
        rng = np.random.default_rng(7)
        series = []
        for i in range(S):
            ts = np.arange(N, dtype=np.int64) * 15_000 + start
            v = np.cumsum(rng.integers(0, 50, N)).astype(np.float64)
            mn = MetricName.from_dict({"__name__": "__warmup__",
                                       "i": str(i)})
            series.append(SeriesData(mn, ts, v, raw_name=mn.marshal()))
        cfg = RollupConfig(start=start + 600_000,
                           end=start + (N - 1) * 15_000, step=60_000,
                           window=300_000)
        gids = np.zeros(S, np.int32)
        for func in funcs:
            if try_rollup_tpu(engine, func, series, cfg, ()) is not None:
                n_runs += 1
            for aggr in aggrs:
                if try_aggr_rollup_tpu(engine, aggr, func, series, gids, 1,
                                       cfg) is not None:
                    n_runs += 1
    except Exception as e:  # pragma: no cover - device drift
        import sys
        print(f"vmtpu: device warmup failed (serving continues): {e!r}",
              file=sys.stderr)
    return n_runs


def _v0_dev(engine: TPUEngine, v0):
    """Rebase offsets in tile dtype for the kernel's counter-reset
    threshold (None for f64 engines — no rebase happened)."""
    if v0 is None:
        return None
    import jax.numpy as jnp
    return jnp.asarray(v0.offsets.astype(np.float32))


def _counter_unsafe(engine: TPUEngine, func: str, tiles) -> bool:
    """True when `func` reads sample values but this f32 tile's rebased
    dynamic range exceeds the f32-safe bound (see V0Info.wide_range)."""
    v0 = tiles[3]
    return v0 is not None and v0.wide_range and func not in VALUE_FREE_FUNCS


def _pad_rows(arr, n_rows: int, fill):
    """Pad a [S]-vector to the tile's padded row count (mesh tiles round S
    up to a multiple of the series axis)."""
    import jax.numpy as jnp
    arr = jnp.asarray(arr)
    if arr.shape[0] >= n_rows:
        return arr
    pad = jnp.full((n_rows - arr.shape[0],), fill, dtype=arr.dtype)
    return jnp.concatenate([arr, pad])


def _dispatch_fused(engine: TPUEngine, aggr: str, func: str, tiles,
                    gids_dev, num_groups: int, cfg: RollupConfig,
                    shift: int = 0, min_ts=None):
    """Route a fused aggr(rollup()) to the single-device kernel or the
    mesh-sharded psum path (parallel/mesh.py). Padded rows carry count=0 so
    their rollup is NaN and contributes nothing to any group moment.
    `shift` rebases rolling-tile timestamps onto the query grid and
    `min_ts` reproduces fetch truncation on over-covering tiles (both
    traced, so rolling windows never recompile)."""
    from ..ops.device_rollup import (MIN_TS_NONE, normalized_cfg,
                                     rollup_aggregate_tile)
    if min_ts is None:
        min_ts = MIN_TS_NONE
    ts_t, v_t, counts, v0 = tiles
    gids_dev = _pad_rows(gids_dev, ts_t.shape[0], 0)
    cfg = normalized_cfg(func, cfg)
    if engine.series_shards() > 1:
        import jax.numpy as jnp
        from ..parallel.mesh import cached_sharded_rollup_aggregate
        fn = cached_sharded_rollup_aggregate(engine.mesh, func, aggr, cfg,
                                             num_groups)
        v0_arr = (np.zeros(int(ts_t.shape[0]), np.float32) if v0 is None
                  else v0.offsets.astype(np.float32))
        out = timed_kernel_call("sharded_rollup_aggregate", fn, ts_t, v_t,
                                counts, gids_dev, np.int32(shift),
                                np.int32(min_ts), v0_arr)
    else:
        out = timed_kernel_call("rollup_aggregate_tile",
                                rollup_aggregate_tile, func, aggr, ts_t,
                                v_t, counts, gids_dev, cfg, num_groups,
                                np.int32(shift), np.int32(min_ts),
                                _v0_dev(engine, v0))
    return _pull_host(out)


def _upload_tiles(engine: TPUEngine, series, cfg: RollupConfig):
    """Cold upload: prefer compact delta planes decoded on device (~2-5
    B/sample over the link, SURVEY §7 'compressed columns cross the
    boundary'); fall back to dense tiles when the data needs >int32.

    With a multi-device mesh the rows (series axis) are padded to a multiple
    of the mesh's series axis and placed per the partition-rule table
    (parallel/partition.py) — the delta-plane decode is per-row, so under
    GSPMD each device decodes only its shard and the decoded tile never
    leaves its device (the scatter half of the reference's
    scatter-gather)."""
    import dataclasses

    import jax.numpy as jnp

    from ..ops import decimal as dec
    from ..ops import device_decode as dd
    from ..ops.device_rollup import TS_PAD, pack_series
    from ..models.tile_cache import chunked_device_put
    from ..parallel.partition import shard_put

    n_sh = engine.series_shards()

    def _put(a: np.ndarray, pad_value=0, name="ts"):
        if n_sh > 1:
            return shard_put(engine.mesh, name, a, pad_value)
        return chunked_device_put(np.asarray(a))

    f32 = engine.is_f32()
    v0 = risky = None
    if f32:
        # per-series rebase offsets, float64, HOST-resident: the affine
        # addback and append-slice rebasing must not round through f32
        v0 = np.array([sd.values[0] if sd.values.size and
                       np.isfinite(sd.values[0]) else 0.0
                       for sd in series], dtype=np.float64)
        risky = any(
            sd.values.size and np.isfinite(sd.values).any() and
            float(np.nanmax(np.abs(np.where(np.isfinite(sd.values),
                                            sd.values, v0[i]) - v0[i])))
            >= F32_SAFE_RANGE
            for i, sd in enumerate(series))
    triples = []
    for sd in series:
        m, e = dec.float_to_decimal(sd.values)
        triples.append((sd.timestamps, m, e))
    if f32 and not risky:
        # The one f32 rounding happens on the REBASED MANTISSA (the delta
        # planes reconstruct m - m[0], then scale): with fractional scales
        # (10^-k) the mantissa range can exceed 2^24 while the value-space
        # gate above passes, silently costing integer exactness that
        # equality-sensitive funcs (changes, reset classification) need.
        # Specials (NaN/Inf sentinels ~ 2^63) can't reach here: the int32
        # plane check below rejects them first, but mask to |m|<2^31
        # anyway so the gate never trips on a sentinel-only artifact.
        for _, m, _ in triples:
            if not m.size:
                continue
            # range test, NOT np.abs: abs(INT64_MIN) overflows back to
            # INT64_MIN (the V_NAN sentinel) and would pass an abs-< gate
            sane = (m > -(2 ** 31)) & (m < 2 ** 31)
            if not sane.any():
                continue
            base = m[0] if sane[0] else m[sane][0]
            if float(np.abs(m[sane] - base).max()) >= F32_SAFE_RANGE:
                risky = True
                break
    planes = dd.pack_delta_planes(triples, cfg.start,
                                  value_dtype=engine.value_dtype,
                                  rebase=f32)
    if planes is not None:
        n = int(planes.counts.max())
        n_cap = tile_capacity(n)
        if n_cap > n:
            # headroom columns for rolling appends: zero d2 planes decode
            # into garbage tails that every kernel masks out via counts
            pad = max(n_cap - 2 - planes.ts_d2.shape[1], 0)
            planes = dataclasses.replace(
                planes,
                ts_d2=np.pad(planes.ts_d2, ((0, 0), (0, pad))),
                val_d2=np.pad(planes.val_d2, ((0, 0), (0, pad))))
        if f32:
            # v0 must match the DECODED first value exactly (mant * scale),
            # not the pre-codec float, so addback + decode compose to the
            # device's own absolute values
            v0 = np.array([float(m[0]) if m.size else 0.0
                           for _, m, _ in triples], dtype=np.float64) * \
                np.array([10.0 ** e for _, _, e in triples])
            v0[~np.isfinite(v0)] = 0.0
        # padded rows get count=0 and scale=1: decode masks them to TS_PAD
        pad_vals = {"scale": 1}
        dev = [_put(getattr(planes, f.name), pad_vals.get(f.name, 0),
                    name=f.name)
               for f in dataclasses.fields(planes)]
        ts_t, v_t = dd.decode_tiles(*dev[:6], dev[6], dev[7], n_cap,
                                    engine.value_dtype, rebase=f32)
        return ts_t, v_t, dev[7], _pad_v0(v0, int(ts_t.shape[0]), risky)
    pairs = []
    for i, sd in enumerate(series):
        vals_i = sd.values
        if f32:
            vals_i = vals_i - v0[i]
        pairs.append((sd.timestamps, vals_i))
    ts, vals, counts = pack_series(
        pairs, cfg.start,
        n_pad=tile_capacity(
            max((sd.timestamps.size for sd in series), default=1)),
        dtype=engine.value_dtype)
    ts_d = _put(ts, TS_PAD, name="ts")
    return (ts_d, _put(vals, name="values"), _put(counts, name="counts"),
            _pad_v0(v0, int(ts_d.shape[0]), risky))


def _pad_v0(v0, n_rows: int, risky):
    """Row-pad the host float64 rebase vector to the tile's padded row
    count and wrap it as V0Info (None passes through for f64 engines)."""
    if v0 is None:
        return None
    if v0.shape[0] < n_rows:
        v0 = np.concatenate([v0, np.zeros(n_rows - v0.shape[0])])
    return V0Info(v0, bool(risky))


def tile_capacity(n: int) -> int:
    """Column capacity for a freshly built tile: ~25% headroom (min 32
    columns) rounded to a multiple of 64, so rolling appends have room and
    rebuilt tiles land on few distinct compiled shapes."""
    return (max(n + 32, n * 5 // 4) + 63) // 64 * 64


class RollingTile:
    """An HBM-resident tile that advances with append-only ingest instead of
    rebuilding (the VERDICT-r2 'incremental tile maintenance': the
    reference's rollupResultCache reuses cached tails,
    rollup_result_cache.go:283 — here the TILE is the cache and new blocks
    append into reserved column headroom).

    Accuracy contract: the tail kernel's estimate-dependent prev-sample
    gating can drift vs a cold fresh-tile eval by up to ~one gated
    sample's increase per window under jittered scrape intervals
    (bounded in tests/test_served_device_path.py; the reference's cached
    columns drift the same way). Paths that need cold-exact results
    (the HTTP result cache's suffix eval) set EvalConfig.no_device_roll.

    Shared per selector across every fused query shape over it (sum/avg/...
    states reference the same RollingTile, so one append serves them all).
    The append DONATES the old device buffers; anything else holding them
    (the exact-key TileCache entry it was adopted from) must be invalidated
    first — advance_rolling() does that via `adopted_key`."""

    __slots__ = ("tiles", "base_ms", "n_cap", "lo_ms", "hi_ms", "version",
                 "structural", "counts_host", "row_of_raw", "n_samples",
                 "adopted_key", "appends", "segments")

    def __init__(self, tiles, base_ms, n_cap, lo_ms, hi_ms, version,
                 structural, counts_host, row_of_raw, n_samples,
                 adopted_key):
        self.tiles = tiles
        self.base_ms = base_ms
        self.n_cap = n_cap
        self.lo_ms = lo_ms
        self.hi_ms = hi_ms
        self.version = version
        self.structural = structural
        self.counts_host = counts_host
        self.row_of_raw = row_of_raw
        self.n_samples = n_samples
        self.adopted_key = adopted_key
        self.appends = 0
        # (seg_lo, seg_hi, n) per build/append: lets sample accounting for
        # -search.maxSamplesPerQuery charge only segments a query's fetch
        # range would actually touch, not the tile's whole history
        self.segments = [(lo_ms, hi_ms, n_samples)]

    def samples_in_range(self, fetch_lo: int) -> int:
        return sum(n for _, seg_hi, n in self.segments if seg_hi >= fetch_lo)


def advance_rolling(engine: TPUEngine, rt: RollingTile, storage, filters,
                    start: int, fetch_lo: int, end: int, max_series, tenant,
                    drop_stale: bool, tracer=None) -> bool:
    """Bring `rt` up to date with storage for a query fetching
    [fetch_lo, end]: fetch only the slice newer than the tile's covered
    range and append it on device. Returns False when the tile cannot be
    advanced (late/backfilled data, deletes, new series, capacity/int32
    exhausted) — the caller rebuilds via the cold path."""
    def no(reason: str) -> bool:
        engine.last_roll_decline = reason
        return False

    ver = getattr(storage, "data_version", None)
    if ver is None or \
            getattr(storage, "structural_version", None) != rt.structural:
        return no("deletes/retention changed visible data")
    if getattr(storage, "dedup_interval_ms", 0):
        return no("dedup interval set")  # buckets could straddle the append
    if rt.lo_ms > fetch_lo:
        return no("tile history does not reach this query's lookback")
    if start < rt.base_ms:
        # a negative shift would wrap the TS_PAD sentinel in int32 and
        # break row sortedness
        return no("query starts before the tile's rebase origin")
    if end - rt.base_ms >= 2**31 - 1:
        # window-slide compaction instead of a decline: drop samples
        # older than this query's fetch bound on device and move the
        # rebase origin there (compact_tile, donated) — the resident
        # window then rolls indefinitely instead of dying of int32
        if not compact_window(engine, rt, fetch_lo) or \
                end - rt.base_ms >= 2**31 - 1:
            return no("int32 rebase exhausted")
    if ver != rt.version:
        try:
            lo_new = storage.min_appended_since(rt.version)
        except LookupError:
            return no("append log trimmed past tile version")
        if lo_new is not None and lo_new <= rt.hi_ms:
            return no("late data landed inside the covered range")
    if end > rt.hi_ms:
        # extend coverage: anything in (hi, end] — new ingest OR data that
        # simply lay beyond the previous query's fetch bound — appends in
        # one slice fetch
        qt = tracer.new_child("slice fetch (%d, %d]", rt.hi_ms, end) \
            if tracer is not None else None
        try:
            cols = storage.search_columns(filters, rt.hi_ms + 1, end,
                                          max_series=max_series,
                                          tenant=tenant)
        except ResourceWarning as e:
            from .limits import QueryLimitError
            raise QueryLimitError(
                f"{e}; either narrow the selector or raise "
                f"-search.maxUniqueTimeseries") from None
        if getattr(storage, "last_partial", False):
            return no("partial slice fetch")
        if drop_stale:
            cols.drop_stale_nans()
        if qt is not None:
            qt.donef("%d series, %d samples", cols.n_series, cols.n_samples)
        if cols.n_series:
            qa = tracer.new_child("device append") if tracer is not None \
                else None
            ok = _append_cols(engine, rt, cols, fetch_lo)
            if qa is not None:
                qa.donef("%d samples -> row tails", cols.n_samples)
            if not ok:
                return no(engine.last_roll_decline)
            rt.segments.append((rt.hi_ms + 1, end, cols.n_samples))
        rt.hi_ms = end
    rt.version = ver
    return True


def compact_window(engine: TPUEngine, rt: RollingTile,
                   cutoff_abs: int) -> bool:
    """Slide the resident window on device: drop every sample older than
    `cutoff_abs` (this query's fetch lower bound — nothing at or past it
    can contribute to this or any later rolling query) and rebase the
    tile origin there, freeing column headroom and int32 range WITHOUT a
    re-upload (ops.device_rollup.compact_tile, donated buffers).  Queries
    reaching further back than the new origin decline via rt.lo_ms and
    rebuild — the loud fallback.  Returns False when nothing would move
    (cutoff at/behind the current origin)."""
    cutoff_rel = cutoff_abs - rt.base_ms
    if cutoff_rel <= 0 or cutoff_rel >= 2**31 - 1:
        # nothing to drop, or the tile is so stale (paused dashboard
        # resumed much later) that even the cutoff overflows the int32
        # frame: decline BEFORE mutating any state — np.int32() below
        # would raise OverflowError instead of the loud rebuild
        return False
    from ..models.tile_cache import count_window_compaction
    from ..ops.device_rollup import compact_tile
    # the old buffers are donated: drop the TileCache reference first so
    # no reachable entry keeps deleted arrays
    if rt.adopted_key is not None:
        engine.cache().invalidate(rt.adopted_key)
        rt.adopted_key = None
    ts_t, v_t, counts_t, v0 = rt.tiles
    new_ts, new_vals, new_counts = timed_kernel_call(
        "compact_tile", compact_tile, ts_t, v_t, counts_t,
        np.int32(cutoff_rel), np.int32(cutoff_rel))
    counts_host = np.asarray(new_counts).astype(np.int64)
    rt.tiles = (new_ts, new_vals, new_counts, v0)
    rt.counts_host = counts_host
    rt.n_samples = int(counts_host.sum())
    rt.base_ms = cutoff_abs
    rt.lo_ms = max(rt.lo_ms, cutoff_abs)
    # clamp the sample-accounting segments to the new history start;
    # partially clipped segments keep their full n (a conservative
    # overcount for -search.maxSamplesPerQuery accounting)
    rt.segments = [(max(lo, cutoff_abs), hi, n)
                   for lo, hi, n in rt.segments if hi >= cutoff_abs]
    count_window_compaction()
    return True


def _append_cols(engine: TPUEngine, rt: RollingTile, cols,
                 fetch_lo: int) -> bool:
    """Scatter a fetched slice (ColumnarSeries) onto the tile tails."""
    from ..ops.device_rollup import append_tile
    rows_idx = np.empty(cols.n_series, dtype=np.int64)
    for i, rn in enumerate(cols.raw_names):
        r = rt.row_of_raw.get(rn)
        if r is None:
            engine.last_roll_decline = "new series appeared"
            return False
        rows_idx[i] = r
    new_n = rt.counts_host[rows_idx] + cols.counts
    if int(new_n.max()) > rt.n_cap:
        # window-slide compaction before giving up: free the columns
        # holding samples older than this query's fetch bound
        if not compact_window(engine, rt, fetch_lo):
            engine.last_roll_decline = "column headroom exhausted"
            return False
        new_n = rt.counts_host[rows_idx] + cols.counts
        if int(new_n.max()) > rt.n_cap:
            engine.last_roll_decline = "column headroom exhausted"
            return False
    ts_t0, v_t0, counts_t0, v0 = rt.tiles
    S_tile = int(ts_t0.shape[0])
    K = int(cols.ts.shape[1])
    K_pad = (K + 7) // 8 * 8  # few distinct compiled append shapes
    new_ts = np.zeros((S_tile, K_pad), dtype=np.int32)
    new_vals = np.zeros((S_tile, K_pad), dtype=np.float64)
    new_counts = np.zeros(S_tile, dtype=np.int32)
    new_ts[rows_idx, :K] = (cols.ts - rt.base_ms).astype(np.int32)
    vals_in = cols.vals
    if v0 is not None:
        # f32 tiles hold rebased values: rebase the appended slice by the
        # SAME per-row offsets (f64 host subtraction, one f32 rounding).
        # An append pushing the rebased magnitude past the f32-safe range
        # (large-base counter reset, or >16M of growth) declines — the
        # caller rebuilds and the cold path re-gates via V0Info.
        vals_in = vals_in - v0[rows_idx][:, None]
        live = np.arange(K)[None, :] < cols.counts[:, None]
        sub = vals_in[live]  # padding rebases to -v0; exclude it
        finite = sub[np.isfinite(sub)]
        if not v0.wide_range and finite.size and \
                float(np.abs(finite).max()) >= F32_SAFE_RANGE:
            engine.last_roll_decline = \
                "append exceeds the f32-safe rebased range"
            return False
    new_vals[rows_idx, :K] = vals_in
    new_counts[rows_idx] = cols.counts
    # the old buffers are donated: drop the TileCache reference first so no
    # reachable entry keeps deleted arrays
    if rt.adopted_key is not None:
        engine.cache().invalidate(rt.adopted_key)
        rt.adopted_key = None
    ts_t, v_t, counts_t = ts_t0, v_t0, counts_t0
    if engine.series_shards() > 1:
        # the tile rows are already padded to the mesh multiple, so these
        # shard_puts never re-pad — they just place per the rule table
        from ..parallel.partition import shard_put
        new_ts_d = shard_put(engine.mesh, "ts", new_ts)
        new_vals_d = shard_put(engine.mesh, "values", new_vals)
        new_counts_d = shard_put(engine.mesh, "counts", new_counts)
    else:
        from ..models.tile_cache import count_upload
        count_upload(new_ts.nbytes + new_vals.nbytes + new_counts.nbytes)
        new_ts_d, new_vals_d, new_counts_d = new_ts, new_vals, new_counts
    rt.tiles = append_tile(ts_t, v_t, counts_t, new_ts_d, new_vals_d,
                           new_counts_d) + (v0,)
    rt.counts_host[rows_idx] = new_n
    rt.n_samples += cols.n_samples
    rt.appends += 1
    return True


def aux_cache(engine: TPUEngine):
    """Host-side LRU mapping a query-shape key to (tile_key, adjusted cfg,
    device gids, group keys, sample count): lets a warm fused query skip the
    host fetch entirely and go straight to the resident tile."""
    if engine._aux is None:
        from collections import OrderedDict
        engine._aux = OrderedDict()
    return engine._aux


def aux_get(engine: TPUEngine, key):
    aux = aux_cache(engine)
    hit = aux.get(key)
    if hit is not None:
        aux.move_to_end(key)  # true LRU: hits refresh recency
    return hit


def aux_put(engine: TPUEngine, key, value, cap: int = 1024):
    aux = aux_cache(engine)
    aux[key] = value
    aux.move_to_end(key)
    while len(aux) > cap:
        aux.popitem(last=False)


def run_fused_on_tiles(engine: TPUEngine, aggr: str, func: str, tiles,
                       gids_dev, num_groups: int, cfg: RollupConfig,
                       shift: int = 0, min_ts=None):
    """Fused kernel over an HBM-resident tile (warm-path shortcut: no host
    fetch, no upload)."""
    return _dispatch_fused(engine, aggr, func, tiles, gids_dev, num_groups,
                           cfg, shift, min_ts)


# HBM budget for the dense [G, M, T] quantile tensor. The kernel holds the
# scatter target AND its sorted copy simultaneously, so the element cap is
# budget / (itemsize * 2).
_QUANTILE_DENSE_BYTES = 512 << 20


def group_slots(gids, num_groups: int):
    """Per-series slot within its group + the largest group size — the ONE
    place this ordering is defined (warm-path reuse depends on it matching
    the cold-path scatter exactly)."""
    counts_per_group = np.bincount(gids, minlength=num_groups)
    max_group = int(counts_per_group.max()) if num_groups else 0
    next_slot = np.zeros(num_groups, dtype=np.int32)
    slots = np.empty(len(gids), dtype=np.int32)
    for i, g in enumerate(gids):
        slots[i] = next_slot[g]
        next_slot[g] += 1
    return slots, max_group


def quantile_dense_fits(engine: TPUEngine, num_groups: int, max_group: int,
                        cfg: RollupConfig) -> bool:
    T = (cfg.end - cfg.start) // cfg.step + 1
    itemsize = np.dtype(engine.value_dtype).itemsize
    return num_groups * max_group * T <= \
        _QUANTILE_DENSE_BYTES // (itemsize * 2)


def try_quantile_rollup_tpu(engine: TPUEngine, phi: float, func: str,
                            series, gids, num_groups: int,
                            cfg: RollupConfig, slots, max_group: int,
                            cache_key=None):
    """Fused quantile/median(phi, rollup(selector)) by (...) on device.
    `slots`/`max_group` come from group_slots(). Returns [G, T] float64 or
    None for host fallback."""
    if func not in rollup_np.CORE_SUPPORTED:
        return None
    # the quantile interpolates ACROSS group members (different v0)
    if engine.func_mode(func, per_series=False) != "direct":
        return None
    if len(series) < engine.min_series:
        return None
    span = cfg.end - cfg.start + cfg.lookback
    if span >= 2**31 - 1:
        return None
    if not quantile_dense_fits(engine, num_groups, max_group, cfg):
        return None  # skewed grouping: dense tensor too big, host wins
    try:
        import jax.numpy as jnp

        from ..ops.device_rollup import rollup_quantile_tile
    except Exception:
        return None
    key = cache_key or _fingerprint(series, cfg.start)
    cache = engine.cache()
    tiles = cache.get(key)
    if tiles is None:
        tiles = _upload_tiles(engine, series, cfg)
        cache.put_device(key, tiles)
    if _counter_unsafe(engine, func, tiles):
        return None
    return run_quantile_on_tiles(engine, phi, func, tiles,
                                 jnp.asarray(gids), jnp.asarray(slots),
                                 num_groups, max_group, cfg)


def run_quantile_on_tiles(engine: TPUEngine, phi: float, func: str, tiles,
                          gids_dev, slots_dev, num_groups: int,
                          max_group: int, cfg: RollupConfig,
                          shift: int = 0, min_ts=None):
    """Warm-path fused quantile over an HBM-resident tile. On a mesh the
    jitted kernel runs under GSPMD on the sharded tile; padded rows get
    out-of-bounds (group, slot) indices so their NaN rollup rows are DROPPED
    by the scatter instead of clobbering a live slot."""
    from ..ops.device_rollup import (MIN_TS_NONE, normalized_cfg,
                                     rollup_quantile_tile)
    if min_ts is None:
        min_ts = MIN_TS_NONE
    ts_t, v_t, counts, v0 = tiles
    gids_dev = _pad_rows(gids_dev, ts_t.shape[0], num_groups)
    slots_dev = _pad_rows(slots_dev, ts_t.shape[0], max_group)
    out = rollup_quantile_tile(func, phi, ts_t, v_t, counts, gids_dev,
                               slots_dev, normalized_cfg(func, cfg),
                               num_groups, max_group, np.int32(shift),
                               np.int32(min_ts), _v0_dev(engine, v0))
    return _pull_host(out)
