"""Rollup function registry (reference app/vmselect/promql/rollup.go:24-110,
87 functions).

Two tiers:
- ORACLE_FUNCS: vectorized via ops/rollup_np (and the TPU kernels in
  ops/device_rollup for the device path) — the hot subset.
- GENERIC_FUNCS: per-window NumPy callables run by `generic_rollup`, covering
  the long tail. Window signature: fn(w_vals, w_ts, prev_v, prev_t, t_end,
  args) -> float, with NaN for "no value". Window = (t-d, t], prev = last
  sample at or before the window start (doInternal semantics).

Some functions yield multiple output series per input (rollup(),
rollup_candlestick(), aggr_over_time(), quantiles_over_time()): these are
MULTI_FUNCS and return [(label_tag, fn)] expansions.
"""

from __future__ import annotations

import numpy as np

from ..ops import rollup_np
from ..ops.rollup_np import RollupConfig

ORACLE_FUNCS = set(rollup_np.CORE_SUPPORTED)

nan = float("nan")


def _quantile(phi: float, vals: np.ndarray) -> float:
    if vals.size == 0:
        return nan
    if phi < 0:
        return -np.inf
    if phi > 1:
        return np.inf
    return float(np.quantile(vals, phi))


def _remove_resets(v: np.ndarray) -> np.ndarray:
    return rollup_np.remove_counter_resets(v)


# -- generic single-output windows ------------------------------------------

def _w_quantile(w, t, pv, pt, te, args):
    return _quantile(args[0], w)


def _w_median(w, t, pv, pt, te, args):
    return _quantile(0.5, w)


def _w_mad(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    med = np.median(w)
    return float(np.median(np.abs(w - med)))


def _w_iqr(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    q25, q75 = np.quantile(w, [0.25, 0.75])
    return float(q75 - q25)


def _w_zscore(w, t, pv, pt, te, args):
    """rollup.go:2361 rollupZScoreOverTime: gated on lag <= scrape interval,
    and (last - avg) == 0 short-circuits to 0 before dividing by stddev."""
    if w.size == 0:
        return nan
    if pv is not None:
        prev_ts, n = pt, t.size
    else:
        if t.size < 2:
            return nan
        prev_ts, n = t[0], t.size - 1
    scrape_interval = (t[-1] - prev_ts) / 1e3 / n
    lag = (te - t[-1]) / 1e3
    if lag > scrape_interval:
        return nan
    d = w[-1] - w.mean()
    if d == 0:
        return 0.0
    sd = w.std()
    return float(d / sd) if sd > 0 else nan


def _w_range(w, t, pv, pt, te, args):
    return float(w.max() - w.min()) if w.size else nan


def _w_distinct(w, t, pv, pt, te, args):
    return float(np.unique(w[~np.isnan(w)]).size) if w.size else nan


def _w_geomean(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    return float(np.exp(np.log(np.abs(w) + 0.0).mean())) if (w > 0).all() \
        else float(np.power(np.abs(np.prod(w)), 1.0 / w.size))


def _w_sum2(w, t, pv, pt, te, args):
    return float((w * w).sum()) if w.size else nan


def _w_tmin(w, t, pv, pt, te, args):
    return float(t[np.argmin(w)] / 1e3) if w.size else nan


def _w_tmax(w, t, pv, pt, te, args):
    return float(t[np.argmax(w)] / 1e3) if w.size else nan


def _w_resets(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    seq = w if pv is None else np.concatenate([[pv], w])
    return float((np.diff(seq) < 0).sum())


def _w_increases(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    seq = w if pv is None else np.concatenate([[pv], w])
    return float((np.diff(seq) > 0).sum())


def _w_decreases(w, t, pv, pt, te, args):
    return _w_resets(w, t, pv, pt, te, args)


def _w_integrate(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    vs, ts_ = w, t
    if pv is not None:
        vs = np.concatenate([[pv], w])
        ts_ = np.concatenate([[pt], t])
    if vs.size < 2:
        return 0.0
    dt = np.diff(ts_) / 1e3
    return float((vs[:-1] * dt).sum())


def _w_rate_over_sum(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    dt = (t[-1] - (pt if pt is not None else t[0])) / 1e3
    return float(w.sum() / dt) if dt > 0 else nan


def _w_count_eq(w, t, pv, pt, te, args):
    return float((w == args[0]).sum()) if w.size else nan


def _w_count_ne(w, t, pv, pt, te, args):
    return float((w != args[0]).sum()) if w.size else nan


def _w_count_le(w, t, pv, pt, te, args):
    return float((w <= args[0]).sum()) if w.size else nan


def _w_count_gt(w, t, pv, pt, te, args):
    return float((w > args[0]).sum()) if w.size else nan


def _w_share_le(w, t, pv, pt, te, args):
    return float((w <= args[0]).mean()) if w.size else nan


def _w_share_gt(w, t, pv, pt, te, args):
    return float((w > args[0]).mean()) if w.size else nan


def _w_share_eq(w, t, pv, pt, te, args):
    return float((w == args[0]).mean()) if w.size else nan


def _w_sum_eq(w, t, pv, pt, te, args):
    return float(w[w == args[0]].sum()) if w.size else nan


def _w_sum_le(w, t, pv, pt, te, args):
    return float(w[w <= args[0]].sum()) if w.size else nan


def _w_sum_gt(w, t, pv, pt, te, args):
    return float(w[w > args[0]].sum()) if w.size else nan


def _w_predict_linear(w, t, pv, pt, te, args):
    if w.size < 2:
        return nan
    t_s = (t - t[0]) / 1e3
    n = t_s.size
    st, sv = t_s.sum(), w.sum()
    stt, stv = (t_s * t_s).sum(), (t_s * w).sum()
    den = n * stt - st * st
    if den == 0:
        return nan
    k = (n * stv - st * sv) / den
    b = (sv - k * st) / n
    dt = (te - t[0]) / 1e3 + args[0]
    return float(k * dt + b)


def _w_holt_winters(w, t, pv, pt, te, args):
    sf, tf = args[0], args[1]
    if w.size < 2 or not (0 < sf < 1) or not (0 < tf < 1):
        return nan
    s = w[0]
    b = w[1] - w[0]
    for x in w[1:]:
        s_prev = s
        s = sf * x + (1 - sf) * (s + b)
        b = tf * (s - s_prev) + (1 - tf) * b
    return float(s)


def _w_mode(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    vals, counts = np.unique(w, return_counts=True)
    return float(vals[np.argmax(counts)])


def _w_ascent(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    seq = w if pv is None else np.concatenate([[pv], w])
    d = np.diff(seq)
    return float(d[d > 0].sum())


def _w_descent(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    seq = w if pv is None else np.concatenate([[pv], w])
    d = np.diff(seq)
    return float(-d[d < 0].sum())


def _w_changes_prometheus(w, t, pv, pt, te, args):
    # strict Prometheus semantics: no prev-value continuity
    if w.size == 0:
        return nan
    return float((np.diff(w) != 0).sum())


def _w_delta_prometheus(w, t, pv, pt, te, args):
    if w.size < 2:
        return nan
    return float(w[-1] - w[0])


def _w_increase_prometheus(w, t, pv, pt, te, args):
    if w.size < 2:
        return nan
    c = _remove_resets(w)
    return float(c[-1] - c[0])


def _w_ideriv(w, t, pv, pt, te, args):
    if w.size >= 2:
        dt = (t[-1] - t[-2]) / 1e3
        return float((w[-1] - w[-2]) / dt) if dt > 0 else nan
    if w.size == 1 and pv is not None:
        dt = (t[-1] - pt) / 1e3
        return float((w[-1] - pv) / dt) if dt > 0 else nan
    return nan


def _w_stale_samples(w, t, pv, pt, te, args):
    from ..ops import decimal as dec
    return float(dec.is_stale_nan(w).sum()) if w.size else nan


def _w_duration_over_time(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    max_gap = args[0] * 1e3 if args else nan
    d = np.diff(t).astype(np.float64)
    if args:
        d = d[d <= max_gap]
    return float(d.sum() / 1e3)


def _w_hoeffding_lower(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    avg, bound = _hoeffding(w, args[0])
    return float(max(avg - bound, 0.0))


def _w_hoeffding_upper(w, t, pv, pt, te, args):
    if w.size == 0:
        return nan
    avg, bound = _hoeffding(w, args[0])
    return float(avg + bound)


def _hoeffding(w, phi):
    rng = w.max() - w.min()
    if w.size < 2 or rng == 0 or not (0 < phi < 1):
        return float(w.mean()), 0.0
    bound = rng * np.sqrt(np.log(1.0 / (1 - phi)) / (2 * w.size))
    return float(w.mean()), float(bound)


def _w_tlast_change(w, t, pv, pt, te, args):
    # timestamp of the last value change (rollup.go:1669 rollupTlastChange)
    if w.size == 0:
        return nan
    last = w[-1]
    for i in range(w.size - 2, -1, -1):
        if w[i] != last:
            return float(t[i + 1]) / 1e3
    if pv is None or pv != last:
        return float(t[0]) / 1e3
    return nan


def _w_outlier_iqr(w, t, pv, pt, te, args):
    # last value when outside [q25-1.5iqr, q75+1.5iqr] (rollup.go:1427)
    if w.size < 2:
        return nan
    q25, q75 = np.quantile(w, [0.25, 0.75])
    iqr = 1.5 * (q75 - q25)
    v = float(w[-1])
    if v > q75 + iqr or v < q25 - iqr:
        return v
    return nan


# name -> (window_fn, n_extra_args, rollup_arg_index)
GENERIC_FUNCS = {
    "quantile_over_time": (_w_quantile, 1, 1),
    "median_over_time": (_w_median, 0, 0),
    "mad_over_time": (_w_mad, 0, 0),
    "iqr_over_time": (_w_iqr, 0, 0),
    "zscore_over_time": (_w_zscore, 0, 0),
    "range_over_time": (_w_range, 0, 0),
    "distinct_over_time": (_w_distinct, 0, 0),
    "geomean_over_time": (_w_geomean, 0, 0),
    "sum2_over_time": (_w_sum2, 0, 0),
    "tmin_over_time": (_w_tmin, 0, 0),
    "tmax_over_time": (_w_tmax, 0, 0),
    "resets": (_w_resets, 0, 0),
    "increases_over_time": (_w_increases, 0, 0),
    "decreases_over_time": (_w_decreases, 0, 0),
    "integrate": (_w_integrate, 0, 0),
    "rate_over_sum": (_w_rate_over_sum, 0, 0),
    "count_eq_over_time": (_w_count_eq, 1, 0),
    "count_ne_over_time": (_w_count_ne, 1, 0),
    "count_le_over_time": (_w_count_le, 1, 0),
    "count_gt_over_time": (_w_count_gt, 1, 0),
    "share_le_over_time": (_w_share_le, 1, 0),
    "share_gt_over_time": (_w_share_gt, 1, 0),
    "share_eq_over_time": (_w_share_eq, 1, 0),
    "sum_eq_over_time": (_w_sum_eq, 1, 0),
    "sum_le_over_time": (_w_sum_le, 1, 0),
    "sum_gt_over_time": (_w_sum_gt, 1, 0),
    "predict_linear": (_w_predict_linear, 1, 0),
    "holt_winters": (_w_holt_winters, 2, 0),
    "double_exponential_smoothing": (_w_holt_winters, 2, 0),
    "mode_over_time": (_w_mode, 0, 0),
    "ascent_over_time": (_w_ascent, 0, 0),
    "descent_over_time": (_w_descent, 0, 0),
    "changes_prometheus": (_w_changes_prometheus, 0, 0),
    "delta_prometheus": (_w_delta_prometheus, 0, 0),
    "increase_prometheus": (_w_increase_prometheus, 0, 0),
    "ideriv": (_w_ideriv, 0, 0),
    "stale_samples_over_time": (_w_stale_samples, 0, 0),
    "duration_over_time": (_w_duration_over_time, 1, 0),
    "hoeffding_bound_lower": (_w_hoeffding_lower, 1, 1),
    "hoeffding_bound_upper": (_w_hoeffding_upper, 1, 1),
    "timestamp_with_name": (None, 0, 0),   # alias of timestamp, keeps name
    "tlast_change_over_time": (_w_tlast_change, 0, 0),
    "outlier_iqr_over_time": (_w_outlier_iqr, 0, 0),
}

# multi-output rollups: name -> list of (rollup_tag, oracle-or-generic name)
MULTI_FUNCS = {
    "rollup": [("min", None), ("max", None), ("avg", None)],
    "rollup_rate": [("min", None), ("max", None), ("avg", None)],
    "rollup_increase": [("min", None), ("max", None), ("avg", None)],
    "rollup_delta": [("min", None), ("max", None), ("avg", None)],
    "rollup_deriv": [("min", None), ("max", None), ("avg", None)],
    "rollup_candlestick": [("open", "first_over_time"),
                           ("close", "last_over_time"),
                           ("high", "max_over_time"),
                           ("low", "min_over_time")],
    "rollup_scrape_interval": [("min", None), ("max", None), ("avg", None)],
}


def _deriv_values(vals: np.ndarray, ts: np.ndarray) -> np.ndarray:
    """rollup.go:976 derivValues: replace each value with the derivative of
    the pair (i, i+1), assigned to the LEFT index; the last value repeats the
    last derivative; duplicate timestamps reuse the previous derivative."""
    v = np.asarray(vals, dtype=np.float64).copy()
    if v.size <= 1:
        if v.size == 1:
            v[0] = 0.0
        return v
    dts = np.diff(ts)
    if np.all(dts > 0):
        d = np.diff(v) / (dts / 1e3)
        v[:-1] = d
        v[-1] = d[-1]
        return v
    prev_deriv, prev_val, prev_ts = 0.0, v[0], ts[0]
    out = v.copy()
    for i in range(1, v.size):
        if ts[i] == prev_ts:
            out[i - 1] = prev_deriv
            continue
        prev_deriv = (v[i] - prev_val) / ((ts[i] - prev_ts) / 1e3)
        out[i - 1] = prev_deriv
        prev_val, prev_ts = v[i], ts[i]
    out[-1] = prev_deriv
    return out


def _delta_values(vals: np.ndarray) -> np.ndarray:
    """rollup.go:960 deltaValues: pairwise delta assigned to the LEFT index,
    last value repeats the last delta."""
    v = np.asarray(vals, dtype=np.float64).copy()
    if v.size <= 1:
        if v.size == 1:
            v[0] = 0.0
        return v
    d = np.diff(v)
    v[:-1] = d
    v[-1] = d[-1]
    return v


def _interval_values(ts: np.ndarray) -> np.ndarray:
    """rollup_scrape_interval preprocessing (rollup.go:478): seconds between
    adjacent samples; the leading NaN is overwritten with the 2nd interval."""
    v = np.empty(ts.shape, dtype=np.float64)
    if v.size == 0:
        return v
    v[0] = np.nan
    if v.size > 1:
        v[1:] = np.diff(ts) / 1e3
        v[0] = v[1]
    return v


# pre-transform applied to the whole series before min/max/avg windowing
# (rollup.go:413-495 appendRollupConfigs + preFunc chain)
PRE_ROLLUP_FUNCS = frozenset((
    "rollup", "rollup_rate", "rollup_deriv", "rollup_increase",
    "rollup_delta", "rollup_scrape_interval"))


def _candlestick(kind: str, ts: np.ndarray, vals: np.ndarray,
                 cfg: RollupConfig) -> np.ndarray:
    """rollup_candlestick OHLC (rollup.go:2209-2283 + eval.go:943): windows
    are shifted one step FORWARD (`offset -step` auto-applied), samples at
    the window end are excluded, and `open` is the last sample at/before the
    window start when it lies within the window length. The one-step
    forward grid shift (`offset -step`, eval.go:943) is applied by the
    EVALUATOR via a shifted EvalConfig so the inner subquery grid shifts
    with it."""
    out_ts = cfg.out_timestamps()
    window = cfg.lookback
    lo = np.searchsorted(ts, out_ts - window, side="right")
    hi = np.searchsorted(ts, out_ts, side="left")  # drop ts >= currTimestamp
    out = np.full(out_ts.size, np.nan)
    for j in range(out_ts.size):
        a, b = lo[j], hi[j]
        w = vals[a:b]
        first = nan
        if a >= 1 and ts[a - 1] + window >= out_ts[j]:
            first = float(vals[a - 1])
        if kind == "open":
            out[j] = first if first == first else (w[0] if w.size else nan)
        elif kind == "close":
            out[j] = w[-1] if w.size else first
        elif kind == "high":
            if first == first:
                out[j] = max(first, w.max()) if w.size else first
            else:
                out[j] = w.max() if w.size else nan
        elif kind == "low":
            if first == first:
                out[j] = min(first, w.min()) if w.size else first
            else:
                out[j] = w.min() if w.size else nan
    return out


def _pre_rollup(func: str, ts: np.ndarray, vals: np.ndarray,
                cfg: RollupConfig, args: tuple) -> np.ndarray:
    agg = args[0] if args and isinstance(args[0], str) else "avg"
    v = np.asarray(vals, dtype=np.float64)
    if func in ("rollup_rate", "rollup_increase"):
        v = rollup_np.remove_counter_resets(v)
    if func in ("rollup_rate", "rollup_deriv"):
        v = _deriv_values(v, ts)
    elif func in ("rollup_increase", "rollup_delta"):
        v = _delta_values(v)
    elif func == "rollup_scrape_interval":
        v = _interval_values(ts)
    return rollup_np.rollup(f"{agg}_over_time", ts, v, cfg)

# funcs whose implicit window expands to cover >=2 samples
# (rollup.go:204 rollupFuncsCanAdjustWindow; default_rollup excluded here
# because our default_rollup already uses the full lookback_delta window)
ADJUSTABLE_WINDOW_FUNCS = frozenset("""
deriv deriv_fast ideriv irate rate rate_over_sum rollup
rollup_candlestick rollup_deriv rollup_rate rollup_scrape_interval
scrape_interval timestamp
""".split())


# canonical implementations live in ops/rollup_np.py (the window walkers
# there share them for prevValue gating); re-exported here for the
# adjusted-window machinery and tests
scrape_interval_estimate = rollup_np.scrape_interval_estimate
max_prev_interval = rollup_np.max_prev_interval


def adjusted_window_ms(func: str, ts: np.ndarray, step: int) -> int:
    """The implicit lookbehind for rate/deriv-style funcs: at least the
    series' (inflated) scrape interval so windows hold >=2 samples
    (rollup.go:747-751)."""
    w = step
    if func in ADJUSTABLE_WINDOW_FUNCS:
        mpi = max_prev_interval(scrape_interval_estimate(ts, step))
        if w < mpi:
            w = mpi
    return w


def adjusted_windows(func: str, window: int, step: int, ts_list
                     ) -> list[int] | None:
    """Per-series adjusted windows for an implicit lookbehind, or None
    when no adjustment applies (explicit window / non-adjustable func)."""
    if window != 0 or func not in ADJUSTABLE_WINDOW_FUNCS or not ts_list:
        return None
    S = len(ts_list)
    if S >= 64:
        # batched: only the last <=21 samples of each series matter, so pack
        # the tails and run the vectorized estimator once (bit-compatible
        # with the per-series path)
        tails = [np.asarray(ts)[-21:] for ts in ts_list]
        counts = np.fromiter((t.size for t in tails), np.int64, count=S)
        t2 = np.full((S, 21), np.iinfo(np.int64).max, dtype=np.int64)
        t2[np.arange(21)[None, :] < counts[:, None]] = np.concatenate(tails)
        mpi = rollup_np.max_prev_interval_batch(
            rollup_np.scrape_interval_estimate_batch(t2, counts, step))
        return np.maximum(mpi, step).tolist()
    return [adjusted_window_ms(func, ts, step) for ts in ts_list]


# funcs that keep the metric name in results (rollup.go keepMetricName set)
KEEP_METRIC_NAMES = frozenset("""
avg_over_time default_rollup first_over_time geomean_over_time
hoeffding_bound_lower hoeffding_bound_upper holt_winters iqr_over_time
last_over_time max_over_time median_over_time min_over_time mode_over_time
predict_linear quantile_over_time quantiles_over_time rollup
rollup_candlestick timestamp_with_name double_exponential_smoothing
""".split())

ROLLUP_FUNC_NAMES = (ORACLE_FUNCS | set(GENERIC_FUNCS) | set(MULTI_FUNCS)
                     | {"aggr_over_time", "quantiles_over_time",
                        "absent_over_time", "rate_prometheus",
                        "count_values_over_time", "histogram_over_time"})


def generic_rollup(fn, ts: np.ndarray, vals: np.ndarray, cfg: RollupConfig,
                   args: tuple = ()) -> np.ndarray:
    """Apply a per-window function over one series (the long-tail path)."""
    out_ts = cfg.out_timestamps()
    lo = np.searchsorted(ts, out_ts - cfg.lookback, side="right")
    hi = np.searchsorted(ts, out_ts, side="right")
    out = np.full(out_ts.size, np.nan)
    # prevValue is seeded only when the sample before the window lies within
    # maxPrevInterval of the window start (rollup.go:781 doInternal)
    mpi = rollup_np._max_prev_interval_for(np.asarray(ts), cfg)
    for j in range(out_ts.size):
        a, b = lo[j], hi[j]
        if b <= a and a == 0:
            continue
        pv = pt = None
        if a >= 1 and ts[a - 1] > out_ts[j] - cfg.lookback - mpi:
            pv = float(vals[a - 1])
            pt = int(ts[a - 1])
        if b <= a:
            continue
        out[j] = fn(vals[a:b], ts[a:b], pv, pt, int(out_ts[j]), args)
    return out


def rollup_series(func: str, ts: np.ndarray, vals: np.ndarray,
                  cfg: RollupConfig, args: tuple = ()) -> np.ndarray:
    """Single-series rollup dispatch: oracle fast path else generic."""
    if func == "timestamp_with_name":
        func = "timestamp"
    if func == "absent_over_time":
        # 1 for empty windows, NaN otherwise (rollup.go:1755 rollupAbsent;
        # the cross-series collapse happens in eval)
        cnt = rollup_np.rollup("count_over_time", ts, vals, cfg)
        return np.where(np.isnan(cnt), 1.0, np.nan)
    if func in PRE_ROLLUP_FUNCS:
        return _pre_rollup(func, ts, vals, cfg, args)
    if func == "rollup_candlestick":
        return _candlestick(args[0] if args else "close", ts, vals, cfg)
    if func == "rate_prometheus":
        # delta_prometheus / window_seconds (rollup.go:1946)
        c = rollup_np.remove_counter_resets(vals)
        d = generic_rollup(_w_delta_prometheus, ts, c, cfg, args)
        return d / (cfg.lookback / 1e3)
    if func in ORACLE_FUNCS:
        return rollup_np.rollup(func, ts, vals, cfg)
    spec = GENERIC_FUNCS.get(func)
    if spec is None:
        raise ValueError(f"unknown rollup function {func!r}")
    fn, _, _ = spec
    return generic_rollup(fn, ts, vals, cfg, args)
