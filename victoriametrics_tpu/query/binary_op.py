"""Binary operators with vector matching (reference
app/vmselect/promql/binary_op.go:15-205).

Arithmetic, comparison (filtering or bool), set ops (and/or/unless), and the
MetricsQL extensions default/if/ifnot. Matching: one-to-one by full label
signature (minus metric name) or on()/ignoring(); many-to-one via
group_left/group_right with optional label copying.
"""

from __future__ import annotations

import numpy as np

from ..storage.metric_name import MetricName
from .types import Timeseries

nan = np.nan


def _arith(fn):
    def wrapped(a, b):
        with np.errstate(all="ignore"):
            return fn(a, b)
    return wrapped


ARITH_OPS = {
    "+": _arith(lambda a, b: a + b),
    "-": _arith(lambda a, b: a - b),
    "*": _arith(lambda a, b: a * b),
    "/": _arith(lambda a, b: a / b),
    "%": _arith(lambda a, b: np.fmod(a, b)),
    "^": _arith(lambda a, b: np.power(a, b)),
    "atan2": _arith(lambda a, b: np.arctan2(a, b)),
}

CMP_OPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    ">": lambda a, b: a > b,
    "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
}

SET_OPS = {"and", "or", "unless", "default", "if", "ifnot"}


def signature(mn: MetricName, on: list[str] | None, ignoring: list[str] | None
              ) -> tuple:
    """Label signature for matching (metric name excluded unless on() lists
    __name__)."""
    if on is not None:
        keys = set(on)
        items = []
        for k in sorted(keys):
            kb = k.encode()
            if kb == b"__name__":
                items.append((kb, mn.metric_group))
            else:
                v = mn.get_label(kb)
                items.append((kb, v or b""))
        return tuple(items)
    ig = {k.encode() for k in (ignoring or [])}
    return tuple((k, v) for k, v in mn.labels if k not in ig)


def merge_values_non_overlapping(dv: np.ndarray, sv: np.ndarray) -> bool:
    """Array-level mergeNonOverlappingTimeseries (binary_op.go:367): merge
    src values into dst in place when they overlap in <=2 points and have
    enough points; src wins at the (<=2) overlap points."""
    overlaps = int((~np.isnan(sv) & ~np.isnan(dv)).sum())
    if overlaps > 2:
        return False
    if sv.size <= 2 and dv.size <= 2:
        return False
    ok = ~np.isnan(sv)
    dv[ok] = sv[ok]
    return True


def _merge_non_overlapping(dst: Timeseries, src: Timeseries) -> bool:
    """Merge src into dst when they overlap in <=2 points and have enough
    points (binary_op.go:367 mergeNonOverlappingTimeseries): duplicate
    signatures from complementary filters like (m<10, m>=10) combine."""
    return merge_values_non_overlapping(dst.values, src.values)


def _group_by_sig(series, on, ignoring):
    m: dict[tuple, list] = {}
    order = []
    for ts in series:
        sig = signature(ts.metric_name, on, ignoring)
        if sig not in m:
            order.append(sig)
        m.setdefault(sig, []).append(ts)
    return m, order


def _merge_group(tss, side: str, op: str) -> Timeseries:
    """Collapse one signature group by non-overlapping merge; raise only
    when the group genuinely overlaps (ensureSingleTimeseries semantics —
    unmatched groups never reach this)."""
    cur = Timeseries(tss[0].metric_name, tss[0].values.copy())
    for ts in tss[1:]:
        if not _merge_non_overlapping(cur, ts):
            raise ValueError(
                f"duplicate time series on the {side} side of {op}: "
                f"{ts.metric_name}")
    return cur


def _result_labels(left_mn: MetricName, keep_name: bool) -> MetricName:
    return MetricName(left_mn.metric_group if keep_name else b"",
                      list(left_mn.labels))


def _set_join_tags(mn, add: list[bytes], prefix: bytes, skip: set[bytes],
                   src) -> None:
    """metric_name.go:317 SetTags: copy the join tags from the one side onto
    the result. `*` copies ALL non-skip tags; a named tag missing on the one
    side is REMOVED from the result; `prefix` prepends to copied tag names."""
    if add == [b"*"]:
        for k, v in src.labels:
            if k in skip:
                continue
            nk = prefix + k
            mn.labels = [(a, b) for a, b in mn.labels if a != nk]
            mn.labels.append((nk, v))
        mn.sort_labels()
        return
    for tag in add:
        if tag in skip:
            continue
        if tag == b"__name__":
            mn.metric_group = src.metric_group
            continue
        v = src.get_label(tag)
        if v is not None:
            # SetTagBytes only overwrites prefix+tag; with a prefix the
            # many side's own unprefixed tag survives (metric_name.go:344)
            mn.labels = [(a, b) for a, b in mn.labels if a != prefix + tag]
            mn.labels.append((prefix + tag, v))
        else:
            # missing on the one side: the UNPREFIXED tag is removed
            # (metric_name.go:341 RemoveTag(tagName))
            mn.labels = [(a, b) for a, b in mn.labels if a != tag]
    mn.sort_labels()


def eval_binary_op(op: str, left: list[Timeseries], right: list[Timeseries],
                   bool_modifier: bool, group_mod, join_mod,
                   keep_metric_names: bool, is_cmp_with_scalar_right=None
                   ) -> list[Timeseries]:
    on = group_mod.args if group_mod.op == "on" else None
    ignoring = group_mod.args if group_mod.op == "ignoring" else None

    if op in SET_OPS:
        return _eval_set_op(op, left, right, on, ignoring)

    is_cmp = op in CMP_OPS
    fn = CMP_OPS[op] if is_cmp else ARITH_OPS[op]

    swap = join_mod.op == "group_left"
    # group_left: many on the LEFT match one on the right; group_right is the
    # mirror. We normalize to "many" and "one" sides.
    if join_mod.op == "group_right":
        many, one = right, left
    elif join_mod.op == "group_left":
        many, one = left, right
    else:
        many = one = None

    out: list[Timeseries] = []
    if many is not None:
        # binary_op.go:304 groupJoin: each many-side series pairs with EVERY
        # matching one-side series; the join tags copied from the one side
        # (with optional `prefix`) must make the results unique, else the
        # one-side values are merged when non-overlapping (duplicate error
        # otherwise).
        one_groups, _ = _group_by_sig(one, on, ignoring)
        extra = [l.encode() for l in join_mod.args]
        prefix = getattr(join_mod, "prefix", "").encode()
        skip = {k.encode() for k in on} if on is not None else set()
        keep = keep_metric_names or (is_cmp and not bool_modifier)
        pairs: list[tuple] = []           # (joined MetricName, many, one)
        for m_ts in many:
            grp = one_groups.get(signature(m_ts.metric_name, on, ignoring))
            if grp is None:
                continue
            # the duplicate-name map resets per many-side series
            # (binary_op.go:331); identical joined names from DIFFERENT
            # many series are legal duplicate outputs
            pair_idx: dict[bytes, int] = {}
            for o_ts in grp:
                mn = _result_labels(m_ts.metric_name, keep)
                _set_join_tags(mn, extra, prefix, skip, o_ts.metric_name)
                if len(grp) == 1:
                    pairs.append((mn, m_ts, o_ts))
                    continue
                key = mn.marshal()
                hit = pair_idx.get(key)
                if hit is None:
                    pair_idx[key] = len(pairs)
                    # merge destination: values must be OWNED — the merge
                    # below writes in place, and o_ts.values may be a
                    # read-only result-cache view (or shared with other
                    # pairs via copy_shallow_labels)
                    pairs.append((mn, m_ts,
                                  Timeseries(o_ts.metric_name,
                                             o_ts.values.copy())))
                elif not _merge_non_overlapping(pairs[hit][2], o_ts):
                    raise ValueError(
                        f"duplicate time series on the 'one' side of "
                        f"{op} {join_mod.op}: {mn}")
        for mn, m_ts, o_ts in pairs:
            lv, rv = (m_ts.values, o_ts.values)
            a, b = (lv, rv) if join_mod.op == "group_left" else (rv, lv)
            vals = _apply(fn, a, b, is_cmp, bool_modifier,
                          keep_left=m_ts.values)
            out.append(Timeseries(mn, vals))
        return out

    right_groups, _ = _group_by_sig(right, on, ignoring)
    left_groups, left_order = _group_by_sig(left, on, ignoring)
    for sig in left_order:
        r_grp = right_groups.get(sig)
        if r_grp is None:
            continue  # unmatched groups are dropped, duplicates and all
        l_ts = _merge_group(left_groups[sig], "left", op)
        r_ts = _merge_group(r_grp, "right", op)
        vals = _apply(fn, l_ts.values, r_ts.values, is_cmp, bool_modifier,
                      keep_left=l_ts.values)
        keep_name = keep_metric_names or (is_cmp and not bool_modifier)
        mn = _result_labels(l_ts.metric_name, keep_name)
        if on is not None:
            # RemoveTagsOn (metric_name.go:247) resets the metric group
            # unless __name__ is in the on-list; only an explicit
            # keep_metric_names adds it there (binary_op.go:238) — a non-bool
            # comparison does NOT survive the on() reduction
            keep = {k.encode() for k in on}
            mn.labels = [(k, v) for k, v in mn.labels if k in keep]
            if b"__name__" not in keep and not keep_metric_names:
                mn.metric_group = b""
        elif ignoring is not None:
            # reference binary_op.go one-to-one branch calls
            # MetricName.RemoveTagsIgnoring(groupTags): ignored labels are
            # dropped from the result series
            drop = {k.encode() for k in ignoring}
            mn.labels = [(k, v) for k, v in mn.labels if k not in drop]
        out.append(Timeseries(mn, vals))
    return out


def _apply(fn, a, b, is_cmp, bool_modifier, keep_left):
    if not is_cmp:
        return np.asarray(fn(a, b), dtype=np.float64)
    with np.errstate(all="ignore"):
        m = fn(a, b)
    m = m & ~np.isnan(a) & ~np.isnan(b)
    if bool_modifier:
        out = m.astype(np.float64)
        out[np.isnan(a) | np.isnan(b)] = nan
        return out
    return np.where(m, keep_left, nan)


def _group_map(series, on, ignoring):
    m: dict[tuple, list] = {}
    for ts in series:
        m.setdefault(signature(ts.metric_name, on, ignoring), []).append(ts)
    return m


def _any_right_value(rights):
    """[T] bool: does ANY series in the group have a value at each step."""
    return ~np.all(np.vstack([np.isnan(r.values) for r in rights]), axis=0)


def _is_scalar_group(tss) -> bool:
    return (len(tss) == 1 and not tss[0].metric_name.metric_group
            and not tss[0].metric_name.labels)


def _series_by_key(m: dict, sig):
    """mr lookup with the reference's seriesByKey fallback: a lone
    scalar-signature right group matches every left signature."""
    got = m.get(sig)
    if got is not None:
        return got
    if len(m) == 1:
        (only,) = m.values()
        if _is_scalar_group(only):
            return only
    return None


def _eval_set_op(op, left, right, on, ignoring):
    """Group-based per-point set ops (binary_op.go:416-623): groups are the
    on()/ignoring() signature; and/if mask left to right-present points,
    unless/ifnot to right-absent, default fills left gaps from the group,
    or merges per point (with whole-labelset merge for identical series)."""
    ml = _group_map(left, on, ignoring)
    mr = _group_map(right, on, ignoring)
    out: list[Timeseries] = []

    if op in ("and", "if"):
        for sig, lefts in ml.items():
            rights = mr.get(sig) if op == "and" else _series_by_key(mr, sig)
            if not rights:
                continue
            has = _any_right_value(rights)
            for ts in lefts:
                out.append(Timeseries(ts.metric_name,
                                      np.where(has, ts.values, nan)))
        return out

    if op in ("unless", "ifnot"):
        for sig, lefts in ml.items():
            rights = (mr.get(sig) if op == "unless"
                      else _series_by_key(mr, sig))
            if not rights:
                out.extend(lefts)
                continue
            has = _any_right_value(rights)
            for ts in lefts:
                out.append(Timeseries(ts.metric_name,
                                      np.where(has, nan, ts.values)))
        return out

    if op == "default":
        if not ml:
            for rights in mr.values():
                out.extend(rights)
            return out
        for sig, lefts in ml.items():
            rights = _series_by_key(mr, sig)
            if not rights:
                out.extend(lefts)
                continue
            for ts in lefts:
                vals = ts.values.copy()
                for r in rights:
                    gap = np.isnan(vals)
                    if not gap.any():
                        break
                    vals[gap] = r.values[gap]
                out.append(Timeseries(ts.metric_name, vals))
        return out

    if op == "or":
        # left side first (non-empty series), then per-group right handling
        # (binary_op.go:483 binaryOpOr)
        kept_left: dict[tuple, list] = {}
        for sig, lefts in ml.items():
            # copies: the merge below fills left gaps in place
            keep = [Timeseries(ts.metric_name, ts.values.copy())
                    for ts in lefts if not np.isnan(ts.values).all()]
            kept_left[sig] = keep
            out.extend(keep)
        out.sort(key=lambda ts: ts.metric_name.marshal())
        n_left = len(out)
        for sig, rights in mr.items():
            lefts = kept_left.get(sig)
            if not lefts:
                out.extend(rights)
                continue
            rights = [Timeseries(r.metric_name, r.values.copy())
                      for r in rights]
            scalar_right = _is_scalar_group(rights)
            for ts in lefts:
                merged_scalar = scalar_right and _is_scalar_group([ts])
                lname = ts.metric_name.marshal()
                for r in rights:
                    mergeable = merged_scalar or                         r.metric_name.marshal() == lname
                    left_nan = np.isnan(ts.values)
                    if mergeable:
                        ts.values[left_nan] = r.values[left_nan]
                        r.values[:] = nan
                    else:
                        r.values[~left_nan] = nan
            extra = [r for r in rights if not np.isnan(r.values).all()]
            extra.sort(key=lambda ts: ts.metric_name.marshal())
            out.extend(extra)
        out[n_left:] = sorted(out[n_left:],
                              key=lambda ts: ts.metric_name.marshal())
        return out

    raise ValueError(f"unknown set op {op}")
