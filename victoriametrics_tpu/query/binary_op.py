"""Binary operators with vector matching (reference
app/vmselect/promql/binary_op.go:15-205).

Arithmetic, comparison (filtering or bool), set ops (and/or/unless), and the
MetricsQL extensions default/if/ifnot. Matching: one-to-one by full label
signature (minus metric name) or on()/ignoring(); many-to-one via
group_left/group_right with optional label copying.
"""

from __future__ import annotations

import numpy as np

from ..storage.metric_name import MetricName
from .types import Timeseries

nan = np.nan


def _arith(fn):
    def wrapped(a, b):
        with np.errstate(all="ignore"):
            return fn(a, b)
    return wrapped


ARITH_OPS = {
    "+": _arith(lambda a, b: a + b),
    "-": _arith(lambda a, b: a - b),
    "*": _arith(lambda a, b: a * b),
    "/": _arith(lambda a, b: a / b),
    "%": _arith(lambda a, b: np.fmod(a, b)),
    "^": _arith(lambda a, b: np.power(a, b)),
    "atan2": _arith(lambda a, b: np.arctan2(a, b)),
}

CMP_OPS = {
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    ">": lambda a, b: a > b,
    "<": lambda a, b: a < b,
    ">=": lambda a, b: a >= b,
    "<=": lambda a, b: a <= b,
}

SET_OPS = {"and", "or", "unless", "default", "if", "ifnot"}


def signature(mn: MetricName, on: list[str] | None, ignoring: list[str] | None
              ) -> tuple:
    """Label signature for matching (metric name excluded unless on() lists
    __name__)."""
    if on is not None:
        keys = set(on)
        items = []
        for k in sorted(keys):
            kb = k.encode()
            if kb == b"__name__":
                items.append((kb, mn.metric_group))
            else:
                v = mn.get_label(kb)
                items.append((kb, v or b""))
        return tuple(items)
    ig = {k.encode() for k in (ignoring or [])}
    return tuple((k, v) for k, v in mn.labels if k not in ig)


def _result_labels(left_mn: MetricName, keep_name: bool) -> MetricName:
    return MetricName(left_mn.metric_group if keep_name else b"",
                      list(left_mn.labels))


def eval_binary_op(op: str, left: list[Timeseries], right: list[Timeseries],
                   bool_modifier: bool, group_mod, join_mod,
                   keep_metric_names: bool, is_cmp_with_scalar_right=None
                   ) -> list[Timeseries]:
    on = group_mod.args if group_mod.op == "on" else None
    ignoring = group_mod.args if group_mod.op == "ignoring" else None

    if op in SET_OPS:
        return _eval_set_op(op, left, right, on, ignoring)

    is_cmp = op in CMP_OPS
    fn = CMP_OPS[op] if is_cmp else ARITH_OPS[op]

    swap = join_mod.op == "group_left"
    # group_left: many on the LEFT match one on the right; group_right is the
    # mirror. We normalize to "many" and "one" sides.
    if join_mod.op == "group_right":
        many, one = right, left
    elif join_mod.op == "group_left":
        many, one = left, right
    else:
        many = one = None

    out: list[Timeseries] = []
    if many is not None:
        one_by_sig: dict[tuple, Timeseries] = {}
        for ts in one:
            sig = signature(ts.metric_name, on, ignoring)
            if sig in one_by_sig:
                raise ValueError(
                    f"duplicate series on the 'one' side of {op} "
                    f"{join_mod.op} for {ts.metric_name}")
            one_by_sig[sig] = ts
        extra = [l.encode() for l in join_mod.args]
        for m_ts in many:
            o_ts = one_by_sig.get(signature(m_ts.metric_name, on, ignoring))
            if o_ts is None:
                continue
            lv, rv = (m_ts.values, o_ts.values)
            a, b = (lv, rv) if join_mod.op == "group_left" else (rv, lv)
            vals = _apply(fn, a, b, is_cmp, bool_modifier,
                          keep_left=m_ts.values)
            mn = _result_labels(m_ts.metric_name,
                                keep_metric_names or (is_cmp and not bool_modifier))
            for lab in extra:
                v = o_ts.metric_name.get_label(lab)
                mn.labels = [(k, x) for k, x in mn.labels if k != lab]
                if v:
                    mn.labels.append((lab, v))
            mn.sort_labels()
            out.append(Timeseries(mn, vals))
        return out

    right_by_sig: dict[tuple, Timeseries] = {}
    for ts in right:
        sig = signature(ts.metric_name, on, ignoring)
        if sig in right_by_sig:
            raise ValueError(f"duplicate series on right side of {op}: "
                             f"{ts.metric_name}")
        right_by_sig[sig] = ts
    seen = set()
    for l_ts in left:
        sig = signature(l_ts.metric_name, on, ignoring)
        r_ts = right_by_sig.get(sig)
        if r_ts is None:
            continue
        if sig in seen:
            raise ValueError(f"duplicate series on left side of {op}")
        seen.add(sig)
        vals = _apply(fn, l_ts.values, r_ts.values, is_cmp, bool_modifier,
                      keep_left=l_ts.values)
        mn = _result_labels(l_ts.metric_name,
                            keep_metric_names or (is_cmp and not bool_modifier))
        if on is not None:
            keep = {k.encode() for k in on}
            mn.labels = [(k, v) for k, v in mn.labels if k in keep]
            if b"__name__" not in keep:
                mn.metric_group = b""
        elif ignoring is not None:
            # reference binary_op.go one-to-one branch calls
            # MetricName.RemoveTagsIgnoring(groupTags): ignored labels are
            # dropped from the result series
            drop = {k.encode() for k in ignoring}
            mn.labels = [(k, v) for k, v in mn.labels if k not in drop]
        out.append(Timeseries(mn, vals))
    return out


def _apply(fn, a, b, is_cmp, bool_modifier, keep_left):
    if not is_cmp:
        return np.asarray(fn(a, b), dtype=np.float64)
    with np.errstate(all="ignore"):
        m = fn(a, b)
    m = m & ~np.isnan(a) & ~np.isnan(b)
    if bool_modifier:
        out = m.astype(np.float64)
        out[np.isnan(a) | np.isnan(b)] = nan
        return out
    return np.where(m, keep_left, nan)


def _eval_set_op(op, left, right, on, ignoring):
    right_sigs = {}
    for ts in right:
        right_sigs.setdefault(signature(ts.metric_name, on, ignoring), ts)
    out = []
    if op == "and":
        for ts in left:
            r = right_sigs.get(signature(ts.metric_name, on, ignoring))
            if r is not None:
                vals = np.where(np.isnan(r.values), nan, ts.values)
                out.append(Timeseries(ts.metric_name, vals))
        return out
    if op == "unless":
        for ts in left:
            r = right_sigs.get(signature(ts.metric_name, on, ignoring))
            if r is None:
                out.append(ts)
            else:
                vals = np.where(np.isnan(r.values), ts.values, nan)
                out.append(Timeseries(ts.metric_name, vals))
        return out
    if op == "or":
        left_sigs = {signature(ts.metric_name, on, ignoring) for ts in left}
        out = list(left)
        for ts in right:
            if signature(ts.metric_name, on, ignoring) not in left_sigs:
                out.append(ts)
        return out
    if op == "default":
        for ts in left:
            r = right_sigs.get(signature(ts.metric_name, on, ignoring))
            if r is None:
                out.append(ts)
            else:
                vals = np.where(np.isnan(ts.values), r.values, ts.values)
                out.append(Timeseries(ts.metric_name, vals))
        return out
    if op == "if":
        for ts in left:
            r = right_sigs.get(signature(ts.metric_name, on, ignoring))
            if r is not None:
                vals = np.where(np.isnan(r.values), nan, ts.values)
                out.append(Timeseries(ts.metric_name, vals))
        return out
    if op == "ifnot":
        for ts in left:
            r = right_sigs.get(signature(ts.metric_name, on, ignoring))
            if r is None:
                out.append(ts)
            else:
                vals = np.where(np.isnan(r.values), ts.values, nan)
                out.append(Timeseries(ts.metric_name, vals))
        return out
    raise ValueError(f"unknown set op {op}")
