"""Query execution top (reference app/vmselect/promql/exec.go:36): parse
cache -> eval -> sorted results."""

from __future__ import annotations

import threading

import numpy as np

from .eval import QueryError, eval_expr
from .metricsql import parse
from .metricsql.ast import Expr
from .types import EvalConfig, Timeseries

_parse_cache: dict[str, Expr] = {}
_parse_lock = threading.Lock()
_PARSE_CACHE_MAX = 10_000


def parse_cached(q: str) -> Expr:
    with _parse_lock:
        e = _parse_cache.get(q)
    if e is not None:
        return e
    e = parse(q)
    with _parse_lock:
        if len(_parse_cache) >= _PARSE_CACHE_MAX:
            _parse_cache.clear()
        _parse_cache[q] = e
    return e


_SORT_FUNCS = frozenset({
    "sort", "sort_desc", "sort_by_label", "sort_by_label_desc",
    "sort_by_label_numeric", "sort_by_label_numeric_desc", "limit_offset"})


def exec_query(ec: EvalConfig, q: str) -> list[Timeseries]:
    """Range query: returns series on the ec grid, sorted by labels unless
    the top-level function imposes its own order (exec.go:80-100 analog)."""
    expr = parse_cached(q)
    rows = eval_expr(ec, expr)
    # drop all-NaN series (absent everywhere)
    out = [ts for ts in rows if not np.isnan(ts.values).all()]
    from .metricsql.ast import FuncExpr
    if not (isinstance(expr, FuncExpr) and expr.name in _SORT_FUNCS):
        out.sort(key=lambda ts: ts.metric_name.marshal())
    return out


def exec_instant(ec_base: EvalConfig, q: str, ts_ms: int) -> list[Timeseries]:
    """Instant query at ts_ms (single-point grid)."""
    ec = ec_base.child(start=ts_ms, end=ts_ms)
    return exec_query(ec, q)
