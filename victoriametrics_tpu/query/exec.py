"""Query execution top (reference app/vmselect/promql/exec.go:36): parse
cache -> eval -> sorted results."""

from __future__ import annotations

import os
import threading

import numpy as np

from .eval import QueryError, eval_expr
from .metricsql import parse
from .metricsql.ast import Expr
from .types import EvalConfig, Timeseries

_parse_cache: dict[tuple, Expr] = {}
_parse_lock = threading.Lock()
_PARSE_CACHE_MAX = 10_000


def optimize_enabled() -> bool:
    """Common-filter pushdown (metricsql Optimize analog) on?
    ``VM_MQL_OPTIMIZE=0`` restores raw-parse evaluation exactly — the
    escape hatch AND the equality oracle."""
    return os.environ.get("VM_MQL_OPTIMIZE", "1") != "0"


def parse_cached(q: str) -> Expr:
    """Parse (and, by default, optimize) one query; the cache key
    includes the optimizer flag so flipping VM_MQL_OPTIMIZE never serves
    a stale AST from the other mode."""
    opt = optimize_enabled()
    key = (q, opt)
    with _parse_lock:
        e = _parse_cache.get(key)
    if e is not None:
        return e
    e = parse(q)
    if opt:
        from .metricsql.optimizer import optimize
        e = optimize(e)
    with _parse_lock:
        if len(_parse_cache) >= _PARSE_CACHE_MAX:
            _parse_cache.clear()
        _parse_cache[key] = e
    return e


_SORT_FUNCS = frozenset({
    "sort", "sort_desc", "sort_by_label", "sort_by_label_desc",
    "sort_by_label_numeric", "sort_by_label_numeric_desc", "limit_offset"})


def exec_query(ec: EvalConfig, q: str) -> list[Timeseries]:
    """Range query: returns series on the ec grid, sorted by labels unless
    the top-level function imposes its own order (exec.go:80-100 analog)."""
    expr = parse_cached(q)
    # every storage/cache/device seam under this eval accounts into the
    # query's CostTracker (workpool propagates it to fan-out workers);
    # nested evals over the same shared tracker re-install it, harmless
    import time as _time

    from ..utils import costacc
    prev_cost = costacc.set_current(ec._cost)
    t0 = _time.perf_counter()
    w0 = ec._cost.local_wall_ms_total()
    try:
        rows = eval_expr(ec, expr)
    finally:
        # name the leftover: eval wall not claimed by any LOCAL phase
        # lap (parse/AST walk/series glue) lands in eval:other instead
        # of silently vanishing from the cost split.  Baseline is the
        # local-lap total only — remote nodes' laps merged in during a
        # fan-out accrue concurrently and may sum past local wall,
        # which would wrongly suppress this bucket
        dt_ms = (_time.perf_counter() - t0) * 1e3
        inner_ms = ec._cost.local_wall_ms_total() - w0
        if dt_ms > inner_ms:
            costacc.lap("eval:other", (dt_ms - inner_ms) / 1e3)
        costacc.set_current(prev_cost)
    # drop all-NaN series (absent everywhere)
    out = [ts for ts in rows if not np.isnan(ts.values).all()]
    from .metricsql.ast import FuncExpr
    if not (isinstance(expr, FuncExpr) and expr.name in _SORT_FUNCS):
        out.sort(key=lambda ts: ts.metric_name.marshal())
    return out


def exec_instant(ec_base: EvalConfig, q: str, ts_ms: int) -> list[Timeseries]:
    """Instant query at ts_ms (single-point grid)."""
    ec = ec_base.child(start=ts_ms, end=ts_ms)
    return exec_query(ec, q)
