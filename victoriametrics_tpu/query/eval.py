"""The MetricsQL evaluator (reference app/vmselect/promql/eval.go:279-1900).

Walks the AST producing lists of Timeseries on the shared output grid.
Rollups fetch raw samples from storage and window them (oracle/NumPy host
path; the TPU fast path in tpu_engine.py takes over for supported
aggr(rollup(selector)) shapes when EvalConfig.tpu is set).
"""

from __future__ import annotations

import numpy as np

from ..ops.rollup_np import RollupConfig
from ..storage.metric_name import MetricName
from ..storage.tag_filters import TagFilter
from .aggr_funcs import (PER_SERIES, SIMPLE, a_quantile, series_rank_metric,
                         topk_mask_per_ts)
from .binary_op import ARITH_OPS, CMP_OPS, eval_binary_op
from .metricsql.ast import (AggrFuncExpr, BinaryOpExpr, DurationExpr, Expr,
                            FuncExpr, LabelFilter, MetricExpr, NumberExpr,
                            RollupExpr, StringExpr)
from .rollup_funcs import (GENERIC_FUNCS, KEEP_METRIC_NAMES, MULTI_FUNCS,
                           ORACLE_FUNCS, ROLLUP_FUNC_NAMES,
                           adjusted_windows, rollup_series)
from .transform_funcs import TRANSFORM_FUNCS
from .types import EvalConfig, Timeseries, const_series, new_series

nan = np.nan

# host-rollup share of vm_fetch_phase_seconds_total (storage/storage.py
# owns the fetch-side phases; bench.py reads the whole family to
# attribute a refresh between index/collect/decode/assemble/rollup)
def _rollup_phase_lap(t0: float) -> None:
    import time as _t

    from ..utils import costacc as _costacc
    from ..utils import flightrec as _flightrec
    from ..utils import metrics as _metricslib
    now = _t.perf_counter()
    _metricslib.REGISTRY.float_counter(
        'vm_fetch_phase_seconds_total{phase="rollup"}').inc(now - t0)
    _flightrec.rec("fetch:rollup", t0, now - t0)
    _costacc.lap("fetch:rollup", now - t0)


class QueryError(ValueError):
    pass


def _tag_filters(fs) -> list[TagFilter]:
    out = []
    for f in fs:
        key = b"" if f.label == "__name__" else f.label.encode()
        out.append(TagFilter(key, f.value.encode(), negate=f.is_negative,
                             regex=f.is_regexp))
    return out


def filter_sets_from_metric_expr(me: MetricExpr) -> list[list[TagFilter]]:
    """All OR'd filter sets of a selector as storage TagFilter lists."""
    return [_tag_filters(fs) for fs in me.filter_sets()]


def filters_from_metric_expr(me: MetricExpr, storage=None):
    """Storage-facing filters for a selector: a plain list[TagFilter] for
    the common single-set case; a list of filter SETS for `{a="b" or
    c="d"}` selectors (plain Storage unions them at the tsid level —
    supports_filter_union).  Backends without union support fail loudly
    instead of silently matching only the first set."""
    sets = filter_sets_from_metric_expr(me)
    if len(sets) == 1:
        return sets[0]
    if storage is not None and \
            not getattr(storage, "supports_filter_union", False):
        raise QueryError(
            "selector-level `or` filters are not supported by this "
            "storage backend yet; rewrite the query as `expr_a or expr_b`")
    return sets


def eval_expr(ec: EvalConfig, e: Expr) -> list[Timeseries]:
    if isinstance(e, NumberExpr):
        return [const_series(ec, e.value)]
    if isinstance(e, DurationExpr):
        return [const_series(ec, e.value_ms(ec.step) / 1e3)]
    if isinstance(e, StringExpr):
        return []  # bare string literals evaluate to no series (exec_test)
    if isinstance(e, MetricExpr):
        re_ = RollupExpr(expr=e)
        return _eval_rollup_expr(ec, "default_rollup", re_, ())
    if isinstance(e, RollupExpr):
        return _eval_rollup_expr(ec, "default_rollup", e, ())
    if isinstance(e, FuncExpr):
        return _eval_func(ec, e)
    if isinstance(e, AggrFuncExpr):
        return _eval_aggr(ec, e)
    if isinstance(e, BinaryOpExpr):
        return _eval_binary(ec, e)
    raise QueryError(f"cannot evaluate {type(e).__name__}")


# ---------------------------------------------------------------------------
# Functions
# ---------------------------------------------------------------------------

def _eval_func(ec: EvalConfig, fe: FuncExpr) -> list[Timeseries]:
    name = fe.name
    if name in ROLLUP_FUNC_NAMES:
        return _eval_rollup_func(ec, fe)
    tf = TRANSFORM_FUNCS.get(name)
    if tf is None:
        raise QueryError(f"unknown function {name!r}")
    args = []
    for a in fe.args:
        if isinstance(a, StringExpr):
            args.append(a.value)
        else:
            # everything else is a series list; scalar params unwrap via
            # _scalar_arg (const scalars become 1-series constants)
            args.append(eval_expr(ec, a))
    out = tf(ec, args)
    if fe.keep_metric_names:
        srcs = [a for a in args if isinstance(a, list)]
        if srcs and len(srcs[0]) == len(out):
            for ts, src in zip(out, srcs[0]):
                ts.metric_name.metric_group = src.metric_name.metric_group
                ts.raw = None  # in-place name edit: memo is stale
    return out


# ---------------------------------------------------------------------------
# Rollups
# ---------------------------------------------------------------------------

def _find_rollup_arg_idx(fe: FuncExpr) -> int:
    spec = GENERIC_FUNCS.get(fe.name)
    if spec is not None and spec[0] is not None:
        return spec[2]
    if fe.name in ("quantiles_over_time", "aggr_over_time",
                   "count_values_over_time"):
        return len(fe.args) - 1
    return 0


def _eval_rollup_func(ec: EvalConfig, fe: FuncExpr) -> list[Timeseries]:
    if not fe.args:
        raise QueryError(f"{fe.name} needs arguments")
    ridx = _find_rollup_arg_idx(fe)
    if ridx >= len(fe.args):
        raise QueryError(f"{fe.name}: missing rollup argument")
    rarg = fe.args[ridx]
    if isinstance(rarg, MetricExpr):
        rarg = RollupExpr(expr=rarg)
    elif not isinstance(rarg, RollupExpr):
        rarg = RollupExpr(expr=rarg)  # subquery over inner expr

    # extra scalar/string args (quantile phi, predict_linear t, ...)
    extra = []
    for i, a in enumerate(fe.args):
        if i == ridx:
            continue
        if isinstance(a, StringExpr):
            extra.append(a.value)
        elif isinstance(a, FuncExpr) and a.name == "union" and \
                all(isinstance(x, StringExpr) for x in a.args):
            # ("fn1", "fn2", ...) function-name lists (aggr_over_time)
            extra.extend(x.value for x in a.args)
        else:
            extra.append(float(eval_expr(ec, a)[0].values[0]))

    if fe.name == "aggr_over_time":
        funcs = [a for a in extra if isinstance(a, str)]
        out = []
        for f in funcs:
            sub = _eval_rollup_expr(ec, f, rarg, ())
            for ts in sub:
                ts.metric_name.labels.append((b"rollup", f.encode()))
                ts.metric_name.sort_labels()
                ts.raw = None  # memoized marshal is stale now
            out.extend(sub)
        return out

    if fe.name == "quantiles_over_time":
        dst_label = extra[0] if extra and isinstance(extra[0], str) else "phi"
        phis = [a for a in extra if isinstance(a, float)]
        out = []
        for phi in phis:
            sub = _eval_rollup_expr(ec, "quantile_over_time", rarg, (phi,),
                                    keep_name=True)
            for ts in sub:
                ts.metric_name.labels.append(
                    (dst_label.encode(), repr(phi).encode()))
                ts.metric_name.sort_labels()
                ts.raw = None  # memoized marshal is stale now
            out.extend(sub)
        return out

    if fe.name == "absent_over_time":
        rows = _eval_rollup_expr(ec, "absent_over_time", rarg, ())
        return _aggregate_absent_over_time(ec, rarg.expr, rows)

    if fe.name in ("count_values_over_time", "histogram_over_time"):
        return _eval_multi_value_rollup(ec, fe.name, rarg, extra,
                                        fe.keep_metric_names)

    if fe.name in MULTI_FUNCS:
        # rollup.go:413 appendRollupConfigs: an explicit 2nd arg ("min" /
        # "max" / "avg", or candlestick's leg name) selects ONE output and —
        # except for rollup_candlestick — suppresses the `rollup` tag.
        out = []
        tags = MULTI_FUNCS[fe.name]
        explicit = extra[0] if extra and isinstance(extra[0], str) else None
        keep = fe.keep_metric_names or fe.name in KEEP_METRIC_NAMES
        if fe.name == "rollup_candlestick":
            if explicit is not None:
                legs = dict(tags)
                if explicit not in legs:
                    raise QueryError(
                        f"unexpected second arg for {fe.name}: {explicit!r}")
                tags = [(explicit, legs[explicit])]
            # eval.go:943: auto `offset -step` — evaluate one step forward
            # (shifting the inner subquery grid too), relabel back
            ec2 = ec.child(start=ec.start + ec.step, end=ec.end + ec.step)
            for tag, _ in tags:
                sub = _eval_rollup_expr(ec2, "rollup_candlestick", rarg,
                                        (tag,), keep_name=keep)
                for ts in sub:
                    ts.metric_name.labels.append((b"rollup", tag.encode()))
                    ts.metric_name.sort_labels()
                    ts.raw = None  # memoized marshal is stale now
                out.extend(sub)
            return out
        if explicit is not None and explicit not in ("min", "max", "avg"):
            raise QueryError(
                f"unexpected second arg for {fe.name}: {explicit!r}; "
                "want `min`, `max` or `avg`")
        sel = [t for t, _ in tags] if explicit is None else [explicit]
        for tag in sel:
            sub = _eval_rollup_expr(ec, fe.name, rarg, (tag,), keep_name=keep)
            if explicit is None:
                for ts in sub:
                    ts.metric_name.labels.append((b"rollup", tag.encode()))
                    ts.metric_name.sort_labels()
                    ts.raw = None  # memoized marshal is stale now
            out.extend(sub)
        return out

    keep = fe.keep_metric_names or fe.name in KEEP_METRIC_NAMES
    return _eval_rollup_expr(ec, fe.name, rarg, tuple(extra), keep_name=keep)


def _eval_at(ec: EvalConfig, at_expr: Expr) -> int:
    v = float(eval_expr(ec, at_expr)[0].values[0])
    return int(v * 1e3)


def _eval_rollup_expr(ec: EvalConfig, func: str, re_: RollupExpr,
                      args: tuple, keep_name: bool | None = None
                      ) -> list[Timeseries]:
    if keep_name is None:
        keep_name = func in KEEP_METRIC_NAMES
    offset = re_.offset.value_ms(ec.step) if re_.offset is not None else 0
    window = re_.window.value_ms(ec.step) if re_.window is not None else 0

    at_ts = _eval_at(ec, re_.at) if re_.at is not None else None
    if at_ts is not None:
        # evaluate at the fixed timestamp, then broadcast over the grid
        sub_ec = ec.child(start=at_ts, end=at_ts, step=ec.step)
        rows = _eval_rollup_expr(sub_ec, func,
                                 RollupExpr(expr=re_.expr, window=re_.window,
                                            step=re_.step,
                                            inherit_step=re_.inherit_step,
                                            offset=re_.offset),
                                 args, keep_name)
        T = ec.n_points
        return [Timeseries(ts.metric_name,
                           np.full(T, ts.values[0]))
                for ts in rows]

    if isinstance(re_.expr, MetricExpr) and not re_.needs_subquery():
        return _rollup_from_storage(ec, func, re_, window, offset, args,
                                    keep_name)
    return _rollup_subquery(ec, func, re_, window, offset, args, keep_name)


def _fetch_for_rollup(ec: EvalConfig, func: str, re_: RollupExpr,
                      window: int, offset: int, fetcher, trace_label: str):
    """Shared fetch bookkeeping for both rollup fetch shapes (per-series
    and columnar): deadline, -search.maxSamplesPerQuery, rollup memory
    admission (eval.go:1776-1885), partial-result capture, tracing.

    `fetcher(filters, lo, hi, qt)` performs the storage search plus any
    stale-sample handling and returns (payload, n_series, n_samples); `qt`
    is the fetch span (cluster storages thread it through the RPC so
    storage-node spans graft under it); the caller holds the returned
    `admission` while computing the rollup."""
    from .limits import admit_rollup
    me: MetricExpr = re_.expr
    if ec.storage is None:
        raise QueryError("no storage attached to the query engine")
    ec.check_deadline()
    lookback = window if window > 0 else (
        ec.lookback_delta if func == "default_rollup" else ec.step)
    start = ec.start - offset
    end = ec.end - offset
    fetch_lo = start - lookback - ec.lookback_delta
    # device tile identity: the ACTUAL fetch bounds plus the data version
    # read BEFORE the fetch — a concurrent ingest then caches under the old
    # version and the next query rebuilds (never serves mid-write tiles as
    # current)
    fetch_info = (fetch_lo, end,
                  getattr(ec.storage, "data_version", None))
    filters = filters_from_metric_expr(me, ec.storage)
    with ec.tracer.new_child(trace_label + " %s window=%dms", me,
                             lookback) as qt:
        try:
            payload, n_series, n_samples = fetcher(filters, fetch_lo, end,
                                                   qt)
        except ResourceWarning as e:
            from .limits import QueryLimitError
            raise QueryLimitError(
                f"{e}; either narrow the selector or raise "
                f"-search.maxUniqueTimeseries") from None
        if getattr(ec.storage, "last_partial", False):
            # capture partiality PER QUERY right after the fetch: the
            # shared storage flag is reset by every new incoming request
            ec._partial[0] = True
        if getattr(ec.storage, "last_partial_resolution", False):
            # a downsampled tier coarser than the query's step served a
            # range whose raw data is gone (see storage/downsample.py)
            ec._partial_res[0] = True
        ec.count_samples(n_samples)
        qt.donef("%d series, %d samples", n_series, n_samples)
    cfg = RollupConfig(start=start, end=end, step=ec.step, window=lookback)
    admission = admit_rollup(str(me), n_series, ec.n_points,
                             ec.max_memory_per_query)
    return payload, cfg, admission, fetch_info


# Rollup func -> the downsampled-tier aggregate column that can serve it
# (storage/downsample.py AGG_COLUMNS).  "last" is literally query-time
# dedup at the tier resolution, so funcs that consume raw samples
# (rate/increase/delta/default_rollup) read it as a coarser sample
# stream; count reads the per-bucket count column (summed — see the
# count->sum rewrite); avg composes sum/count.
_DS_AGG = {
    "min_over_time": "min", "max_over_time": "max",
    "sum_over_time": "sum", "count_over_time": "count",
    "avg_over_time": "avg",
    "last_over_time": "last", "default_rollup": "last",
    "rate": "last", "increase": "last", "delta": "last",
}


def _ds_hint(ec: EvalConfig, func: str, window: int):
    """``(agg_column, max_resolution_ms)`` when this rollup may be served
    from downsampled tiers, else None.  The resolution bound is the
    rollup's effective lookback: every window then spans at least one
    whole tier bucket.  None whenever the storage has no tiers or
    VM_DOWNSAMPLE_READ=0 (the raw-oracle escape hatch)."""
    st = ec.storage
    if st is None or not getattr(st, "supports_downsample_read", False):
        return None
    if not st.downsample_active:
        return None
    from ..storage import downsample as _dsmod
    if not _dsmod.read_enabled():
        return None
    agg = _DS_AGG.get(func)
    if agg is None:
        return None
    lookback = window if window > 0 else (
        ec.lookback_delta if func == "default_rollup" else ec.step)
    if lookback <= 0:
        return None
    return (agg, int(lookback))


def _tracer_kw(ec: EvalConfig, qt) -> dict:
    """Thread the fetch span AND the query deadline through storages
    that can propagate them over RPC (ClusterStorage); plain storages
    take neither kwarg.  The deadline makes every per-node socket
    timeout a function of the query's REMAINING budget — a hung
    vmstorage costs one deadline, not a fixed timeout per hop."""
    kw = {}
    if qt.enabled and getattr(ec.storage, "supports_search_tracer", False):
        kw["tracer"] = qt
    if ec.deadline and getattr(ec.storage, "supports_search_deadline",
                               False):
        import time as _t
        remaining = ec.deadline - _t.monotonic()
        if remaining > 0:
            # reserve 20% of the remaining budget for the rollup/merge
            # tail: a stalled node then costs ~0.8 deadlines and the
            # surviving nodes' PARTIAL result still computes and serves
            # inside the query deadline, instead of the fetch eating the
            # whole budget and the post-fetch check failing the query
            kw["deadline"] = ec.deadline - 0.2 * remaining
        else:
            kw["deadline"] = ec.deadline  # exhausted: fail fast in rpc
    return kw


def _fetch_series_for_rollup(ec: EvalConfig, func: str, re_: RollupExpr,
                             window: int, offset: int):
    def fetcher(filters, lo, hi, qt):
        series = ec.storage.search_series(filters, lo, hi,
                                          max_series=ec.max_series,
                                          tenant=ec.tenant,
                                          **_tracer_kw(ec, qt))
        series = _drop_stale_nans(func, series)
        return series, len(series), sum(s.timestamps.size for s in series)

    return _fetch_for_rollup(ec, func, re_, window, offset, fetcher,
                             "fetch")


def _fetch_columns_for_rollup(ec: EvalConfig, func: str, re_: RollupExpr,
                              window: int, offset: int, ds=None):
    """Columnar twin of _fetch_series_for_rollup: one batched decode pass
    into padded (S, N) columns (storage.search_columns).  ``ds`` is the
    optional downsampled-tier hint (see _ds_hint), passed through only
    when set — plain storages without tier support never see the kwarg."""
    def fetcher(filters, lo, hi, qt):
        kw = _tracer_kw(ec, qt)
        if ds is not None:
            kw["ds"] = ds
        cols = ec.storage.search_columns(filters, lo, hi,
                                         max_series=ec.max_series,
                                         tenant=ec.tenant, **kw)
        if func not in ("default_rollup", "stale_samples_over_time"):
            cols.drop_stale_nans()  # dropStaleNaNs (eval.go:2081), batched
        return cols, cols.n_series, cols.n_samples

    return _fetch_for_rollup(ec, func, re_, window, offset, fetcher,
                             "fetch cols")


def _finish_rollup_cols(cols, rows, keep_name: bool) -> list[Timeseries]:
    return _finish_rollup_names(cols.metric_names, rows, keep_name,
                                cols.raw_names)


def _rollup_from_storage_cols(ec: EvalConfig, func: str, re_: RollupExpr,
                              window: int, offset: int, args: tuple,
                              keep_name: bool, ckey,
                              ds=None) -> list[Timeseries]:
    """Columnar host rollup: fetch -> (S, N) columns -> batched rollup,
    zero per-series Python on the hot path.  With a ``ds`` hint the fetch
    may return tier aggregate columns; count_over_time then computes as
    sum_over_time (count column per aged bucket + 1-per-raw-sample tail —
    see downsample.count_tail_piece — sum to the true count)."""
    from ..ops import rollup_np
    if ds is not None and ds[0] == "avg":
        return _ds_avg_composed(ec, re_, window, offset, args, keep_name,
                                ckey, ds)
    cols, cfg, admission, _ = _fetch_columns_for_rollup(
        ec, func, re_, window, offset, ds)
    if ds is not None and ds[0] == "count":
        func = "sum_over_time"
    per_series_cfg = None
    adj = adjusted_windows(func, window, ec.step, cols.ts_list())
    if adj:
        if all(a == adj[0] for a in adj):
            cfg = RollupConfig(start=cfg.start, end=cfg.end, step=cfg.step,
                               window=adj[0])
        else:
            per_series_cfg = [RollupConfig(start=cfg.start, end=cfg.end,
                                           step=cfg.step, window=a)
                              for a in adj]
    with admission:
        import time as _time
        t0r = _time.perf_counter()
        if per_series_cfg is None:
            with ec.tracer.new_child("host rollup %s (columns)",
                                     func) as qt:
                rows = rollup_np.rollup_batch_packed(func, cols.ts,
                                                     cols.vals, cols.counts,
                                                     cfg, args)
                if rows is not None:
                    _rollup_phase_lap(t0r)
                    qt.donef("%d series (packed)", cols.n_series)
                    return _cache_rollup(ec, ckey,
                                         _finish_rollup_cols(cols, rows,
                                                             keep_name))
                qt.donef("fell back to per-series (non-finite values)")
        with ec.tracer.new_child("host rollup %s (per-series)", func) as qt:
            out_rows = []
            counts = cols.counts
            for i in range(cols.n_series):
                if i % 256 == 0:
                    ec.check_deadline()
                n = int(counts[i])
                c = per_series_cfg[i] if per_series_cfg is not None else cfg
                out_rows.append(rollup_series(func, cols.ts[i, :n],
                                              cols.vals[i, :n], c, args))
            _rollup_phase_lap(t0r)
            qt.donef("%d series", cols.n_series)
        return _cache_rollup(ec, ckey,
                             _finish_rollup_cols(cols, out_rows, keep_name))


def _ds_avg_composed(ec: EvalConfig, re_: RollupExpr, window: int,
                     offset: int, args: tuple, keep_name: bool, ckey,
                     ds) -> list[Timeseries]:
    """avg_over_time over downsampled tiers: sum column / count column.
    A per-bucket average cannot be re-averaged correctly (buckets hold
    different sample counts); the sum/count pair can.  The composition
    is correct even when raw ends up serving the fetch: the count leg
    then reads 1-per-sample (downsample.count_tail_piece), so the
    division still yields the exact raw average."""
    sums = _rollup_from_storage_cols(ec, "sum_over_time", re_, window,
                                     offset, args, keep_name, None,
                                     ds=("sum", ds[1]))
    cnts = _rollup_from_storage_cols(ec, "count_over_time", re_, window,
                                     offset, args, keep_name, None,
                                     ds=("count", ds[1]))
    by_key = {bytes(ts.metric_name.marshal()): ts for ts in cnts}
    out = []
    for ts in sums:
        c = by_key.get(bytes(ts.metric_name.marshal()))
        if c is None:
            continue
        with np.errstate(invalid="ignore", divide="ignore"):
            vals = np.where(c.values > 0, ts.values / c.values, nan)
        out.append(Timeseries(ts.metric_name, vals))
    return _cache_rollup(ec, ckey, out)


def _rollup_from_storage(ec: EvalConfig, func: str, re_: RollupExpr,
                         window: int, offset: int, args: tuple,
                         keep_name: bool) -> list[Timeseries]:
    me: MetricExpr = re_.expr
    if me.is_empty():
        return []

    # eval-level per-expression rollup cache (rollup_result_cache.go:283):
    # repeated and rolling evaluations of the same rollup recompute only
    # the uncovered tail, independent of the enclosing query
    use_cache = (ec.n_points > 1 and func != "default_rollup"
                 and offset >= 0 and not ec.disable_cache
                 and not ec.no_eval_cache)
    ckey = None
    if use_cache:
        import time as _t

        from .rollup_result_cache import GLOBAL as rcache
        now_ms = int(_t.time() * 1000)
        # the ds token splits cache entries computed with tier serving on
        # vs off (VM_DOWNSAMPLE_READ flips live; tier floats differ from
        # raw floats, so the two populations must never merge)
        ckey = (f"rollup|{func}|{me}|{window}|{offset}|{args!r}|"
                f"{keep_name}|"
                f"ds{0 if _ds_hint(ec, func, window) is None else 1}")
        cached, new_start = rcache.get(ec, ckey, now_ms)
        if cached is not None and new_start > ec.end:
            ec.tracer.printf("eval rollup cache: full hit %s", ckey)
            return cached.rows()
        if cached is not None:
            ec.tracer.printf("eval rollup cache: tail from %d", new_start)
            sub_start, trim = suffix_child_bounds(ec, new_start)
            sub = ec.child(start=sub_start)
            sub.no_eval_cache = True  # the suffix must not clobber ckey
            fresh = _rollup_from_storage(sub, func, re_, window, offset,
                                         args, keep_name)
            if trim:
                fresh = trim_suffix_rows(fresh)
            rows = rcache.merge(cached, fresh, ec, new_start,
                                now_ms=now_ms)
            if not ec._partial[0] and not ec._partial_res[0]:
                rcache.put(ec, ckey, rows, now_ms)
            return rows

    from ..ops import rollup_np as _rnp
    if (ec.tpu is None and ec.storage is not None
            and _rnp.batch_supported(func, args)
            and getattr(ec.storage, "search_columns", None) is not None):
        # columnar host path: batched decode -> packed rollup, no
        # per-series materialization (device tiles go through the series
        # path below so tile caching keys stay unified)
        return _rollup_from_storage_cols(ec, func, re_, window, offset,
                                         args, keep_name, ckey,
                                         ds=_ds_hint(ec, func, window))

    series, cfg, admission, fetch_info = _fetch_series_for_rollup(
        ec, func, re_, window, offset)
    per_series_cfg = None
    adj = adjusted_windows(func, window, ec.step,
                           [sd.timestamps for sd in series])
    if adj:
        if all(a == adj[0] for a in adj):
            cfg = RollupConfig(start=cfg.start, end=cfg.end, step=cfg.step,
                               window=adj[0])
        else:
            per_series_cfg = [RollupConfig(start=cfg.start, end=cfg.end,
                                           step=cfg.step, window=a)
                              for a in adj]
    with admission:
        if per_series_cfg is not None:
            # windows differ per series: per-series host loop
            with ec.tracer.new_child("host rollup %s (per-series window)",
                                     func) as qt:
                out_rows = []
                for i, (sd, c) in enumerate(zip(series, per_series_cfg)):
                    if i % 256 == 0:
                        ec.check_deadline()
                    out_rows.append(rollup_series(func, sd.timestamps,
                                                  sd.values, c, args))
                qt.donef("%d series", len(out_rows))
            return _cache_rollup(ec, ckey,
                                 _finish_rollup(series, out_rows,
                                                keep_name))
        if ec.tpu is not None:
            from .tpu_engine import try_rollup_tpu
            with ec.tracer.new_child("tpu rollup %s", func) as qt:
                got = try_rollup_tpu(ec.tpu, func, series, cfg, args,
                                     cache_key=_tile_cache_key(ec, me, cfg,
                                                               fetch_info))
                if got is not None:
                    qt.donef("device path, %d series", len(got))
                    return _cache_rollup(ec, ckey,
                                         _finish_rollup(series, got,
                                                        keep_name))
                qt.donef("fell back to host")

        with ec.tracer.new_child("host rollup %s", func) as qt:
            if len(series) >= 8 and _rnp.batch_supported(func, args):
                from ..ops import rollup_np
                rows = rollup_np.rollup_batch(
                    func, [(sd.timestamps, sd.values) for sd in series],
                    cfg, args)
                if rows is not None:
                    qt.donef("%d series (batched)", len(series))
                    return _cache_rollup(
                        ec, ckey, _finish_rollup(series, list(rows),
                                                 keep_name))
            out_rows = []
            for i, sd in enumerate(series):
                if i % 256 == 0:
                    ec.check_deadline()
                vals = rollup_series(func, sd.timestamps, sd.values, cfg,
                                     args)
                out_rows.append(vals)
            qt.donef("%d series", len(out_rows))
        return _cache_rollup(ec, ckey,
                             _finish_rollup(series, out_rows, keep_name))


def _aggregate_absent_over_time(ec: EvalConfig, expr,
                                rows: list[Timeseries]) -> list[Timeseries]:
    """Collapse per-series absent windows into one series: 1 only where NO
    matching series has a sample (eval.go:990 aggregateAbsentOverTime);
    labels come from the selector's literal equality filters."""
    labels = []
    # selector labels apply only for a SINGLE filter set: with OR'd sets
    # there is no one label combination that "was absent" (the reference
    # applies them only when len(labelFilterss) == 1)
    if isinstance(expr, MetricExpr) and not expr.or_sets:
        for f in expr.label_filters:
            if not f.is_negative and not f.is_regexp and \
                    f.label != "__name__":
                labels.append((f.label.encode(), f.value.encode()))
    out = Timeseries(MetricName(b"", sorted(labels)),
                     np.ones(ec.n_points, dtype=np.float64))
    for ts in rows:
        # a NaN in the per-series absent rollup means the series HAS a
        # sample there — so the collapsed result must be NaN too
        out.values[np.isnan(ts.values)] = nan
    return [out]


def _eval_multi_value_rollup(ec: EvalConfig, func: str, re_: RollupExpr,
                             extra: list,
                             keep_name: bool = False) -> list[Timeseries]:
    """count_values_over_time("label", m[d]) and histogram_over_time(m[d]):
    one output series per distinct value / vmrange bucket per input series
    (rollup.go:1490 newRollupCountValues, :1526 rollupHistogram)."""
    dst_label = b""
    if func == "count_values_over_time":
        if not extra or not isinstance(extra[0], str):
            raise QueryError("count_values_over_time needs a label name")
        dst_label = extra[0].encode()
    offset = re_.offset.value_ms(ec.step) if re_.offset is not None else 0
    window = re_.window.value_ms(ec.step) if re_.window is not None else 0

    def _series_rows(func, s_ts, s_vals, src_mn, cfg):
        from .format_value import fmt_value as _fmt_value
        from .vmhistogram import histogram_counts
        out_ts = cfg.out_timestamps()
        T = out_ts.size
        lo = np.searchsorted(s_ts, out_ts - cfg.lookback, side="right")
        hi = np.searchsorted(s_ts, out_ts, side="right")
        per_key: dict[bytes, np.ndarray] = {}
        for j in range(T):
            w = s_vals[lo[j]:hi[j]]
            if w.size == 0:
                continue
            if func == "count_values_over_time":
                vals, counts = np.unique(w, return_counts=True)
                items = [(_fmt_value(v).encode(), float(c))
                         for v, c in zip(vals, counts)]
            else:
                items = [(k.encode(), float(c))
                         for k, c in histogram_counts(w).items()]
            for key, c in items:
                row = per_key.get(key)
                if row is None:
                    row = per_key[key] = np.full(T, nan)
                row[j] = c
        label = dst_label if func == "count_values_over_time" else b"vmrange"
        group = src_mn.metric_group if keep_name else b""
        rows = []
        for key, row in sorted(per_key.items()):
            mn = MetricName(group,
                            [(k, v) for k, v in src_mn.labels
                             if k != label] + [(label, key)])
            mn.sort_labels()
            rows.append(Timeseries(mn, row))
        return rows

    out: list[Timeseries] = []
    if isinstance(re_.expr, MetricExpr) and not re_.needs_subquery():
        series, cfg, admission, _fi = _fetch_series_for_rollup(
            ec, func, re_, window, offset)
        with admission:
            for sd in series:
                out.extend(_series_rows(func, sd.timestamps, sd.values,
                                        sd.metric_name, cfg))
    else:
        rows, cfg = _subquery_series(ec, re_, window, offset)
        for s_ts, s_vals, src_mn in rows:
            out.extend(_series_rows(func, s_ts, s_vals, src_mn, cfg))
    return out


def suffix_child_bounds(ec: EvalConfig, new_start: int) -> tuple[int, bool]:
    """Grid start for evaluating the uncovered tail [new_start, ec.end] of
    a result-cache partial hit, plus whether the leading column must be
    dropped.  A single-column tail is evaluated on a TWO-column grid and
    the extra leading column discarded: a one-point grid flips rollups
    into instant-query maxPrevInterval semantics (rollup.go:719-728 —
    prevValue gated by step instead of the estimated scrape interval),
    which would diverge from the full-grid eval the cache stitches
    against.  The recomputed leading column is thrown away, never merged,
    so cached (final) columns are still never overwritten."""
    if new_start == ec.end and ec.end - ec.step >= ec.start:
        return new_start - ec.step, True
    return new_start, False


def trim_suffix_rows(rows: list[Timeseries]) -> list[Timeseries]:
    """Drop the extra leading column of a widened single-column tail eval
    (see suffix_child_bounds); zero-copy views."""
    return [Timeseries(ts.metric_name, ts.values[1:], raw=ts.raw)
            for ts in rows]


def _cache_rollup(ec, ckey, rows):
    if ckey is not None and not ec._partial[0] and not ec._partial_res[0]:
        import time as _t

        from .rollup_result_cache import GLOBAL as rcache
        rcache.put(ec, ckey, rows, int(_t.time() * 1000))
    return rows


def _drop_stale_nans(func: str, series):
    """Strip Prometheus staleness markers before rollup computation
    (reference eval.go:2081 dropStaleNaNs). default_rollup needs them for
    staleness detection; stale_samples_over_time counts them."""
    if func in ("default_rollup", "stale_samples_over_time"):
        return series
    from ..ops import decimal as dec_ops
    for sd in series:
        if not getattr(sd, "maybe_stale", True):
            continue  # every contributing block known stale-free (memo)
        stale = dec_ops.is_stale_nan(sd.values)
        if stale.any():
            keep = ~stale
            sd.timestamps = sd.timestamps[keep]
            sd.values = sd.values[keep]
    return series


def _blank_raw(raw: bytes) -> bytes:
    """marshal() of the name with metric_group blanked, as a suffix slice:
    escapes map 0x00 -> 0x02 0x03, so the first LITERAL 0x00 in a
    canonical raw name is the group/label separator."""
    i = raw.find(b"\x00")
    return raw[i:] if i >= 0 else b""


def _finish_rollup_names(metric_names, rows, keep_name: bool, raws=None
                         ) -> list[Timeseries]:
    """Build output rows; when the storage's canonical raw names are
    available they are attached (sliced for keep_name=False) so the rollup
    result cache never re-marshals 8k names per refresh."""
    out = []
    if raws is None:
        for mn_src, vals in zip(metric_names, rows):
            mn = MetricName(mn_src.metric_group if keep_name else b"",
                            list(mn_src.labels))
            out.append(Timeseries(mn, np.asarray(vals, dtype=np.float64)))
        return out
    for mn_src, vals, raw in zip(metric_names, rows, raws):
        mn = MetricName(mn_src.metric_group if keep_name else b"",
                        list(mn_src.labels))
        out.append(Timeseries(mn, np.asarray(vals, dtype=np.float64),
                              raw=raw if keep_name else _blank_raw(raw)))
    return out


def _finish_rollup(series, rows, keep_name: bool) -> list[Timeseries]:
    raws = [getattr(sd, "raw_name", None) for sd in series]
    if any(r is None for r in raws):
        raws = None
    return _finish_rollup_names((sd.metric_name for sd in series), rows,
                                keep_name, raws)


def _subquery_series(ec: EvalConfig, re_: RollupExpr, window: int,
                     offset: int):
    """Evaluate the inner expression of a subquery and return the NaN-
    stripped per-series samples plus the outer rollup config
    (eval.go:1006 evalRollupFuncWithSubquery)."""
    sub_step = (re_.step.value_ms(ec.step) if re_.step is not None
                else ec.step)
    if sub_step <= 0:
        raise QueryError("subquery step must be positive")
    lookback = window if window > 0 else ec.step
    start = ec.start - offset
    end = ec.end - offset
    # eval.go:1023: extend the inner range by window + step + the max
    # silence interval (5m) so prevValue / adjusted windows see the samples
    # just before the outer range, then step-align both ends as Prometheus
    # subqueries do (eval.go alignStartEnd). NOTE: the RAW window is used
    # here (0 when unspecified), not the effective lookback — using the
    # lookback shifts the inner grid by a full outer step, which visibly
    # shifts seeded rand() streams.
    sub_start = start - window - sub_step - 300_000
    sub_end = end + sub_step
    sub_start -= sub_start % sub_step
    if sub_end % sub_step:
        sub_end += sub_step - sub_end % sub_step
    inner_ec = ec.child(start=sub_start, end=sub_end, step=sub_step)
    inner = eval_expr(inner_ec, re_.expr)
    grid = inner_ec.timestamps()
    cfg = RollupConfig(start=start, end=end, step=ec.step, window=lookback)
    rows = []
    for ts in inner:
        ok = ~np.isnan(ts.values)
        s_ts = grid[ok]
        s_vals = ts.values[ok]
        if s_ts.size == 0:
            continue
        rows.append((s_ts, s_vals, ts.metric_name))
    return rows, cfg


def _rollup_subquery(ec: EvalConfig, func: str, re_: RollupExpr, window: int,
                     offset: int, args: tuple, keep_name: bool
                     ) -> list[Timeseries]:
    rows, cfg = _subquery_series(ec, re_, window, offset)
    out = []
    for s_ts, s_vals, src_mn in rows:
        c = cfg
        adj1 = adjusted_windows(func, window, ec.step, [s_ts])
        if adj1:
            c = RollupConfig(start=cfg.start, end=cfg.end, step=ec.step,
                             window=adj1[0])
        vals = rollup_series(func, s_ts, s_vals, c, args)
        mn = MetricName(src_mn.metric_group if keep_name else b"",
                        list(src_mn.labels))
        out.append(Timeseries(mn, vals))
    return out


# ---------------------------------------------------------------------------
# Aggregates
# ---------------------------------------------------------------------------

def _group_key(mn: MetricName, grouping: list[bytes], without: bool) -> bytes:
    if without:
        kept = [(k, v) for k, v in mn.labels if k not in grouping]
        return MetricName(b"", kept).marshal()
    kept = []
    group = b""
    for g in grouping:
        if g == b"__name__":
            group = mn.metric_group  # sum by (__name__) keeps the name
            continue
        v = mn.get_label(g)
        if v is not None:
            kept.append((g, v))
    return MetricName(group, sorted(kept)).marshal()


# (raw name, grouping signature) -> group key: a steady-state dashboard
# re-groups the SAME 10k series every refresh; the key is a pure function
# of the (immutable) raw name, so memoizing kills the per-refresh
# label-scan + marshal (bounded; cleared wholesale when full)
_GROUP_KEY_MEMO: dict = {}
_GROUP_KEY_MEMO_MAX = 1 << 18  # ~40MB worst case; clear-all on overflow


def _group_series(series: list[Timeseries], grouping: list[str],
                  without: bool):
    if not grouping and not without:
        # aggr over everything: the group key is the same empty name for
        # every series — skip the per-series marshal entirely
        if not series:
            return {}, {}  # match the loop below: no series, no groups
        key = MetricName(b"", []).marshal()
        return {key: list(series)}, {key: MetricName.unmarshal(key)}
    gb = [g.encode() for g in grouping]
    sig = (tuple(gb), without)
    memo = _GROUP_KEY_MEMO
    groups: dict[bytes, list[Timeseries]] = {}
    names: dict[bytes, MetricName] = {}
    for ts in series:
        raw = ts.raw
        if raw is not None:
            mkey = (raw, sig)
            key = memo.get(mkey)
            if key is None:
                key = _group_key(ts.metric_name, gb, without)
                if len(memo) >= _GROUP_KEY_MEMO_MAX:
                    memo.clear()
                memo[mkey] = key
        else:  # mutated/synthetic name: compute directly
            key = _group_key(ts.metric_name, gb, without)
        groups.setdefault(key, []).append(ts)
        if key not in names:
            names[key] = MetricName.unmarshal(key)
    return groups, names


_FUSED_AGGR_NAMES = ("sum", "count", "avg", "min", "max", "stddev",
                     "stdvar", "group")


def _tile_cache_key(ec: EvalConfig, expr, cfg: RollupConfig, fetch_info):
    """Query-level device tile-cache key: the tile content is fully
    determined by (selector, tenant, ACTUAL fetch bounds, dedup config,
    storage data version read before the fetch), so keying on those skips
    the per-series fingerprint hash on warm queries. cfg.start is included
    because tile timestamps are rebased to it. Falls back to content
    fingerprinting when the backing store exposes no data_version (e.g.
    cluster adapters)."""
    fetch_lo, fetch_hi, ver = fetch_info
    if ver is None:
        return None
    dedup = getattr(ec.storage, "dedup_interval_ms", 0)
    return ("tileq", str(expr), ec.tenant, fetch_lo, fetch_hi, cfg.start,
            dedup, ver)


def _device_aggr_shape(ae: AggrFuncExpr):
    """(phi, func, rollup-arg) of a device-fusable aggr(rollup(selector))
    expression, or None when the shape can't fuse (shared by the fused
    dispatch and the serving layer's residency-readiness probe)."""
    phi = None
    if ae.name in ("quantile", "median"):
        # quantile(phi, q) fuses when phi is a literal; median = 0.5
        if ae.name == "quantile":
            if len(ae.args) != 2 or not isinstance(ae.args[0], NumberExpr):
                return None
            phi = float(ae.args[0].value)
            arg = ae.args[1]
        else:
            if len(ae.args) != 1:
                return None
            phi = 0.5
            arg = ae.args[0]
    elif len(ae.args) != 1 or ae.name not in _FUSED_AGGR_NAMES:
        return None
    else:
        arg = ae.args[0]
    if isinstance(arg, FuncExpr):
        if len(arg.args) != 1 or arg.keep_metric_names:
            return None
        func, rarg = arg.name, arg.args[0]
    elif isinstance(arg, (MetricExpr, RollupExpr)):
        func, rarg = "default_rollup", arg
    else:
        return None
    if isinstance(rarg, MetricExpr):
        rarg = RollupExpr(expr=rarg)
    if not isinstance(rarg, RollupExpr) or \
            not isinstance(rarg.expr, MetricExpr) or rarg.expr.is_empty() or \
            rarg.needs_subquery() or rarg.at is not None:
        return None
    return phi, func, rarg


def _device_roll_keys(ec: EvalConfig, ae: AggrFuncExpr, func: str, rarg,
                      phi, window: int):
    """(roll_state_key, roll_tile_key) of the device-resident rolling
    window that serves this query shape, or (None, None) when the shape
    cannot roll (time-valued funcs read absolute grids; adjustable
    windows depend on per-fetch data)."""
    from ..ops.device_rollup import TIME_VALUED_FUNCS
    from .rollup_funcs import ADJUSTABLE_WINDOW_FUNCS
    if func in TIME_VALUED_FUNCS or func == "lifetime" or \
            (window <= 0 and (func in ADJUSTABLE_WINDOW_FUNCS
                              or func == "default_rollup")):
        return None, None
    roll_state_key = ("roll-aggr", str(rarg.expr), ec.tenant, func,
                      ae.name, phi, tuple(ae.grouping), ae.without,
                      ec.max_series)
    roll_tile_key = ("roll-tile", str(rarg.expr), ec.tenant, ec.max_series)
    return roll_state_key, roll_tile_key


def device_window_ready(ec: EvalConfig, e: Expr) -> bool:
    """True when the device plane holds a RESIDENT rolling window able to
    serve expression `e` O(new samples): the serving layer then runs the
    full-window eval (device rolling advance + [G, T] ring reuse) instead
    of the host ring-cache suffix path, so the refresh uploads only tail
    columns and the rollup never re-crosses the host boundary."""
    if ec.tpu is None or ec.disable_cache or ec.no_device_roll:
        return False
    from ..models.tile_cache import device_resident_enabled
    if not device_resident_enabled():
        return False
    if not isinstance(e, AggrFuncExpr):
        return False
    shape = _device_aggr_shape(e)
    if shape is None:
        return False
    phi, func, rarg = shape
    from ..ops import rollup_np
    from .tpu_engine import FUSED_AGGRS
    if func not in rollup_np.CORE_SUPPORTED or \
            (phi is None and e.name not in FUSED_AGGRS):
        return False
    if getattr(ec.storage, "data_version", None) is None or \
            getattr(ec.storage, "structural_version", None) is None:
        return False
    window = rarg.window.value_ms(ec.step) if rarg.window is not None else 0
    roll_state_key, _ = _device_roll_keys(ec, e, func, rarg, phi, window)
    if roll_state_key is None:
        return False
    wc = ec.tpu.window_cache()
    if wc.peek(roll_state_key) is None:
        # fleet members carry no per-shape wcache entry (adoption moved
        # the window into the batched plane); they are device-resident
        # all the same — and bypass the churn backoff below, because the
        # fleet advances them without per-shape rebuild churn
        from . import fleet as fleetmod
        return fleetmod.resident(ec.tpu, roll_state_key)
    # persistent-churn backoff: consecutive rolling declines mean this
    # shape keeps rebuilding FULL windows on device (each rebuild
    # re-registers the window, so entry existence alone would route the
    # next refresh right back).  Send it to the host suffix path (O(new
    # samples)) instead, retrying the device window every 16 refreshes
    # so shapes whose churn stopped come back to residency.
    st = wc.peek(("roll-declines",) + roll_state_key)
    if st is not None and st.get("streak", 0) >= 2:
        st["skipped"] = st.get("skipped", 0) + 1
        if st["skipped"] < 16:
            return False
        st["streak"] = 0
        st["skipped"] = 0
    return True


def _try_device_fused_aggr(ec: EvalConfig, ae: AggrFuncExpr
                           ) -> list[Timeseries] | None:
    """aggr by (...)(rollup(selector)) fused on device: rollup + segment
    aggregation in one kernel so only [G, T] crosses the link (the
    incremental-aggregation pushdown; None -> host path)."""
    if ec.tpu is None:
        return None
    shape = _device_aggr_shape(ae)
    if shape is None:
        return None
    phi, func, rarg = shape
    from ..models.tile_cache import count_window_hit, device_resident_enabled
    from ..ops import rollup_np
    from .rollup_result_cache import RingBlock
    from .tpu_engine import (FUSED_AGGRS, RollingTile, advance_rolling,
                             aux_get, aux_put, group_slots,
                             run_fused_on_tiles, run_quantile_on_tiles,
                             try_aggr_rollup_tpu, try_quantile_rollup_tpu)
    if func not in rollup_np.CORE_SUPPORTED or \
            (phi is None and ae.name not in FUSED_AGGRS):
        return None
    offset = rarg.offset.value_ms(ec.step) if rarg.offset is not None else 0
    window = rarg.window.value_ms(ec.step) if rarg.window is not None else 0

    def _emit(out, group_keys):
        rows = [Timeseries(MetricName.unmarshal(k),
                           np.asarray(out[g], dtype=np.float64))
                for g, k in enumerate(group_keys)]
        if ae.limit and len(rows) > ae.limit:
            rows = rows[:ae.limit]  # first-seen order (aggrPrepareSeries)
        rows.sort(key=lambda ts: ts.metric_name.marshal())
        return rows

    # warm shortcut: a query with the same shape against unchanged data
    # reuses the HBM-resident tile AND the cached group assignment — the
    # host fetch/decode/group pass is skipped entirely (only the [G, T]
    # aggregate crosses the link)
    aux_key = None
    ver = getattr(ec.storage, "data_version", None)
    if ec.no_device_roll:  # result-cache suffix eval: fresh tiles only
        ver = None         # (see EvalConfig.no_device_roll)
    if ec.disable_cache:  # nocache=1 / -search.disableCache bypasses every
        ver = None        # resident-tile reuse path (aux, rolling) too
    if not device_resident_enabled():
        ver = None  # VM_DEVICE_RESIDENT=0: full upload every query — the
        #             loud escape hatch and the residency equality oracle
    if ver is not None:
        # fleet shortcut: a matstream advance whose interval the fleet
        # prepass already served by the SHARED batched launch — the [G, T]
        # slice is sitting in the plane's result table (version- and
        # grid-matched), so this eval does zero storage reads and zero
        # launches.  The ver-gating above keeps every oracle path
        # (nocache / no_device_roll / VM_DEVICE_RESIDENT=0) off the fleet.
        rsk_fleet, _ = _device_roll_keys(ec, ae, func, rarg, phi, window)
        if rsk_fleet is not None:
            from . import fleet as fleetmod
            hit = fleetmod.take(ec, rsk_fleet)
            if hit is not None:
                count_window_hit()
                return _emit(hit[0], hit[1])
    if ver is not None:
        aux_key = ("fused-aux", str(rarg.expr), ec.tenant, ec.start, ec.end,
                   ec.step, window, offset, func, ae.name, phi,
                   tuple(ae.grouping), ae.without,
                   getattr(ec.storage, "dedup_interval_ms", 0),
                   ec.lookback_delta, ec.max_series, ver)
        aux = aux_get(ec.tpu, aux_key)
        if aux is not None:
            tile_key, cfg2, gids_dev, group_keys, n_samples, qx = aux
            tiles = ec.tpu.cache().get(tile_key)
            if tiles is not None:
                ec.check_deadline()
                ec.count_samples(n_samples)
                with ec.tracer.new_child("tpu fused %s(%s) warm", ae.name,
                                         func) as qt:
                    if qx is not None:
                        slots_dev, max_group = qx
                        out = run_quantile_on_tiles(
                            ec.tpu, phi, func, tiles, gids_dev, slots_dev,
                            len(group_keys), max_group, cfg2)
                    else:
                        out = run_fused_on_tiles(ec.tpu, ae.name, func,
                                                 tiles, gids_dev,
                                                 len(group_keys), cfg2)
                    qt.donef("resident tile, %d groups", len(group_keys))
                count_window_hit()
                return _emit(out, group_keys)

    # rolling shortcut: the same query SHAPE with advanced bounds and/or
    # append-only ingest. The resident tile absorbs only the new samples
    # (device scatter into reserved headroom, storage append-watermark
    # guarded) and answers with a traced grid shift — no host fetch, no
    # re-upload, no recompile. The tail-reuse role of the reference's
    # rollupResultCache (rollup_result_cache.go:283) done at tile level.
    lookback = window if window > 0 else (
        ec.lookback_delta if func == "default_rollup" else ec.step)
    roll_state_key = roll_tile_key = None
    if ver is not None and \
            getattr(ec.storage, "structural_version", None) is not None:
        roll_state_key, roll_tile_key = _device_roll_keys(
            ec, ae, func, rarg, phi, window)
    if roll_state_key is not None:
        wcache = ec.tpu.window_cache()
        stv = wcache.get(roll_state_key)
        if stv is not None:
            rt, gids_dev, group_keys, qx, rb = stv
            start = ec.start - offset
            end = ec.end - offset
            fetch_lo = start - lookback - ec.lookback_delta
            filters = filters_from_metric_expr(rarg.expr, ec.storage)
            drop_stale = func not in ("default_rollup",
                                      "stale_samples_over_time")
            qt = ec.tracer.new_child("tpu fused %s(%s) rolling", ae.name,
                                     func)
            if advance_rolling(ec.tpu, rt, ec.storage, filters, start,
                               fetch_lo, end, ec.max_series, ec.tenant,
                               drop_stale, tracer=qt):
                ec.check_deadline()
                ec.count_samples(rt.samples_in_range(fetch_lo))
                cfg2 = RollupConfig(start=start, end=end, step=ec.step,
                                    window=lookback)
                def kernel(kcfg):
                    # grid shift + fetch truncation are relative to the
                    # KERNEL grid's start (the tail sub-grid rebases both)
                    sh = kcfg.start - rt.base_ms
                    mt = fetch_lo - kcfg.start
                    if qx is not None:
                        slots_dev, max_group = qx
                        return run_quantile_on_tiles(
                            ec.tpu, phi, func, rt.tiles, gids_dev,
                            slots_dev, len(group_keys), max_group, kcfg,
                            sh, mt)
                    return run_fused_on_tiles(ec.tpu, ae.name, func,
                                              rt.tiles, gids_dev,
                                              len(group_keys), kcfg, sh,
                                              mt)

                # Incremental grid: an advanced window re-uses the previous
                # [G, T] result for every column at or before the previous
                # end — append-only ingest (watermark-guarded) cannot touch
                # windows ending there, so only the columns past the
                # previous end run on device (the rollupResultCache
                # tail-merge contract, rollup_result_cache.go:283, done at
                # the [G, T] level by a RingBlock: the ring-cache entry
                # machinery with fixed group rows.  Like the reference
                # cache, re-used columns keep the scrape-interval
                # estimates they were computed under — the constant-shape
                # sliding advance only; anything else recomputes fresh.)
                n_new = rb.try_advance(start, end, ec.step, lookback) \
                    if rb is not None else None
                if n_new == 0:
                    rows_out = rb.commit(start, end, None)
                    qt.printf("pure shift: %d columns reused", rb.T)
                elif n_new is not None:
                    qk = qt.new_child("fused tail kernel + D2H")
                    # the tail sub-grid must sit ON the eval grid's phase:
                    # the grid's last column is start + (T-1)*step, which
                    # is NOT `end` when (end - start) % step != 0 —
                    # anchoring the sub-grid at `end` would compute
                    # off-phase columns (a few-percent rate error that
                    # used to hide inside the documented drift bound).
                    # One extra leading column keeps start < end: a
                    # single-column sub-grid would hit the instant-query
                    # maxPrevInterval rule (rollup.go:719-728) and flip
                    # prev gating
                    grid_end = start + ((end - start) // ec.step) * ec.step
                    tail = kernel(RollupConfig(
                        start=grid_end - n_new * ec.step, end=grid_end,
                        step=ec.step, window=lookback))[:, 1:]
                    rows_out = rb.commit(start, end, tail)
                    qk.donef("[%d, %d] tail, %d columns reused",
                             len(group_keys), n_new, rb.T - n_new)
                else:
                    qk = qt.new_child("fused kernel + D2H")
                    out = kernel(cfg2)
                    qk.donef("[%d, %d] float64 out", len(group_keys),
                             out.shape[1] if out.ndim > 1 else 0)
                    if rb is not None:
                        rb.reset(out, start, end, ec.step, lookback)
                        rows_out = rb.rows()
                    else:
                        rows_out = list(out)
                qt.donef("advanced tile (%d appends), %d groups",
                         rt.appends, len(group_keys))
                count_window_hit()
                wcache.invalidate(("roll-declines",) + roll_state_key)
                return _emit(rows_out, group_keys)
            qt.donef("not advanceable (%s); rebuilding",
                     ec.tpu.last_roll_decline)
            # feed the serving layer's churn backoff (device_window_ready)
            dk = ("roll-declines",) + roll_state_key
            dst = wcache.peek(dk) or {}
            wcache.put(dk, {"streak": dst.get("streak", 0) + 1,
                            "skipped": 0})

    series, cfg, admission, fetch_info = _fetch_series_for_rollup(
        ec, func, rarg, window, offset)
    adj = adjusted_windows(func, window, ec.step,
                           [sd.timestamps for sd in series])
    if adj:
        if all(a == adj[0] for a in adj):
            cfg = RollupConfig(start=cfg.start, end=cfg.end, step=cfg.step,
                               window=adj[0])
        else:
            with admission:
                pass
            ec.count_samples(-sum(s.timestamps.size for s in series))
            return None  # host path handles per-series windows
    n_fetched = sum(s.timestamps.size for s in series)

    def _decline():
        # the host path will re-fetch and re-count the same samples
        ec.count_samples(-n_fetched)
        return None

    with admission:
        if len(series) < ec.tpu.min_series:
            return _decline()  # host path re-fetches from warm caches
        gb = [g.encode() for g in ae.grouping]
        key_to_gid: dict[bytes, int] = {}
        gids = np.empty(len(series), dtype=np.int32)
        group_keys: list[bytes] = []
        for i, sd in enumerate(series):
            key = _group_key(sd.metric_name, gb, ae.without)
            gid = key_to_gid.get(key)
            if gid is None:
                gid = len(group_keys)
                key_to_gid[key] = gid
                group_keys.append(key)
            gids[i] = gid
        with ec.tracer.new_child("tpu fused %s(%s)", ae.name, func) as qt:
            tile_key = _tile_cache_key(ec, rarg.expr, cfg, fetch_info)
            qx = None
            slots = max_group = None
            if phi is not None:
                slots, max_group = group_slots(gids, len(group_keys))
                out = try_quantile_rollup_tpu(ec.tpu, phi, func, series,
                                              gids, len(group_keys), cfg,
                                              slots, max_group,
                                              cache_key=tile_key)
            else:
                out = try_aggr_rollup_tpu(ec.tpu, ae.name, func, series,
                                          gids, len(group_keys), cfg,
                                          cache_key=tile_key)
            if out is None:
                qt.donef("fell back to host")
                return _decline()
            qt.donef("device path, %d series -> %d groups", len(series),
                     len(group_keys))
        import jax.numpy as jnp
        if phi is not None:
            qx = (jnp.asarray(slots), max_group)
        if aux_key is not None and tile_key is not None and \
                not ec._partial[0]:
            aux_put(ec.tpu, aux_key,
                    (tile_key, cfg, jnp.asarray(gids), list(group_keys),
                     n_fetched, qx))
        if roll_state_key is not None and adj is None and \
                tile_key is not None and not ec._partial[0] and \
                not getattr(ec.storage, "dedup_interval_ms", 0) and \
                all(sd.raw_name is not None for sd in series):
            tiles_now = ec.tpu.cache().get(tile_key)
            if tiles_now is not None:
                wcache = ec.tpu.window_cache()
                rt = wcache.get(roll_tile_key)
                if not isinstance(rt, RollingTile) or \
                        rt.adopted_key != tile_key:
                    rt = RollingTile(
                        tiles=tiles_now, base_ms=cfg.start,
                        n_cap=int(tiles_now[0].shape[1]),
                        lo_ms=fetch_info[0], hi_ms=fetch_info[1],
                        version=fetch_info[2],
                        structural=ec.storage.structural_version,
                        counts_host=np.fromiter(
                            (sd.timestamps.size for sd in series),
                            np.int64, len(series)),
                        row_of_raw={sd.raw_name: i
                                    for i, sd in enumerate(series)},
                        n_samples=n_fetched, adopted_key=tile_key)
                    wcache.put(roll_tile_key, rt)
                wcache.put(roll_state_key,
                           (rt, jnp.asarray(gids), list(group_keys), qx,
                            RingBlock(out, cfg.start, cfg.end, cfg.step,
                                      cfg.lookback)))
    return _emit(out, group_keys)


_CHUNK_AGGRS = frozenset({"sum", "count", "avg", "min", "max"})


def _aggr_rollup_shape(arg):
    """aggr(func(selector[d])) shape shared by the host fused and chunked
    aggregation paths: returns (func, RollupExpr over a non-empty
    MetricExpr) or None when the argument is not a plain storage rollup."""
    if isinstance(arg, FuncExpr):
        if len(arg.args) != 1 or arg.keep_metric_names:
            return None
        func, rarg = arg.name, arg.args[0]
    elif isinstance(arg, (MetricExpr, RollupExpr)):
        func, rarg = "default_rollup", arg
    else:
        return None
    if isinstance(rarg, MetricExpr):
        rarg = RollupExpr(expr=rarg)
    if not isinstance(rarg, RollupExpr) or \
            not isinstance(rarg.expr, MetricExpr) or rarg.expr.is_empty() or \
            rarg.needs_subquery() or rarg.at is not None:
        return None
    return func, rarg


def _try_host_chunked_aggr(ec: EvalConfig, ae) -> list[Timeseries] | None:
    """Bounded-memory host incremental aggregation for BIG
    aggr by(...)(rollup(selector)) queries: chunked columnar fetch ->
    batched rollup per chunk -> running [G, T] accumulators, so the full
    padded (S, N) sample matrix never exists (the reference's
    tmp-blocks-spool + incremental-aggregation pairing,
    netstorage/tmp_blocks_file.go + eval.go:1055). Engages only when the
    estimated fetch would overflow half the rollup memory budget — the
    small/medium case keeps the cached full-fetch path. None = not
    applicable, use the normal path."""
    if ec.tpu is not None or ae.name not in _CHUNK_AGGRS:
        return None
    if len(ae.args) != 1 or ae.limit:
        return None
    shape = _aggr_rollup_shape(ae.args[0])
    if shape is None:
        return None
    func, rarg = shape
    from ..ops import rollup_np
    if not rollup_np.batch_supported(func, ()):
        return None
    st = ec.storage
    if getattr(st, "search_columns_chunked", None) is None or \
            getattr(st, "estimate_series", None) is None:
        return None
    offset = rarg.offset.value_ms(ec.step) if rarg.offset is not None else 0
    window = rarg.window.value_ms(ec.step) if rarg.window is not None else 0
    lookback = window if window > 0 else (
        ec.lookback_delta if func == "default_rollup" else ec.step)
    start = ec.start - offset
    end = ec.end - offset
    fetch_lo = start - lookback - ec.lookback_delta
    filters = filters_from_metric_expr(rarg.expr, ec.storage)
    from .limits import admit_rollup, rollup_memory_limiter
    try:
        n_series_est = st.estimate_series(filters, fetch_lo, end,
                                          tenant=ec.tenant)
    except Exception:
        return None
    est_samples = n_series_est * max((end - fetch_lo) // 15_000, 1)
    import os as _os
    budget = rollup_memory_limiter().max_size
    threshold = int(_os.environ.get("VM_CHUNKED_AGGR_MIN_BYTES",
                                    budget // 2))
    if est_samples * 16 <= threshold:
        return None  # fits comfortably: the cached full-fetch path wins

    T = ec.n_points
    cfg0 = RollupConfig(start=start, end=end, step=ec.step,
                        window=lookback)
    gb = [g.encode() for g in ae.grouping]
    # rollups that drop the metric name must group on the BLANKED name,
    # exactly like _finish_rollup_names(keep_name=False) before _group_key
    # on the normal path — `by (__name__)` output names must not depend
    # on which path ran
    keep_name = func == "default_rollup" or func in KEEP_METRIC_NAMES
    gidx: dict[bytes, int] = {}
    aggr = ae.name
    init = np.inf if aggr == "min" else -np.inf if aggr == "max" else 0.0
    # [G, T] running accumulators with geometric capacity growth (exact
    # regrowth per chunk would copy the full matrix O(n_chunks) times
    # for high-cardinality groupings)
    cap = 64
    acc_buf = np.full((cap, T), init)
    cnt_buf = np.zeros((cap, T))
    qt = ec.tracer.new_child(
        "host chunked %s(%s) %s: ~%d series", aggr, func, rarg.expr,
        n_series_est)
    n_samples = n_chunks = 0
    max_chunk = int(_os.environ.get(
        "VM_CHUNK_FETCH_SAMPLES", max(int(budget // 4 // 16), 1_000_000)))
    seen_series = 0
    try:
        for cols in st.search_columns_chunked(
                filters, fetch_lo, end, tenant=ec.tenant,
                max_chunk_samples=max_chunk):
            ec.check_deadline()
            if cols.n_series == 0:
                continue
            seen_series += cols.n_series
            if seen_series > ec.max_series:
                raise ResourceWarning(
                    f"query matches more than {ec.max_series} series")
            if func not in ("default_rollup", "stale_samples_over_time"):
                cols.drop_stale_nans()
            n_samples += cols.n_samples
            ec.count_samples(cols.n_samples)
            with admit_rollup(str(rarg.expr), cols.n_series, T,
                              ec.max_memory_per_query):
                cfg = cfg0
                adj = adjusted_windows(func, window, ec.step,
                                       cols.ts_list())
                per_series_cfg = None
                if adj:
                    if all(a == adj[0] for a in adj):
                        cfg = RollupConfig(start=start, end=end,
                                           step=ec.step, window=adj[0])
                    else:
                        per_series_cfg = [
                            RollupConfig(start=start, end=end,
                                         step=ec.step, window=a)
                            for a in adj]
                import time as _time
                t0r = _time.perf_counter()
                rows = None
                if per_series_cfg is None:
                    rows = rollup_np.rollup_batch_packed(
                        func, cols.ts, cols.vals, cols.counts, cfg, ())
                if rows is None:  # non-finite values / per-series windows
                    counts = cols.counts
                    rows = np.empty((cols.n_series, T))
                    for i in range(cols.n_series):
                        if i % 256 == 0:
                            ec.check_deadline()
                        c = (per_series_cfg[i]
                             if per_series_cfg is not None else cfg)
                        rows[i] = rollup_series(
                            func, cols.ts[i, :counts[i]],
                            cols.vals[i, :counts[i]], c, ())
                _rollup_phase_lap(t0r)
                rows = np.asarray(rows, dtype=np.float64)
                gids = np.empty(cols.n_series, np.int64)
                for i, mn in enumerate(cols.metric_names):
                    if gb or ae.without:
                        gmn = mn if keep_name else \
                            MetricName(b"", mn.labels)
                        key = _group_key(gmn, gb, ae.without)
                    else:
                        key = b""
                    g = gidx.get(key)
                    if g is None:
                        g = len(gidx)
                        gidx[key] = g
                    gids[i] = g
                while len(gidx) > cap:
                    cap *= 2
                if cap > acc_buf.shape[0]:
                    na = np.full((cap, T), init)
                    na[:acc_buf.shape[0]] = acc_buf
                    nc = np.zeros((cap, T))
                    nc[:cnt_buf.shape[0]] = cnt_buf
                    acc_buf, cnt_buf = na, nc
                # group-sorted reduceat: buffered row-block reductions
                # instead of ufunc.at's unbuffered per-scalar scatter
                # (10-30x on the (S_chunk, T) hot loop)
                finite = ~np.isnan(rows)
                order_g = np.argsort(gids, kind="stable")
                sg = gids[order_g]
                starts_i = np.flatnonzero(
                    np.concatenate([[True], sg[1:] != sg[:-1]]))
                uniq_g = sg[starts_i]
                rows_s = rows[order_g]
                finite_s = finite[order_g]
                if aggr in ("sum", "avg"):
                    acc_buf[uniq_g] += np.add.reduceat(
                        np.where(finite_s, rows_s, 0.0), starts_i, axis=0)
                elif aggr == "min":
                    acc_buf[uniq_g] = np.minimum(
                        acc_buf[uniq_g],
                        np.minimum.reduceat(
                            np.where(finite_s, rows_s, np.inf),
                            starts_i, axis=0))
                elif aggr == "max":
                    acc_buf[uniq_g] = np.maximum(
                        acc_buf[uniq_g],
                        np.maximum.reduceat(
                            np.where(finite_s, rows_s, -np.inf),
                            starts_i, axis=0))
                cnt_buf[uniq_g] += np.add.reduceat(
                    finite_s.astype(np.float64), starts_i, axis=0)
            n_chunks += 1
    except ResourceWarning as e:
        from .limits import QueryLimitError
        qt.donef("error: %s", e)
        raise QueryLimitError(
            f"{e}; either narrow the selector or raise "
            f"-search.maxUniqueTimeseries") from None
    except BaseException as e:
        qt.donef("error: %s", e)  # close the span on deadline/limit aborts
        raise
    qt.donef("%d chunks, %d samples, %d groups", n_chunks, n_samples,
             len(gidx))
    out = []
    nan = np.nan
    for key, g in gidx.items():
        have = cnt_buf[g] > 0
        if aggr == "count":
            vals = np.where(have, cnt_buf[g], nan)
        elif aggr == "avg":
            with np.errstate(invalid="ignore"):
                vals = np.where(have, acc_buf[g] / cnt_buf[g], nan)
        else:
            vals = np.where(have, acc_buf[g], nan)
        out.append(Timeseries(MetricName.unmarshal(key), vals))
    out.sort(key=lambda ts: ts.metric_name.marshal())
    return out


# (storage token, tenant, grouping, without, keep_name) -> (raw-name
# tuple, gids, group_keys, sorted emit order): a steady-state dashboard
# groups the SAME series set every refresh, so the per-series group-key
# scan collapses to one tuple comparison. Invalidated automatically when
# the fetched series set changes (new/vanished series); bounded clear-all.
_FUSED_GIDS_MEMO: dict = {}
_FUSED_GIDS_MEMO_MAX = 64
_EMPTY_NAME_KEY = MetricName(b"", []).marshal()


def _fused_group_ids(ec: EvalConfig, ae, cols, keep_name: bool,
                     sel_id: str):
    """Group assignment for the fused host aggregation: group keys,
    sorted output order and per-group row-index arrays
    (rows in input order, matching _group_series's vstack order),
    memoized on the fetched raw-name tuple (the hot steady-state case is
    an identical series set).  sel_id (the rollup argument's source
    text) keeps same-grouping panels over DIFFERENT selectors in
    separate slots — without it two such panels evict each other's memo
    every refresh."""
    gb = tuple(g.encode() for g in ae.grouping)
    token = getattr(ec.storage, "cache_token", None)
    sig = (token if token is not None else id(ec.storage), ec.tenant, gb,
           ae.without, keep_name, sel_id)
    raws_t = tuple(cols.raw_names)
    memo = _FUSED_GIDS_MEMO.get(sig)
    if memo is not None and memo[0] == raws_t:
        return memo[1], memo[2], memo[3]
    gbl = list(gb)
    key_to_gid: dict[bytes, int] = {}
    group_keys: list[bytes] = []
    rows_of: list[list[int]] = []
    for i, mn in enumerate(cols.metric_names):
        if i % 256 == 0:
            ec.check_deadline()
        if gbl or ae.without:
            # rollups that drop the metric name group on the BLANKED name,
            # exactly like _finish_rollup_names(keep_name=False) before
            # _group_key on the normal path
            gmn = mn if keep_name else MetricName(b"", mn.labels)
            key = _group_key(gmn, gbl, ae.without)
        else:
            key = _EMPTY_NAME_KEY
        gid = key_to_gid.get(key)
        if gid is None:
            gid = len(group_keys)
            key_to_gid[key] = gid
            group_keys.append(key)
            rows_of.append([])
        rows_of[gid].append(i)
    order = sorted(range(len(group_keys)), key=lambda g: group_keys[g])
    group_rows = [np.asarray(r, np.int64) for r in rows_of]
    if len(_FUSED_GIDS_MEMO) >= _FUSED_GIDS_MEMO_MAX:
        _FUSED_GIDS_MEMO.clear()
    # benign memo race: racing fills for one sig store equal values
    # (pure function of sig); a clear-vs-fill race just re-misses
    _FUSED_GIDS_MEMO[sig] = (raws_t, group_keys, order, group_rows)  # vmt: disable=VMT015
    return group_keys, order, group_rows


def _host_fused_aggr_compute(ec: EvalConfig, ae, func: str, rarg,
                             window: int, offset: int, keep_name: bool
                             ) -> list[Timeseries]:
    """One fused columnar pass: fetch -> packed rollup -> reduceat group
    aggregation -> (G, T) rows. No per-series Timeseries ever exists, so
    a tail suffix eval costs O(new samples) instead of O(S) Python."""
    from ..ops import rollup_np
    cols, cfg, admission, _ = _fetch_columns_for_rollup(
        ec, func, rarg, window, offset)
    T = ec.n_points
    aggr = ae.name
    qt = ec.tracer.new_child("host fused rollup %s(%s) (columns)", aggr,
                             func)
    try:
        with admission:
            if cols.n_series == 0:
                qt.donef("0 series")
                return []
            per_series_cfg = None
            adj = adjusted_windows(func, window, ec.step, cols.ts_list())
            if adj:
                if all(a == adj[0] for a in adj):
                    cfg = RollupConfig(start=cfg.start, end=cfg.end,
                                       step=cfg.step, window=adj[0])
                else:
                    per_series_cfg = [
                        RollupConfig(start=cfg.start, end=cfg.end,
                                     step=cfg.step, window=a)
                        for a in adj]
            import time as _time
            t0r = _time.perf_counter()
            rows = None
            if per_series_cfg is None:
                rows = rollup_np.rollup_batch_packed(
                    func, cols.ts, cols.vals, cols.counts, cfg, ())
            if rows is None:  # non-finite values / per-series windows
                counts = cols.counts
                rows = np.empty((cols.n_series, T))
                for i in range(cols.n_series):
                    if i % 256 == 0:
                        ec.check_deadline()
                    c = (per_series_cfg[i]
                         if per_series_cfg is not None else cfg)
                    rows[i] = rollup_series(func, cols.ts[i, :counts[i]],
                                            cols.vals[i, :counts[i]], c,
                                            ())
            rows = np.asarray(rows, dtype=np.float64)
            _rollup_phase_lap(t0r)
            group_keys, order, group_rows = _fused_group_ids(
                ec, ae, cols, keep_name, f"{func}|{rarg}")
            G = len(group_keys)
            # per-group reduction with the SAME aggregate kernels
            # _simple_aggr applies to its vstacked groups (rows gathered
            # in input order): bit-identical to the unfused path by
            # construction — reduceat would sum in a different order and
            # drift by ulps, breaking the served==cold rtol=0 invariant
            fn = SIMPLE[aggr]
            vals = np.empty((G, T))
            for g in range(G):
                vals[g] = fn(rows[group_rows[g]])
        qt.donef("%d series -> %d groups", cols.n_series, G)
    except BaseException as e:
        qt.donef("error: %s", e)  # close the span on deadline/limit aborts
        raise
    return [Timeseries(MetricName.unmarshal(group_keys[g]), vals[g],
                       raw=group_keys[g])
            for g in order]


def _try_host_fused_aggr(ec: EvalConfig, ae) -> list[Timeseries] | None:
    """aggr by (...)(rollup(selector)) fused on host: columnar fetch ->
    packed rollup -> reduceat group reduction, materializing only the
    (G, T) aggregated block — the host twin of the device fused path and
    the steady-state lever of ROADMAP item 2: a dashboard-suffix eval
    never rebuilds S per-series Timeseries or the S-row eval cache entry.
    The (G, T) result is cached in the rollup result cache keyed by the
    FULL aggregation (ring entries make the rolling merge in-place), so
    repeated/rolling evals of the same shape cost O(new samples).
    VM_HOST_FUSED_AGGR=0 restores the unfused path (equality oracle).
    None -> not applicable, use the normal path."""
    if ec.tpu is not None or ae.name not in _CHUNK_AGGRS:
        return None
    if len(ae.args) != 1 or ae.limit:
        return None
    import os as _os
    if _os.environ.get("VM_HOST_FUSED_AGGR", "1") == "0":
        return None
    shape = _aggr_rollup_shape(ae.args[0])
    if shape is None:
        return None
    func, rarg = shape
    from ..ops import rollup_np
    if not rollup_np.batch_supported(func, ()):
        return None
    if ec.storage is None or \
            getattr(ec.storage, "search_columns", None) is None:
        return None
    offset = rarg.offset.value_ms(ec.step) if rarg.offset is not None else 0
    window = rarg.window.value_ms(ec.step) if rarg.window is not None else 0
    keep_name = func == "default_rollup" or func in KEEP_METRIC_NAMES
    # mirror _rollup_from_storage's eval-cache gating (default_rollup's
    # lookback depends on ec state; negative offsets touch the volatile
    # now-edge)
    use_cache = (ec.n_points > 1 and func != "default_rollup"
                 and offset >= 0 and not ec.disable_cache
                 and not ec.no_eval_cache)
    if not use_cache:
        return _host_fused_aggr_compute(ec, ae, func, rarg, window, offset,
                                        keep_name)
    import time as _t

    from .rollup_result_cache import GLOBAL as rcache
    now_ms = int(_t.time() * 1000)
    ckey = (f"fusedaggr|{ae.name}|{','.join(ae.grouping)}|{ae.without}|"
            f"{func}|{rarg.expr}|{window}|{offset}|{keep_name}")
    cached, new_start = rcache.get(ec, ckey, now_ms)
    if cached is not None and new_start > ec.end:
        ec.tracer.printf("host fused aggr cache: full hit %s", ckey)
        return cached.rows()
    if cached is not None:
        ec.tracer.printf("host fused aggr cache: tail from %d", new_start)
        sub_start, trim = suffix_child_bounds(ec, new_start)
        sub = ec.child(start=sub_start)
        sub.no_eval_cache = True  # the suffix must not clobber ckey
        fresh = _host_fused_aggr_compute(sub, ae, func, rarg, window,
                                         offset, keep_name)
        if trim:
            fresh = trim_suffix_rows(fresh)
        rows = rcache.merge(cached, fresh, ec, new_start, now_ms=now_ms)
        if not ec._partial[0]:
            rcache.put(ec, ckey, rows, now_ms)
        return rows
    rows = _host_fused_aggr_compute(ec, ae, func, rarg, window, offset,
                                    keep_name)
    if not ec._partial[0]:
        rcache.put(ec, ckey, rows, now_ms)
    return rows


def _eval_aggr(ec: EvalConfig, ae: AggrFuncExpr) -> list[Timeseries]:
    name = ae.name

    fused = _try_device_fused_aggr(ec, ae)
    if fused is not None:
        return fused
    chunked = _try_host_chunked_aggr(ec, ae)
    if chunked is not None:
        return chunked
    hfused = _try_host_fused_aggr(ec, ae)
    if hfused is not None:
        return hfused

    # arg layouts
    if name in ("topk", "bottomk", "limitk", "outliersk") or \
            name.startswith(("topk_", "bottomk_")):
        remaining = None
        if len(ae.args) == 3 and isinstance(ae.args[2], StringExpr) and \
                name.startswith(("topk_", "bottomk_")):
            remaining = ae.args[2].value  # remaining-sum series tag
        elif len(ae.args) != 2:
            raise QueryError(f"{name} needs (k, q)")
        k = float(eval_expr(ec, ae.args[0])[0].values[0])
        if np.isnan(k) or k < 0:
            k = 0.0  # getIntK clamps (aggr.go:793)
        if name not in ("limitk", "outliersk") and not np.isinf(k):
            got = _try_device_topk(ec, ae, name, k, remaining)
            if got is not None:
                return got
        series = eval_expr(ec, ae.args[1])
        if np.isinf(k):
            k = float(len(series))
        return _eval_topk_family(ec, ae, name, k, series, remaining)
    if name == "quantile":
        phi = float(eval_expr(ec, ae.args[0])[0].values[0])
        series = eval_expr(ec, ae.args[1])
        return _simple_aggr(ec, ae, series,
                            lambda m: a_quantile(m, phi))
    if name == "quantiles":
        dst = ae.args[0]
        if not isinstance(dst, StringExpr):
            raise QueryError("quantiles needs a label name first")
        phis = [float(eval_expr(ec, a)[0].values[0]) for a in ae.args[1:-1]]
        series = eval_expr(ec, ae.args[-1])
        out = []
        for phi in phis:
            rows = _simple_aggr(ec, ae, series, lambda m: a_quantile(m, phi))
            for ts in rows:
                ts.metric_name.labels.append(
                    (dst.value.encode(), repr(phi).encode()))
                ts.metric_name.sort_labels()
                ts.raw = None  # memoized marshal is stale now
            out.extend(rows)
        return out
    if name == "count_values":
        dst = ae.args[0]
        if not isinstance(dst, StringExpr):
            raise QueryError("count_values needs a label name first")
        series = eval_expr(ec, ae.args[1])
        return _eval_count_values(ec, ae, dst.value, series)
    if name in ("share", "zscore"):
        series = eval_expr(ec, ae.args[0])
        return _eval_per_series(ec, ae, PER_SERIES[name], series)
    if name in ("mad", "iqr"):
        # plain aggregates union ALL their args (aggr.go getAggrTimeseries)
        series = [ts for a in ae.args for ts in eval_expr(ec, a)]
        def mad_fn(m):
            med = np.nanmedian(m, axis=0)
            return np.nanmedian(np.abs(m - med), axis=0)
        def iqr_fn(m):
            lo, hi = np.nanquantile(m, [0.25, 0.75], axis=0)
            return hi - lo
        with np.errstate(all="ignore"):
            return _simple_aggr(ec, ae, series,
                                mad_fn if name == "mad" else iqr_fn)
    if name == "outliers_mad":
        tol = float(eval_expr(ec, ae.args[0])[0].values[0])
        series = eval_expr(ec, ae.args[1])
        return _eval_outliers_mad(ec, ae, tol, series)
    if name == "outliers_iqr":
        series = eval_expr(ec, ae.args[0])
        return _eval_outliers_iqr(ec, ae, series)

    if name == "histogram":
        series = [ts for a in ae.args for ts in eval_expr(ec, a)]
        return _eval_histogram_aggr(ec, ae, series)

    if name == "any":
        # first series per group, ORIGINAL identity kept (aggr.go:156)
        series = [ts for a in ae.args for ts in eval_expr(ec, a)]
        groups, _ = _group_series(series, ae.grouping, ae.without)
        out = [rows[0] for rows in groups.values()]
        out.sort(key=lambda ts: ts.metric_name.marshal())
        return out

    series = [ts for a in ae.args for ts in eval_expr(ec, a)]
    fn = SIMPLE.get(name)
    if fn is None:
        raise QueryError(f"unknown aggregate {name!r}")
    return _simple_aggr(ec, ae, series, fn)


def _eval_histogram_aggr(ec, ae, series) -> list[Timeseries]:
    """histogram(q): per-step VM histogram over each group's values,
    emitted as CUMULATIVE le= buckets with zero-filled gaps — the
    reference converts through vmrangeBucketsToLE (aggr.go:256-285)."""
    from .transform_funcs import _vmrange_to_le
    from .vmhistogram import vmrange_for
    groups, names = _group_series(series, ae.grouping, ae.without)
    out = []
    for key, rows in groups.items():
        m = np.vstack([ts.values for ts in rows])
        per_range: dict[str, np.ndarray] = {}
        T = m.shape[1]
        for j in range(T):
            col = m[:, j]
            for v in col[~np.isnan(col)]:
                r = vmrange_for(float(v))
                if r is None:
                    continue
                row = per_range.get(r)
                if row is None:
                    row = per_range[r] = np.zeros(T)
                row[j] += 1.0
        base = names[key]
        raw = []
        for r, vals in sorted(per_range.items()):
            mn = MetricName(base.metric_group,
                            list(base.labels) + [(b"vmrange", r.encode())])
            mn.sort_labels()
            raw.append(Timeseries(mn, vals))
        out.extend(_vmrange_to_le(raw))
    out.sort(key=lambda ts: ts.metric_name.marshal())
    return out


def _simple_aggr(ec, ae, series, fn) -> list[Timeseries]:
    groups, names = _group_series(series, ae.grouping, ae.without)
    # `limit N` keeps the first N groups in INPUT order — groups past the
    # limit are skipped at grouping time (aggr.go:139 aggrPrepareSeries),
    # not after sorting.
    if ae.limit and len(groups) > ae.limit:
        groups = {k: groups[k] for k in list(groups)[:ae.limit]}
    out = []
    for key, rows in groups.items():
        m = np.vstack([ts.values for ts in rows])
        vals = fn(m)
        out.append(Timeseries(names[key], np.asarray(vals, dtype=np.float64)))
    out.sort(key=lambda ts: ts.metric_name.marshal())
    return out


def _eval_per_series(ec, ae, fn, series) -> list[Timeseries]:
    groups, _ = _group_series(series, ae.grouping, ae.without)
    out = []
    for key, rows in groups.items():
        m = np.vstack([ts.values for ts in rows])
        res = fn(m)
        for i, ts in enumerate(rows):
            out.append(Timeseries(MetricName(b"", list(ts.metric_name.labels)),
                                  res[i]))
    return out


def _remaining_sum_series(ec, ae, rows, selected_idx, tag_spec: str
                          ) -> Timeseries:
    """Sum of the NON-selected series, tagged tag[=value]
    (aggr.go:751 getRemainingSumTimeseries)."""
    if "=" in tag_spec:
        tag, _, value = tag_spec.partition("=")
    else:
        tag = value = tag_spec
    base = rows[0].metric_name
    gb = {g.encode() for g in ae.grouping}
    if ae.without:
        labels = [(kk, vv) for kk, vv in base.labels if kk not in gb]
    else:
        labels = [(kk, vv) for kk, vv in base.labels if kk in gb]
    labels = [(kk, vv) for kk, vv in labels if kk != tag.encode()]
    labels.append((tag.encode(), value.encode()))
    mn = MetricName(b"", sorted(labels))
    rest = [r for i, r in enumerate(rows) if i not in selected_idx]
    if not rest:
        return Timeseries(mn, np.full(ec.n_points, nan))
    m = np.vstack([r.values for r in rest])
    with np.errstate(all="ignore"):
        vals = np.where(np.isnan(m).all(axis=0), nan, np.nansum(m, axis=0))
    return Timeseries(mn, vals)


def _vm_name_hash(mn: MetricName) -> int:
    """aggr.go getHash: xxhash64 over MetricGroup then raw key+value bytes of
    the sorted tags — NOT the length-prefixed marshal. Drives limitk()'s
    stable uniform series selection."""
    import xxhash
    parts = [mn.metric_group]
    for lk, lv in sorted(mn.labels):
        parts.append(lk)
        parts.append(lv)
    return xxhash.xxh64_intdigest(b"".join(parts))


def _try_device_topk(ec, ae, name: str, k: float,
                     remaining) -> list[Timeseries] | None:
    """topk/bottomk[_kind](k, rollup(selector)) fused on device: the
    [S, T] rollup stays in HBM, selection runs there, and only winner
    indices plus the k chosen rows cross the link (None -> host path)."""
    if ec.tpu is None or remaining is not None or ae.grouping or ae.without:
        return None
    arg = ae.args[1]
    if isinstance(arg, FuncExpr):
        if len(arg.args) != 1 or arg.keep_metric_names:
            return None
        func, rarg = arg.name, arg.args[0]
    elif isinstance(arg, (MetricExpr, RollupExpr)):
        func, rarg = "default_rollup", arg
    else:
        return None
    if isinstance(rarg, MetricExpr):
        rarg = RollupExpr(expr=rarg)
    if not isinstance(rarg, RollupExpr) or \
            not isinstance(rarg.expr, MetricExpr) or rarg.expr.is_empty() or \
            rarg.needs_subquery() or rarg.at is not None:
        return None
    from ..ops import rollup_np
    if func not in rollup_np.CORE_SUPPORTED:
        return None
    from .tpu_engine import try_topk_rollup_tpu
    keep_name = func in KEEP_METRIC_NAMES
    offset = rarg.offset.value_ms(ec.step) if rarg.offset is not None else 0
    window = rarg.window.value_ms(ec.step) if rarg.window is not None else 0
    series, cfg, admission, fetch_info = _fetch_series_for_rollup(
        ec, func, rarg, window, offset)
    adj = adjusted_windows(func, window, ec.step,
                           [sd.timestamps for sd in series])
    if adj:
        if not all(a == adj[0] for a in adj):
            # per-series windows: host path. Release the admission
            # reservation and roll back the sample count — the host
            # re-fetches and re-counts (same contract as
            # _try_device_fused_aggr's decline path)
            with admission:
                pass
            ec.count_samples(-sum(s.timestamps.size for s in series))
            return None
        cfg = RollupConfig(start=cfg.start, end=cfg.end, step=cfg.step,
                           window=adj[0])
    with admission:
        with ec.tracer.new_child("tpu fused %s(%s)", name, func) as qt:
            got = try_topk_rollup_tpu(
                ec.tpu, name, k, func, series, cfg,
                cache_key=_tile_cache_key(ec, rarg.expr, cfg, fetch_info))
            if got is None:
                qt.donef("fell back to host")
                ec.count_samples(-sum(s.timestamps.size for s in series))
                return None
            qt.donef("device selection, %d of %d series kept",
                     len(got), len(series))
    return _finish_rollup_names(
        (series[i].metric_name for i, _ in got),
        [vals for _, vals in got], keep_name)


def _eval_topk_family(ec, ae, name, k, series,
                      remaining: str | None = None) -> list[Timeseries]:
    groups, _ = _group_series(series, ae.grouping, ae.without)
    out = []
    bottom = name.startswith("bottomk")
    for key, rows in groups.items():
        m = np.vstack([ts.values for ts in rows])
        if name in ("topk", "bottomk"):
            mask = topk_mask_per_ts(m, int(k), bottom)
            for i, ts in enumerate(rows):
                vals = np.where(mask[i], ts.values, nan)
                if not np.isnan(vals).all():
                    out.append(Timeseries(ts.metric_name, vals))
        elif name == "limitk":
            if k <= 0:
                continue
            ranked = sorted(rows, key=lambda ts: _vm_name_hash(ts.metric_name))
            out.extend(ranked[:int(k)])
        elif name == "outliersk":
            med = np.nanmedian(m, axis=0)
            with np.errstate(all="ignore"):
                dev = np.nansum((m - med) ** 2, axis=1)
            # stable ascending sort, keep the LAST k: ties favor later
            # series (getRangeTopKTimeseries ordering)
            order = np.argsort(dev, kind="stable")
            kn = max(int(k), 0)
            for i in (order[-kn:] if kn else []):
                out.append(rows[i])
        else:
            kind = name.split("_", 1)[1]
            rank = series_rank_metric(kind, m)
            rank = np.where(np.isnan(rank), -np.inf if not bottom else np.inf,
                            rank)
            kn = max(int(k), 0)
            if bottom:
                # stable desc sort, keep last k: ties favor later series
                order = np.argsort(-rank, kind="stable")
            else:
                order = np.argsort(rank, kind="stable")
            sel = order[-kn:] if kn else []
            for i in sel:
                out.append(rows[i])
            if remaining is not None:
                out.append(_remaining_sum_series(ec, ae, rows, set(
                    int(i) for i in sel), remaining))
    return out


def _eval_count_values(ec, ae, dst_label, series) -> list[Timeseries]:
    # aggr.go:576: the dst label leaves `by` grouping / joins `without`
    # grouping, so the per-value output label always wins
    grouping = list(ae.grouping)
    if ae.without:
        if dst_label not in grouping:
            grouping.append(dst_label)
    else:
        grouping = [g for g in grouping if g != dst_label]
    groups, names = _group_series(series, grouping, ae.without)
    out = []
    for key, rows in groups.items():
        m = np.vstack([ts.values for ts in rows])
        uniq = np.unique(m[~np.isnan(m)])
        for u in uniq:
            cnt = np.nansum(np.where(m == u, 1.0, 0.0), axis=0)
            cnt = np.where(cnt > 0, cnt, nan)
            mn = MetricName(b"", list(names[key].labels))
            sval = repr(float(u))
            if float(u) == int(u) and abs(u) < 1e15:
                sval = str(int(u))
            mn.labels.append((dst_label.encode(), sval.encode()))
            mn.sort_labels()
            out.append(Timeseries(mn, cnt))
    return out


def _eval_outliers_mad(ec, ae, tolerance, series) -> list[Timeseries]:
    groups, _ = _group_series(series, ae.grouping, ae.without)
    out = []
    for key, rows in groups.items():
        m = np.vstack([ts.values for ts in rows])
        with np.errstate(all="ignore"):
            med = np.nanmedian(m, axis=0)
            mad = np.nanmedian(np.abs(m - med), axis=0)
        for i, ts in enumerate(rows):
            with np.errstate(all="ignore"):
                if np.any(np.abs(ts.values - med) > tolerance * mad):
                    out.append(ts)
    return out


def _eval_outliers_iqr(ec, ae, series) -> list[Timeseries]:
    groups, _ = _group_series(series, ae.grouping, ae.without)
    out = []
    for key, rows in groups.items():
        m = np.vstack([ts.values for ts in rows])
        with np.errstate(all="ignore"):
            q25, q75 = np.nanquantile(m, [0.25, 0.75], axis=0)
            iqr = q75 - q25
            lo, hi = q25 - 1.5 * iqr, q75 + 1.5 * iqr
        for i, ts in enumerate(rows):
            with np.errstate(all="ignore"):
                if np.any((ts.values < lo) | (ts.values > hi)):
                    out.append(ts)
    return out


# ---------------------------------------------------------------------------
# Binary ops
# ---------------------------------------------------------------------------

def _is_const_scalar(e: Expr) -> bool:
    """True scalars per PromQL: literals and scalar() — NOT time()/rand(),
    which are instant vectors (so comparisons keep THEIR values)."""
    if isinstance(e, (NumberExpr, DurationExpr)):
        return True
    if isinstance(e, FuncExpr) and e.name == "scalar":
        return True
    if isinstance(e, BinaryOpExpr) and e.op in ARITH_OPS:
        return _is_const_scalar(e.left) and _is_const_scalar(e.right)
    return False


def _is_union_expr(e: Expr) -> bool:
    return isinstance(e, FuncExpr) and e.name in ("union", "")


def _eval_binary(ec: EvalConfig, be: BinaryOpExpr) -> list[Timeseries]:
    if be.op in ("==", "!=") and \
            (_is_union_expr(be.left) or _is_union_expr(be.right)):
        # `q == (v1,...,vN)` value-list filtering (binary_op.go:58)
        left = eval_expr(ec, be.left)
        right = eval_expr(ec, be.right)
        if _is_union_expr(be.left):
            left, right = right, left
        if not left or not right:
            return [] if be.op == "==" else left
        vals_r = np.vstack([r.values for r in right])
        out = []
        for ts in left:
            contained = np.any(vals_r == ts.values[None, :], axis=0)
            keep = contained if be.op == "==" else ~contained
            out.append(Timeseries(ts.metric_name,
                                  np.where(keep, ts.values, nan)))
        return out

    l_scalar = _is_const_scalar(be.left)
    r_scalar = _is_const_scalar(be.right)
    left = eval_expr(ec, be.left)
    right = eval_expr(ec, be.right)

    if be.op in ARITH_OPS or be.op in CMP_OPS:
        if l_scalar and r_scalar:
            a, b = left[0].values, right[0].values
            if be.op in ARITH_OPS:
                return [new_series(ARITH_OPS[be.op](a, b))]
            m = CMP_OPS[be.op](a, b)
            if be.bool_modifier:
                return [new_series(m.astype(np.float64))]
            return [new_series(np.where(m, a, nan))]
        if r_scalar:
            b = right[0].values
            return _scalar_side(be, left, b, scalar_on_left=False)
        if l_scalar:
            a = left[0].values
            return _scalar_side(be, right, a, scalar_on_left=True)

    if be.op == "default" and r_scalar:
        b = right[0].values
        out = []
        for ts in left:
            vals = np.where(np.isnan(ts.values), b, ts.values)
            out.append(Timeseries(ts.metric_name, vals))
        return out

    return eval_binary_op(be.op, left, right, be.bool_modifier,
                          be.group_modifier, be.join_modifier,
                          be.keep_metric_names)


def _scalar_side(be: BinaryOpExpr, vec: list[Timeseries], s: np.ndarray,
                 scalar_on_left: bool) -> list[Timeseries]:
    out = []
    is_cmp = be.op in CMP_OPS
    for ts in vec:
        a, b = (s, ts.values) if scalar_on_left else (ts.values, s)
        if is_cmp:
            with np.errstate(all="ignore"):
                m = CMP_OPS[be.op](a, b)
            m = m & ~np.isnan(ts.values)
            if be.bool_modifier:
                vals = m.astype(np.float64)
                vals[np.isnan(ts.values)] = nan
            else:
                vals = np.where(m, ts.values, nan)
            # non-bool comparisons keep names on scalar compare; `bool`
            # resets the metric group (eval.go resetMetricGroupIfRequired)
            keep = not be.bool_modifier
        else:
            vals = ARITH_OPS[be.op](a, b)
            keep = be.keep_metric_names
        mn = MetricName(ts.metric_name.metric_group if keep else b"",
                        list(ts.metric_name.labels))
        out.append(Timeseries(mn, np.asarray(vals, dtype=np.float64)))
    return out
