"""Bit-exact replica of Go's math/rand generator (the legacy ALFG source).

The reference's rand()/rand_normal()/rand_exponential() transform functions
(transform.go:2653 newTransformRand) draw from rand.New(rand.NewSource(seed))
— Go's additive lagged Fibonacci generator y[n] = y[n-273] + y[n-607] mod
2^64, seeded via a MINSTD LCG chain XORed against the `rngCooked` state
table.  Replicating the stream bit-for-bit makes the seeded rand() golden
cases (exec_test.go) reproducible.

The cooked table below is NOT copied from Go's sources: it is re-derived by
running the documented generation procedure (gen_cooked.go: seed the state
with srand(1), advance 7.8e12 steps, dump the state vector).  The 7.8e12-step
warmup is fast-forwarded analytically — the recurrence is linear over Z_2^64,
so the state after N steps is x^N reduced modulo the characteristic
polynomial x^607 - x^334 - 1 (see `_generate_cooked`, which rebuilds the
table in ~0.1s and is cross-checked by tests against the embedded blob).

Validated against the golden corpus: the seed-0/seed-1 Float64 streams,
ziggurat NormFloat64/ExpFloat64 draws, and multi-hundred-draw subquery cases
all match the reference's expected values.
"""

from __future__ import annotations

import base64
import math

import numpy as np

_M31 = (1 << 31) - 1
_M63 = (1 << 63) - 1
_M64 = (1 << 64) - 1

_COOKED_B85 = (
    ">i?MQGfMZxTVIyCK}vhT53hmC6UAN=>y<;NT>At{j?WNx9E0em!@)O?=;U2}sfGVTch4bXVf"
    "mkGRR+sK(%0xf(a0ulp!yLsA5o`CRZylPNq6mWTrKKf#-CiBXhQ)?Ijg-t3pA2xSu^@@0&1q"
    "tPWuq9iqX7_sl1=}tbn5~vCQ|E8!Pk5l-H2~uPoyvG%{*GD-;1AlFUNJXn^xipN1l#AUUrm5"
    "u>rCmYu@A`J@M1h9!Y1JeMkJ;Zl0{+cTtUp2><kZXuL|e3@HO#i@YXY4^+X@AAU~3?cvSG32"
    "$T^;0RCG*ie9>Cq71G8AeqNwLvcnPTL<QF%CaI82#mW3HQ3Vbm24cc9Yg#FoFk^B~%4hGI{!"
    "zn9xa7TS;@dX+L5Jcn^mOOV<bI#4@4D22!JrHJD*cj(X=tP97!a_}4YaA_p~hNw^W6pGRJOm"
    "oz<c=>Lmj;ALvSMe`+rt6#43L7K<FI(v!SxLp(IW0_x^7@rwT{Q2oy(^qn9^#BpIbe$myV5m"
    "OaCQ~%_t^3E3XonMH`IT97%h+<dF$Yhe0w5)n7iu%Vh(vYap59)td4<1K-1b##nxB`nwE0Rl"
    "(@fyy(zQfK~aULM(4V*Ru!|HNpEQR1dyOSX@^jLZ4^)paLHPe|JXIn*i;p&v>2G)pxQHJ{eJ"
    "UJR^YM)t?7~VDL(ky3+rbW@|jXol5!-t>r7r+mqq5fE5kcn6?p3GZs@F6Q(h@f2)nIMnre`X"
    "$zw$JY6wvM{MH%$tc3%upE}XurPSuToCBM;GoK0kHChaWBqDg(&l=T5yf{u5K2F|LpD*}R4)"
    "3#v3i=J<X_rHL?AZzG5l~Fb$HSgc)#DSy51B^`;#<zoe&7c`z9Jd7xX{w2VaI&PAL<@O-PZB"
    "<-d<411?e0Bj@NGcx@h!7HVVww&?!VN+cClv(>;`sn#~;I(ZY`?^=U|EwT~7~U~k3U_WH4~t"
    "K?BZC%LE2=tq|9-eJCd7(%CZJPvqCK#g~R6Xa~dMnrLv3vlNdz*|RU7j4*J9e4;1;BocU=?@"
    "Q|S8j+izhZ7j=Cn>y{|s@ArI|RMrB>)9nizvui+rC{6aWGSoKpyxXH-$L?pge}6sDPki~o=F"
    "B2qm%=PRc5I7mz@gMy1t-uN$Q$Da`O3_rzO@vR)wCRl4g2?cV&`j)&9QgHz3`;*f)HU0z#O8"
    "c(NZkt@j89=_0FvqWp>WO&QnYHi-n^^j|NebQ=@DGE@aZVR6-GyhOfVXh8zGAT>VYj9rB7?="
    "vB)#vEBPO#ZP`7*g&;%cvuN*zlA?dEX3Hk7)3yvI}?R=_6`L<$uo~PLS6hG*^1<%Af#5bS_p"
    "n1hb&=Pr4j)+r07ki&5vY?On+PwlGU9bC5gh$E}r~~ARP^6zSH==$G2=cZ9zXU(aGwnHwK4g"
    "~18Dv}6p4XE7tms&>$RTJUG3iEo|2jSCYAd(=jD@f^XA%GBpFCc!rNSIJmBaKZc6RG;2mQq6"
    "bLZ_b`?c>-uKe3sGbDd)+Yb&jK<ATjlHJCnb077OW~m(dZc9^?K@Ha+MRIDuk_8eOy%x<d+}"
    "11%SZhU%?<s_84ZLDpUODNwsZMlA;jV;Sf)wu<G~#hOw=y0|z$jA0CvWZ#q2$uwJ1$N}2w>r"
    "Y^k=&xCvzm|LA4PN*RLvl4EcSo8aXT!mh|CNbVB+i_6|TD6Q0fxW|jt-+RVY%G+9L=Ad=d*I"
    "YL3^R#hkVk&vIXy^1JgJ!_GIpl=4pq0myFq413RY-TECgRNt*9hjYrN`AIjVWQt--@@$cHvT"
    "-$|7268E9>O8(HD0Oo)^+@+eaccx1w>mJH-&yCC}yCVN!E*ttbzZi|c;`0o|2aTC`YmK(Vbb"
    "gAh!3c1N^-22=;@^ynZP*}%uPKwl@^Cl7n3-S{nYg~)`&uQw*PG4_jtNruT`Sbrxl?jHu<RT"
    "Qi(1tQax>8^O>h6K}kMbXYmA0lTmxMnph1qYZ%xuAZhP*Vb^)0`Ik!hRY;0H-NYusSDK@<X3"
    "-`}gF!NWZw~SJ+z|S@Use7?jo86mUYEt%^}U7=n(>xWhwCx8IU)R=OXUtiUx?#j30akG#t`5"
    "Qwl}bGp84RkcI%)Su@&FrO4-btG{gL*A<POTx>xSEbTSa^7jdb!ogIG=X*u^o;k)pA`~HV%<"
    "FPJ`Fh=1kiKU2I#Fj*^BCF@?p(*J+F&b$(?9_I;j{lHB!F498jk%01~mAj6`i)v@!B#%4TH)"
    "<TtnC=~14z&uE(*REI`z$u}uoqbT1fwXj5Kb+Z18oK7RcLqWnRj|KvbPkH_HedQJ3Xz-8)3B"
    "Zl3t(dLk2BegMg0WRWKXm`pCoo3NMF{L@5hDiEIm5t)NtgkyS`L_2Hke_|8U;PC60)?mdf{w"
    "vO7W_2Ksw%)$;(GIUsmYp*|-8?G_2bl80txiLC#YsxxA-SI&YyyB@?QmVkpT=V`jnDaiEjD&"
    "T9XXQR-}|fHFXOBwA*Hk2@GzWaPlofJ`eQraHP<O+B84W92W)iG!lK<o*(sxD29$y$rKqu#w"
    "iw37@R<__{fWxWv)zvjg8<3GIT*#FQrXVAGq2xdX<~S7t(h_u5#6t1_Nq8{2=#;V*_3)i`<A"
    "0PI;OmR36B*^o;YFUT7OhFsFh>s>pm+clfr&}(+6hZM?#wkBUfQ}%YCKr1|5G}icXis@u@L3"
    "mE;KHUN@cX(1l=zo_$VjU}z)Ee;nDe6v(Dx8(d0yt9;p?u0|*?S~cV@Wn^GP0*!WCwhIduQV"
    "cr9Z~F*he{Vbp5xWdEkuPps^n#cW%kILNm4&CxGVH38^{F-R#O4TWBW~O}CMb(rlcZR5}d~d"
    "HcbU5w;t+z0+rY5#siV4g8kVBI`-8`>cLN)lFsfo0s7o$|zk}r))!AdD$2;hT_-LMHZdUJie"
    "zdu*{x&;itDBg2E};&L1l)4KZY1H;`J`y?r7P7HTug&2ad-^(ARzUU7s=CD7nQN0K3Tb;S+{"
    "aoPJ=59D4K;k>O|Hq3ZEl`@cncA%AnQ#)!o)4++3rij>x9H^UPfu~$|oQ<zPlPH~0KMlm{#C"
    "_k#*fIVA+tz(J1AuJ<mkJ_i_aO$12PX)6%&68`V0A|LDp%?%!}<NmHUW_w;)N3t{@p&u--n~"
    "7qPgvP`}W1$deK(Z$D=4~`3WcYYR-*9@M$eo7Q&L6zO=iXT^#hv_O3Tin5oyz5D`Pu${ozJ<"
    "sE!H*{fb+rxAk$EFP!~v>fDK{J$OLXj?c#|DyTSa35;ZT+6U0xy<Uy<PhLvL9X3D;2z1SQdE"
    "Bq%M6!TR54+GbxZ{i3mk%r2nK}t5zj=#O=~Y;r?)mGfB7b%)rJ={liPBGaLuX7+EFA_2U_RX"
    "1d(MZ4WZ?vujaV;%j+bNBPH{IGkg;WUqW7~7!ji~9xH%AVGCrvUmPp(&^&&Yr@6X+=!p;mK?"
    "pv&v-=zIYttMJt;2si!&$dz+4Qo%0@4LZR&|6-gZegNDhsIK<2r*HXSk(?jpdK)D2QNr7v~{"
    "YpUW!)-WGn`<s{M)mDJ!-f{W%UqX^3gbxOc1C7m)zrZxD5s2S{+_n9Y65bg4Iz^yt&7iZ*m;"
    "(axVY@Ix|t_OLqHCx*R;A_q&*zsH0C<4EmW3%BUd*LyzCAoc-o<iv(C1`#;t6vV4Gg+U%aW?"
    "8IED$m|L+{eE!N`Bd9@2gdM`DijfD&KoLaq_>%}2Kvj)VHC4h8|>g#64~j8LbnWBCL>grKUU"
    "rPJ%^r1werY~Mls-fM><e4ge9PjV!|w5D1;@(oA7xRCXkAT^jNJ!O?x^pMOE(fBB<p$%07+c"
    "X<1T+Bjv0EDcRHk-EyEN=OP3)OPJko5@b_<3-YatH;2A-8O4r`0yB$y<oj;g|k-wTQTE{p18"
    "v5Q3Cq0XMl82zl>3R%H{uCVHp6D5%{O6NZob&s(koxVNGRKru4V9;wLFKI~_>paB4f<=vC8`"
    "N8(&c!LYP>}v>)$NX==mr-?|_yqDa?geKgOePl9f_0L|JQGHBq<i7B;<Tz<8xcRFUL`{m7LG"
    "lloPp3pvncUMO(3f|`y$b7sy2@!zYr;0x|?6%5Uj3{$|~d!u(nj4%gqmZIAo9L2{uOoUm#k<"
    "Ey%dGj_qj2tRwwH4U#4DWO;##TCKe$t6BlM@0`4TJ#*@$kLMmdq#}8B(S;7<A^w5j#XS;XW="
    ">~$5W$Ar`?(sqKbIJZDvWK)RqoUPwY3#eZASprDsAfGvuIHKh07z|5UE`tTX&x}5nh{@*Xm_"
    "`baKcm=bgbSs)M?g)oY;B5QinA=R=bg+55Z#b7E^Gna%Eq0s}lP8!$^Fzw9vyT?t`4m_+1L;"
    "_Cas5Qx$_Zq@jka3_UzGF}4jZL+1tJJ^9cYo}Oq@j4E4H+UfoVUYw{x_fZxPQWy>B*PNVfpp"
    "gB-xS^w2dKe41>|L71<gj5BjR2Dg<I{(0Lm1VFy=JCz3DaTsj*z8OwSk;tX)`x`W@$bw&uUa"
    "m=%PTfNI|D(+FXvbbv>U!~rHrDEstDxUA=8njRl)U!$YoAo9aRehn6^mnTBR6)de0TGhU(jj"
    "U4MEWW)d4Es11&~8YgSWu#m-5=?DP|p}4O34Oo<w>^ux$+sl0_X4D&=8|-aA@a$8P>F&W9x*"
    "}ZRx0?7Fqa${XKx9MoeVaL<$-@VyaTLix?#u5xpSB7^^<|nd%VTPEXP^Y|J)gB{^+4=%Fk7o"
    "^^g;abRqGJ-Z%~FKLF42y42QdD8ys>MFgmtPrd7-ogyGE8DiPgc2MplK;J1+r-q@9weZkkbz"
    "r2uFqh8MfQ0ckscV7j4@oG)#Ed;ai)MYRj0c9UX^)u*RaOW_Q`iALo8<Y!XXbTyqktaytIbG"
    "Nz!h}v1Z~u#X<?_jz^XS8?NRqi(%7mcj{d}ItK_puW@c2&_{R<{;K=LuYn)UK*@Lq#&NzRA("
    "m<c-?qXX&ukBslTMV-2SB491ZT(M#&%^O$@v>nQC@M^D|%~&K{k1eJaw0P<Yh{D8LZk;>-Te"
    "f3lY-?w<*NDKPPLZ3RHDk$Sp7y-sDyaaMM7@|69=f15=0Ah$Z8t2JOD9I%;G1CS{6sI3VWe+"
    "IqeL3iQNV$G)`5!@-{l&S`qwWUAW=JcU2`w$(X~Ww=Pbh06!?h|Dum1ms3%R*dw4k`WO;FDv"
    "Kf)iXnTU9ID8UgJc+3i#_gEH{=Z`aMrFPbt%c)c^^m$QR(#2bZ(YH}X~r3DG>$LtS9y$9m!V"
    "8APoPZxF!9zF;bQ1E{-+eO0CsJeZkjQDgkniL&e|B8`X$!#+;jr&bYsj2Mz65L);gcmicdL<"
    "_;Ovt3<|w;riGV)nlkWt{M%yC`aL@h~sR2X^$=t(}K7c#s61nQ+^cm&FmLql$GEL&tQQIWlh"
    "%Kum2@(3h=;#hO!6!&!;)bru>QB!iw|^>}TJt@rO^<<Idd9ZSjO5+a>HAGSApMm++4Yw7N*t"
    "#+&W(PC?o*<Jb1cH43xrg)+{XUNDI<*S03euee%jS<2$fNT>$wsv#+FW#D>Owgy8e&=^huoR"
    "zdp-1hD1g$+wF)|&K<mH}B3O(~frR(&%CfRdypszZ4GMcmI52kLI=3!%IPc$Fn%SpgQ+`b(0"
    "15{q?pi=k5AO%5v;+Y$--NU%$pg<>Cmuy7)rB3_=rsuCme^_p<q=bWe6D(BTTploc5Bv`*^o"
    "m^yFh>P~dBA-L!a2s%wkw)&sC-5W-Zt8_Yr8_?b_2;CQ?Pkp_d^)mn9qy_!LAXo7ql0I8)V("
    "^Pu<?^GU!PoD2F$U`{?Huh`aFB^upK=NKm$gZ3}i!bE-vs6bRB&g+gLk#OG)kpeqhQ0ATl{x"
    "fy;33<G3gk5v~PpT*|DNHKMJL)bg-^3r#jZ{Y!ljks=RXoe^>&gXJf2iB2Pv4V~3RKDvPr)9"
    "!-2cAA}-Q7n?hE;i7WB!d|oB2P~IX-71BK3^NZ3up2W}JSXX5%;RIOwmWN4wXOy|?1q->aS<"
    "(ZRLyYRXSCDH~f-QlaEo4s>;m3ek~#*p<qEI#Ybi+p&go_xolNy~EK)x@MYa8gR8Fv2oT*O~"
    "9_63jISYRb}Di7cmrABdkagyTw?8#R}tqy?6GikALu_CvutmP~otgUCc<RL7U{T9&rj}csa@"
    ")puhZNgvV?DFRKceUgkooP-k2@^z)ULOzM9qibD2D7d94^lp&v)lGS&~_+OLu*=AA1sT@O(q"
    "677(>=3?D!=uwt>6R!KfZ%!cBq$(TIO(<J492ioN*=oV2ePUuSY_U*E;x(FjRfA|*WY@~mRe"
    "JEJMdm}o1s^J#wEZpItrjU"
)

_cooked: list[int] | None = None


def _cooked_table() -> list[int]:
    global _cooked
    if _cooked is None:
        raw = base64.b85decode(_COOKED_B85)
        _cooked = [int(v) for v in np.frombuffer(raw, dtype=np.uint64)]
    return _cooked


def _seedrand(x: int) -> int:
    """MINSTD step with Schrage's trick (rng.go seedrand): a=48271, m=2^31-1."""
    hi, lo = divmod(x, 44488)
    x = 48271 * lo - 3399 * hi
    return x + _M31 if x < 0 else x


def _generate_cooked(n_steps: int = 7_800_000_000_000) -> np.ndarray:
    """Re-derive rngCooked: gen_cooked.go seeds the 607-slot state with
    srand(1) (20/10/0-bit LCG packing) and runs the ALFG for 7.8e12 steps;
    the table is the resulting state vector.  The warmup is jumped via
    square-and-multiply of x^N mod (x^607 - x^334 - 1) with uint64
    coefficient wraparound."""
    # srand(1): gen_cooked.go's packing uses shifts 20/10/0
    x = 1
    vec = np.zeros(607, dtype=np.uint64)
    with np.errstate(over="ignore"):
        for i in range(-20, 607):
            x = _seedrand(x)
            if i >= 0:
                u = x << 20
                x = _seedrand(x)
                u ^= x << 10
                x = _seedrand(x)
                u ^= x
                vec[i] = np.uint64(u)

        def polymul_mod(a, b):
            c = np.zeros(1213, dtype=np.uint64)
            for i in range(607):
                if a[i]:
                    c[i:i + 607] += a[i] * b
            for d in range(1212, 606, -1):
                if c[d]:
                    c[d - 273] += c[d]
                    c[d - 607] += c[d]
                    c[d] = np.uint64(0)
            return c[:607].copy()

        result = np.zeros(607, dtype=np.uint64)
        result[0] = 1
        base = np.zeros(607, dtype=np.uint64)
        base[1] = 1
        n = n_steps
        while n:
            if n & 1:
                result = polymul_mod(result, base)
            n >>= 1
            if n:
                base = polymul_mod(base, base)

        # base sequence z_m = y_{m-606}; initial slot consumption order puts
        # y_{-606+m} at vec[(333 - m) % 607]
        z = np.array([vec[(333 - m) % 607] for m in range(607)],
                     dtype=np.uint64)
        g = result  # x^N mod f -> z_N = y_{N-606}
        ys = {}
        for j in range(607):
            ys[n_steps - 606 + j] = int((g * z).sum()) & _M64
            c = g[606]
            g = np.roll(g, 1)
            g[0] = np.uint64(0)
            g[334] += c
            g[0] += c
        # state slot s was last written at the largest step k <= N with
        # k == (334 - s) mod 607
        out = np.zeros(607, dtype=np.uint64)
        for s in range(607):
            r = (334 - s) % 607
            out[s] = np.uint64(ys[n_steps - ((n_steps - r) % 607)])
    return out


# -- ziggurat tables (normal.go / exp.go, float32 like Go's) ----------------

def _norm_tables():
    f32 = np.float32
    kn = [0] * 128
    wn = [f32(0)] * 128
    fn = [f32(0)] * 128
    m1 = 1 << 31
    dn = tn = 3.442619855899
    vn = 9.91256303526217e-3
    q = vn / math.exp(-0.5 * dn * dn)
    kn[0] = int((dn / q) * m1) & 0xFFFFFFFF
    kn[1] = 0
    wn[0] = f32(q / m1)
    wn[127] = f32(dn / m1)
    fn[0] = f32(1.0)
    fn[127] = f32(math.exp(-0.5 * dn * dn))
    for i in range(126, 0, -1):
        dn = math.sqrt(-2.0 * math.log(vn / dn + math.exp(-0.5 * dn * dn)))
        kn[i + 1] = int((dn / tn) * m1) & 0xFFFFFFFF
        tn = dn
        fn[i] = f32(math.exp(-0.5 * dn * dn))
        wn[i] = f32(dn / m1)
    return kn, wn, fn


def _exp_tables():
    f32 = np.float32
    ke = [0] * 256
    we = [f32(0)] * 256
    fe = [f32(0)] * 256
    m2 = 1 << 32
    de = te = 7.697117470131487
    ve = 3.949659822581572e-3
    q = ve / math.exp(-de)
    ke[0] = int((de / q) * m2) & 0xFFFFFFFF
    ke[1] = 0
    we[0] = f32(q / m2)
    we[255] = f32(de / m2)
    fe[0] = f32(1.0)
    fe[255] = f32(math.exp(-de))
    for i in range(254, 0, -1):
        de = -math.log(ve / de + math.exp(-de))
        ke[i + 1] = int((de / te) * m2) & 0xFFFFFFFF
        te = de
        fe[i] = f32(math.exp(-de))
        we[i] = f32(de / m2)
    return ke, we, fe


_NORM = None
_EXP = None


class GoRand:
    """rand.New(rand.NewSource(seed)) equivalent: Int63/Uint32/Float64 plus
    the ziggurat NormFloat64/ExpFloat64."""

    def __init__(self, seed: int):
        cooked = _cooked_table()
        seed %= _M31
        if seed < 0:
            seed += _M31
        if seed == 0:
            seed = 89482311
        x = seed
        vec = [0] * 607
        for i in range(-20, 607):
            x = _seedrand(x)
            if i >= 0:
                u = (x << 40) & _M64
                x = _seedrand(x)
                u ^= x << 20
                x = _seedrand(x)
                u ^= x
                u ^= cooked[i]
                vec[i] = u & _M64
        self.vec = vec
        self.tap = 0
        self.feed = 607 - 273

    def int63(self) -> int:
        self.tap = (self.tap - 1) % 607
        self.feed = (self.feed - 1) % 607
        v = (self.vec[self.feed] + self.vec[self.tap]) & _M64
        self.vec[self.feed] = v
        return v & _M63

    def uint32(self) -> int:
        return self.int63() >> 31

    def float64(self) -> float:
        while True:
            f = self.int63() / (1 << 63)
            if f != 1.0:
                return f

    def norm_float64(self) -> float:
        global _NORM
        if _NORM is None:
            _NORM = _norm_tables()
        kn, wn, fn = _NORM
        f32 = np.float32
        rn = 3.442619855899
        while True:
            u = self.uint32()
            j = u - (1 << 32) if u >= (1 << 31) else u  # int32 view
            i = j & 0x7F
            x = float(j) * float(wn[i])
            if abs(j) < kn[i]:
                return x
            if i == 0:
                while True:
                    x = -math.log(self.float64()) * (1.0 / rn)
                    y = -math.log(self.float64())
                    if y + y >= x * x:
                        break
                x += rn
                return x if j > 0 else -x
            if f32(float(fn[i]) + self.float64() *
                   (float(fn[i - 1]) - float(fn[i]))) <                     f32(math.exp(-0.5 * x * x)):
                return x

    def exp_float64(self) -> float:
        global _EXP
        if _EXP is None:
            _EXP = _exp_tables()
        ke, we, fe = _EXP
        f32 = np.float32
        re = 7.69711747013104972
        while True:
            j = self.uint32()
            i = j & 0xFF
            x = float(j) * float(we[i])
            if j < ke[i]:
                return x
            if i == 0:
                return re - math.log(self.float64())
            if f32(float(fe[i]) + self.float64() *
                   (float(fe[i - 1]) - float(fe[i]))) < f32(math.exp(-x)):
                return x
