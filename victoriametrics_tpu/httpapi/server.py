"""HTTP server shell (reference lib/httpserver/httpserver.go:113):
threaded stdlib server with route dispatch, gzip/zstd response compression,
optional basic auth, /metrics, /health, and graceful shutdown."""

from __future__ import annotations

import gzip
import json
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..ingest.ratelimiter import RateLimitedError
from ..ops import compress as zstd
from ..parallel.rpc import (ClusterUnavailableError, PartialResultError,
                            RPCError)
from ..utils import logger
from ..utils import metrics as metricslib
from ..utils.workpool import SearchLimitError


class Request:
    def __init__(self, handler: BaseHTTPRequestHandler, body: bytes):
        self.handler = handler
        self.method = handler.command
        parsed = urllib.parse.urlparse(handler.path)
        self.path = parsed.path
        self.query = urllib.parse.parse_qs(parsed.query)
        self.headers = handler.headers
        self.body = body
        if self.method == "POST" and handler.headers.get(
                "Content-Type", "").startswith("application/x-www-form-urlencoded"):
            form = urllib.parse.parse_qs(body.decode("utf-8", "replace"))
            for k, v in form.items():
                self.query.setdefault(k, []).extend(v)

    def arg(self, name: str, default: str = "") -> str:
        vals = self.query.get(name)
        return vals[0] if vals else default

    def args(self, name: str) -> list[str]:
        return self.query.get(name, [])


class Response:
    def __init__(self, status=200, body=b"", content_type="application/json"):
        self.status = status
        self.body = body if isinstance(body, bytes) else body.encode()
        self.content_type = content_type
        self.headers: dict[str, str] = {}

    @classmethod
    def json(cls, obj, status=200):
        return cls(status, json.dumps(obj).encode(), "application/json")

    @classmethod
    def error(cls, msg: str, status=422, errtype="error"):
        return cls.json({"status": "error", "errorType": errtype,
                         "error": msg}, status=status)

    @classmethod
    def text(cls, s: str, status=200):
        return cls(status, s.encode(), "text/plain; charset=utf-8")


class StreamingResponse:
    """A chunked/streaming response (SSE push, long exports): `chunks`
    is an iterator of byte chunks written (and flushed) one at a time.
    No Content-Length; the connection closes when the iterator ends, so
    clients see a clean EOF.  Closing the generator (client disconnect)
    runs its ``finally`` blocks — handlers unsubscribe there."""

    def __init__(self, chunks, status: int = 200,
                 content_type: str = "text/event-stream",
                 headers: dict | None = None, on_close=None):
        self.chunks = chunks
        self.status = status
        self.content_type = content_type
        self.headers = dict(headers or {})
        #: cleanup invoked when the stream ends for ANY reason.  The
        #: generator's own finally blocks only run once it has STARTED —
        #: a client that disconnects before the first chunk (headers
        #: write raises) would otherwise leak whatever the handler
        #: registered (e.g. a watch subscription).
        self.on_close = on_close


class HTTPServer:
    """Route-dispatching server. Routes: exact path or prefix (trailing /)."""

    def __init__(self, addr: str = "127.0.0.1", port: int = 8428,
                 auth_key: str = "", basic_auth: tuple | None = None,
                 tls_cert_file: str = "", tls_key_file: str = ""):
        self.routes: dict[str, object] = {}
        self.prefix_routes: list[tuple[str, object]] = []
        self._path_metric_memo: dict[str, tuple] = {}
        self.auth_key = auth_key
        self.basic_auth = basic_auth
        # per-instance thread-safe counter (tests run several servers per
        # process; the per-path vm_http_requests_total metrics are global)
        self._request_count = metricslib.Counter("requests")
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # quiet
                pass

            def _handle(self):
                outer._request_count.inc()
                ln = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(ln) if ln else b""
                enc = (self.headers.get("Content-Encoding") or "").lower()
                try:
                    if enc == "gzip":
                        body = gzip.decompress(body)
                    elif enc == "zstd":
                        body = zstd.decompress(body)
                    elif enc == "snappy":
                        from ..ingest import snappy as snappy_codec
                        body = snappy_codec.decompress(body)
                except Exception as e:
                    self._send(Response.error(f"cannot decompress body: {e}",
                                              400))
                    return
                req = Request(self, body)
                fn, pattern = outer._route_match(req.path)
                if fn is None:
                    # unmatched paths share one label: raw-path labels
                    # would let clients mint unbounded series
                    outer._path_metrics("*unsupported*")[0].inc()
                    self._send(Response.error(
                        f"unsupported path {req.path}", 404, "not_found"))
                    return
                requests, duration, errors = outer._path_metrics(pattern)
                requests.inc()
                t0 = time.perf_counter()
                try:
                    resp = fn(req)
                except RateLimitedError as e:
                    resp = Response.error(str(e), 429,
                                          "too_many_requests")
                    resp.headers["Retry-After"] = str(e.retry_after_s)
                except SearchLimitError as e:
                    # shed load from the (tenant) search gate on paths
                    # without their own handler mapping: same 429 +
                    # Retry-After contract as the ingest rate limiter
                    resp = Response.error(str(e), 429,
                                          "too_many_requests")
                    resp.headers["Retry-After"] = str(e.retry_after_s)
                except ClusterUnavailableError as e:
                    # no live storage at all: the promised 503 on every
                    # route, not just the query handlers' own arms
                    # (before RPCError — it is a subclass)
                    resp = Response.error(str(e), 503, "unavailable")
                except PartialResultError as e:
                    # deny_partial refusal: capacity degradation, 503
                    resp = Response.error(str(e), 503, "unavailable")
                except RPCError as e:
                    # a storage hop failed (protocol error, dead peer):
                    # the gateway is degraded, the serving code is not
                    # broken — 502, so clients and SLO burn rates can
                    # tell a bad backend from a serving bug
                    resp = Response.error(str(e), 502, "storage_rpc")
                except Exception as e:  # noqa: BLE001 - error boundary
                    logger.errorf("http handler %s: %s", req.path, e)
                    import traceback
                    traceback.print_exc()
                    resp = Response.error(str(e), 500, "internal")
                duration.update(time.perf_counter() - t0)
                if resp.status >= 500:
                    errors.inc()
                self._send(resp)

            def _send(self, resp: Response):
                if isinstance(resp, StreamingResponse):
                    self._send_stream(resp)
                    return
                body = resp.body
                accept = (self.headers.get("Accept-Encoding") or "")
                headers = dict(resp.headers)
                if len(body) > 256 and "gzip" in accept:
                    body = gzip.compress(body, 1)
                    headers["Content-Encoding"] = "gzip"
                try:
                    self.send_response(resp.status)
                    self.send_header("Content-Type", resp.content_type)
                    self.send_header("Content-Length", str(len(body)))
                    for k, v in headers.items():
                        self.send_header(k, v)
                    self.end_headers()
                    self.wfile.write(body)
                except (BrokenPipeError, ConnectionResetError):
                    pass

            def _send_stream(self, resp: "StreamingResponse"):
                # no Content-Length: the response ends when the chunk
                # iterator does, and the connection closes (HTTP/1.1
                # clients see Connection: close + EOF framing)
                self.close_connection = True
                chunks = resp.chunks
                try:
                    self.send_response(resp.status)
                    self.send_header("Content-Type", resp.content_type)
                    self.send_header("Cache-Control", "no-cache")
                    self.send_header("Connection", "close")
                    for k, v in resp.headers.items():
                        self.send_header(k, v)
                    self.end_headers()
                    for chunk in chunks:
                        self.wfile.write(chunk)
                        self.wfile.flush()
                except (BrokenPipeError, ConnectionResetError, OSError):
                    pass
                except Exception as e:  # noqa: BLE001 — mid-stream error
                    # headers are long gone: all we can do is log and
                    # close so the client sees the stream end
                    logger.errorf("streaming handler %s: %s",
                                  self.path, e)
                finally:
                    close = getattr(chunks, "close", None)
                    if close is not None:
                        close()  # runs a STARTED generator's finally
                    if resp.on_close is not None:
                        # runs even when the generator never started
                        # (close() skips finally blocks then)
                        try:
                            resp.on_close()
                        except Exception as e:  # noqa: BLE001
                            logger.errorf("stream on_close %s: %s",
                                          self.path, e)

            do_GET = do_POST = do_PUT = do_DELETE = _handle

        self._handler_cls = Handler
        self._srv = ThreadingHTTPServer((addr, port), Handler)
        self._srv.daemon_threads = True
        if tls_cert_file and tls_key_file:
            # -tls / -tlsCertFile / -tlsKeyFile (lib/httpserver TLS)
            import ssl
            ctx = ssl.SSLContext(ssl.PROTOCOL_TLS_SERVER)
            ctx.load_cert_chain(tls_cert_file, tls_key_file)
            self._srv.socket = ctx.wrap_socket(self._srv.socket,
                                               server_side=True)
            self.tls = True
        else:
            self.tls = False
        self.port = self._srv.server_address[1]
        self.addr = addr
        self._thread: threading.Thread | None = None

    @property
    def request_count(self) -> int:
        return self._request_count.get()

    def route(self, path: str, fn):
        if path.endswith("/"):
            self.prefix_routes.append((path, fn))
        else:
            self.routes[path] = fn

    def _path_metrics(self, pattern: str):
        """(requests counter, duration histogram, errors counter) for one
        route pattern, resolved once per pattern — keeps the name
        formatting and registry lock off the per-request path.  Patterns
        are the registered routes, so the memo is bounded."""
        m = self._path_metric_memo.get(pattern)
        if m is None:
            labels = {"path": pattern}
            m = self._path_metric_memo[pattern] = (
                metricslib.REGISTRY.counter(metricslib.format_name(
                    "vm_http_requests_total", labels)),
                metricslib.REGISTRY.histogram(metricslib.format_name(
                    "vm_request_duration_seconds", labels)),
                metricslib.REGISTRY.counter(metricslib.format_name(
                    "vm_http_request_errors_total", labels)))
        return m

    def _route_for(self, path: str):
        return self._route_match(path)[0]

    def _route_match(self, path: str):
        """(handler, route pattern) — the pattern (exact path or prefix)
        is the bounded-cardinality label for per-path metrics."""
        fn = self.routes.get(path)
        if fn is not None:
            return fn, path
        for prefix, pfn in self.prefix_routes:
            if path.startswith(prefix):
                return pfn, prefix
        return None, ""

    def start(self):
        self._started = True
        # long-lived HTTP accept loop, one per server — not fan-out work
        self._thread = threading.Thread(  # vmt: disable=VMT011
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        logger.infof("http server listening on %s:%d", self.addr, self.port)

    def serve_forever(self):
        self._started = True
        self._srv.serve_forever()

    def stop(self):
        # BaseServer.shutdown() waits on a flag only serve_forever sets;
        # calling it on a never-started server would block forever.
        if getattr(self, "_started", False):
            self._srv.shutdown()
        self._srv.server_close()
