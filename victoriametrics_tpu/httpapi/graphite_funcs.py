"""Graphite render function library (reference
app/vmselect/graphite/functions.json — 151 entries — evaluated by
app/vmselect/graphite/eval.go and transform.go).

Together with the core functions defined in graphite_api.py this covers
ALL 151 reference entries (the combined dispatch table _G_FUNCS is
asserted against functions.json in tests/test_graphite_funcs.py).
Everything is vectorized numpy over the aligned render grid; functions
receive (api, args, grid, step, tenant) and return GraphiteSeries lists.
register() installs them into the dispatch table and backs the
/functions introspection endpoint.
"""

from __future__ import annotations

import math
import re

import numpy as np

_REL_RE = re.compile(r"^-?(\d+)(ms|s|min|h|d|w|mon|y)$")
_UNIT_S = {"ms": 0.001, "s": 1, "min": 60, "h": 3600, "d": 86400,
           "w": 7 * 86400, "mon": 30 * 86400, "y": 365 * 86400}


def _interval_s(text, default=60):
    m = _REL_RE.match(text if text.startswith("-") else "-" + text)
    if not m:
        try:
            return float(text)
        except ValueError:
            return default
    return int(m.group(1)) * _UNIT_S[m.group(2)]


# aggregation reducers shared by aggregate/groupBy*/moving*/sortBy/filter.
# axis=0 reduces ACROSS series (one value per timestamp), axis=1 reduces
# along time (one value per series/window).
def _r_last(m, axis):
    if axis == 0:
        # last non-null series wins at each timestamp
        out = m[-1].copy()
        for i in range(m.shape[0] - 2, -1, -1):
            out = np.where(np.isnan(out), m[i], out)
        return out
    out = np.full(m.shape[0], np.nan)
    for i in range(m.shape[0]):
        ok = ~np.isnan(m[i])
        if ok.any():
            out[i] = m[i][ok][-1]
    return out


def _r_first(m, axis):
    if axis == 0:
        out = m[0].copy()
        for i in range(1, m.shape[0]):
            out = np.where(np.isnan(out), m[i], out)
        return out
    out = np.full(m.shape[0], np.nan)
    for i in range(m.shape[0]):
        ok = ~np.isnan(m[i])
        if ok.any():
            out[i] = m[i][ok][0]
    return out


_REDUCERS = {
    "sum": np.nansum, "total": np.nansum,
    "avg": np.nanmean, "average": np.nanmean,
    # avg_zero: nulls count as zero (divide by the TOTAL series count)
    "avg_zero": lambda m, axis: np.mean(np.where(np.isnan(m), 0.0, m),
                                        axis=axis),
    "min": np.nanmin, "max": np.nanmax,
    "median": np.nanmedian,
    "diff": lambda m, axis: m[0] - np.nansum(np.where(
        np.isnan(m[1:]), 0, m[1:]), axis=0) if axis == 0 else
        np.where(np.isnan(m[:, :1]), np.nan, 0).ravel() + m[:, 0] -
        np.nansum(np.where(np.isnan(m[:, 1:]), 0, m[:, 1:]), axis=1),
    "stddev": np.nanstd, "dev": np.nanstd,
    "range": lambda m, axis: np.nanmax(m, axis=axis) - np.nanmin(m, axis=axis),
    "rangeOf": lambda m, axis: np.nanmax(m, axis=axis) - np.nanmin(m, axis=axis),
    "multiply": lambda m, axis: np.nanprod(
        np.where(np.isnan(m), np.nan, m), axis=axis),
    "count": lambda m, axis: np.sum(~np.isnan(m), axis=axis).astype(float),
    "last": _r_last, "current": _r_last,
    "first": _r_first,
}


def _pow_reduce(m, axis=0):
    out = m[0].copy()
    for i in range(1, m.shape[0]):
        out = np.power(out, m[i])
    return out


_REDUCERS["pow"] = _pow_reduce


def _reduce(m, agg, axis=0):
    red = _REDUCERS.get(agg, np.nanmean)
    with np.errstate(all="ignore"):
        out = red(m, axis=axis)
    if axis == 0:
        return np.where(np.isnan(m).all(axis=0), np.nan, out)
    return out


def _series_stat(s, agg):
    """One scalar per series (for sortBy/filter/highest/lowest)."""
    v = s.values
    ok = ~np.isnan(v)
    if not ok.any():
        return np.nan
    with np.errstate(all="ignore"):
        if agg in ("last", "current"):
            return float(v[ok][-1])
        if agg == "first":
            return float(v[ok][0])
        if agg in ("max", "maximum"):
            return float(np.nanmax(v))
        if agg in ("min", "minimum"):
            return float(np.nanmin(v))
        if agg in ("sum", "total"):
            return float(np.nansum(v))
        if agg in ("stddev", "dev"):
            return float(np.nanstd(v))
        if agg == "median":
            return float(np.nanmedian(v))
        if agg == "count":
            return float(ok.sum())
        if agg == "range":
            return float(np.nanmax(v) - np.nanmin(v))
    return float(np.nanmean(v))


def register(G, H):
    """Install functions into dispatch table G using helper namespace H
    (the graphite_api module)."""
    GraphiteSeries = H.GraphiteSeries
    _series_args = H._series_args
    _scalars = H._scalars
    _strings = H._strings

    def series_of(api, node, grid, step, tenant):
        return _series_args(api, [node], grid, step, tenant)

    def mk(name, s, vals, grid):
        return GraphiteSeries(name, {"name": name}, grid, vals)

    def keep(s, name, grid, vals=None):
        return GraphiteSeries(name, s.tags, grid,
                              s.values if vals is None else vals,
                              s.path_expr)

    # ---- generic combiners ------------------------------------------------
    def f_aggregate(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        agg = (_strings(args) or ["average"])[0]
        agg = agg[:-6] if agg.endswith("Series") else agg
        if not series:
            return []
        m = np.vstack([s.values for s in series])
        vals = _reduce(m, agg, axis=0)
        label = f'{agg}Series({",".join(s.path_expr or s.name for s in series)})'
        return [mk(label, None, vals, grid)]

    def combine(agg, label):
        def fn(api, args, grid, step, tenant):
            series = _series_args(api, args, grid, step, tenant)
            if not series:
                return []
            m = np.vstack([s.values for s in series])
            vals = _reduce(m, agg, axis=0)
            name = label.format(",".join(s.path_expr or s.name
                                         for s in series))
            return [mk(name, None, vals, grid)]
        return fn

    G["aggregate"] = f_aggregate
    G["multiplySeries"] = combine("multiply", "multiplySeries({})")
    G["diffSeries"] = combine("diff", "diffSeries({})")
    G["stddevSeries"] = combine("stddev", "stddevSeries({})")
    G["rangeOfSeries"] = combine("range", "rangeOfSeries({})")
    G["countSeries"] = combine("count", "countSeries({})")
    G["medianSeries"] = combine("median", "medianSeries({})")

    def f_group(api, args, grid, step, tenant):
        return _series_args(api, args, grid, step, tenant)
    G["group"] = f_group

    def f_percentile_of_series(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        n = (_scalars(args) or [50])[0]
        if not series:
            return []
        m = np.vstack([s.values for s in series])
        with np.errstate(all="ignore"):
            vals = np.nanpercentile(m, n, axis=0)
        name = f"percentileOfSeries({series[0].path_expr or series[0].name},{n:g})"
        return [mk(name, None, vals, grid)]
    G["percentileOfSeries"] = f_percentile_of_series

    def f_weighted_average(api, args, grid, step, tenant):
        # weightedAverage(seriesAvg, seriesWeight, *nodes)
        src = [a for a in args if a.kind in ("path", "func")]
        if len(src) < 2:
            return []
        avg_s = series_of(api, src[0], grid, step, tenant)
        w_s = series_of(api, src[1], grid, step, tenant)
        nodes = [int(v) for v in _scalars(args)]

        def key(s):
            segs = s.name.split(".")
            return ".".join(segs[n] for n in nodes
                            if -len(segs) <= n < len(segs))
        wmap = {key(s): s for s in w_s}
        num = np.zeros(grid.size)
        den = np.zeros(grid.size)
        for s in avg_s:
            w = wmap.get(key(s))
            if w is None:
                continue
            prod = s.values * w.values
            ok = ~np.isnan(prod)
            num[ok] += prod[ok]
            ok2 = ~np.isnan(w.values)
            den[ok2] += w.values[ok2]
        with np.errstate(all="ignore"):
            vals = np.where(den != 0, num / den, np.nan)
        return [mk("weightedAverage", None, vals, grid)]
    G["weightedAverage"] = f_weighted_average

    # ---- wildcards / nodes ------------------------------------------------
    def with_wildcards(agg_from_args):
        def fn(api, args, grid, step, tenant):
            series = _series_args(api, args, grid, step, tenant)
            agg, positions = agg_from_args(args)
            groups = {}
            for s in series:
                segs = s.name.split(".")
                name = ".".join(seg for i, seg in enumerate(segs)
                                if i not in positions)
                groups.setdefault(name, []).append(s)
            out = []
            for name, members in groups.items():
                m = np.vstack([s.values for s in members])
                out.append(mk(name, None, _reduce(m, agg, axis=0), grid))
            return out
        return fn

    G["aggregateWithWildcards"] = with_wildcards(
        lambda args: ((_strings(args) or ["average"])[0],
                      {int(v) for v in _scalars(args)}))
    G["sumSeriesWithWildcards"] = with_wildcards(
        lambda args: ("sum", {int(v) for v in _scalars(args)}))
    G["averageSeriesWithWildcards"] = with_wildcards(
        lambda args: ("average", {int(v) for v in _scalars(args)}))
    G["multiplySeriesWithWildcards"] = with_wildcards(
        lambda args: ("multiply", {int(v) for v in _scalars(args)}))

    def f_group_by_nodes(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        agg = (_strings(args) or ["average"])[0]
        nodes = [int(v) for v in _scalars(args)]
        groups = {}
        for s in series:
            segs = s.name.split(".")
            key = ".".join(segs[n] for n in nodes
                           if -len(segs) <= n < len(segs))
            groups.setdefault(key, []).append(s)
        out = []
        for key, members in sorted(groups.items()):
            m = np.vstack([s.values for s in members])
            out.append(mk(key, None, _reduce(m, agg, axis=0), grid))
        return out
    G["groupByNodes"] = f_group_by_nodes

    def f_group_by_tags(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        agg = (_strings(args) or ["average"])[0]
        tags = _strings(args)[1:]
        groups = {}
        for s in series:
            key = ";".join(f"{t}={s.tags.get(t, '')}" for t in tags)
            groups.setdefault(key, []).append(s)
        out = []
        for key, members in sorted(groups.items()):
            m = np.vstack([s.values for s in members])
            name = f"{agg}Series({key})" if key else f"{agg}Series()"
            g = GraphiteSeries(name, dict(
                kv.split("=", 1) for kv in key.split(";") if "=" in kv),
                grid, _reduce(m, agg, axis=0))
            out.append(g)
        return out
    G["groupByTags"] = f_group_by_tags

    def f_apply_by_node(api, args, grid, step, tenant):
        # applyByNode(series, node, templateFunc, [newName]) — evaluate the
        # template per distinct node prefix
        src = [a for a in args if a.kind in ("path", "func")]
        nodes = [int(v) for v in _scalars(args)]
        strs = _strings(args)
        if not src or not nodes or not strs:
            return []
        series = series_of(api, src[0], grid, step, tenant)
        template = strs[0]
        prefixes = []
        for s in series:
            p = ".".join(s.name.split(".")[:nodes[0] + 1])
            if p not in prefixes:
                prefixes.append(p)
        out = []
        for p in prefixes:
            target = template.replace("%", p)
            node = H._parse_target(target)
            out.extend(api._eval(node, grid, step, tenant))
        return out
    G["applyByNode"] = f_apply_by_node

    # ---- alias family -----------------------------------------------------
    def f_alias_sub(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        strs = _strings(args)
        if len(strs) < 2:
            return series
        rx = re.compile(strs[0])
        return [keep(s, rx.sub(strs[1], s.name), grid) for s in series]
    G["aliasSub"] = f_alias_sub

    def f_alias_by_metric(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        return [keep(s, s.name.split(".")[-1].split(",")[0], grid)
                for s in series]
    G["aliasByMetric"] = f_alias_by_metric

    # ---- per-point transforms --------------------------------------------
    def per_point(name_fmt, fn_vals, n_scalars=0, defaults=()):
        def fn(api, args, grid, step, tenant):
            series = _series_args(api, args, grid, step, tenant)
            ks = list(_scalars(args)) + list(defaults)[len(_scalars(args)):]
            out = []
            for s in series:
                with np.errstate(all="ignore"):
                    vals = fn_vals(s.values, *ks[:n_scalars])
                nm = name_fmt.format(s.name, *[f"{k:g}" for k in ks[:n_scalars]])
                out.append(keep(s, nm, grid, vals))
            return out
        return fn

    G["invert"] = per_point("invert({0})",
                            lambda v: np.where(v != 0, 1.0 / v, np.nan))
    G["logarithm"] = per_point(
        "log({0},{1})",
        lambda v, base=10: np.where(v > 0, np.log(v) / np.log(base), np.nan),
        1, (10,))
    G["log"] = G["logarithm"]
    G["logit"] = per_point(
        "logit({0})", lambda v: np.where((v > 0) & (v < 1),
                                         np.log(v / (1 - v)), np.nan))
    G["pow"] = per_point("pow({0},{1})", lambda v, p=1: np.power(v, p),
                         1, (1,))
    G["squareRoot"] = per_point(
        "squareRoot({0})", lambda v: np.where(v >= 0, np.sqrt(v), np.nan))
    G["exp"] = per_point("exp({0})", np.exp)
    G["sigmoid"] = per_point("sigmoid({0})", lambda v: 1 / (1 + np.exp(-v)))
    G["sin"] = per_point("sin({0})", np.sin)
    G["absolute"] = per_point("absolute({0})", np.abs)
    G["add"] = per_point("add({0},{1})", lambda v, k=0: v + k, 1, (0,))
    G["round"] = per_point(
        "round({0})", lambda v, p=0: np.round(v, int(p)), 1, (0,))
    G["minMax"] = per_point(
        "minMax({0})",
        lambda v: np.where(np.nanmax(v) > np.nanmin(v),
                           (v - np.nanmin(v)) /
                           (np.nanmax(v) - np.nanmin(v)), 0.0))
    G["offsetToZero"] = per_point("offsetToZero({0})",
                                  lambda v: v - np.nanmin(v))

    def f_transform_null(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        dflt = (_scalars(args) or [0])[0]
        return [keep(s, f"transformNull({s.name},{dflt:g})", grid,
                     np.where(np.isnan(s.values), dflt, s.values))
                for s in series]
    G["transformNull"] = f_transform_null

    def f_is_non_null(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        return [keep(s, f"isNonNull({s.name})", grid,
                     (~np.isnan(s.values)).astype(float))
                for s in series]
    G["isNonNull"] = f_is_non_null

    def f_interpolate(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        out = []
        for s in series:
            v = s.values.copy()
            ok = ~np.isnan(v)
            if ok.sum() >= 2:
                idx = np.arange(v.size)
                v[~ok] = np.interp(idx[~ok], idx[ok], v[ok])
                # graphite leaves leading/trailing gaps untouched
                first, last = idx[ok][0], idx[ok][-1]
                v[:first] = np.nan
                v[last + 1:] = np.nan
            out.append(keep(s, f"interpolate({s.name})", grid, v))
        return out
    G["interpolate"] = f_interpolate

    def f_changed(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        out = []
        for s in series:
            v = s.values
            prev = np.concatenate([[np.nan], v[:-1]])
            chg = ((~np.isnan(v)) & (~np.isnan(prev)) &
                   (v != prev)).astype(float)
            out.append(keep(s, f"changed({s.name})", grid, chg))
        return out
    G["changed"] = f_changed

    def f_integral(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        out = []
        for s in series:
            vals = np.nancumsum(s.values)
            vals[np.isnan(s.values)] = np.nan
            out.append(keep(s, f"integral({s.name})", grid, vals))
        return out
    G["integral"] = f_integral

    def f_integral_by_interval(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        iv = _interval_s((_strings(args) or ["1h"])[0]) * 1000
        out = []
        for s in series:
            bucket = (grid - grid[0]) // int(iv)
            vals = np.empty(grid.size)
            acc = 0.0
            cur = -1
            for i in range(grid.size):
                if bucket[i] != cur:
                    cur = bucket[i]
                    acc = 0.0
                x = s.values[i]
                if not math.isnan(x):
                    acc += x
                vals[i] = acc
            out.append(keep(s, f"integralByInterval({s.name})", grid, vals))
        return out
    G["integralByInterval"] = f_integral_by_interval

    def f_scale_to_seconds(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        secs = (_scalars(args) or [1])[0]
        k = secs / (step / 1000.0)
        return [keep(s, f"scaleToSeconds({s.name},{secs:g})", grid,
                     s.values * k) for s in series]
    G["scaleToSeconds"] = f_scale_to_seconds

    def f_delay(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        n = int((_scalars(args) or [1])[0])
        out = []
        for s in series:
            v = np.full(grid.size, np.nan)
            if n >= 0:
                v[n:] = s.values[:grid.size - n] if n < grid.size else []
            else:
                v[:n] = s.values[-n:]
            out.append(keep(s, f"delay({s.name},{n})", grid, v))
        return out
    G["delay"] = f_delay

    def f_time_shift(api, args, grid, step, tenant):
        # re-evaluate the inner expression over a shifted grid
        src = [a for a in args if a.kind in ("path", "func")]
        strs = _strings(args)
        if not src or not strs:
            return []
        shift_s = _interval_s(strs[0])
        if not strs[0].startswith(("+", "-")):
            shift_s = abs(shift_s)
        if not strs[0].startswith("+"):
            shift_s = -abs(shift_s)
        shift = int(shift_s * 1000)
        sgrid = grid + shift
        inner = series_of(api, src[0], sgrid, step, tenant)
        return [GraphiteSeries(f'timeShift({s.name},"{strs[0]}")', s.tags,
                               grid, s.values, s.path_expr) for s in inner]
    G["timeShift"] = f_time_shift

    def f_time_slice(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        strs = _strings(args)
        now = int(grid[-1])
        start = H.parse_graphite_time(strs[0], grid[0]) if strs else grid[0]
        end = H.parse_graphite_time(strs[1], now) if len(strs) > 1 else now
        out = []
        for s in series:
            v = np.where((grid >= start) & (grid <= end), s.values, np.nan)
            out.append(keep(s, f"timeSlice({s.name})", grid, v))
        return out
    G["timeSlice"] = f_time_slice

    # ---- moving windows ---------------------------------------------------
    def moving(agg_default, label):
        def fn(api, args, grid, step, tenant):
            series = _series_args(api, args, grid, step, tenant)
            strs = _strings(args)
            nums = _scalars(args)
            agg = agg_default
            if label == "movingWindow" and len(strs) > 1:
                agg = strs[1]
            if strs:
                win = max(int(_interval_s(strs[0]) * 1000 // step), 1)
                wtxt = f'"{strs[0]}"'
            else:
                win = max(int(nums[0]) if nums else 5, 1)
                wtxt = str(win)
            red = _REDUCERS.get(agg, np.nanmean)
            out = []
            for s in series:
                v = s.values
                sw = np.lib.stride_tricks.sliding_window_view(
                    np.concatenate([np.full(win - 1, np.nan), v]), win)
                with np.errstate(all="ignore"):
                    if agg in ("last", "current", "first"):
                        vals = red(sw, axis=1)
                    else:
                        vals = red(sw, axis=1)
                    vals = np.where(np.isnan(sw).all(axis=1), np.nan, vals)
                out.append(keep(s, f"{label}({s.name},{wtxt})", grid, vals))
            return out
        return fn

    G["movingAverage"] = moving("average", "movingAverage")
    G["movingMedian"] = moving("median", "movingMedian")
    G["movingMin"] = moving("min", "movingMin")
    G["movingMax"] = moving("max", "movingMax")
    G["movingSum"] = moving("sum", "movingSum")
    G["movingWindow"] = moving("average", "movingWindow")

    def f_ema(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        strs = _strings(args)
        nums = _scalars(args)
        if strs:
            win = max(int(_interval_s(strs[0]) * 1000 // step), 1)
        else:
            win = max(int(nums[0]) if nums else 10, 1)
        alpha = 2.0 / (win + 1)
        out = []
        for s in series:
            v = s.values
            vals = np.full(v.size, np.nan)
            ema = np.nan
            for i in range(v.size):
                x = v[i]
                if math.isnan(x):
                    vals[i] = ema
                    continue
                ema = x if math.isnan(ema) else alpha * x + (1 - alpha) * ema
                vals[i] = ema
            out.append(keep(s, f"exponentialMovingAverage({s.name},{win})",
                            grid, vals))
        return out
    G["exponentialMovingAverage"] = f_ema

    # ---- filters ----------------------------------------------------------
    def thresh_filter(stat, cmp, label):
        def fn(api, args, grid, step, tenant):
            series = _series_args(api, args, grid, step, tenant)
            n = (_scalars(args) or [0])[0]
            return [s for s in series
                    if cmp(_series_stat(s, stat), n)]
        return fn

    def _gt(a, b):
        return not math.isnan(a) and a > b

    def _lt(a, b):
        return not math.isnan(a) and a < b

    G["maximumAbove"] = thresh_filter("max", _gt, "maximumAbove")
    G["maximumBelow"] = thresh_filter("max", _lt, "maximumBelow")
    G["minimumAbove"] = thresh_filter("min", _gt, "minimumAbove")
    G["minimumBelow"] = thresh_filter("min", _lt, "minimumBelow")
    G["averageAbove"] = thresh_filter("average", _gt, "averageAbove")
    G["averageBelow"] = thresh_filter("average", _lt, "averageBelow")
    G["currentAbove"] = thresh_filter("last", _gt, "currentAbove")
    G["currentBelow"] = thresh_filter("last", _lt, "currentBelow")

    def f_filter_series(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        strs = _strings(args)
        nums = _scalars(args)
        if len(strs) < 2 or not nums:
            return series
        stat, op, n = strs[0], strs[1], nums[0]
        ops = {">": lambda a: a > n, ">=": lambda a: a >= n,
               "<": lambda a: a < n, "<=": lambda a: a <= n,
               "=": lambda a: a == n, "!=": lambda a: a != n}
        f = ops.get(op)
        if f is None:
            raise ValueError(f"unsupported filterSeries op {op!r}")
        return [s for s in series
                if not math.isnan(_series_stat(s, stat))
                and f(_series_stat(s, stat))]
    G["filterSeries"] = f_filter_series

    def top_bottom(best, stat_default):
        def fn(api, args, grid, step, tenant):
            series = _series_args(api, args, grid, step, tenant)
            nums = _scalars(args)
            strs = _strings(args)
            n = int(nums[0]) if nums else 1
            stat = strs[0] if strs else stat_default
            scored = [(s, _series_stat(s, stat)) for s in series]
            scored = [(s, x) for s, x in scored if not math.isnan(x)]
            scored.sort(key=lambda sx: sx[1], reverse=best)
            return [s for s, _ in scored[:n]]
        return fn

    G["highest"] = top_bottom(True, "average")
    G["lowest"] = top_bottom(False, "average")
    G["highestAverage"] = top_bottom(True, "average")
    G["lowestAverage"] = top_bottom(False, "average")
    G["highestCurrent"] = top_bottom(True, "last")
    G["lowestCurrent"] = top_bottom(False, "last")
    G["highestMax"] = top_bottom(True, "max")

    def f_limit(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        n = int((_scalars(args) or [1])[0])
        return series[:n]
    G["limit"] = f_limit

    def remove_value(cmp, label):
        def fn(api, args, grid, step, tenant):
            series = _series_args(api, args, grid, step, tenant)
            n = (_scalars(args) or [0])[0]
            out = []
            for s in series:
                v = np.where(cmp(s.values, n), np.nan, s.values)
                out.append(keep(s, f"{label}({s.name},{n:g})", grid, v))
            return out
        return fn

    G["removeAboveValue"] = remove_value(lambda v, n: v > n,
                                         "removeAboveValue")
    G["removeBelowValue"] = remove_value(lambda v, n: v < n,
                                         "removeBelowValue")

    def remove_pct(above):
        def fn(api, args, grid, step, tenant):
            series = _series_args(api, args, grid, step, tenant)
            n = (_scalars(args) or [50])[0]
            out = []
            for s in series:
                with np.errstate(all="ignore"):
                    p = np.nanpercentile(s.values, n) \
                        if not np.isnan(s.values).all() else np.nan
                v = np.where(s.values > p, np.nan, s.values) if above \
                    else np.where(s.values < p, np.nan, s.values)
                label = "removeAbovePercentile" if above \
                    else "removeBelowPercentile"
                out.append(keep(s, f"{label}({s.name},{n:g})", grid, v))
            return out
        return fn

    G["removeAbovePercentile"] = remove_pct(True)
    G["removeBelowPercentile"] = remove_pct(False)

    def f_remove_empty(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        nums = _scalars(args)
        xff = nums[0] if nums else 0.0
        out = []
        for s in series:
            ok = ~np.isnan(s.values)
            frac = ok.mean() if s.values.size else 0.0
            if ok.any() and (xff <= 0 or frac >= xff):
                out.append(s)
        return out
    G["removeEmptySeries"] = f_remove_empty

    def f_grep(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        rx = re.compile((_strings(args) or [""])[0])
        return [s for s in series if rx.search(s.name)]
    G["grep"] = f_grep

    def f_exclude(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        rx = re.compile((_strings(args) or [""])[0])
        return [s for s in series if not rx.search(s.name)]
    G["exclude"] = f_exclude

    def f_unique(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        seen = set()
        out = []
        for s in series:
            if s.name not in seen:
                seen.add(s.name)
                out.append(s)
        return out
    G["unique"] = f_unique

    def f_average_outside_percentile(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        n = (_scalars(args) or [95])[0]
        n = max(n, 100 - n)
        avgs = [_series_stat(s, "average") for s in series]
        if not avgs:
            return []
        lo_t = np.nanpercentile(avgs, 100 - n)
        hi_t = np.nanpercentile(avgs, n)
        return [s for s, a in zip(series, avgs)
                if not math.isnan(a) and (a < lo_t or a > hi_t)]
    G["averageOutsidePercentile"] = f_average_outside_percentile

    def f_most_deviant(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        n = int((_scalars(args) or [1])[0])
        scored = [(s, _series_stat(s, "stddev")) for s in series]
        scored = [(s, x) for s, x in scored if not math.isnan(x)]
        scored.sort(key=lambda sx: sx[1], reverse=True)
        return [s for s, _ in scored[:n]]
    G["mostDeviant"] = f_most_deviant

    def f_use_series_above(api, args, grid, step, tenant):
        # useSeriesAbove(series, value, search, replace)
        series = _series_args(api, args, grid, step, tenant)
        nums = _scalars(args)
        strs = _strings(args)
        if not nums or len(strs) < 2:
            return []
        n, search, repl = nums[0], strs[0], strs[1]
        out = []
        for s in series:
            if _gt(_series_stat(s, "max"), n):
                target = s.name.replace(search, repl)
                node = H._parse_target(target)
                out.extend(api._eval(node, grid, step, tenant))
        return out
    G["useSeriesAbove"] = f_use_series_above

    # ---- sorting ----------------------------------------------------------
    def f_sort_by(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        strs = _strings(args)
        stat = strs[0] if strs else "average"
        rev = bool(args and args[-1].kind == "bool" and args[-1].value) \
            if hasattr(args[-1] if args else None, "kind") else False
        rev = any(getattr(a, "kind", "") == "bool" and a.value for a in args)
        series.sort(key=lambda s: (math.isnan(_series_stat(s, stat)),
                                   _series_stat(s, stat)), reverse=rev)
        return series
    G["sortBy"] = f_sort_by

    def sort_by_stat(stat, rev):
        def fn(api, args, grid, step, tenant):
            series = _series_args(api, args, grid, step, tenant)
            series.sort(key=lambda s: (math.isnan(_series_stat(s, stat)),
                                       _series_stat(s, stat)), reverse=rev)
            return series
        return fn

    G["sortByTotal"] = sort_by_stat("sum", True)
    G["sortByMaxima"] = sort_by_stat("max", True)
    G["sortByMinima"] = sort_by_stat("min", False)

    def f_sort_by_name(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        natural = any(getattr(a, "kind", "") == "bool" and a.value
                      for a in args)

        def natkey(s):
            return [int(t) if t.isdigit() else t
                    for t in re.split(r"(\d+)", s.name)]
        series.sort(key=natkey if natural else (lambda s: s.name))
        return series
    G["sortByName"] = f_sort_by_name

    # ---- division / percent ----------------------------------------------
    def f_divide_series(api, args, grid, step, tenant):
        src = [a for a in args if a.kind in ("path", "func")]
        if len(src) < 2:
            return []
        dividends = series_of(api, src[0], grid, step, tenant)
        divisors = series_of(api, src[1], grid, step, tenant)
        if len(divisors) != 1:
            raise ValueError("divideSeries needs exactly one divisor series")
        d = divisors[0].values
        out = []
        with np.errstate(all="ignore"):
            for s in dividends:
                vals = np.where(d != 0, s.values / d, np.nan)
                out.append(keep(
                    s, f"divideSeries({s.name},{divisors[0].name})", grid,
                    vals))
        return out
    G["divideSeries"] = f_divide_series

    def series_lists(op, label):
        def fn(api, args, grid, step, tenant):
            src = [a for a in args if a.kind in ("path", "func")]
            if len(src) < 2:
                return []
            a_s = series_of(api, src[0], grid, step, tenant)
            b_s = series_of(api, src[1], grid, step, tenant)
            if len(a_s) != len(b_s):
                raise ValueError(f"{label}: series list lengths differ "
                                 f"({len(a_s)} vs {len(b_s)})")
            out = []
            with np.errstate(all="ignore"):
                for x, y in zip(a_s, b_s):
                    out.append(keep(x, f"{label}({x.name},{y.name})", grid,
                                    op(x.values, y.values)))
            return out
        return fn

    G["divideSeriesLists"] = series_lists(
        lambda a, b: np.where(b != 0, a / b, np.nan), "divideSeriesLists")
    G["multiplySeriesLists"] = series_lists(
        lambda a, b: a * b, "multiplySeriesLists")
    G["sumSeriesLists"] = series_lists(lambda a, b: a + b, "sumSeriesLists")
    G["diffSeriesLists"] = series_lists(lambda a, b: a - b,
                                        "diffSeriesLists")

    def f_as_percent(api, args, grid, step, tenant):
        src = [a for a in args if a.kind in ("path", "func")]
        series = series_of(api, src[0], grid, step, tenant) if src else []
        nums = _scalars(args)
        out = []
        with np.errstate(all="ignore"):
            if nums:
                total = np.full(grid.size, float(nums[0]))
            elif len(src) > 1:
                ts = series_of(api, src[1], grid, step, tenant)
                total = np.nansum(np.vstack([t.values for t in ts]), axis=0) \
                    if ts else np.full(grid.size, np.nan)
            else:
                total = np.nansum(np.vstack([s.values for s in series]),
                                  axis=0) if series else None
            for s in series:
                vals = np.where(total != 0, s.values / total * 100.0, np.nan)
                out.append(keep(s, f"asPercent({s.name})", grid, vals))
        return out
    G["asPercent"] = f_as_percent
    G["pct"] = f_as_percent

    # ---- stats ------------------------------------------------------------
    def f_n_percentile(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        n = (_scalars(args) or [50])[0]
        out = []
        for s in series:
            with np.errstate(all="ignore"):
                p = np.nanpercentile(s.values, n) \
                    if not np.isnan(s.values).all() else np.nan
            out.append(keep(s, f"nPercentile({s.name},{n:g})", grid,
                            np.full(grid.size, p)))
        return out
    G["nPercentile"] = f_n_percentile

    def f_stdev(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        n = int((_scalars(args) or [5])[0])
        out = []
        for s in series:
            sw = np.lib.stride_tricks.sliding_window_view(
                np.concatenate([np.full(n - 1, np.nan), s.values]), n)
            with np.errstate(all="ignore"):
                vals = np.nanstd(sw, axis=1)
            vals = np.where(np.isnan(sw).all(axis=1), np.nan, vals)
            out.append(keep(s, f"stdev({s.name},{n})", grid, vals))
        return out
    G["stdev"] = f_stdev

    def f_linear_regression(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        out = []
        t = (grid - grid[0]) / 1000.0
        for s in series:
            ok = ~np.isnan(s.values)
            if ok.sum() >= 2:
                k, b = np.polyfit(t[ok], s.values[ok], 1)
                vals = k * t + b
            else:
                vals = np.full(grid.size, np.nan)
            out.append(keep(s, f"linearRegression({s.name})", grid, vals))
        return out
    G["linearRegression"] = f_linear_regression

    def f_aggregate_line(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        agg = (_strings(args) or ["average"])[0]
        out = []
        for s in series:
            x = _series_stat(s, agg)
            out.append(keep(s, f"aggregateLine({s.name},{x:g})", grid,
                            np.full(grid.size, x)))
        return out
    G["aggregateLine"] = f_aggregate_line

    # ---- constants / synthetic -------------------------------------------
    def f_constant_line(api, args, grid, step, tenant):
        n = (_scalars(args) or [0])[0]
        return [GraphiteSeries(f"{n:g}", {"name": f"{n:g}"}, grid,
                               np.full(grid.size, float(n)))]
    G["constantLine"] = f_constant_line

    def f_threshold(api, args, grid, step, tenant):
        n = (_scalars(args) or [0])[0]
        strs = _strings(args)
        name = strs[0] if strs else f"{n:g}"
        return [GraphiteSeries(name, {"name": name}, grid,
                               np.full(grid.size, float(n)))]
    G["threshold"] = f_threshold

    def f_identity(api, args, grid, step, tenant):
        name = (_strings(args) or ["identity"])[0]
        return [GraphiteSeries(name, {"name": name}, grid,
                               grid.astype(float) / 1000.0)]
    G["identity"] = f_identity

    def f_time(api, args, grid, step, tenant):
        name = (_strings(args) or ["time"])[0]
        return [GraphiteSeries(name, {"name": name}, grid,
                               grid.astype(float) / 1000.0)]
    G["time"] = f_time
    G["timeFunction"] = f_time

    def f_sin_function(api, args, grid, step, tenant):
        strs = _strings(args)
        nums = _scalars(args)
        name = strs[0] if strs else "sinFunction"
        amp = nums[0] if nums else 1.0
        return [GraphiteSeries(name, {"name": name}, grid,
                               amp * np.sin(grid / 1000.0))]
    G["sinFunction"] = f_sin_function

    def f_random_walk(api, args, grid, step, tenant):
        strs = _strings(args)
        name = strs[0] if strs else "randomWalk"
        rng = np.random.default_rng(abs(hash(name)) % (2**32))
        vals = np.cumsum(rng.uniform(-0.5, 0.5, grid.size))
        return [GraphiteSeries(name, {"name": name}, grid, vals)]
    G["randomWalk"] = f_random_walk
    G["randomWalkFunction"] = f_random_walk

    def f_events(api, args, grid, step, tenant):
        return []
    G["events"] = f_events

    def f_fallback(api, args, grid, step, tenant):
        src = [a for a in args if a.kind in ("path", "func")]
        for a in src:
            series = series_of(api, a, grid, step, tenant)
            if series:
                return series
        return []
    G["fallbackSeries"] = f_fallback

    def f_substr(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        nums = [int(v) for v in _scalars(args)]
        start = nums[0] if nums else 0
        stop = nums[1] if len(nums) > 1 else 0
        out = []
        for s in series:
            base = s.name.split("(")[-1].split(")")[0]
            segs = base.split(".")
            sl = segs[start:stop] if stop else segs[start:]
            out.append(keep(s, ".".join(sl), grid))
        return out
    G["substr"] = f_substr

    def f_hitcount(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        iv = int(_interval_s((_strings(args) or ["1min"])[0]) * 1000)
        win = max(iv // step, 1)
        out = []
        for s in series:
            vals = np.full(grid.size, np.nan)
            for i in range(0, grid.size, win):
                w = s.values[i:i + win]
                if not np.isnan(w).all():
                    vals[i:i + win] = np.nansum(w) * (step / 1000.0)
            out.append(keep(s, f"hitcount({s.name})", grid, vals))
        return out
    G["hitcount"] = f_hitcount

    def f_smart_summarize(api, args, grid, step, tenant):
        return G["summarize"](api, args, grid, step, tenant)
    G["smartSummarize"] = f_smart_summarize

    def f_cumulative(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        return [keep(s, f"cumulative({s.name})", grid) for s in series]
    G["cumulative"] = f_cumulative

    def f_consolidate_by(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        how = (_strings(args) or ["avg"])[0]
        return [keep(s, f'consolidateBy({s.name},"{how}")', grid)
                for s in series]
    G["consolidateBy"] = f_consolidate_by

    def f_set_xff(api, args, grid, step, tenant):
        return _series_args(api, args, grid, step, tenant)
    G["setXFilesFactor"] = f_set_xff
    G["xFilesFactor"] = f_set_xff

    def f_aggregate_series_lists(api, args, grid, step, tenant):
        src = [a for a in args if a.kind in ("path", "func")]
        agg = (_strings(args) or ["sum"])[0]
        if len(src) < 2:
            return []
        a_s = series_of(api, src[0], grid, step, tenant)
        b_s = series_of(api, src[1], grid, step, tenant)
        if len(a_s) != len(b_s):
            raise ValueError("aggregateSeriesLists: lengths differ")
        out = []
        for x, y in zip(a_s, b_s):
            m = np.vstack([x.values, y.values])
            out.append(keep(x, f"{agg}Series({x.name},{y.name})", grid,
                            _reduce(m, agg, axis=0)))
        return out
    G["aggregateSeriesLists"] = f_aggregate_series_lists

    G["powSeries"] = combine("pow", "powSeries({})")

    def f_remove_between_percentile(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        n = (_scalars(args) or [30])[0]
        n = max(n, 100 - n)
        if not series:
            return []
        m = np.vstack([s.values for s in series])
        with np.errstate(all="ignore"):
            lo_b = np.nanpercentile(m, 100 - n, axis=0)
            hi_b = np.nanpercentile(m, n, axis=0)
        out = []
        for s in series:
            v = s.values
            ok = ~np.isnan(v)
            if (ok & ((v < lo_b) | (v > hi_b))).any():
                out.append(s)
        return out
    G["removeBetweenPercentile"] = f_remove_between_percentile

    def f_time_stack(api, args, grid, step, tenant):
        src = [a for a in args if a.kind in ("path", "func")]
        strs = _strings(args)
        nums = [int(v) for v in _scalars(args)]
        if not src:
            return []
        unit = _interval_s(strs[0]) if strs else 86400
        start = nums[0] if nums else 0
        end = nums[1] if len(nums) > 1 else 7
        out = []
        for k in range(start, end):
            shift = int(-k * unit * 1000)
            sgrid = grid + shift
            for s in series_of(api, src[0], sgrid, step, tenant):
                out.append(GraphiteSeries(
                    f"timeShift({s.name},{-k})", s.tags, grid, s.values,
                    s.path_expr))
        return out
    G["timeStack"] = f_time_stack

    def f_map_series(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        nums = [int(v) for v in _scalars(args)]
        node = nums[0] if nums else 0
        groups = {}
        for s in series:
            segs = s.name.split(".")
            key = segs[node] if -len(segs) <= node < len(segs) else ""
            groups.setdefault(key, []).append(s)
        # mapSeries returns the series tagged by group; reduceSeries
        # consumes the grouping via name structure
        out = []
        for key in sorted(groups):
            out.extend(groups[key])
        return out
    G["map"] = f_map_series
    G["mapSeries"] = f_map_series

    def f_reduce_series(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        strs = _strings(args)
        nums = [int(v) for v in _scalars(args)]
        if not strs:
            return series
        fn_name = strs[0]
        red_node = nums[0] if nums else 1
        matchers = strs[1:]
        groups = {}
        for s in series:
            segs = s.name.split(".")
            key = ".".join(seg for i, seg in enumerate(segs)
                           if i != red_node or i >= len(segs))
            groups.setdefault(key, []).append(s)
        red = G.get(fn_name) or G.get(fn_name + "Series")
        out = []
        for key, members in sorted(groups.items()):
            if matchers:
                ordered = []
                for want in matchers:
                    for s in members:
                        segs = s.name.split(".")
                        if red_node < len(segs) and segs[red_node] == want:
                            ordered.append(s)
                members = ordered
            m = np.vstack([s.values for s in members]) if members else None
            if m is None:
                continue
            agg = fn_name[:-6] if fn_name.endswith("Series") else fn_name
            if agg == "asPercent" and len(members) == 2:
                with np.errstate(all="ignore"):
                    vals = np.where(members[1].values != 0,
                                    members[0].values / members[1].values
                                    * 100.0, np.nan)
            elif agg == "divide" and len(members) == 2:
                with np.errstate(all="ignore"):
                    vals = np.where(members[1].values != 0,
                                    members[0].values / members[1].values,
                                    np.nan)
            elif agg == "diff":
                vals = _reduce(m, "diff", axis=0)
            else:
                vals = _reduce(m, agg, axis=0)
            out.append(mk(key, None, vals, grid))
        return out
    G["reduce"] = f_reduce_series
    G["reduceSeries"] = f_reduce_series

    def f_alias_query(api, args, grid, step, tenant):
        # aliasQuery(series, search, replace, newName): run a query derived
        # from each series name, use its last value in the new name
        series = _series_args(api, args, grid, step, tenant)
        strs = _strings(args)
        if len(strs) < 3:
            return series
        rx = re.compile(strs[0])
        out = []
        for s in series:
            target = rx.sub(strs[1].replace("\\\\", "\\"), s.name)
            node = H._parse_target(target)
            got = api._eval(node, grid, step, tenant)
            last = np.nan
            if got:
                ok = ~np.isnan(got[0].values)
                if ok.any():
                    last = float(got[0].values[ok][-1])
            out.append(keep(s, strs[2].replace("%d", f"{last:g}")
                            .replace("%g", f"{last:g}"), grid))
        return out
    G["aliasQuery"] = f_alias_query

    # ---- holt-winters -----------------------------------------------------
    def _hw_params(args):
        strs = _strings(args)
        boot = _interval_s(strs[0]) if strs else 7 * 86400
        season = _interval_s(strs[1]) if len(strs) > 1 else 86400
        return boot, season

    def _hw_series(api, args, grid, step, tenant):
        """Evaluate the inner expr over (grid extended by the bootstrap
        interval) and run the graphite holtWintersAnalysis recurrence
        (additive triple exponential smoothing, alpha=.1 beta=.0035
        gamma=.1); returns (series, forecasts, deviations, n_boot)."""
        src = [a for a in args if a.kind in ("path", "func")]
        if not src:
            return [], [], [], 0
        boot_s, season_s = _hw_params(args)
        n_boot = min(int(boot_s * 1000 // step), 200_000 // max(1, 1))
        egrid = np.arange(grid[0] - n_boot * step, grid[-1] + 1, step,
                          dtype=np.int64)
        n_boot = egrid.size - grid.size
        season_len = max(int(season_s * 1000 // step), 1)
        series = series_of(api, src[0], egrid, step, tenant)
        forecasts, deviations = [], []
        for s in series:
            v = s.values
            n = v.size
            pred = np.full(n, np.nan)
            dev = np.full(n, np.nan)
            intercept = slope = 0.0
            seasonal = np.zeros(season_len)
            sdev = np.zeros(season_len)
            alpha, beta, gamma = 0.1, 0.0035, 0.1
            started = False
            for i in range(n):
                x = v[i]
                si = i % season_len
                if math.isnan(x):
                    pred[i] = intercept + slope + seasonal[si]
                    dev[i] = sdev[si]
                    continue
                if not started:
                    intercept, slope = x, 0.0
                    started = True
                p = intercept + slope + seasonal[si]
                pred[i] = p
                new_i = alpha * (x - seasonal[si]) + \
                    (1 - alpha) * (intercept + slope)
                slope = beta * (new_i - intercept) + (1 - beta) * slope
                intercept = new_i
                seasonal[si] = gamma * (x - intercept) + \
                    (1 - gamma) * seasonal[si]
                sdev[si] = gamma * abs(x - p) + (1 - gamma) * sdev[si]
                dev[i] = sdev[si]
            forecasts.append(pred)
            deviations.append(dev)
        return series, forecasts, deviations, n_boot

    def f_hw_forecast(api, args, grid, step, tenant):
        series, fc, _, nb = _hw_series(api, args, grid, step, tenant)
        return [GraphiteSeries(f"holtWintersForecast({s.name})", s.tags,
                               grid, p[nb:], s.path_expr)
                for s, p in zip(series, fc)]
    G["holtWintersForecast"] = f_hw_forecast

    def f_hw_bands(api, args, grid, step, tenant):
        series, fc, dv, nb = _hw_series(api, args, grid, step, tenant)
        delta = 3.0
        out = []
        for s, p, d in zip(series, fc, dv):
            out.append(GraphiteSeries(
                f"holtWintersConfidenceUpper({s.name})", s.tags, grid,
                p[nb:] + delta * d[nb:], s.path_expr))
            out.append(GraphiteSeries(
                f"holtWintersConfidenceLower({s.name})", s.tags, grid,
                p[nb:] - delta * d[nb:], s.path_expr))
        return out
    G["holtWintersConfidenceBands"] = f_hw_bands
    G["holtWintersConfidenceArea"] = f_hw_bands

    def f_hw_aberration(api, args, grid, step, tenant):
        series, fc, dv, nb = _hw_series(api, args, grid, step, tenant)
        delta = 3.0
        out = []
        for s, p, d in zip(series, fc, dv):
            actual = s.values[nb:]
            upper = p[nb:] + delta * d[nb:]
            lower = p[nb:] - delta * d[nb:]
            ab = np.where(actual > upper, actual - upper,
                          np.where(actual < lower, actual - lower, 0.0))
            out.append(GraphiteSeries(
                f"holtWintersAberration({s.name})", s.tags, grid, ab,
                s.path_expr))
        return out
    G["holtWintersAberration"] = f_hw_aberration

    # display no-ops: rendering hints the JSON API carries through untouched
    def noop(api, args, grid, step, tenant):
        return _series_args(api, args, grid, step, tenant)
    for name in ("alpha", "color", "dashed", "drawAsInfinite", "lineWidth",
                 "secondYAxis", "stacked", "legendValue", "cactiStyle",
                 "areaBetween", "verticalLine"):
        G[name] = noop

    return G
