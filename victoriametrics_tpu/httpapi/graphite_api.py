"""Graphite query API (reference app/vmselect/graphite/: metrics_api.go,
tags_api.go, render_api.go + transform functions in functions.go).

Implements the surface Grafana's Graphite datasource uses:
  /metrics/find         hierarchical browsing with * globs
  /metrics/expand
  /render               target expressions with the common function set
  /tags /tags/<name> /tags/autoComplete/{tags,values} /tags/findSeries

Graphite metrics are series whose __name__ is the dotted path (the
graphite ingest listener produces exactly that; `;tag=value` suffixes
become labels).
"""

from __future__ import annotations

import math
import re
import time

import numpy as np

from ..storage.tag_filters import TagFilter
from ..utils import fasttime
from .server import HTTPServer, Request, Response


# -- time parsing (graphite from/until) --------------------------------------

_REL_RE = re.compile(r"^-(\d+)(s|min|h|d|w|mon|y)$")
_UNIT_S = {"s": 1, "min": 60, "h": 3600, "d": 86400, "w": 7 * 86400,
           "mon": 30 * 86400, "y": 365 * 86400}


def parse_graphite_time(s: str, default_ms: int) -> int:
    if not s:
        return default_ms
    s = s.strip()
    if s == "now":
        return fasttime.unix_ms()
    m = _REL_RE.match(s)
    if m:
        return fasttime.unix_ms() - \
            int(m.group(1)) * _UNIT_S[m.group(2)] * 1000
    try:
        v = float(s)
        # heuristic: epoch seconds vs ms like the reference
        return int(v * 1000) if v < 1e12 else int(v)
    except ValueError:
        raise ValueError(f"cannot parse graphite time {s!r}")


# -- target expression parser -------------------------------------------------

class _GNode:
    """func call | path glob | string | number"""

    def __init__(self, kind, value, args=None):
        self.kind = kind
        self.value = value
        self.args = args or []


def _parse_target(s: str) -> _GNode:
    pos = 0

    def parse_expr():
        nonlocal pos
        while pos < len(s) and s[pos].isspace():
            pos += 1
        c = s[pos]
        if c in "\"'":
            end = s.index(c, pos + 1)
            node = _GNode("str", s[pos + 1:end])
            pos = end + 1
            return node
        if c.isdigit() or c == "-" or c == ".":
            m = re.match(r"-?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?", s[pos:])
            if m:
                node = _GNode("num", float(m.group(0)))
                pos += m.end()
                return node
        m = re.match(r"[A-Za-z_][A-Za-z0-9_]*\(", s[pos:])
        if m:
            name = m.group(0)[:-1]
            pos += m.end()
            args = []
            while True:
                while pos < len(s) and s[pos].isspace():
                    pos += 1
                if s[pos] == ")":
                    pos += 1
                    break
                args.append(parse_expr())
                while pos < len(s) and s[pos].isspace():
                    pos += 1
                if pos < len(s) and s[pos] == ",":
                    pos += 1
            return _GNode("func", name, args)
        m = re.match(r"(?:true|false)(?=[,)\s]|$)", s[pos:])
        if m:
            node = _GNode("bool", m.group(0) == "true")
            pos += m.end()
            return node
        m = re.match(r"[^,()\s]+", s[pos:])
        if not m:
            raise ValueError(f"cannot parse target at {pos}: {s!r}")
        node = _GNode("path", m.group(0))
        pos += m.end()
        return node

    node = parse_expr()
    while pos < len(s) and s[pos].isspace():
        pos += 1
    if pos != len(s):
        raise ValueError(f"trailing garbage in target: {s[pos:]!r}")
    return node


def _glob_to_regex(glob: str) -> str:
    """Graphite glob -> regex over the full dotted name: * does not cross
    dots; {a,b} alternation; [] classes pass through."""
    out = []
    i = 0
    while i < len(glob):
        c = glob[i]
        if c == "*":
            out.append(r"[^.]*")
        elif c == "?":
            out.append(r"[^.]")
        elif c == "{":
            j = glob.index("}", i)
            alts = glob[i + 1:j].split(",")
            out.append("(?:" + "|".join(re.escape(a) for a in alts) + ")")
            i = j
        elif c == "[":
            j = glob.index("]", i)
            out.append(glob[i:j + 1])
            i = j
        else:
            out.append(re.escape(c))
        i += 1
    return "".join(out)


class GraphiteSeries:
    __slots__ = ("name", "tags", "timestamps", "values", "path_expr")

    def __init__(self, name, tags, timestamps, values, path_expr=""):
        self.name = name
        self.tags = tags
        self.timestamps = timestamps  # ms grid
        self.values = values
        self.path_expr = path_expr


class GraphiteAPI:
    def __init__(self, storage, default_step_ms: int = 60_000):
        self.storage = storage
        self.step_ms = default_step_ms

    def register(self, srv: HTTPServer):
        r = srv.route
        r("/metrics/find", self.h_find)
        r("/metrics/find/", self.h_find)
        r("/metrics/expand", self.h_expand)
        r("/render", self.h_render)
        r("/render/", self.h_render)
        r("/tags/autoComplete/tags", self.h_ac_tags)
        r("/tags/autoComplete/values", self.h_ac_values)
        r("/tags/findSeries", self.h_find_series)
        r("/tags", self.h_tags)
        r("/tags/", self.h_tag_values)
        r("/functions", self.h_functions)
        r("/functions/", self.h_functions)

    def h_functions(self, req: Request) -> Response:
        """Introspection: the render functions this server implements
        (reference graphiteFunctions handler, render_api.go)."""
        out = {name: {"name": name, "function": f"{name}(seriesList)",
                      "description": "", "module": "graphite.render",
                      "group": "", "params": []}
               for name in sorted(_G_FUNCS)}
        return Response.json(out)

    # -- metrics api ---------------------------------------------------------

    def _names(self, tenant=(0, 0)) -> list[str]:
        return self.storage.label_values("__name__", tenant=tenant)

    def _find_nodes(self, query: str, tenant=(0, 0)):
        """(text, full_path, is_leaf) nodes one level below the glob."""
        # the common tree-expansion shape ("*" / "prefix.*") pushes down
        # to the storage's path-suffix index (tagValueSuffixes — on a
        # cluster that is one fanned-out RPC instead of pulling every
        # metric name)
        sfx_fn = getattr(self.storage, "tag_value_suffixes", None)
        m = re.fullmatch(r"((?:[^*?,{}\[\]]+\.)?)\*", query)
        if sfx_fn is not None and m:
            prefix = m.group(1)
            merged: dict[str, list] = {}
            for s in sfx_fn("__name__", prefix, ".", tenant=tenant):
                kids = s.endswith(".")
                text = s[:-1] if kids else s
                if not text:
                    continue
                e = merged.setdefault(text, [False, False])
                if kids:
                    e[1] = True
                else:
                    e[0] = True
            return [(text, prefix + text, leaf, kids)
                    for text, (leaf, kids) in sorted(merged.items())]
        depth = query.count(".") + 1
        rx = re.compile("^" + _glob_to_regex(query))
        # path -> [is_leaf, has_children]: a path can be both a metric and
        # a branch; Grafana needs expandable=1 whenever children exist
        nodes: dict[str, list] = {}
        for name in self._names(tenant):
            segs = name.split(".")
            if len(segs) < depth:
                continue
            prefix = ".".join(segs[:depth])
            if not rx.fullmatch(prefix):
                continue
            e = nodes.setdefault(prefix, [False, False])
            if len(segs) == depth:
                e[0] = True
            else:
                e[1] = True
        return [(p.rsplit(".", 1)[-1], p, leaf, kids)
                for p, (leaf, kids) in sorted(nodes.items())]

    def h_find(self, req: Request) -> Response:
        query = req.arg("query", "*")
        fmt = req.arg("format", "treejson")
        nodes = self._find_nodes(query, _tenant(req))
        if fmt == "completer":
            return Response.json({"metrics": [
                {"name": text, "path": p + ("." if kids else ""),
                 "is_leaf": "1" if leaf else "0"}
                for text, p, leaf, kids in nodes]})
        return Response.json([
            {"text": text, "id": p, "leaf": 1 if leaf else 0,
             "expandable": 1 if kids else 0,
             "allowChildren": 1 if kids else 0, "context": {}}
            for text, p, leaf, kids in nodes])

    def h_expand(self, req: Request) -> Response:
        out = set()
        for q in req.args("query"):
            for _, p, _leaf, _kids in self._find_nodes(q, _tenant(req)):
                out.add(p)
        return Response.json({"results": sorted(out)})

    # -- tags api ------------------------------------------------------------

    def h_tags(self, req: Request) -> Response:
        names = [n for n in self.storage.label_names(tenant=_tenant(req))
                 if n != "__name__"]
        return Response.json([{"tag": "name"}] +
                             [{"tag": n} for n in names])

    def h_tag_values(self, req: Request) -> Response:
        tag = req.path.rsplit("/", 1)[-1]
        key = "__name__" if tag == "name" else tag
        vals = self.storage.label_values(key, tenant=_tenant(req))
        return Response.json({
            "tag": tag,
            "values": [{"value": v, "count": 1} for v in sorted(vals)]})

    def h_ac_tags(self, req: Request) -> Response:
        prefix = req.arg("tagPrefix", "")
        names = ["name"] + [
            n for n in self.storage.label_names(tenant=_tenant(req))
            if n != "__name__"]
        return Response.json(sorted(n for n in names
                                    if n.startswith(prefix)))

    def h_ac_values(self, req: Request) -> Response:
        tag = req.arg("tag")
        prefix = req.arg("valuePrefix", "")
        key = "__name__" if tag == "name" else tag
        vals = self.storage.label_values(key, tenant=_tenant(req))
        return Response.json(sorted(v for v in vals
                                    if v.startswith(prefix)))

    def h_find_series(self, req: Request) -> Response:
        filters = [_tag_expr_filter(e) for e in req.args("expr")]
        now = fasttime.unix_ms()
        names = self.storage.search_metric_names(
            filters, 0, now, tenant=_tenant(req))
        out = []
        for mn in names:
            path = mn.metric_group.decode("utf-8", "replace")
            tags = ";".join(f"{k.decode()}={v.decode()}"
                            for k, v in mn.labels)
            out.append(path + (";" + tags if tags else ""))
        return Response.json(sorted(out))

    # -- render --------------------------------------------------------------

    def h_render(self, req: Request) -> Response:
        now = fasttime.unix_ms()
        try:
            frm = parse_graphite_time(req.arg("from"), now - 3600_000)
            until = parse_graphite_time(req.arg("until"), now)
            mdp = int(req.arg("maxDataPoints", "0") or 0)
        except ValueError as e:
            return Response.error(f"cannot render: {e}", 400)
        step = self.step_ms
        if mdp > 0:
            step = max(step, ((until - frm) // mdp + step - 1)
                       // step * step)
        # grid end rounds UP so samples newer than the last whole step
        # still land in the final bucket (fresh writes at `now`)
        grid_end = until if until % step == 0 else until + step - until % step
        grid = np.arange(frm - frm % step, grid_end + 1, step,
                         dtype=np.int64)
        out = []
        try:
            for target in req.args("target"):
                node = _parse_target(target)
                out.extend(self._eval(node, grid, step, _tenant(req)))
        except (ValueError, KeyError, IndexError) as e:
            return Response.error(f"cannot render: {e}", 400)
        body = [{
            "target": s.name,
            "tags": s.tags,
            "datapoints": [
                [None if math.isnan(v) else v, int(t) // 1000]
                for t, v in zip(s.timestamps, s.values)],
        } for s in out]
        return Response.json(body)

    def _fetch(self, path_glob: str, grid, step, tenant):
        """Series matching a dotted glob, aligned to the grid."""
        rx = "^" + _glob_to_regex(path_glob) + "$"
        filters = [TagFilter(b"", rx.encode(), regex=True)]
        return _fetch_aligned(self.storage, filters, grid, step, tenant,
                              path_glob)

    def _eval(self, node: _GNode, grid, step, tenant
              ) -> list[GraphiteSeries]:
        if node.kind == "path":
            return self._fetch(node.value, grid, step, tenant)
        if node.kind != "func":
            raise ValueError(f"unexpected {node.kind} at top level")
        fn = _G_FUNCS.get(node.value)
        if fn is None:
            raise ValueError(f"unsupported graphite function {node.value!r}")
        return fn(self, node.args, grid, step, tenant)


def _tenant(req) -> tuple:
    return getattr(req, "tenant", None) or (0, 0)


def _tag_expr_filter(expr: str) -> TagFilter:
    """Graphite tag expression: tag=value, tag!=value, tag=~re, tag!=~re."""
    m = re.match(r"([^!=~]+)(!?=~?)(.*)", expr)
    if not m:
        raise ValueError(f"cannot parse tag expression {expr!r}")
    tag, op, value = m.groups()
    key = b"" if tag == "name" else tag.encode()
    return TagFilter(key, value.encode(), negate=op.startswith("!"),
                     regex=op.endswith("~"))


# -- graphite transform functions (functions.go subset) -----------------------

def _series_args(api, args, grid, step, tenant):
    out = []
    for a in args:
        if a.kind in ("path", "func"):
            out.extend(api._eval(a, grid, step, tenant))
    return out


def _scalars(args):
    return [a.value for a in args if a.kind == "num"]


def _strings(args):
    return [a.value for a in args if a.kind == "str"]


def _combine(name_fmt):
    def make(reduce_fn):
        def fn(api, args, grid, step, tenant):
            series = _series_args(api, args, grid, step, tenant)
            if not series:
                return []
            m = np.vstack([s.values for s in series])
            with np.errstate(all="ignore"):
                vals = reduce_fn(m)
                vals = np.where(np.isnan(m).all(axis=0), np.nan, vals)
            label = name_fmt.format(
                ",".join(s.path_expr or s.name for s in series))
            return [GraphiteSeries(label, {"name": label}, grid, vals)]
        return fn
    return make


def _per_series(fn_vals, rename=None):
    def fn(api, args, grid, step, tenant):
        series = _series_args(api, args, grid, step, tenant)
        extra = _scalars(args)
        out = []
        for s in series:
            with np.errstate(all="ignore"):
                vals = fn_vals(s.values, grid, step, *extra)
            name = rename(s.name, *extra) if rename else s.name
            out.append(GraphiteSeries(name, s.tags, grid, vals,
                                      s.path_expr))
        return out
    return fn


def _f_alias(api, args, grid, step, tenant):
    series = _series_args(api, args, grid, step, tenant)
    name = (_strings(args) or [""])[0]
    return [GraphiteSeries(name, s.tags, grid, s.values, s.path_expr)
            for s in series]


def _f_alias_by_node(api, args, grid, step, tenant):
    series = _series_args(api, args, grid, step, tenant)
    nodes = [int(v) for v in _scalars(args)]
    out = []
    for s in series:
        segs = s.name.split(".")
        name = ".".join(segs[n] for n in nodes
                        if -len(segs) <= n < len(segs))
        out.append(GraphiteSeries(name, s.tags, grid, s.values,
                                  s.path_expr))
    return out


def _f_group_by_node(api, args, grid, step, tenant):
    series = _series_args(api, args, grid, step, tenant)
    nums = _scalars(args)
    node = int(nums[0]) if nums else 0
    agg = (_strings(args) or ["avg"])[0]
    groups: dict[str, list] = {}
    for s in series:
        segs = s.name.split(".")
        key = segs[node] if -len(segs) <= node < len(segs) else ""
        groups.setdefault(key, []).append(s)
    red = {"sum": np.nansum, "avg": np.nanmean, "average": np.nanmean,
           "min": np.nanmin, "max": np.nanmax}.get(agg, np.nanmean)
    out = []
    for key, members in sorted(groups.items()):
        m = np.vstack([s.values for s in members])
        with np.errstate(all="ignore"):
            vals = red(m, axis=0)
        out.append(GraphiteSeries(key, {"name": key}, grid, vals))
    return out


def _f_summarize(api, args, grid, step, tenant):
    series = _series_args(api, args, grid, step, tenant)
    interval_s = 60
    strs = _strings(args)
    if strs:
        m = _REL_RE.match("-" + strs[0])
        if m:
            interval_s = int(m.group(1)) * _UNIT_S[m.group(2)]
    agg = strs[1] if len(strs) > 1 else "sum"
    red = {"sum": np.nansum, "avg": np.nanmean, "max": np.nanmax,
           "min": np.nanmin, "last": lambda a, axis: a[..., -1]}.get(
               agg, np.nansum)
    win = max(int(interval_s * 1000 // step), 1)
    out = []
    for s in series:
        vals = np.full(grid.size, np.nan)
        for i in range(0, grid.size, win):
            w = s.values[i:i + win]
            if not np.isnan(w).all():
                with np.errstate(all="ignore"):
                    vals[i:i + win] = red(w[None, :], axis=1)[0] \
                        if agg != "last" else w[~np.isnan(w)][-1]
        out.append(GraphiteSeries(
            f'summarize({s.name}, "{strs[0] if strs else "1min"}", "{agg}")',
            s.tags, grid, vals, s.path_expr))
    return out


def _nn_derivative(vals, grid, step, *extra):
    d = np.diff(vals, prepend=np.nan)
    return np.where(d >= 0, d, np.nan)


def _per_second(vals, grid, step, *extra):
    d = np.diff(vals, prepend=np.nan)
    return np.where(d >= 0, d / (step / 1000.0), np.nan)


def _keep_last(vals, grid, step, *extra):
    out = vals.copy()
    last = np.nan
    for i in range(out.size):
        if math.isnan(out[i]):
            out[i] = last
        else:
            last = out[i]
    return out


_G_FUNCS = {
    "sumSeries": _combine("sumSeries({})")(
        lambda m: np.nansum(m, axis=0)),
    "sum": _combine("sumSeries({})")(lambda m: np.nansum(m, axis=0)),
    "averageSeries": _combine("averageSeries({})")(
        lambda m: np.nanmean(m, axis=0)),
    "avg": _combine("averageSeries({})")(lambda m: np.nanmean(m, axis=0)),
    "maxSeries": _combine("maxSeries({})")(
        lambda m: np.nanmax(m, axis=0)),
    "minSeries": _combine("minSeries({})")(
        lambda m: np.nanmin(m, axis=0)),
    "alias": _f_alias,
    "aliasByNode": _f_alias_by_node,
    "aliasByTags": _f_alias_by_node,
    "groupByNode": _f_group_by_node,
    "scale": _per_series(lambda v, g, st, k=1.0: v * k,
                         rename=lambda n, k=1.0: f"scale({n},{k:g})"),
    "offset": _per_series(lambda v, g, st, k=0.0: v + k,
                          rename=lambda n, k=0.0: f"offset({n},{k:g})"),
    "absolute": _per_series(lambda v, g, st: np.abs(v)),
    "derivative": _per_series(
        lambda v, g, st: np.diff(v, prepend=np.nan)),
    "nonNegativeDerivative": _per_series(_nn_derivative),
    "perSecond": _per_series(_per_second),
    "keepLastValue": _per_series(_keep_last),
    "summarize": _f_summarize,
    "seriesByTag": None,  # replaced below (needs filter semantics)
}


def _f_series_by_tag(api, args, grid, step, tenant):
    filters = [_tag_expr_filter(sv) for sv in _strings(args)]
    return _fetch_aligned(api.storage, filters, grid, step, tenant)


def _fetch_aligned(storage, filters, grid, step, tenant, path_expr=""):
    """Fetch + last-value-in-bucket consolidation onto the render grid
    (shared by path-glob fetch and seriesByTag)."""
    frm, until = int(grid[0]), int(grid[-1])
    series = storage.search_series(filters, frm - step, until,
                                   tenant=tenant)
    out = []
    for sd in series:
        vals = np.full(grid.size, np.nan)
        idx = np.searchsorted(sd.timestamps, grid, side="right") - 1
        ok = idx >= 0
        if ok.any():
            got = sd.values[np.clip(idx, 0, None)]
            age = grid - sd.timestamps[np.clip(idx, 0, None)]
            ok &= age < step  # only samples within the bucket
            vals[ok] = got[ok]
        name = sd.metric_name.metric_group.decode("utf-8", "replace")
        tags = {k.decode(): v.decode() for k, v in sd.metric_name.labels}
        tags["name"] = name
        out.append(GraphiteSeries(name, tags, grid, vals, path_expr))
    return out


def _f_alias_by_tags(api, args, grid, step, tenant):
    series = _series_args(api, args, grid, step, tenant)
    tag_names = _strings(args)
    out = []
    for s in series:
        name = ".".join(s.tags.get(t, "") for t in tag_names) or s.name
        out.append(GraphiteSeries(name, s.tags, grid, s.values,
                                  s.path_expr))
    return out


_G_FUNCS["seriesByTag"] = _f_series_by_tag

# the wide function library (graphite_funcs.py) registers itself on top
from . import graphite_funcs as _graphite_funcs  # noqa: E402

_graphite_funcs.register(_G_FUNCS, __import__(
    "sys").modules[__name__])
_G_FUNCS["aliasByTags"] = _f_alias_by_tags
