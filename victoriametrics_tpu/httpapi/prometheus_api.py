"""Prometheus-compatible HTTP API (reference app/vmselect/main.go:94-436
router + app/vmselect/prometheus/*.qtpl responders + app/vminsert/main.go:
134-392 ingestion endpoints), bound to one Storage + query engine.

Implements: /api/v1/{query,query_range,series,labels,label/<n>/values,
export,import,import/prometheus,write (remote-write),admin/tsdb/delete_series,
status/{tsdb,active_queries,top_queries}}, /write (influx), /api/put
(opentsdb http), /datadog/api/v{1,2}/series, /graphite ingest, federate,
/metrics, /health, /snapshot/*, /internal/force_{flush,merge},
/newrelic/infra/v2/metrics/events/bulk.
"""

from __future__ import annotations

import contextlib
import datetime
import json
import math
import re
import threading
import time

import numpy as np

import struct

from ..ingest import parsers, remote_write
from ..ingest.otlp import parse_otlp
from ..query.exec import exec_instant, exec_query, parse_cached
from ..query.eval import QueryError, filter_sets_from_metric_expr
from ..query.metricsql import parse as mql_parse
from ..query.metricsql.ast import MetricExpr
from ..query.metricsql.parser import ParseError, parse_duration_ms
from ..parallel.cluster_api import ClusterUnavailableError, PartialResultError
from ..query.querystats import ActiveQueries, QueryStats, SlowQueryLog
from ..query.types import EvalConfig
from ..storage.metric_name import MetricName
from ..utils import fasttime, flightrec, logger
from ..utils import metrics as metricslib
from ..utils.workpool import SearchLimitError
from .server import HTTPServer, Request, Response, StreamingResponse

#: scatter-gather responses that came back incomplete (a storage node
#: was down/slow) — whether served as isPartial=true or denied as 503
_PARTIAL_TOTAL = metricslib.REGISTRY.counter("vm_partial_results_total")


def parse_time(s: str, default_ms: int) -> int:
    if not s:
        return default_ms
    try:
        return int(float(s) * 1000)
    except ValueError:
        pass
    if s.startswith("-"):
        # relative time: "-1h" = now minus duration (reference supports this)
        try:
            ms, step_based = parse_duration_ms(s[1:])
            if not step_based and ms > 0:
                return fasttime.unix_ms() - int(ms)
        except ValueError:
            pass
    try:
        dt = datetime.datetime.fromisoformat(s.replace("Z", "+00:00"))
        return int(dt.timestamp() * 1000)
    except ValueError:
        raise QueryError(f"cannot parse time {s!r}")


def parse_step(s: str, default_ms: int = 60_000) -> int:
    if not s:
        return default_ms
    try:
        return max(int(float(s) * 1000), 1)
    except ValueError:
        pass
    try:
        ms, step_based = parse_duration_ms(s)
        if not step_based and ms > 0:
            return int(ms)
    except ValueError:
        pass
    raise QueryError(f"cannot parse step {s!r}")


from ..query.format_value import fmt_value as _fmt_value  # noqa: E402


class ConcurrencyGate:
    """Query concurrency limiter with a bounded wait queue (reference
    app/vmselect/main.go:49-92: 2xCPU capped at 16, -search.maxQueueDuration
    timeout returning 429 + Retry-After, like the reference)."""

    def __init__(self, max_concurrent: int | None = None,
                 max_queue_duration_s: float = 10.0):
        if max_concurrent is None:
            from ..utils.memory import available_cpus
            max_concurrent = min(2 * available_cpus(), 16)
        self._sem = threading.Semaphore(max_concurrent)
        self.max_concurrent = max_concurrent
        self.max_queue_duration_s = max_queue_duration_s
        # per-instance thread-safe counter (several APIs per test process;
        # exposed as vm_concurrent_select_limit_reached_total in metrics())
        self._rejected = metricslib.Counter("rejected")

    @property
    def rejected(self) -> int:
        return self._rejected.get()

    def __enter__(self):
        if not self._sem.acquire(timeout=self.max_queue_duration_s):
            self._rejected.inc()
            raise TimeoutError(
                f"query queue wait exceeded {self.max_queue_duration_s}s "
                f"({self.max_concurrent} concurrent queries)")
        return self

    def __exit__(self, *exc):
        self._sem.release()


def _device_window_ready(ec, q: str) -> bool:
    """Does the device plane hold a resident rolling window able to serve
    this query O(new samples)?  Parse failures answer False (the normal
    path will surface the error with its usual handling)."""
    from ..query.eval import device_window_ready
    try:
        return device_window_ready(ec, parse_cached(q))
    except Exception:
        return False


class PrometheusAPI:
    def __init__(self, storage, tpu_engine=None, lookback_delta=300_000,
                 max_series=1_000_000, relabel_configs=None,
                 stream_aggr=None, stream_aggr_keep_input=False,
                 max_concurrent_queries=None, series_limits=None,
                 max_samples_per_query=1_000_000_000,
                 max_memory_per_query=0, max_query_duration_ms=30_000,
                 rate_limiter=None):
        self.storage = storage
        # ingest.ratelimiter.TenantRateLimiters (-maxIngestionRate analog)
        self.rate_limiter = rate_limiter
        self.tpu = tpu_engine
        self.lookback_delta = lookback_delta
        self.max_series = max_series
        self.max_samples_per_query = max_samples_per_query
        self.max_memory_per_query = max_memory_per_query
        self.max_query_duration_ms = max_query_duration_ms
        self.default_tenant = (0, 0)
        self.relabel = relabel_configs   # ingest.relabel.ParsedConfigs
        self.stream_aggr = stream_aggr   # ingest.streamaggr.StreamAggregators
        self.stream_aggr_keep_input = stream_aggr_keep_input
        self.series_limits = series_limits  # ingest.serieslimits.SeriesLimits
        self.columnar_drop_stats: dict = {}
        self.active = ActiveQueries()
        self.qstats = QueryStats()
        self.slowlog = SlowQueryLog()
        self.gate = ConcurrencyGate(max_concurrent_queries)
        # materialized streams + subscription push (query/matstream):
        # one evaluator per distinct expression, suffix deltas fanned to
        # every /api/v1/watch subscriber and vmalert rule group
        from ..query.matstream import MatStreamRegistry
        self.matstreams = MatStreamRegistry(self)
        self.started_at = fasttime.unix_seconds()
        self.rows_inserted = 0
        self.rows_relabel_dropped = 0
        # TYPE/HELP metadata (lib/storage/metricsmetadata analog) and
        # per-metric-name query usage stats (lib/storage/metricnamestats)
        self.metadata: dict[str, dict] = {}
        self.tenant_rows: dict[str, int] = {}
        self.name_usage: dict[str, list] = {}  # name -> [count, last_ts]
        # SLO plane (query/sloplane): lazily built — the engine only
        # spends cycles when pumped (self-scrape on_tick or ?pump=1)
        self.sloplane = None
        self._role = "vmsingle"

    # the columnar ingest path caches relabel/series-limit VERDICTS per raw
    # series key (Storage.add_rows_columnar transform), so any config swap
    # must invalidate those caches — property setters make hot-reload
    # (`self.relabel = ...` on SIGHUP) safe without extra call sites
    @property
    def relabel(self):
        return self._relabel

    @relabel.setter
    def relabel(self, v):
        self._relabel = v
        self._reset_columnar()

    @property
    def series_limits(self):
        return self._series_limits

    @series_limits.setter
    def series_limits(self, v):
        self._series_limits = v
        self._reset_columnar()

    def _reset_columnar(self):
        st = getattr(self, "storage", None)
        if st is not None and getattr(st, "supports_columnar", False):
            st.reset_columnar_spaces()

    # -- wiring ------------------------------------------------------------

    def register(self, srv: HTTPServer, mode: str = "all"):
        """mode: 'all' (vmsingle), 'insert' (vminsert), 'select' (vmselect)
        — mirrors the reference's one-codebase three-role composition."""
        self.srv = srv
        self._role = {"all": "vmsingle", "insert": "vminsert",
                      "select": "vmselect"}.get(mode, mode)
        srv.route("/api/v1/status/health", self.h_health)
        if mode in ("all", "insert"):
            self._register_insert(srv)
            srv.route("/insert/", self._mt_dispatch)
        if mode in ("all", "select"):
            self._register_select(srv)
            srv.route("/select/", self._mt_dispatch)
            srv.route("/admin/tenants", self.h_tenants)
        if mode in ("all", "select"):
            srv.route("/vmui", self.h_vmui)
            srv.route("/vmui/", self.h_vmui)
        srv.route("/metrics", self.h_metrics)
        srv.route("/flags", self.h_flags)
        srv.route("/internal/faults", self.h_faults)
        srv.route("/debug/pprof/", self.h_pprof)
        srv.route("/health", lambda req: Response.text("OK"))
        srv.route("/-/healthy", lambda req: Response.text("OK"))
        srv.route("/-/ready", lambda req: Response.text("OK"))

    def _register_insert(self, srv: HTTPServer):
        r = srv.route
        r("/api/v1/write", self.h_remote_write)
        r("/api/v1/push", self.h_remote_write)
        r("/prometheus/api/v1/write", self.h_remote_write)
        r("/api/v1/import", self.h_import)
        r("/api/v1/import/native", self.h_import_native)
        r("/api/v1/import/prometheus", self.h_import_prometheus)
        r("/api/v1/import/csv", self.h_import_csv)
        r("/write", self.h_influx_write)
        r("/influx/write", self.h_influx_write)
        r("/api/put", self.h_opentsdb_http)
        r("/zabbixconnector/api/v1/history", self.h_zabbix)
        r("/opentsdb/api/put", self.h_opentsdb_http)
        r("/graphite", self.h_graphite_write)
        r("/datadog/api/v1/series", self.h_datadog_v1)
        r("/datadog/api/v2/series", self.h_datadog_v2)
        r("/datadog/api/v1/validate", lambda req: Response.json({"valid": True}))
        r("/newrelic/infra/v2/metrics/events/bulk", self.h_newrelic)
        r("/opentelemetry/v1/metrics", self.h_otlp)
        r("/opentelemetry/api/v1/push", self.h_otlp)
        r("/v1/metrics", self.h_otlp)

    def _register_select(self, srv: HTTPServer):
        r = srv.route
        r("/api/v1/query", self.h_query)
        r("/api/v1/query_range", self.h_query_range)
        r("/api/v1/watch", self.h_watch)
        r("/api/v1/series", self.h_series)
        r("/api/v1/labels", self.h_labels)
        r("/api/v1/label/", self.h_label_values)
        r("/api/v1/export", self.h_export)
        r("/api/v1/read", self.h_remote_read)
        r("/api/v1/export/native", self.h_export_native)
        r("/api/v1/admin/tsdb/delete_series", self.h_delete_series)
        r("/api/v1/status/tsdb", self.h_status_tsdb)
        r("/api/v1/status/active_queries", self.h_active_queries)
        r("/api/v1/status/top_queries", self.h_top_queries)
        r("/api/v1/status/slow_queries", self.h_slow_queries)
        r("/api/v1/status/flight", self.h_flight)
        r("/api/v1/status/quarantine", self.h_quarantine)
        r("/api/v1/status/usage", self.h_usage)
        r("/api/v1/status/profile", self.h_profile)
        r("/api/v1/status/slo", self.h_slo)
        r("/api/v1/status/incidents", self.h_incidents)
        r("/metric-relabel-debug", self.h_relabel_debug)
        r("/prettify-query", self.h_prettify_query)
        r("/expand-with-exprs", self.h_prettify_query)  # WITH folding is
        # part of parsing: the canonical string has templates expanded
        r("/api/v1/parse-query", self.h_query_ast)
        r("/api/v1/metadata", self.h_metadata)
        r("/api/v1/status/metric_names_stats", self.h_name_stats)
        r("/api/v1/admin/status/metric_names_stats/reset",
          self.h_reset_name_stats)
        r("/federate", self.h_federate)
        if hasattr(self.storage, "create_snapshot"):
            r("/snapshot/create", self.h_snapshot_create)
            r("/snapshot/list", self.h_snapshot_list)
            r("/snapshot/delete", self.h_snapshot_delete)
            r("/snapshot/delete_all", self.h_snapshot_delete_all)
        if hasattr(self.storage, "force_flush"):
            r("/internal/force_flush", self.h_force_flush)
            r("/internal/force_merge", self.h_force_merge)

    # -- query -------------------------------------------------------------

    def _tenant(self, req) -> tuple:
        """Per-request tenant: set by the multitenant path router
        (/insert|/select/<accountID[:projectID]>/..., lib/auth.Token)."""
        return getattr(req, "tenant", None) or self.default_tenant

    def _deny_partial(self, req) -> bool:
        """-search.denyPartialResponse semantics per request: the
        ``deny_partial`` query arg wins (1/0), else the
        ``VM_DENY_PARTIAL_RESPONSE`` env default."""
        import os as _os
        v = req.arg("deny_partial")
        if v:
            return v not in ("0", "false", "no")
        return _os.environ.get("VM_DENY_PARTIAL_RESPONSE", "") \
            not in ("", "0", "false", "no")

    def _partial_guard(self, req) -> Response | None:
        """Partial-result accounting + the deny_partial 503: returns the
        error response to serve instead of a silently incomplete 200,
        or None to proceed.  Call right after a successful exec."""
        if not bool(getattr(self.storage, "last_partial", False)):
            return None
        _PARTIAL_TOTAL.inc()
        if not self._deny_partial(req):
            return None
        return Response.error(
            "partial response denied: one or more storage nodes did not "
            "answer (deny_partial=1 / VM_DENY_PARTIAL_RESPONSE; retry or "
            "allow partial results)", 503, "unavailable")

    def _reject_query(self, e: SearchLimitError, q: str, start: int,
                      end: int, step: int, req: Request) -> Response:
        """Shed-load surface: a TenantGate rejection becomes a 429 +
        Retry-After (the ingest limiter's rejection contract) AND a
        rejected record in the slow-query log, so shed queries stay
        visible at /api/v1/status/slow_queries and (via the gate's
        ``gate:rejected`` flight instant) /api/v1/status/flight."""
        self.slowlog.record_rejected(q, start, end, step,
                                     self._tenant(req), str(e))
        resp = Response.error(str(e), 429, "too_many_requests")
        resp.headers["Retry-After"] = str(e.retry_after_s)
        return resp

    def _mt_dispatch(self, req: Request) -> Response:
        """Cluster-style multitenant routing (lib/auth.NewToken +
        app/vmselect/main.go:262 /select/<tenant>/prometheus/...,
        app/vminsert/main.go /insert/<tenant>/<proto>)."""
        parts = req.path.split("/", 3)
        if len(parts) < 4 or not parts[3]:
            return Response.error(f"missing tenant path suffix in "
                                  f"{req.path!r}", 400)
        tstr, rest = parts[2], "/" + parts[3]
        try:
            if ":" in tstr:
                a, p = tstr.split(":", 1)
                tenant = (int(a), int(p))
            else:
                tenant = (int(tstr), 0)
        except ValueError:
            return Response.error(f"cannot parse tenant {tstr!r} "
                                  f"(want accountID[:projectID])", 400)
        if not (0 <= tenant[0] < 2**32 and 0 <= tenant[1] < 2**32):
            return Response.error(f"tenant ids out of uint32 range: {tstr}",
                                  400)
        # cluster URLs nest the protocol: /select/0/prometheus/api/v1/query,
        # /insert/0/prometheus/api/v1/write, /insert/0/influx/write
        if rest.startswith("/prometheus/"):
            rest = rest[len("/prometheus"):]
        elif rest.startswith("/influx/"):
            rest = rest[len("/influx"):]
        elif rest.startswith("/opentsdb/"):
            rest = rest[len("/opentsdb"):]
        elif rest.startswith("/graphite/"):
            rest = rest[len("/graphite"):]
        req.tenant = tenant
        req.path = rest
        fn = self.srv._route_for(rest)
        if fn is None or getattr(fn, "__func__", None) is \
                PrometheusAPI._mt_dispatch:
            return Response.error(f"unsupported path {rest}", 404,
                                  "not_found")
        return fn(req)

    def h_vmui(self, req: Request) -> Response:
        """Static explorer (the reference serves the React vmui bundle at
        app/vmselect/main.go:438; this is a dependency-free equivalent
        with query/graph/table/JSON tabs + cardinality + top queries)."""
        import os as _os
        path = _os.path.join(_os.path.dirname(__file__), "vmui.html")
        with open(path, "rb") as f:
            return Response(200, f.read(), "text/html; charset=utf-8")

    def h_tenants(self, req: Request) -> Response:
        """List tenants with stored data (the vmselect /admin/tenants API,
        app/vmselect/main.go:229 + vmselectapi tenants_v1)."""
        tenants = self.storage.tenants() if hasattr(self.storage, "tenants") \
            else [(0, 0)]
        return Response.json({"status": "success",
                              "data": [f"{a}:{p}" for a, p in tenants]})

    def _ec(self, start, end, step, tenant=(0, 0)) -> EvalConfig:
        import time as _t
        deadline = (_t.monotonic() + self.max_query_duration_ms / 1e3
                    if self.max_query_duration_ms > 0 else 0.0)
        return EvalConfig(start=start, end=end, step=step,
                          storage=self.storage,
                          lookback_delta=self.lookback_delta,
                          max_series=self.max_series, tpu=self.tpu,
                          max_samples_per_query=self.max_samples_per_query,
                          max_memory_per_query=self.max_memory_per_query,
                          deadline=deadline, tenant=tenant)

    @contextlib.contextmanager
    def _query_observability(self, req: Request, q: str, qt, qid: int,
                             start: int, end: int, step: int, ec=None):
        """One query's observability bracket, shared by h_query and
        h_query_range: install the tracer + a fresh flight context (so
        spans recorded anywhere — this thread or pool workers — carry
        the query's ctx and the slow-query log can reassemble the
        per-phase split) + the query's CostTracker (so storage/cache/
        device seams account into it even outside exec_query); on exit
        restore all three, unregister the active query, fold the cost
        into the per-tenant usage table and feed qstats + the
        slow-query log (cost columns included), attaching any flight
        capture the eval noted."""
        from ..utils import costacc, querytracer
        fctx = flightrec.new_ctx()
        prev_ctx = flightrec.set_ctx(fctx)
        prev_tr = querytracer.set_current(qt)
        cost = ec._cost if ec is not None else None
        prev_cost = costacc.set_current(cost)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            costacc.set_current(prev_cost)
            querytracer.set_current(prev_tr)
            flightrec.set_ctx(prev_ctx)
            self.active.unregister(qid)
            dur = time.perf_counter() - t0
            summary = cost.summary() if cost is not None else None
            costacc.record_usage(self._tenant(req), cost, summary=summary)
            self.qstats.record(q, (end - start) / 1e3, dur, cost=summary)
            self.slowlog.maybe_record(
                q, start, end, step, self._tenant(req), dur, ctx=fctx,
                capture_id=flightrec.take_noted_capture(), cost=summary)

    def h_query(self, req: Request) -> Response:
        q = req.arg("query")
        if not q:
            return Response.error("missing 'query' arg")
        now = fasttime.unix_ms()
        try:
            ts = parse_time(req.arg("time"), now)
            step = parse_step(req.arg("step"), 300_000)
        except QueryError as e:
            # bad time=/step= args are the client's mistake: 400, not
            # an escape to the boundary's anonymous 500 (VMT016)
            return Response.error(str(e))
        qid = self.active.register(q, ts, ts, step)
        if hasattr(self.storage, "reset_partial"):
            self.storage.reset_partial()
        from ..utils import querytracer
        qt = querytracer.new(req.arg("trace") == "1", "query %s time=%d",
                             q, ts)
        try:
            ec = self._ec(ts, ts, step, self._tenant(req))
            ec.tracer = qt
            with self._query_observability(req, q, qt, qid, ts, ts, step,
                                           ec=ec):
                with self.gate:
                    rows = exec_query(ec, q)
                ec._cost.add_rows(len(rows))
                self._track_usage(rows)
        except TimeoutError as e:
            resp = Response.error(str(e), 429, "too_many_requests")
            resp.headers["Retry-After"] = "10"
            return resp
        except SearchLimitError as e:
            return self._reject_query(e, q, ts, ts, step, req)
        except PartialResultError as e:
            _PARTIAL_TOTAL.inc()
            return Response.error(str(e), 503, "unavailable")
        except ClusterUnavailableError as e:
            return Response.error(str(e), 503, "unavailable")
        except (QueryError, ParseError, ValueError) as e:
            return Response.error(str(e))
        denied = self._partial_guard(req)
        if denied is not None:
            return denied
        result = []
        for r in rows:
            v = r.values[-1]
            if math.isnan(v):
                continue
            result.append({"metric": r.metric_name.to_dict(),
                           "value": [ts / 1e3, _fmt_value(v)]})
        qt.donef("%d result series", len(result))
        body = {"status": "success",
                "isPartial": bool(getattr(self.storage, "last_partial",
                                          False)),
                "partialResolution": bool(getattr(
                    self.storage, "last_partial_resolution", False)),
                "data": {"resultType": "vector", "result": result}}
        if qt.enabled:
            body["trace"] = qt.to_dict()
        return Response.json(body)

    def h_query_range(self, req: Request) -> Response:
        q = req.arg("query")
        if not q:
            return Response.error("missing 'query' arg")
        now = fasttime.unix_ms()
        try:
            start = parse_time(req.arg("start"), now - 300_000)
            end = parse_time(req.arg("end"), now)
            step = parse_step(req.arg("step"))
        except QueryError as e:
            # bad start=/end=/step= args are the client's mistake: 400,
            # not an escape to the boundary's anonymous 500 (VMT016)
            return Response.error(str(e))
        if end < start:
            return Response.error("end < start")
        # align the grid to the step (AdjustStartEnd analog): start rounds
        # DOWN (phase-stable for the rollup cache), end rounds UP so the
        # freshest samples stay inside the last window
        start -= start % step
        end = start + -(-(end - start) // step) * step
        qid = self.active.register(q, start, end, step)
        if hasattr(self.storage, "reset_partial"):
            self.storage.reset_partial()
        from ..utils import querytracer
        qt = querytracer.new(req.arg("trace") == "1",
                             "query_range %s start=%d end=%d step=%d",
                             q, start, end, step)
        try:
            ec = self._ec(start, end, step, self._tenant(req))
            ec.tracer = qt
            with self._query_observability(req, q, qt, qid,
                                           start, end, step, ec=ec):
                with self.gate:
                    if req.arg("nocache") == "1":
                        # reference -search.disableCache / nocache=1 arg
                        ec.disable_cache = True
                        rows = exec_query(ec, q)
                    else:
                        rows = self._exec_range_cached(ec, q, now)
                ec._cost.add_rows(len(rows))
                self._track_usage(rows)
        except TimeoutError as e:
            resp = Response.error(str(e), 429, "too_many_requests")
            resp.headers["Retry-After"] = "10"
            return resp
        except SearchLimitError as e:
            return self._reject_query(e, q, start, end, step, req)
        except PartialResultError as e:
            _PARTIAL_TOTAL.inc()
            return Response.error(str(e), 503, "unavailable")
        except ClusterUnavailableError as e:
            return Response.error(str(e), 503, "unavailable")
        except (QueryError, ParseError, ValueError) as e:
            return Response.error(str(e))
        denied = self._partial_guard(req)
        if denied is not None:
            return denied
        grid = ec.timestamps() / 1e3
        result = []
        for r in rows:
            vals = [[float(t), _fmt_value(v)]
                    for t, v in zip(grid, r.values) if not math.isnan(v)]
            if vals:
                result.append({"metric": r.metric_name.to_dict(),
                               "values": vals})
        qt.donef("%d result series", len(result))
        body = {"status": "success",
                "isPartial": bool(getattr(self.storage, "last_partial",
                                          False)),
                "partialResolution": bool(getattr(
                    self.storage, "last_partial_resolution", False)),
                "data": {"resultType": "matrix", "result": result}}
        if qt.enabled:
            body["trace"] = qt.to_dict()
        return Response.json(body)

    def h_watch(self, req: Request) -> Response:
        """Materialized-stream subscription push (``/api/v1/watch?query=
        ...&step=...&range=...``): the dashboard holds ONE subscription
        and receives SSE suffix frames instead of re-issuing
        ``query_range`` — the per-interval evaluation is shared by every
        subscriber of the same canonical expression (storage reads per
        interval are O(distinct expressions), not O(subscribers)).

        Args: ``query`` (range expression), ``step`` (grid step,
        default 1m), ``range`` (rolling window length, e.g. ``30m``) or
        a ``start``/``end`` pair whose span defines it, ``max_frames``
        (close after N frames — test/CLI hygiene; 0 = until
        disconnect), ``heartbeat`` (idle keepalive seconds, default 15).
        First frame is a full snapshot (replayed from the warm stream
        when one exists), then deltas.  503 when VM_MATSTREAM=0.

        Reconnect/resume: every SSE event carries ``id:
        <epoch>:<seq>``; a dropped dashboard re-attaches with the
        standard ``Last-Event-ID`` header (or ``resume=`` arg) and
        receives only the missed suffix frames — bounded by
        ``VM_MATSTREAM_QUEUE`` retained frames; an older/foreign token
        degrades loudly to one resync snapshot
        (``vm_matstream_resume_misses_total``)."""
        from ..query import matstream
        if not matstream.enabled():
            return Response.error(
                "materialized streams disabled (VM_MATSTREAM=0)", 503,
                "unavailable")
        q = req.arg("query")
        if not q:
            return Response.error("missing 'query' arg")
        try:
            step = parse_step(req.arg("step"))
            rng = req.arg("range")
            if rng:
                duration = parse_step(rng, 0)
            else:
                now = fasttime.unix_ms()
                start = parse_time(req.arg("start"), now - 300_000)
                end = parse_time(req.arg("end"), now)
                duration = max(end - start, step)
            max_frames = int(req.arg("max_frames", "0") or 0)
            # floor 0.2s: heartbeat=0 would turn the frame loop into a
            # hot keepalive spin (one queue poll + socket write per
            # iteration) — a one-request CPU DoS
            heartbeat = min(max(
                float(req.arg("heartbeat", "15") or 15), 0.2), 3600.0)
        except (QueryError, ValueError) as e:
            return Response.error(str(e))
        resume = req.arg("resume") or \
            (getattr(req, "headers", {}).get("Last-Event-ID") or "").strip()
        try:
            sub = self.matstreams.subscribe(q, step, duration,
                                            self._tenant(req),
                                            resume=resume or None)
        except matstream.MatStreamLimitError as e:
            resp = Response.error(str(e), 429, "too_many_requests")
            resp.headers["Retry-After"] = "10"
            return resp
        except matstream.MatStreamDisabled as e:
            # the enabled() pre-check above races a live VM_MATSTREAM
            # flip: subscribe re-checks under the registry lock, so map
            # the raise too — same 503 as the pre-check path (VMT016)
            return Response.error(str(e), 503, "unavailable")
        except (QueryError, ParseError, ValueError) as e:
            return Response.error(str(e))

        def frames():
            sent = 0
            try:
                while True:
                    f = sub.next_frame(timeout_s=heartbeat)
                    if f is None:
                        if sub.closed:
                            return
                        yield b": keepalive\n\n"
                        continue
                    # frames are SHARED dicts (one per advance, fanned
                    # to every subscriber): encode once process-wide,
                    # not once per subscriber.  The id line is the
                    # resume token Last-Event-ID echoes back.
                    yield (b"event: frame\nid: " +
                           sub.stream.resume_token(f).encode() +
                           b"\ndata: " +
                           matstream.encode_frame(f) + b"\n\n")
                    sent += 1
                    if max_frames and sent >= max_frames:
                        return
            finally:
                sub.close()
        # on_close covers the never-started-generator disconnect (the
        # generator's own finally can't run then) — close() is
        # idempotent, so the normal path closing twice is harmless
        return StreamingResponse(frames(),
                                 content_type="text/event-stream",
                                 on_close=sub.close)

    # queries calling non-deterministic / wall-clock functions bypass the
    # rollup-result cache; \b keeps avg_over_time( from matching time(
    _UNCACHEABLE_RE = re.compile(
        r"\b(?:rand|rand_normal|rand_exponential|now|time)\s*\(")

    def _exec_range_cached(self, ec, q: str, now_ms: int):
        # serve-priority window: background flush/merge admission yields
        # to in-flight serving (workpool.MergeGate) for the WHOLE refresh,
        # not just the storage-fetch slice the SearchGate covers
        from ..utils import workpool
        # a flight context per refresh (reuse the HTTP handler's when one
        # is installed — bench and tests call this directly)
        fctx = flightrec.get_ctx()
        fresh_ctx = fctx == 0
        if fresh_ctx:
            fctx = flightrec.new_ctx()
            flightrec.set_ctx(fctx)
        # the whole refresh accounts into the query's CostTracker — the
        # HTTP bracket installs it too (re-install is idempotent), but
        # direct callers (bench, tests) get the cache merge/put laps
        # only through this install
        from ..utils import costacc
        prev_cost = costacc.set_current(ec._cost)
        w0 = ec._cost.local_wall_ms_total()
        t0 = time.perf_counter()
        try:
            with workpool.serving():
                return self._exec_range_cached_serving(ec, q, now_ms)
        finally:
            dur = time.perf_counter() - t0
            # refresh wall not claimed by any LOCAL phase/eval lap
            # (cache get, row sort/filter, result handling) gets its own
            # named bucket — the bench's >=90%-accounted honesty ratio
            # counts glue it can SEE, not glue that vanished.  Local-lap
            # baseline only: merged remote laps are concurrent
            inner_ms = ec._cost.local_wall_ms_total() - w0
            if dur * 1e3 > inner_ms:
                costacc.lap("serve:other", dur - inner_ms / 1e3)
            costacc.set_current(prev_cost)
            flightrec.rec("serve:refresh", t0, dur, arg=q[:200])
            if fresh_ctx:
                flightrec.clear_ctx()
            # slow-refresh trigger: freeze the cross-thread timeline that
            # explains THIS refresh while it is still in the rings
            th = flightrec.slow_refresh_threshold_ms()
            if th > 0 and dur * 1e3 > th:
                cap = flightrec.RECORDER.capture(
                    "slow_refresh",
                    meta={"query": q[:500], "refresh_ms": round(dur * 1e3, 2),
                          "threshold_ms": th, "ctx": fctx},
                    # only the ring snapshot races the writers; building
                    # the trace JSON + summary waits for first retrieval
                    # so the capture cost is not charged to the very
                    # refresh latency that tripped it (observer effect)
                    defer_build=True)
                # note the id only when an outer handler frame exists to
                # consume it (fresh_ctx means a direct call — bench and
                # tests — where a leftover note would misattach to the
                # NEXT slow query this thread happens to serve)
                if cap is not None and not fresh_ctx:
                    flightrec.note_capture(cap["id"])

    def _exec_range_cached_serving(self, ec, q: str, now_ms: int):
        from ..query.rollup_result_cache import GLOBAL as rcache
        cacheable = (ec.n_points > 1
                     and not self._UNCACHEABLE_RE.search(q))
        if not cacheable:
            return exec_query(ec, q)
        if ec.tpu is not None and _device_window_ready(ec, q):
            # device-resident serving: the device plane holds a rolling
            # window for this query shape, so the FULL eval is O(new
            # samples) — advance_rolling fetches/uploads only the tail
            # columns and the [G, T] ring reuses every covered column.
            # The host ring cache still gets the put() below, so a later
            # device decline falls back to the host suffix path with a
            # warm prefix instead of a cold rebuild.
            ec.tracer.printf("device window resident: full eval")
            rows = exec_query(ec, q)
            if not getattr(self.storage, "last_partial", False):
                rcache.put(ec, q, rows, now_ms, trust_raw=False)
            return rows
        cached, new_start = rcache.get(ec, q, now_ms)
        if cached is not None and new_start > ec.end:
            ec.tracer.printf("rollup cache: full hit")
            # same shape as the partial-hit return below: an in-place
            # merge keeps append-ordered rows (and all-NaN churned rows)
            # in the entry, and its stamped no-op put() skips the
            # caller's filter+sort — re-apply both so full hits match
            # the partial-hit rows (and the ring-off oracle) exactly
            rows = [r for r in cached.rows()
                    if not np.isnan(r.values).all()]
            rows.sort(key=lambda ts: ts.raw)
            return rows
        if cached is not None:
            ec.tracer.printf("rollup cache: partial hit, computing from %d",
                             new_start)
            # single-column tails widen by one leading column (dropped
            # after the eval): a one-point grid would flip rollups into
            # instant-query maxPrevInterval semantics (rollup.go:719-728)
            from ..query.eval import suffix_child_bounds, trim_suffix_rows
            sub_start, trim = suffix_child_bounds(ec, new_start)
            sub = ec.child(start=sub_start)
            sub.tracer = ec.tracer
            # the device rolling tail-reuse must not layer under this
            # cache's own tail merge (see EvalConfig.no_device_roll)
            sub.no_device_roll = True
            # the tail sub-eval must not read or write eval-level cache
            # entries under its own short window: a widened single-column
            # sub has n_points=2, and its put() would replace a
            # full-coverage inner entry with a 2-column one (same guard
            # as the eval-level suffix subs, eval.py "must not clobber")
            sub.no_eval_cache = True
            fresh = exec_query(sub, q)
            if trim:
                fresh = trim_suffix_rows(fresh)
            # trust_raw=False: these are POST-transform rows — in-place
            # label edits (multi-output rollups, label_set, binop
            # keep_metric_names) leave Timeseries.raw stale, so identity
            # must come from a fresh marshal here
            rows = rcache.merge(cached, fresh, ec, new_start,
                                trust_raw=False, now_ms=now_ms)
            rows = [r for r in rows
                    if not np.isnan(r.values).all()]
            # merge() just attached authoritative raws to exactly these
            # rows — reuse them for the sort and let put() trust them
            # (no further name mutation happens between here and put)
            rows.sort(key=lambda ts: ts.raw)
            trust = True
        else:
            rows = exec_query(ec, q)
            trust = False
        if not getattr(self.storage, "last_partial", False):
            # never cache partial cluster results: a later hit would present
            # incomplete data as complete with isPartial=false
            rcache.put(ec, q, rows, now_ms, trust_raw=trust)
        return rows

    # -- metadata ----------------------------------------------------------

    def _matches_to_filters(self, req: Request):
        out = []
        for m in req.args("match[]") or req.args("match"):
            e = mql_parse(m)
            if not isinstance(e, MetricExpr):
                raise QueryError(f"match[] must be a series selector: {m}")
            # multiple match[] values are already a union, so a selector's
            # OR'd filter sets expand into extra entries
            out.extend(filter_sets_from_metric_expr(e))
        return out

    def _time_range(self, req: Request, full_default: bool = False):
        """Default range: last 30 days for metadata APIs, everything for
        export (the reference exports the full retention by default)."""
        now = fasttime.unix_ms()
        default_start = 0 if full_default else now - 86_400_000 * 30
        start = parse_time(req.arg("start"), default_start)
        end = parse_time(req.arg("end"), now)
        return start, end

    def h_series(self, req: Request) -> Response:
        try:
            fl = self._matches_to_filters(req)
            start, end = self._time_range(req)
            if not fl:
                return Response.error("missing match[] arg")
            out = []
            seen = set()
            limit = int(req.arg("limit", "0") or 0) or (1 << 31)
            for filters in fl:
                if len(out) >= limit:
                    break
                for mn in self.storage.search_metric_names(
                        filters, start, end, tenant=self._tenant(req)):
                    raw = mn.marshal()
                    if raw not in seen:
                        seen.add(raw)
                        out.append(mn.to_dict())
                        if len(out) >= limit:
                            break
            return Response.json({"status": "success", "data": out})
        except (QueryError, ParseError, ValueError) as e:
            return Response.error(str(e))

    def h_labels(self, req: Request) -> Response:
        try:
            start, end = self._time_range(req)
        except QueryError as e:
            return Response.error(str(e))
        return Response.json({"status": "success",
                              "data": self.storage.label_names(
                                  start, end, tenant=self._tenant(req))})

    def h_label_values(self, req: Request) -> Response:
        m = re.fullmatch(r"/api/v1/label/([^/]+)/values", req.path)
        if not m:
            return Response.error("bad label values path", 404)
        try:
            start, end = self._time_range(req)
        except QueryError as e:
            return Response.error(str(e))
        vals = self.storage.label_values(m.group(1), start, end,
                                         tenant=self._tenant(req))
        return Response.json({"status": "success", "data": vals})

    # -- export / federate ---------------------------------------------------

    def h_export(self, req: Request) -> Response:
        try:
            fl = self._matches_to_filters(req)
            if not fl:
                return Response.error("missing match[] arg")
            start, end = self._time_range(req, full_default=True)
            lines = []
            for filters in fl:
                for sd in self.storage.search_series(
                        filters, start, end, tenant=self._tenant(req)):
                    mask = ~np.isnan(sd.values)
                    lines.append(parsers.series_to_jsonl(
                        sd.metric_name.to_dict(),
                        sd.timestamps[mask], sd.values[mask]))
            return Response(200, "\n".join(lines) + ("\n" if lines else ""),
                            content_type="application/stream+json")
        except (QueryError, ParseError, ValueError) as e:
            return Response.error(str(e))

    def h_export_native(self, req: Request) -> Response:
        """Binary export (reference /api/v1/export/native,
        app/vmselect/prometheus/export.go): zstd-framed series blocks —
        marshaled MetricName + raw int64 timestamp/float64 value arrays.
        Round-trips losslessly through /api/v1/import/native."""
        from ..ops import compress as zstd_c
        from ..parallel.rpc import Writer
        try:
            fl = self._matches_to_filters(req)
            if not fl:
                return Response.error("missing match[] arg")
            start, end = self._time_range(req, full_default=True)
            out = bytearray(b"vmtpu-native-v1\n")
            for filters in fl:
                for sd in self.storage.search_series(
                        filters, start, end, tenant=self._tenant(req)):
                    w = Writer()
                    w.bytes_(sd.metric_name.marshal())
                    w.array(np.asarray(sd.timestamps, dtype=np.int64))
                    w.array(np.asarray(sd.values, dtype=np.float64))
                    frame = zstd_c.compress(bytes(w.buf))
                    out += struct.pack("<I", len(frame))
                    out += frame
            return Response(200, bytes(out),
                            content_type="application/octet-stream")
        except (QueryError, ParseError, ValueError) as e:
            return Response.error(str(e))

    def h_import_native(self, req: Request) -> Response:
        from ..ops import compress as zstd_c
        from ..parallel.rpc import Reader
        body = req.body
        magic = b"vmtpu-native-v1\n"
        if not body.startswith(magic):
            return Response.error("bad native export header", 400)
        off = len(magic)
        batch = []
        try:
            while off < len(body):
                (flen,) = struct.unpack_from("<I", body, off)
                off += 4
                r = Reader(zstd_c.decompress(body[off:off + flen]))
                off += flen
                mn = MetricName.unmarshal(r.bytes_())
                ts = r.array()
                vals = r.array()
                labels = mn.to_dict()
                for t, v in zip(ts.tolist(), vals.tolist()):
                    batch.append((labels, t, v))
        except Exception as e:  # noqa: BLE001 — any parse failure is a 400
            return Response.error(f"cannot parse native import: {e}", 400)
        self._ingest(batch, self._tenant(req))
        return Response(status=204, body=b"")

    def h_remote_read(self, req: Request) -> Response:
        """Prometheus remote_read server (the reference serves this at
        app/vmselect; lets Prometheus/Thanos/vmctl pull data out)."""
        from ..storage.tag_filters import TagFilter
        try:
            # server.py already decompressed bodies carrying a
            # Content-Encoding header; clients omitting it still send
            # snappy (protocol default)
            try:
                queries = list(remote_write.parse_read_request(req.body,
                                                               "none"))
            except Exception:
                queries = list(remote_write.parse_read_request(req.body,
                                                               "snappy"))
            results = []
            for start, end, matchers in queries:
                filters = []
                for op, name, value in matchers:
                    key = b"" if name == "__name__" else name.encode()
                    filters.append(TagFilter(
                        key, value.encode(), negate=op.startswith("!"),
                        regex=op.endswith("~")))
                series = []
                for sd in self.storage.search_series(
                        filters, start, end, max_series=self.max_series,
                        tenant=self._tenant(req)):
                    mask = ~np.isnan(sd.values)
                    series.append((sd.metric_name.to_dict(),
                                   sd.timestamps[mask], sd.values[mask]))
                results.append(series)
            body = remote_write.build_read_response(results)
            return Response(200, body, "application/x-protobuf")
        except (ValueError, ResourceWarning) as e:
            return Response.error(f"cannot serve remote read: {e}", 400)

    def h_federate(self, req: Request) -> Response:
        try:
            fl = self._matches_to_filters(req)
            if not fl:
                return Response.error("missing match[] arg")
            now = fasttime.unix_ms()
            start = now - self.lookback_delta
            lines = []
            for filters in fl:
                for sd in self.storage.search_series(
                        filters, start, now, tenant=self._tenant(req)):
                    mask = ~np.isnan(sd.values)
                    if not mask.any():
                        continue
                    ts = sd.timestamps[mask][-1]
                    v = sd.values[mask][-1]
                    d = sd.metric_name.to_dict()
                    name = d.pop("__name__", "")
                    lab = ",".join(
                        '{}="{}"'.format(
                            k, v2.replace("\\", "\\\\").replace('"', '\\"')
                                 .replace("\n", "\\n"))
                        for k, v2 in sorted(d.items()))
                    lines.append(f"{name}{{{lab}}} {_fmt_value(v)} {int(ts)}")
            return Response.text("\n".join(lines) + "\n")
        except (QueryError, ParseError, ValueError) as e:
            return Response.error(str(e))

    # -- ingestion -----------------------------------------------------------

    def _columnar_ok(self) -> bool:
        """Columnar fast path covers relabel + series limits (verdicts are
        cached per raw key inside Storage); only stream aggregation — which
        must see every row — forces the Python path."""
        return (self.stream_aggr is None
                and getattr(self.storage, "supports_columnar", False))

    def _columnar_transform(self):
        relabel = self.relabel
        limits = self.series_limits
        if relabel is None and limits is None:
            return None

        def transform(labels):
            d = dict(labels)
            if relabel is not None:
                d = relabel.apply(d)
                if not d or not d.get("__name__"):
                    return None
            if limits is not None and not limits.check(d):
                return None
            return list(d.items())
        return transform

    def _ingest_columnar(self, cr, tenant=(0, 0)) -> int:
        """Shared columnar ingest tail (native.ColumnarRows batches)."""
        if self.rate_limiter is not None and self.rate_limiter.enabled():
            # registers the raw batch size (insert_ctx.go:286 semantics);
            # raises RateLimitedError -> 429 + Retry-After at the server
            self.rate_limiter.register(len(cr), tenant)
        stats: dict = {}
        n = self.storage.add_rows_columnar(
            cr, tenant=tenant, transform=self._columnar_transform(),
            drop_stats=stats)
        if stats:
            self.rows_relabel_dropped += stats.get("transform", 0)
            for k, v in stats.items():
                self.columnar_drop_stats[k] = \
                    self.columnar_drop_stats.get(k, 0) + v
        self.rows_inserted += n
        if n and tenant != (0, 0):
            key = f'{{accountID="{tenant[0]}",projectID="{tenant[1]}"}}'
            self.tenant_rows[key] = self.tenant_rows.get(key, 0) + n
        return n

    def _add_rows(self, rows_iter, tenant=(0, 0)) -> int:
        now = fasttime.unix_ms()
        batch = []
        for row in rows_iter:
            ts = row.timestamp or now
            batch.append((dict(row.labels), ts, row.value))
        return self._ingest(batch, tenant)

    def _ingest(self, batch: list, tenant=(0, 0)) -> int:
        """Shared ingest tail: global relabeling (-relabelConfig analog,
        app/vminsert/relabel) -> stream aggregation hook -> storage."""
        if self.rate_limiter is not None and self.rate_limiter.enabled():
            self.rate_limiter.register(len(batch), tenant)
        if self.relabel is not None:
            out = []
            for labels, ts, val in batch:
                labels = self.relabel.apply(labels)
                if not labels or not labels.get("__name__"):
                    # dropped, or relabeled into a nameless/empty label set —
                    # the reference drops those too rather than indexing an
                    # unreachable series
                    self.rows_relabel_dropped += 1
                    continue
                out.append((labels, ts, val))
            batch = out
        if self.series_limits is not None:
            batch = [(labels, ts, val) for labels, ts, val in batch
                     if self.series_limits.check(labels)]
        if self.stream_aggr is not None:
            passthrough = []
            for labels, ts, val in batch:
                consumed = self.stream_aggr.push(labels, ts, val)
                if not consumed or self.stream_aggr_keep_input:
                    passthrough.append((labels, ts, val))
            batch = passthrough
        if batch:
            # backfill older than the cache offset invalidates cached rollup
            # tails (ResetRollupResultCacheIfNeeded analog)
            from ..query.rollup_result_cache import GLOBAL as rcache
            from ..query.rollup_result_cache import OFFSET_MS
            now = fasttime.unix_ms()
            if min(ts for _, ts, _ in batch) < now - OFFSET_MS:
                rcache.reset()
        n = self.storage.add_rows(batch, tenant=tenant) if batch else 0
        self.rows_inserted += n
        if n and tenant != (0, 0):
            # tenantmetrics (lib/tenantmetrics CounterMap analog)
            key = f'{{accountID="{tenant[0]}",projectID="{tenant[1]}"}}'
            self.tenant_rows[key] = self.tenant_rows.get(key, 0) + n
        return n

    def h_remote_write(self, req: Request) -> Response:
        # server.py already decompressed bodies with a Content-Encoding
        # header; clients that omit it still send snappy (the protocol
        # default), so try raw first, then snappy. parse_write_request is a
        # generator — materialize inside the try so errors surface here.
        if self._columnar_ok():
            from .. import native
            now = fasttime.unix_ms()
            cr = native.parse_rw_columnar(req.body, now)
            if cr is None:
                body = native.snappy_uncompress(req.body)
                if body is not None:
                    cr = native.parse_rw_columnar(body, now)
            if cr is not None:
                self._ingest_columnar(cr, self._tenant(req))
                return Response(status=204, body=b"")
        try:
            series = list(remote_write.parse_write_request(req.body, "none"))
        except Exception:
            try:
                series = list(remote_write.parse_write_request(req.body,
                                                               "snappy"))
            except Exception as e:
                return Response.error(f"cannot parse remote write: {e}", 400)
        batch = []
        now = fasttime.unix_ms()
        for labels, samples in series:
            for ts, val in samples:
                batch.append((dict(labels), ts or now, val))
        self._ingest(batch, self._tenant(req))
        return Response(status=204, body=b"")

    def h_import(self, req: Request) -> Response:
        try:
            n = self._add_rows(parsers.parse_jsonl(
                req.body.decode("utf-8", "replace")), self._tenant(req))
        except (ValueError, KeyError) as e:
            return Response.error(f"cannot parse import data: {e}", 400)
        return Response(status=204, body=b"")

    def h_import_prometheus(self, req: Request) -> Response:
        try:
            ts = parse_time(req.arg("timestamp"), 0)
            if b"# TYPE" in req.body or b"# HELP" in req.body:
                md = parsers.parse_prometheus_metadata(
                    req.body.decode("utf-8", "replace"))
                if len(self.metadata) < 100_000:
                    self.metadata.update(md)
                if getattr(self.storage, "set_metadata", None) is not None:
                    self.storage.set_metadata(md)
            tenant = self._tenant(req)
            cr = None
            if self._columnar_ok():
                from .. import native
                cr = native.parse_prom_columnar(
                    req.body, ts or fasttime.unix_ms())
            if cr is not None:
                # fast path: native parse -> columnar raw-key rows; repeat
                # scrapes resolve whole batches in one native hash-map call
                self._ingest_columnar(cr, tenant)
            elif self.relabel is None and self.series_limits is None and \
                    self.stream_aggr is None and \
                    getattr(self.storage, "supports_raw_keys", False):
                # raw-key row path (native lib present, columnar storage
                # absent — e.g. cluster vminsert)
                rows = parsers.parse_prometheus_fast(req.body, ts)
                self._ingest(rows, tenant)
            else:
                self._add_rows(parsers.parse_prometheus(
                    req.body.decode("utf-8", "replace"), ts), tenant)
        except (ValueError, QueryError) as e:
            return Response.error(f"cannot parse prometheus text: {e}", 400)
        return Response(status=204, body=b"")

    def h_import_csv(self, req: Request) -> Response:
        fmt = req.arg("format")
        if not fmt:
            return Response.error("missing 'format' arg")
        try:
            self._add_rows(parsers.parse_csv(
                req.body.decode("utf-8", "replace"), fmt), self._tenant(req))
        except (ValueError, IndexError) as e:
            return Response.error(f"cannot parse csv: {e}", 400)
        return Response(status=204, body=b"")

    def h_influx_write(self, req: Request) -> Response:
        db = req.arg("db")
        try:
            cr = None
            if self._columnar_ok():
                from .. import native
                cr = native.parse_influx_columnar(
                    req.body, db or "", fasttime.unix_ms())
            if cr is not None:
                self._ingest_columnar(cr, self._tenant(req))
            else:
                self._add_rows(parsers.parse_influx(
                    req.body.decode("utf-8", "replace"), db=db),
                    self._tenant(req))
        except ValueError as e:
            return Response.error(f"cannot parse influx line: {e}", 400)
        return Response(status=204, body=b"")

    def h_opentsdb_http(self, req: Request) -> Response:
        try:
            self._add_rows(parsers.parse_opentsdb_http(req.body), self._tenant(req))
        except (ValueError, KeyError) as e:
            return Response.error(f"cannot parse opentsdb json: {e}", 400)
        return Response(status=204, body=b"")

    def h_graphite_write(self, req: Request) -> Response:
        try:
            self._add_rows(parsers.parse_graphite(
                req.body.decode("utf-8", "replace")), self._tenant(req))
        except ValueError as e:
            return Response.error(f"cannot parse graphite line: {e}", 400)
        return Response(status=204, body=b"")

    def h_otlp(self, req: Request) -> Response:
        try:
            self._add_rows(parse_otlp(req.body), self._tenant(req))
        except (ValueError, struct.error) as e:
            return Response.error(f"cannot parse OTLP payload: {e}", 400)
        # empty body = valid empty ExportMetricsServiceResponse proto
        return Response(200, b"", "application/x-protobuf")

    def h_zabbix(self, req: Request) -> Response:
        try:
            self._add_rows(parsers.parse_zabbixconnector(
                req.body.decode("utf-8", "replace")), self._tenant(req))
        except (ValueError, KeyError) as e:
            return Response.error(f"cannot parse zabbix history: {e}", 400)
        return Response(status=204, body=b"")

    def h_datadog_v1(self, req: Request) -> Response:
        try:
            self._add_rows(parsers.parse_datadog_v1(req.body),
                           self._tenant(req))
        except (ValueError, KeyError) as e:
            return Response.error(f"cannot parse datadog: {e}", 400)
        return Response.json({"status": "ok"}, status=202)

    def h_datadog_v2(self, req: Request) -> Response:
        try:
            self._add_rows(parsers.parse_datadog_v2(req.body),
                           self._tenant(req))
        except (ValueError, KeyError) as e:
            return Response.error(f"cannot parse datadog: {e}", 400)
        return Response.json({"errors": []}, status=202)

    def h_newrelic(self, req: Request) -> Response:
        try:
            self._add_rows(parsers.parse_newrelic(req.body), self._tenant(req))
        except (ValueError, KeyError) as e:
            return Response.error(f"cannot parse newrelic: {e}", 400)
        return Response.json({"status": "ok"}, status=202)

    # -- admin ---------------------------------------------------------------

    def h_delete_series(self, req: Request) -> Response:
        try:
            fl = self._matches_to_filters(req)
            if not fl:
                return Response.error("missing match[] arg")
            n = 0
            for filters in fl:
                n += self.storage.delete_series(filters,
                                                tenant=self._tenant(req))
            return Response(status=204, body=b"")
        except (QueryError, ParseError, ValueError) as e:
            return Response.error(str(e))

    def h_status_tsdb(self, req: Request) -> Response:
        try:
            topn = int(req.arg("topN", "10"))
            date = req.arg("date")
            d = None
            if date:
                d = int(datetime.datetime.fromisoformat(date).timestamp()
                        // 86400)
            fl = self._matches_to_filters(req)
        except (ValueError, QueryError, ParseError) as e:
            return Response.error(f"bad arg: {e}", 400)
        kw = {}
        if fl:
            kw["filters"] = fl[0]  # drill-down selector (match[])
        focus = req.arg("focusLabel")
        if focus:
            kw["focus_label"] = focus
        try:
            st = self.storage.tsdb_status(d, topn, tenant=self._tenant(req),
                                          **kw)
        except TypeError:
            # cluster backend: no drill-down over RPC yet — serve the
            # unfiltered explorer rather than failing
            st = self.storage.tsdb_status(d, topn, tenant=self._tenant(req))
        return Response.json({"status": "success", "data": st})

    def h_relabel_debug(self, req: Request) -> Response:
        """Relabel debugger (reference /metric-relabel-debug +
        vmui's relabel playground): applies a relabel config to one metric
        step by step and returns every intermediate label set."""
        from ..ingest import parsers
        from ..ingest.relabel import parse_relabel_configs
        metric = req.arg("metric")
        cfg_text = req.arg("relabel_configs")
        if not metric:
            return Response.error("missing `metric` arg", 400)
        try:
            labels = dict(parsers.labels_from_series_key(
                metric.strip().encode()))
        except ValueError as e:
            return Response.error(f"cannot parse metric: {e}", 400)
        try:
            cfg = parse_relabel_configs(cfg_text or "")
        except (ValueError, KeyError) as e:
            return Response.error(f"cannot parse relabel config: {e}", 400)
        steps = []
        cur: dict | None = dict(labels)
        for rc in cfg.configs:
            before = dict(cur)
            cur = rc.apply(cur)
            desc = {"action": rc.action}
            if rc.source_labels:
                desc["source_labels"] = rc.source_labels
            if rc.regex_orig is not None:
                desc["regex"] = str(rc.regex_orig)
            if rc.target_label:
                desc["target_label"] = rc.target_label
            if rc.replacement != "$1":
                desc["replacement"] = rc.replacement
            steps.append({"rule": desc, "in": before,
                          "out": dict(cur) if cur is not None else None})
            if cur is None:
                break
        final = cfg.apply(dict(labels))
        return Response.json({"status": "success",
                              "originalLabels": labels,
                              "steps": steps,
                              "resultingLabels": final or None,
                              "dropped": not final})

    def h_prettify_query(self, req: Request) -> Response:
        """Canonicalize/pretty-print a MetricsQL expression (reference
        /prettify-query): parse -> AST -> formatted text. A parse error
        comes back as status=error with the message."""
        q = req.arg("query")
        try:
            expr = mql_parse(q)
        except (ParseError, QueryError) as e:
            return Response.json({"status": "error", "msg": str(e)})
        return Response.json({"status": "success", "query": str(expr)})

    def h_query_ast(self, req: Request) -> Response:
        """AST explorer for the vmui query analyzer: the parsed expression
        as a nested-node JSON tree."""
        q = req.arg("query")
        try:
            expr = mql_parse(q)
        except (ParseError, QueryError) as e:
            return Response.json({"status": "error", "msg": str(e)})

        def node(e):
            d = {"kind": type(e).__name__, "text": str(e)}
            kids = []
            for attr in ("args", ):
                for c in getattr(e, attr, []) or []:
                    if hasattr(c, "__class__") and hasattr(c, "__module__") \
                            and "ast" in type(c).__module__:
                        kids.append(node(c))
            for attr in ("expr", "left", "right"):
                c = getattr(e, attr, None)
                if c is not None and hasattr(type(c), "__module__") and \
                        "ast" in type(c).__module__:
                    kids.append(node(c))
            if kids:
                d["children"] = kids
            return d
        return Response.json({"status": "success", "ast": node(expr)})

    def h_faults(self, req: Request) -> Response:
        """Chaos fault-injection control (devtools/faultinject; the
        live half of the ``VM_FAULTS`` env seam).  GET lists the armed
        table; ``?set=<spec>`` replaces it; ``?clear=1`` disarms; 403
        unless the process opted into chaos (VM_FAULT_INJECT=1 or a
        VM_FAULTS table armed at start)."""
        from ..devtools import faultinject
        return faultinject.handle_http(req, Response)

    def h_active_queries(self, req: Request) -> Response:
        return Response.json({"status": "ok",
                              "data": self.active.snapshot()})

    def h_top_queries(self, req: Request) -> Response:
        n = int(req.arg("topN", "20"))
        tops = self.qstats.tops(n)
        return Response.json({
            "status": "ok",
            "topByCount": tops["count"],
            "topBySumDuration": tops["sumDuration"],
            "topByAvgDuration": tops["avgDuration"],
            # cumulative-cost orderings (utils/costacc): the most
            # EXPENSIVE queries, not just the slowest
            "topBySumCpuMs": tops["sumCpuMs"],
            "topBySumSamplesScanned": tops["sumSamplesScanned"],
        })

    def h_usage(self, req: Request) -> Response:
        """Per-tenant cumulative resource usage (/api/v1/status/usage):
        the costacc TENANT_USAGE table — samples scanned, bytes read,
        CPU ms, device/RPC bytes, rows returned and query count per
        tenant, most CPU-expensive tenant first.  On a vmselect these
        totals are CLUSTER-wide: the fan-out merges each node's shipped
        cost frame before the bracket records it.  ``?reset=1`` clears
        the table (bench/test hygiene)."""
        from ..utils import costacc
        rows = costacc.TENANT_USAGE.snapshot(
            reset=req.arg("reset") == "1")
        data = {"tenants": rows}
        ms = getattr(self, "matstreams", None)
        if ms is not None:
            # per-stream attribution: each row's totals are the SHARED
            # evaluations, counted once per interval — not multiplied by
            # the stream's subscriber count
            data["matstreams"] = ms.usage_rows()
            data["matstreamInstant"] = ms.instant_stats()
        return Response.json({
            "status": "success",
            "data": data,
        })

    def h_profile(self, req: Request) -> Response:
        """Continuous-profiler surface (/api/v1/status/profile):
        collapsed-stack text (default), ``?format=speedscope`` JSON, or
        ``?format=raw`` snapshots.  On a vmselect the local snapshot is
        merged with the profile_v1 fan-out, node-tagged.  503 when
        VM_PROFILE_HZ=0."""
        from ..utils import profiler
        # tag the local snapshot only when node-tagged fan-out snapshots
        # will sit next to it (a bare vmsingle keeps untagged roles)
        fanned = getattr(self.storage, "profile_report", None) is not None
        return profiler.handle_http(req, Response, storage=self.storage,
                                    local_node="vmselect" if fanned
                                    else None)

    def h_slow_queries(self, req: Request) -> Response:
        """The slow-query log (vmselect -search.logSlowQueryDuration
        analog, queryable): per-record duration, per-phase split, and
        the flight-capture id when the refresh tripped one."""
        return Response.json({
            "status": "ok",
            "thresholdMs": self.slowlog.threshold_ms(),
            "data": self.slowlog.snapshot(),
        })

    def h_quarantine(self, req: Request) -> Response:
        """Parts moved aside by the open-time integrity check (torn or
        bit-flipped files): the store serves WITHOUT them, every result
        is flagged partial, and this listing is the operator's recovery
        worksheet (restore from a replica/snapshot, or delete the
        quarantine dir to accept the loss)."""
        if getattr(self.storage, "reset_partial", None) is not None:
            self.storage.reset_partial()
        rep = (self.storage.quarantine_report()
               if getattr(self.storage, "quarantine_report", None)
               is not None else [])
        # partial covers BOTH quarantined parts and nodes whose report
        # could not be fetched — an unreachable node may be the one
        # holding torn parts, and this worksheet must never read clean
        # while that is possible
        partial = bool(rep) or \
            bool(getattr(self.storage, "last_partial", False))
        return Response.json({
            "status": "success",
            "data": {"quarantined": rep, "count": len(rep),
                     "partial": partial},
        })

    def h_flight(self, req: Request) -> Response:
        """Flight-recorder captures.  No args: list capture metadata
        (newest first).  ``?id=N``: that capture's Chrome trace-event
        JSON (load it in Perfetto / chrome://tracing).  ``?capture=1``:
        take an on-demand capture of the live window first."""
        if not flightrec.enabled():
            return Response.error(
                "flight recorder disabled (VM_FLIGHTREC=0)", 503,
                "unavailable")
        if req.arg("capture") == "1":
            cap = flightrec.RECORDER.capture(
                "on_demand", meta={"source": "http"})
            return Response.json({
                "status": "ok", "captured": cap["id"],
                "data": flightrec.RECORDER.list()})
        cap_id = req.arg("id")
        if cap_id:
            try:
                cap = flightrec.RECORDER.get(int(cap_id))
            except ValueError:
                return Response.error(f"bad capture id {cap_id!r}")
            if cap is None:
                return Response.error(f"no capture with id {cap_id} "
                                      f"(captures are a bounded ring; "
                                      f"it may have aged out)", 404,
                                      "not_found")
            # the bare trace object: saving the response body to a file
            # makes it directly Perfetto-loadable
            return Response.json(cap["trace"])
        return Response.json({"status": "ok",
                              "data": flightrec.RECORDER.list()})

    # -- SLO plane / health ------------------------------------------------

    def init_sloplane(self):
        """Get-or-create the SLO engine (idempotent).  Lazy so a
        process that never enables self-scrape nor touches the SLO
        endpoints pays nothing."""
        if self.sloplane is None:
            from ..query.sloplane import SLOEngine
            self.sloplane = SLOEngine(self, role=self._role)
        return self.sloplane

    def h_slo(self, req: Request) -> Response:
        """Burn-rate dashboard (/api/v1/status/slo): every objective's
        per-window burn rates, remaining error budget, firing pairs and
        open incident id.  ``?pump=1`` forces an eval round first (the
        deterministic seam tests and operators poke instead of waiting
        out the interval)."""
        eng = self.init_sloplane()
        if req.arg("pump") == "1":
            eng.maybe_eval(force=True)
        return Response.json(eng.status())

    def h_incidents(self, req: Request) -> Response:
        """The incident ring (/api/v1/status/incidents).  No args:
        newest-first summaries.  ``?id=N``: the full frozen record —
        burn state, flight-capture id, profiler snapshot, top queries,
        tenant cost and the health verdict at breach time."""
        eng = self.init_sloplane()
        inc_id = req.arg("id")
        if inc_id:
            try:
                rec = eng.incidents.get(int(inc_id))
            except ValueError:
                return Response.error(f"bad incident id {inc_id!r}")
            if rec is None:
                return Response.error(
                    f"no incident with id {inc_id} (bounded ring; it "
                    f"may have aged out)", 404, "not_found")
            return Response.json({"status": "success", "data": rec})
        return Response.json({"status": "success",
                              "data": eng.incidents.list()})

    def h_health(self, req: Request) -> Response:
        """The health roll-up (/api/v1/status/health): one verdict
        ``ok|degraded|critical`` with machine-readable reasons.  On a
        vmselect this fans health_v1 across the storage nodes and
        merges liveness/ring state; the verdict names the nodes."""
        from ..query import sloplane
        return Response.json(sloplane.health_for_api(
            self, engine=self.sloplane, role=self._role))

    def _track_usage(self, rows):
        now = fasttime.unix_timestamp()
        for r in rows:
            g = r.metric_name.metric_group
            if not g:
                continue
            name = g.decode("utf-8", "replace")
            e = self.name_usage.get(name)
            if e is None:
                if len(self.name_usage) >= 100_000:
                    continue
                e = self.name_usage[name] = [0, 0]
            e[0] += 1
            e[1] = now

    def h_metadata(self, req: Request) -> Response:
        """Prometheus /api/v1/metadata shape. Merges the API-local store
        with storage-resident metadata (on a cluster vmselect that is the
        searchMetadata RPC fan-out)."""
        limit = int(req.arg("limit", "0") or 0)
        metric = req.arg("metric", "")
        merged = dict(self.metadata)
        if getattr(self.storage, "search_metadata", None) is not None:
            try:
                merged.update(self.storage.search_metadata(
                    limit or 100_000, metric))
            except Exception as e:
                logger.errorf("search_metadata: %s", e)
        data = {}
        for name, md in merged.items():
            if metric and name != metric:
                continue
            data[name] = [{"type": md.get("type") or "unknown",
                           "help": md.get("help", ""), "unit": ""}]
            if limit and len(data) >= limit:
                break
        return Response.json({"status": "success", "data": data})

    def h_name_stats(self, req: Request) -> Response:
        """Per-metric-name query usage (the reference's
        /api/v1/status/metric_names_stats, lib/storage/metricnamestats).
        Merges the API-local tracker with storage-resident stats (on a
        cluster vmselect that is the metricNamesUsageStats RPC)."""
        limit = int(req.arg("limit", "1000") or 1000)
        le = req.arg("le", "")
        # storage-resident stats are authoritative when available (the
        # reference serves these from vmstorage); the API-local tracker
        # records the SAME query events, so merging would double-count
        if getattr(self.storage, "metric_names_usage_stats",
                   None) is not None:
            try:
                items = self.storage.metric_names_usage_stats(
                    limit, int(le) if le else None)
                return Response.json(
                    {"status": "success",
                     "statsCollectedSince": int(self.started_at),
                     "records": items})
            except Exception as e:
                logger.errorf("metric_names_usage_stats: %s", e)
        items = [{"metricName": n, "requestsCount": c,
                  "lastRequestTimestamp": t}
                 for n, (c, t) in self.name_usage.items()]
        if le:
            items = [x for x in items if x["requestsCount"] <= int(le)]
        items.sort(key=lambda x: x["requestsCount"])
        return Response.json({"status": "success",
                              "statsCollectedSince": int(self.started_at),
                              "records": items[:limit]})

    def h_reset_name_stats(self, req: Request) -> Response:
        """/api/v1/admin/status/metric_names_stats/reset."""
        self.name_usage.clear()
        if getattr(self.storage, "reset_metric_names_stats",
                   None) is not None:
            self.storage.reset_metric_names_stats()
        return Response.json({"status": "success"})

    flags_map: dict | None = None  # set by apps for the /flags page

    def h_flags(self, req: Request) -> Response:
        """Flag values page (lib/httpserver/httpserver.go:400 /flags)."""
        flags = self.flags_map or {}
        body = "".join(f"{k}={v}\n" for k, v in sorted(flags.items()))
        return Response.text(body or "# no flags registered\n")

    def h_pprof(self, req: Request) -> Response:
        """Pythonic /debug/pprof/: goroutine analog = thread stacks;
        profile = cProfile over `seconds` of live traffic."""
        kind = req.path.rsplit("/", 1)[-1]
        if kind in ("goroutine", "threads", ""):
            import sys
            import traceback
            names = {t.ident: t.name for t in threading.enumerate()}
            parts = []
            for tid, frame in sys._current_frames().items():
                parts.append(f"Thread {names.get(tid, '?')} ({tid}):\n" +
                             "".join(traceback.format_stack(frame)))
            return Response.text("\n".join(parts))
        if kind == "profile":
            import cProfile
            import io as _io
            import pstats
            seconds = min(float(req.arg("seconds", "5")), 60.0)
            pr = cProfile.Profile()
            pr.enable()
            time.sleep(seconds)
            pr.disable()
            buf = _io.StringIO()
            pstats.Stats(pr, stream=buf).sort_stats("cumulative")\
                .print_stats(60)
            return Response.text(buf.getvalue())
        return Response.error(f"unsupported pprof kind {kind!r}", 404,
                              "not_found")

    def app_metrics(self) -> dict:
        """The app-level counters layered over the central registry —
        one collection shared by the /metrics exposition AND the
        self-scrape plane, so the scraped history matches what an
        external Prometheus would see sample-for-sample."""
        m = dict(self.storage.metrics()) \
            if getattr(self.storage, "metrics", None) is not None else {}
        srv = getattr(self, "srv", None)
        if srv is not None:
            m["vm_http_requests_all_total"] = srv.request_count
        else:
            m["vm_http_requests_all_total"] = 0
        m["vm_rows_inserted_total"] = self.rows_inserted
        m["vm_relabel_metrics_dropped_total"] = self.rows_relabel_dropped
        if self.rate_limiter is not None and \
                self.rate_limiter.global_rl is not None:
            m["vm_max_ingestion_rate_limit_reached_total"] = \
                self.rate_limiter.global_rl.limit_reached
        if self.series_limits is not None:
            m.update(self.series_limits.metrics())
        m["vm_concurrent_select_limit_reached_total"] = self.gate.rejected
        for lvl, cnt in logger.message_counters().items():
            m[metricslib.format_name("vm_log_messages_total",
                                     {"level": lvl})] = cnt
        for tkey, cnt in self.tenant_rows.items():
            m[f"vm_tenant_inserted_rows_total{tkey}"] = cnt
        return m

    def h_metrics(self, req: Request) -> Response:
        """Prometheus exposition for the whole process: the central
        registry (per-path HTTP histograms, cache hit/miss, RPC
        durations, TPU kernel split, process_*) plus the app-level
        counters collected here."""
        return Response.text(metricslib.REGISTRY.write_prometheus(
            extra=self.app_metrics()))

    def h_snapshot_create(self, req: Request) -> Response:
        name = self.storage.create_snapshot()
        return Response.json({"status": "ok", "snapshot": name})

    def h_snapshot_list(self, req: Request) -> Response:
        return Response.json({"status": "ok",
                              "snapshots": self.storage.list_snapshots()})

    def h_snapshot_delete(self, req: Request) -> Response:
        name = req.arg("snapshot")
        if self.storage.delete_snapshot(name):
            return Response.json({"status": "ok"})
        return Response.error(f"snapshot {name!r} not found", 404)

    def h_snapshot_delete_all(self, req: Request) -> Response:
        for name in self.storage.list_snapshots():
            self.storage.delete_snapshot(name)
        return Response.json({"status": "ok"})

    def h_force_flush(self, req: Request) -> Response:
        self.storage.force_flush()
        return Response.text("OK")

    def h_force_merge(self, req: Request) -> Response:
        self.storage.force_merge()
        return Response.text("OK")
