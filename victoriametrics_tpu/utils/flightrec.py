"""Flight recorder: cross-thread latency attribution for the serving
hot path (the per-event sibling of the aggregate phase counters, and
the cross-thread extension of the reference's query tracer + slow-query
log: lib/querytracer sees one query's own spans, this sees what ELSE the
process was doing while the query ran).

Always-on, low-overhead: every thread that records owns a private
fixed-capacity ring of (t0, dur, name, ctx, arg) event slots.  The ring
arrays are preallocated at first use; the record path is index
arithmetic + five slot stores + one integer bump — no allocation, no
lock, no syscall.  Writers never synchronize with readers: a capture
snapshots each ring's write cursor and walks backward, and any slot the
writer overtook mid-read is discarded by re-checking the cursor (the
classic seqlock-reader discipline, per-slot granularity is one event so
a torn event can only be dropped, never misattributed).

Event model: COMPLETE spans (Chrome trace ``"ph": "X"``) recorded at
END time — callers time the region themselves (they already do, for the
phase counters) and call :func:`rec` once.  Instant events
(``"ph": "i"``) mark decisions (cache inplace/rebuild, merge-gate
yields).  Timestamps are ``time.perf_counter()`` floats — one monotonic
clock shared by every thread, so cross-thread overlap is meaningful.

Cross-thread attribution: a serving thread opens a *flight context*
(:func:`set_ctx`, an integer id per refresh/query); utils/workpool
propagates the submitting thread's ctx to its pool workers around each
task, so fetch/decode spans executed on workers carry the query's ctx
and :func:`ctx_events` can reassemble one query's work from every
thread's ring (the per-phase split the slow-query log records).

Capture: :meth:`FlightRecorder.capture` merges the live window of all
thread rings into one Chrome trace-event-format JSON object
(Perfetto/chrome://tracing-loadable) and keeps it in a bounded ring of
recent captures served at ``/api/v1/status/flight``.  The serving layer
triggers a capture when a refresh exceeds ``VM_SLOW_REFRESH_MS``;
anything can trigger one on demand.

``VM_FLIGHTREC=0`` is the escape hatch: :func:`rec`/:func:`instant`
return after one global-flag check and captures return empty.

Self-metrics: ``vm_flight_captures_total``,
``vm_flight_dropped_events_total`` (ring-overwritten events noticed at
capture time), ``vm_flight_events_total`` is deliberately absent — a
per-event counter bump would double the record cost.
"""

from __future__ import annotations

import os
import threading
import time

__all__ = ["enabled", "rec", "instant", "span", "new_ctx", "set_ctx",
           "get_ctx", "ctx_events", "clear_ctx", "RECORDER",
           "FlightRecorder", "reconfigure"]

#: ring capacity per thread (events); power of two for mask arithmetic
_DEFAULT_CAP = 1 << 13


def _env_enabled() -> bool:
    return os.environ.get("VM_FLIGHTREC", "1") != "0"


def _env_cap() -> int:
    try:
        n = int(os.environ.get("VM_FLIGHTREC_EVENTS", "0"))
    except ValueError:
        n = 0
    if n <= 0:
        return _DEFAULT_CAP
    # round up to a power of two (the record path uses `& mask`)
    return 1 << max(n - 1, 1).bit_length()


_ENABLED = _env_enabled()


def enabled() -> bool:
    """True when the recorder is on (``VM_FLIGHTREC`` != 0)."""
    return _ENABLED


def reconfigure() -> None:
    """Re-read ``VM_FLIGHTREC`` (tests flip the env var mid-process;
    production reads it once at import)."""
    global _ENABLED
    _ENABLED = _env_enabled()


class _Ring:
    """One thread's event ring.  Only the owner thread writes; capture
    threads read racily and validate against the cursor afterward.

    Slots are parallel preallocated lists (not tuples): a record is five
    slot stores + one cursor bump, allocating nothing."""

    __slots__ = ("t0", "dur", "name", "ctx", "arg", "i", "w", "cap",
                 "mask", "tid", "tname", "taken", "thread")

    def __init__(self, cap: int, thread: threading.Thread):
        self.t0 = [0.0] * cap
        self.dur = [0.0] * cap
        self.name = [""] * cap
        self.ctx = [0] * cap
        self.arg = [None] * cap
        self.i = 0          # monotonic write cursor (slot = i & mask)
        self.w = -1         # cursor mid-store marker: w == i <=> in rec()
        self.cap = cap
        self.mask = cap - 1
        self.tid = thread.ident or 0
        self.tname = thread.name
        self.taken = 0      # first cursor NOT yet included in a capture
        self.thread = thread    # liveness probe for ring reclamation

    def newest_t0(self) -> float:
        """t0 of the most recent event (0.0 when empty); racy read, only
        meaningful for DEAD owners (no concurrent writer)."""
        if self.i == 0:
            return 0.0
        return self.t0[(self.i - 1) & self.mask]

    def snapshot(self, min_t0: float) -> list[tuple]:
        """Racy read of the live window: events with t0 >= min_t0, oldest
        first.  Slots overwritten while reading are re-checked against the
        advanced cursor and dropped (seqlock-reader discipline)."""
        end = self.i
        lo = max(end - self.cap, 0)
        out = []
        t0s, durs, names, ctxs, args = (self.t0, self.dur, self.name,
                                        self.ctx, self.arg)
        mask = self.mask
        for k in range(lo, end):
            j = k & mask
            t0 = t0s[j]
            if t0 < min_t0:
                continue
            out.append((t0, durs[j], names[j], ctxs[j], args[j], k))
        # validate: any slot the writer lapped during the walk holds a
        # NEWER event than its cursor position promised — discard those.
        # STRICT bound: the writer stores the five slots BEFORE bumping
        # the cursor, so the slot at cursor (i - cap) may be mid-store
        # (torn) while i still reads one low — drop it too.  Costs at
        # most the single oldest event of an idle full ring; keeps the
        # "can drop, never misattribute" guarantee.
        min_keep = self.i - self.cap
        if min_keep >= lo:
            out = [e for e in out if e[5] > min_keep]
        return out


_tls = threading.local()

# every ring ever created (threads die, their last events remain
# capturable); appended under _rings_lock, iterated lock-free by capture
_rings: list[_Ring] = []
_rings_lock = threading.Lock()

_ctx_counter = [0]
_ctx_lock = threading.Lock()


def _prune_dead_rings(min_t0: float) -> None:
    """Drop rings whose owner thread died AND whose newest event has
    aged out of the capture window.  Without this, one ring per
    recording thread (e.g. per-connection HTTP handler threads) leaks
    forever; with it, a dead thread's last events stay capturable for
    the window and the ring list stays bounded by live threads +
    recently-dead ones.  Caller holds _rings_lock."""
    keep = [r for r in _rings
            if r.thread.is_alive() or r.newest_t0() >= min_t0]
    if len(keep) != len(_rings):
        _rings[:] = keep


def _prune_window_s() -> float:
    try:
        return float(os.environ.get("VM_FLIGHT_WINDOW_S", "60"))
    except ValueError:
        return 60.0


def _new_ring() -> _Ring:
    ring = _Ring(_env_cap(), threading.current_thread())
    with _rings_lock:
        _prune_dead_rings(time.perf_counter() - _prune_window_s())
        _rings.append(ring)
    return ring


def rec(name: str, t0: float, dur: float, arg=None) -> None:
    """Record one complete span [t0, t0+dur) (perf_counter seconds) on
    the calling thread's ring.  The hot-path primitive: one flag check,
    one TLS lookup, five slot stores, one cursor bump."""
    if not _ENABLED:
        return
    ring = getattr(_tls, "ring", None)
    if ring is None:
        ring = _tls.ring = _new_ring()
    i = ring.i
    # w == i marks this slot mid-store: the gc hook (which can fire
    # DURING these stores — the cursor bump's int allocation can
    # trigger a collection) checks it and stands down instead of
    # interleaving a second event into the same slot
    ring.w = i
    j = i & ring.mask
    ring.t0[j] = t0
    ring.dur[j] = dur
    ring.name[j] = name
    ring.ctx[j] = getattr(_tls, "ctx", 0)
    ring.arg[j] = arg
    ring.i = i + 1


def instant(name: str, arg=None) -> None:
    """Record a zero-duration marker (a decision, not a region)."""
    if not _ENABLED:
        return
    rec(name, time.perf_counter(), 0.0, arg)


class _Span:
    """``with flightrec.span("name"):`` — times the body and records one
    complete event on exit (even when the body raises)."""

    __slots__ = ("name", "arg", "t0")

    def __init__(self, name: str, arg=None):
        self.name = name
        self.arg = arg

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        rec(self.name, self.t0, time.perf_counter() - self.t0, self.arg)
        return False


def span(name: str, arg=None) -> _Span:
    return _Span(name, arg)


# -- flight context (cross-thread query attribution) --------------------------

def new_ctx() -> int:
    """Fresh nonzero context id for one query/refresh."""
    with _ctx_lock:
        _ctx_counter[0] += 1
        return _ctx_counter[0]


def set_ctx(ctx: int) -> int:
    """Install `ctx` as the calling thread's flight context; returns the
    previous one (callers restore it).  utils/workpool calls this around
    each task with the submitter's ctx."""
    prev = getattr(_tls, "ctx", 0)
    _tls.ctx = ctx
    return prev


def get_ctx() -> int:
    return getattr(_tls, "ctx", 0)


def clear_ctx() -> None:
    _tls.ctx = 0


def note_capture(cap_id: int) -> None:
    """Thread-local hand-off: the serving layer notes the capture id a
    slow refresh just produced so the HTTP handler (same thread, outer
    frame) can attach it to the slow-query record."""
    _tls.noted_capture = cap_id


def take_noted_capture() -> int | None:
    cap_id = getattr(_tls, "noted_capture", None)
    _tls.noted_capture = None
    return cap_id


def ctx_events(ctx: int, window_s: float = 120.0) -> list[tuple]:
    """Every live ring event carrying `ctx`, merged across threads and
    sorted by t0: (t0, dur, name, tid).  The slow-query log uses this to
    compute a per-phase split for ONE query even though the phase spans
    ran on several pool workers."""
    if ctx == 0:
        return []
    min_t0 = time.perf_counter() - window_s
    with _rings_lock:
        rings = list(_rings)
    out = []
    for ring in rings:
        for t0, dur, name, c, _arg, _k in ring.snapshot(min_t0):
            if c == ctx:
                out.append((t0, dur, name, ring.tid))
    out.sort(key=lambda e: e[0])
    return out


def phase_split(ctx: int, window_s: float = 120.0) -> dict[str, float]:
    """Per-name span seconds for one flight context (the slow-query
    log's per-phase split), summed across every thread that worked on
    the query."""
    split: dict[str, float] = {}
    for _t0, dur, name, _tid in ctx_events(ctx, window_s):
        if dur > 0.0:
            split[name] = split.get(name, 0.0) + dur
    return split


# -- capture ------------------------------------------------------------------

class FlightRecorder:
    """Owner of the bounded capture ring.  One process-wide instance
    (:data:`RECORDER`); tests may build private ones (they share the
    thread rings — captures differ only in their retention ring)."""

    def __init__(self, max_captures: int | None = None):
        if max_captures is None:
            try:
                max_captures = int(os.environ.get("VM_FLIGHT_CAPTURES", "8"))
            except ValueError:
                max_captures = 8
        import collections
        self._lock = threading.Lock()
        # builds serialize on their own lock so a serving-path
        # capture(defer_build=True) — which only needs _lock for the
        # id/append — never stalls behind a retrieval building traces
        self._build_lock = threading.Lock()
        self._captures: "collections.deque[dict]" = collections.deque(
            maxlen=max(max_captures, 1))
        self._next_id = 0
        from . import metrics as metricslib
        self._captures_total = metricslib.REGISTRY.counter(
            "vm_flight_captures_total")
        self._dropped_total = metricslib.REGISTRY.counter(
            "vm_flight_dropped_events_total")

    # .. capture ..............................................................

    def capture(self, reason: str, window_s: float | None = None,
                meta: dict | None = None,
                defer_build: bool = False) -> dict | None:
        """Merge the live window of every thread ring into one Chrome
        trace-event JSON object and retain it.  Returns the capture
        record (meta + ``"trace"``), or None when the recorder is off.

        ``defer_build=True`` (the slow-refresh trigger path) does only
        the part that races the writers — snapshotting the rings — and
        postpones building the trace dicts and attribution summary until
        first retrieval, so the cost charged to the slow refresh itself
        (and to the latency its trigger is measuring — the observer
        effect) is the raw slot copy, not the JSON assembly."""
        if not _ENABLED:
            return None
        if window_s is None:
            window_s = _prune_window_s()
        now = time.perf_counter()
        min_t0 = now - window_s
        with _rings_lock:
            # reclaim dead-thread rings past the RETENTION window (not
            # this capture's, which may be narrower)
            _prune_dead_rings(
                now - max(window_s, _prune_window_s()))
            rings = list(_rings)
        snaps = []
        dropped = 0
        for ring in rings:
            snap = ring.snapshot(min_t0)
            # overwritten-before-capture accounting: cursor positions
            # below (i - cap) that no capture ever included are gone.
            # ring.taken is only ever touched by captures — serialize
            # the read-modify-write under _rings_lock so two concurrent
            # captures can't double-count the same lost events
            with _rings_lock:
                lost_floor = ring.i - ring.cap
                if lost_floor > ring.taken:
                    dropped += lost_floor - ring.taken
                    ring.taken = lost_floor
                if snap:
                    # first-uncaptured, hence the +1: snap[-1][5] itself
                    # WAS captured — counting it as lost on the next
                    # wrap would report drops on a lossless system
                    ring.taken = max(ring.taken, snap[-1][5] + 1)
            if snap:
                # tid/tname, not the ring itself: holding the ring would
                # keep a dead thread's slot arrays alive past the prune
                snaps.append((ring.tid, ring.tname, snap))
        if dropped:
            self._dropped_total.inc(dropped)
        from . import fasttime
        cap = {
            "reason": reason,
            "unix_ms": fasttime.unix_ms(),
            "window_s": window_s,
            "n_events": sum(len(s) for _t, _n, s in snaps),
            "n_threads": len(snaps),
            "_raw": (snaps, now),
        }
        if meta:
            cap.update(meta)
        with self._lock:
            self._next_id += 1
            cap["id"] = self._next_id
            self._captures.append(cap)
        self._captures_total.inc()
        if not defer_build:
            self._build(cap)
        return cap

    def _build(self, cap: dict) -> None:
        """Turn a capture's raw ring snapshots into ``cap["trace"]`` +
        ``cap["summary"]`` (idempotent; concurrent retrievals serialize
        on the build lock, so the loser waits and then sees the winner's
        finished build instead of a half-written capture)."""
        with self._build_lock:
            raw = cap.pop("_raw", None)
            if raw is None:
                return
            snaps, now = raw
            # trace timestamps are µs relative to the window start, so
            # the Perfetto timeline starts at ~0 regardless of process
            # uptime.  Global min over ALL events: rings are in
            # COMPLETION order (spans record at end time), so a ring's
            # first entry is not its earliest t0 — an enclosing span
            # lands after its children and would otherwise get a
            # negative ts
            epoch = min((e[0] for _tid, _tn, snap in snaps for e in snap),
                        default=now)
            trace_events = []
            pid = os.getpid()
            for tid, tname, snap in snaps:
                trace_events.append({
                    "name": "thread_name", "ph": "M", "pid": pid,
                    "tid": tid, "args": {"name": tname}})
                for t0, dur, name, ctx, arg, _k in snap:
                    ev = {"name": name, "ph": "X", "pid": pid, "tid": tid,
                          "ts": round((t0 - epoch) * 1e6, 1),
                          "dur": round(dur * 1e6, 1)}
                    if dur == 0.0:
                        ev["ph"] = "i"
                        ev["s"] = "t"
                        del ev["dur"]
                    args = {}
                    if ctx:
                        args["ctx"] = ctx
                    if arg is not None:
                        args["arg"] = arg
                    if args:
                        ev["args"] = args
                    trace_events.append(ev)
            trace_events.sort(key=lambda e: e.get("ts", 0.0))
            cap["trace"] = {"traceEvents": trace_events,
                            "displayTimeUnit": "ms"}
            cap["summary"] = summarize(
                trace_events, focus_ctx=cap.get("ctx", 0))

    # .. retrieval ............................................................

    def total(self) -> int:
        """Monotonic count of captures ever taken (ids are 1..total);
        unlike ``len(list())`` it is not bounded by the retention ring."""
        with self._lock:
            return self._next_id

    def list(self) -> list[dict]:
        """Capture metadata, newest first (everything but the trace)."""
        with self._lock:
            caps = list(self._captures)
        for c in caps:
            self._build(c)
        return [{k: v for k, v in c.items() if k != "trace"}
                for c in reversed(caps)]

    def get(self, cap_id: int) -> dict | None:
        with self._lock:
            found = None
            for c in self._captures:
                if c["id"] == cap_id:
                    found = c
                    break
        if found is not None:
            self._build(found)
        return found

    def clear(self) -> None:
        with self._lock:
            self._captures.clear()


def summarize(trace_events: list[dict], focus_ctx: int = 0) -> dict:
    """Attribution summary of one capture: total span ms by event name,
    plus — when the capture contains serve:refresh spans — the slowest
    refresh and the background work overlapping it by category (the
    "which work overlapped the slow refresh" answer, precomputed so the
    JSON artifact and the HTTP list are readable without Perfetto).

    `focus_ctx` pins WHICH refresh gets the overlap treatment: a
    slow-refresh-triggered capture passes the triggering refresh's
    flight context so the summary explains THAT refresh, not whatever
    bigger serve span (e.g. the cold first eval) shares the window.
    0 (on-demand captures) falls back to the slowest serve span."""
    by_name: dict[str, float] = {}
    serves = []
    for ev in trace_events:
        if ev["ph"] != "X":
            continue
        dur = ev.get("dur", 0.0)
        by_name[ev["name"]] = by_name.get(ev["name"], 0.0) + dur
        if ev["name"] == "serve:refresh":
            serves.append(ev)
    out = {"span_ms_by_name": {k: round(v / 1e3, 3)
                               for k, v in sorted(by_name.items())}}
    if focus_ctx:
        focused = [e for e in serves
                   if e.get("args", {}).get("ctx", 0) == focus_ctx]
        serves = focused or serves
    if serves:
        slow = max(serves, key=lambda e: e["dur"])
        s0, s1 = slow["ts"], slow["ts"] + slow["dur"]
        sctx = slow.get("args", {}).get("ctx", 0)
        overlap: dict[str, list] = {}
        waiting: dict[str, list] = {}
        for ev in trace_events:
            if ev["ph"] != "X" or ev is slow:
                continue
            # overlap of [ts, ts+dur) with the slow serve window,
            # excluding the serve's own work (same ctx) — what's left is
            # the INTERFERING work the refresh had to share cores with.
            # ctx-only, NOT tid: ambient work that ran ON the serve
            # thread (a gc pause, a foreign pool task the blocked serve
            # thread helped with) carries ctx 0 / another ctx and IS
            # part of the latency story
            if ev.get("args", {}).get("ctx", 0) == sctx:
                continue
            lo = max(ev["ts"], s0)
            hi = min(ev["ts"] + ev.get("dur", 0.0), s1)
            if hi <= lo:
                continue
            name = ev["name"]
            # pure waits are DEFERENCE, not interference: a merge
            # sleeping in the serve-priority yield (or queued at a gate)
            # consumed no CPU during the refresh — charging it as
            # "merge overlap" would invert the attribution.  Reported
            # separately so the deference is still visible.  (lock:*
            # waits stay in the overlap buckets: a thread stalled on a
            # lock a serve-path thread holds IS part of the story.)
            if name.endswith((":queue_wait", ":gate_wait", ":yield")):
                waiting.setdefault(name, []).append((lo, hi))
                continue
            cat = name.split(":", 1)[0]
            overlap.setdefault(cat, []).append((lo, hi))
        # interval UNION per bucket, not a sum: nested spans (the
        # flush:table fan span contains its workers' flush:part spans)
        # and repeated waits would otherwise report more overlap than
        # the refresh's own duration.  The number is wall-clock coverage
        # ("merge work was running for X of the refresh's Y ms"), not
        # cpu-seconds.
        out["slow_refresh"] = {
            "ms": round(slow["dur"] / 1e3, 3),
            "ctx": sctx,
            "arg": slow.get("args", {}).get("arg"),
            "overlap_ms_by_category": {
                k: round(_union(v) / 1e3, 3)
                for k, v in sorted(overlap.items())},
            "waiting_ms_by_name": {
                k: round(_union(v) / 1e3, 3)
                for k, v in sorted(waiting.items())},
        }
    return out


def _union(intervals: list) -> float:
    """Total length of the union of [lo, hi) intervals."""
    total = 0.0
    cur_lo = cur_hi = None
    for lo, hi in sorted(intervals):
        if cur_hi is None or lo > cur_hi:
            if cur_hi is not None:
                total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        elif hi > cur_hi:
            cur_hi = hi
    if cur_hi is not None:
        total += cur_hi - cur_lo
    return total


#: the process-wide recorder behind /api/v1/status/flight
RECORDER = FlightRecorder()


def slow_refresh_threshold_ms() -> float:
    """``VM_SLOW_REFRESH_MS``: refreshes slower than this trigger a
    flight capture on the serving path (0 disables the trigger; the
    default 1000ms only fires on genuinely pathological refreshes —
    bench.py lowers it adaptively around its measured baseline)."""
    try:
        return float(os.environ.get("VM_SLOW_REFRESH_MS", "1000"))
    except ValueError:
        return 1000.0


# -- gc visibility ------------------------------------------------------------

def _gc_hook(t0: float, dur: float, gen) -> None:
    # gc callbacks fire on whatever thread triggered the collection —
    # possibly INSIDE rec()'s slot stores, or inside a _rings_lock
    # critical section (ring creation / capture allocate).  Recording
    # would then tear the in-progress slot or self-deadlock taking the
    # non-reentrant lock from _new_ring, so: only record when this
    # thread already owns a ring and is not mid-record.  (A nested
    # collection can't fire inside THIS rec — gc suppresses reentrant
    # collections while callbacks run.)
    if not _ENABLED:
        return
    ring = getattr(_tls, "ring", None)
    if ring is None or ring.w == ring.i:
        return
    # ctx 0, not the thread's current query ctx: a gc pause is ambient
    # process work, and charging it to the query would hide it from the
    # capture summary's interference buckets (own-ctx work is excluded)
    prev = getattr(_tls, "ctx", 0)
    _tls.ctx = 0
    try:
        rec(f"gc:gen{gen}", t0, dur)
    finally:
        _tls.ctx = prev


def install_gc_events() -> None:
    """Record every gc collection as a flight span on the thread that
    triggered it (gc pauses are a serving-latency suspect).  Piggybacks
    on utils/metrics' single gc callback — the one timing of each
    collection feeds both vm_gc_pause_seconds_total and the timeline."""
    from . import metrics as metricslib
    if _gc_hook not in metricslib.gc_pause_hooks:
        metricslib.gc_pause_hooks.append(_gc_hook)


install_gc_events()
