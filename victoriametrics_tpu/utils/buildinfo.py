"""Build/identity info (reference lib/buildinfo): the version string
exported as ``vm_app_version{version=,short_version=}`` and the default
``instance=`` identity for the self-scrape plane.

The reference stamps the binary at link time; here the "build" is the
package, so the version is the package version plus the git short hash
when one is discoverable (best effort, never an error — a tarball
checkout simply reports the bare version).
"""

from __future__ import annotations

import os

#: bumped with the repo's PR sequence (the closest analog of a release
#: tag for a growing reproduction)
SHORT_VERSION = "0.17.0"

_APP_NAME = "victoria-metrics-tpu"


def _git_rev() -> str:
    """Best-effort short commit hash, read straight from .git (no
    subprocess: this runs at import time on every app start)."""
    d = os.path.dirname(os.path.abspath(__file__))
    for _ in range(6):
        git = os.path.join(d, ".git")
        if os.path.isdir(git):
            try:
                with open(os.path.join(git, "HEAD")) as f:
                    head = f.read().strip()
                if head.startswith("ref:"):
                    ref = head.split(None, 1)[1]
                    with open(os.path.join(git, ref)) as f:
                        head = f.read().strip()
                return head[:12]
            except OSError:
                return ""
        d = os.path.dirname(d)
    return ""


_REV = _git_rev()


def short_version() -> str:
    return SHORT_VERSION


def version() -> str:
    """Full version string (reference buildinfo.Version shape:
    ``victoria-metrics-<version>-<rev>``)."""
    if _REV:
        return f"{_APP_NAME}-{SHORT_VERSION}-{_REV}"
    return f"{_APP_NAME}-{SHORT_VERSION}"
