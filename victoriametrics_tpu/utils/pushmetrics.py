"""Periodic /metrics push to remote endpoints (reference lib/pushmetrics +
the vendored metrics.InitPush): every interval, collect the metrics text and
POST it to each -pushmetrics.url with extra labels appended."""

from __future__ import annotations

import threading
import urllib.request

from . import logger


class MetricsPusher:
    def __init__(self, urls: list[str], collect_fn, interval_s: float = 10.0,
                 extra_labels: str = ""):
        """collect_fn() -> prometheus text exposition string."""
        self.urls = urls
        self.collect_fn = collect_fn
        self.interval_s = interval_s
        self.extra_labels = extra_labels
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self.pushes = 0
        self.errors = 0

    def start(self):
        if self.urls:
            self._thread.start()

    def stop(self):
        self._stop.set()

    def _render(self) -> bytes:
        text = self.collect_fn()
        if not self.extra_labels:
            return text.encode()
        out = []
        for line in text.splitlines():
            if not line or line.startswith("#"):
                out.append(line)
                continue
            name, _, rest = line.partition(" ")
            if "{" in name:
                base, _, tail = name.partition("{")
                out.append(f"{base}{{{self.extra_labels},{tail} {rest}")
            else:
                out.append(f"{name}{{{self.extra_labels}}} {rest}")
        return "\n".join(out).encode()

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                body = self._render()
                for url in self.urls:
                    try:
                        req = urllib.request.Request(
                            url, data=body, method="POST",
                            headers={"Content-Type": "text/plain"})
                        with urllib.request.urlopen(req, timeout=10):
                            self.pushes += 1
                    except OSError as e:
                        self.errors += 1
                        logger.throttled_warnf("pushmetrics", 30,
                                               "pushmetrics %s: %s", url, e)
            except Exception as e:  # collect_fn error must not kill the loop
                self.errors += 1
                logger.throttled_warnf("pushmetrics-collect", 30,
                                       "pushmetrics collect: %s", e)
