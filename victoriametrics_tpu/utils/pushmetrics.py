"""Periodic /metrics push to remote endpoints (reference lib/pushmetrics +
the vendored metrics.InitPush): every interval, collect the metrics text and
POST it to each -pushmetrics.url with extra labels appended."""

from __future__ import annotations

import gzip
import threading
import urllib.request

from . import logger
from .metrics import REGISTRY, splice_extra_labels


class MetricsPusher:
    def __init__(self, urls: list[str], collect_fn, interval_s: float = 10.0,
                 extra_labels: str = ""):
        """collect_fn() -> prometheus text exposition string."""
        self.urls = urls
        self.collect_fn = collect_fn
        self.interval_s = interval_s
        self.extra_labels = extra_labels
        self._stop = threading.Event()
        # one long-lived push ticker per process — not fan-out work
        self._thread = threading.Thread(  # vmt: disable=VMT011
            target=self._loop, daemon=True)
        # registry-backed (reference metrics_push_total /
        # metrics_push_errors_total, vendor/.../metrics/push.go:128)
        self._pushes = REGISTRY.counter("vm_pushmetrics_pushes_total")
        self._errors = REGISTRY.counter("vm_pushmetrics_errors_total")

    @property
    def pushes(self) -> int:
        return self._pushes.get()

    @property
    def errors(self) -> int:
        return self._errors.get()

    def start(self):
        if self.urls:
            self._thread.start()

    def stop(self):
        self._stop.set()

    def _render(self) -> bytes:
        # the shared exposition splicer is quote-aware: label values with
        # spaces/braces survive (the old partition(" ") surgery did not)
        text = splice_extra_labels(self.collect_fn(), self.extra_labels)
        # gzip like the reference metrics.InitPush (push.go:167)
        return gzip.compress(text.encode(), 5)

    def _loop(self):
        while not self._stop.wait(self.interval_s):
            try:
                body = self._render()
                for url in self.urls:
                    try:
                        req = urllib.request.Request(
                            url, data=body, method="POST",
                            headers={"Content-Type": "text/plain",
                                     "Content-Encoding": "gzip"})
                        with urllib.request.urlopen(req, timeout=10):
                            self._pushes.inc()
                    except OSError as e:
                        self._errors.inc()
                        logger.throttled_warnf("pushmetrics", 30,
                                               "pushmetrics %s: %s", url, e)
            except Exception as e:  # collect_fn error must not kill the loop
                self._errors.inc()
                logger.throttled_warnf("pushmetrics-collect", 30,
                                       "pushmetrics collect: %s", e)
