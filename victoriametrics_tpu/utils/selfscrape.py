"""Self-scrape: the process scrapes its own metrics registry into real
storage (reference app/victoria-metrics/self_scraper.go,
``-selfScrapeInterval``).

Every ``interval`` seconds the collector snapshots the central registry
through ``MetricsRegistry.collect_values`` — the same structured
collection pass ``/metrics`` renders, NOT a text round-trip — stamps
``job=``/``instance=`` labels, and hands the rows to a sink:

- vmsingle / vmstorage: ``Storage.add_rows`` directly;
- vmselect / vminsert: ``ClusterStorage.add_rows`` (the cluster write
  path, sharded + rerouted like any ingested series).

``vm_*`` / ``process_*`` history therefore becomes ordinary TSDB data:
MetricsQL-queryable, visible in vmui, durable across restarts — and the
substrate the SLO engine (query/sloplane.py) evaluates burn rates over.

Default OFF; ``VM_SELF_SCRAPE_INTERVAL`` (or the apps'
``-selfScrapeInterval`` flag) enables it.  A bare ``1`` means the
reference's 15s default; otherwise a duration (``15s``, ``500ms``) or
plain seconds.
"""

from __future__ import annotations

import os
import threading
import time

from . import fasttime, logger
from . import metrics as metricslib

DEFAULT_INTERVAL_S = 15.0

_SCRAPES = metricslib.REGISTRY.counter("vm_selfscrape_scrapes_total")
_ROWS = metricslib.REGISTRY.counter("vm_selfscrape_rows_total")
_ERRORS = metricslib.REGISTRY.counter("vm_selfscrape_errors_total")
_DURATION = metricslib.REGISTRY.histogram(
    "vm_selfscrape_duration_seconds")


def parse_interval(raw: str | float | None) -> float:
    """Seconds from a flag/env value: ``0``/empty = off, ``1`` = the
    15s default (the "just turn it on" spelling), else a duration
    string (``15s``, ``500ms``, ``1m``) or plain seconds."""
    if raw is None:
        return 0.0
    s = str(raw).strip()
    if not s or s in ("0", "0s", "false", "no"):
        return 0.0
    if s == "1":
        return DEFAULT_INTERVAL_S
    try:
        return float(s)
    except ValueError:
        pass
    try:
        from ..query.metricsql.parser import parse_duration_ms
        ms, _ = parse_duration_ms(s)
        return max(0.0, ms / 1e3)
    except Exception:  # noqa: BLE001 — bad flag value, not a crash
        logger.errorf("selfscrape: cannot parse interval %r, disabled", s)
        return 0.0


def configured_interval(flag_value: str | float | None = None) -> float:
    """Effective interval in seconds: the ``VM_SELF_SCRAPE_INTERVAL``
    env wins (envflag convention), else the app's flag value."""
    env = os.environ.get("VM_SELF_SCRAPE_INTERVAL")
    if env is not None:
        return parse_interval(env)
    return parse_interval(flag_value)


def _labels_of(sample_name: str) -> dict | None:
    """``name{k="v"}`` -> labels dict with ``__name__`` (the ingest
    row shape).  Registry sample names ARE series keys, so the ingest
    parser's key decomposer is the single authority."""
    from ..ingest.parsers import labels_from_series_key
    try:
        pairs = labels_from_series_key(sample_name.encode())
    except ValueError:
        return None
    return dict(pairs)


class SelfScraper:
    """Background collector: registry snapshot -> labeled rows -> sink.

    ``sink(rows, tenant)`` gets ``[(labels_dict, ts_ms, value), ...]``
    (``Storage.add_rows`` / ``ClusterStorage.add_rows`` compatible).
    ``extra`` is an optional callable returning the app-level metric
    dict (``PrometheusAPI.app_metrics``) so the scraped view matches
    ``/metrics`` exactly.  ``on_tick(now_ms)`` runs after each scrape
    on the scraper thread — the SLO engine's eval pump rides here, so
    burn rates are computed right after the freshest self-sample
    lands."""

    def __init__(self, sink, job: str | None = None,
                 instance: str | None = None,
                 interval_s: float = DEFAULT_INTERVAL_S,
                 extra=None, on_tick=None, tenant=(0, 0)):
        self.sink = sink
        self.job = job if job is not None else os.environ.get(
            "VM_SELF_SCRAPE_JOB", "victoria-metrics")
        self.instance = instance if instance is not None else \
            os.environ.get("VM_SELF_SCRAPE_INSTANCE", "self")
        self.interval_s = max(0.05, float(interval_s))
        self.extra = extra
        self.on_tick = on_tick
        self.tenant = tenant
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        # wrong-plane guard: a sink that keeps failing with an RPC
        # handshake rejection is misconfigured (a 2-field -storageNode
        # spec points the insert plane at a select port), not unlucky —
        # and every retry can mark healthy nodes down in the router,
        # degrading REAL query traffic.  After a few consecutive
        # handshake failures self-ingest turns itself off (scraping and
        # /metrics keep working); other sink errors retry forever.
        self._sink_fails = 0
        self._saw_handshake_fail = False
        self._sink_disabled = False

    # -- collection --------------------------------------------------------

    def collect_rows(self, ts_ms: int | None = None) -> list:
        """One registry snapshot as ingest rows.  NaN samples (a gauge
        callback mid-teardown) are skipped: a self-scraped NaN would
        read as a staleness marker in the stored history."""
        if ts_ms is None:
            ts_ms = fasttime.unix_ms()
        extra = None
        if self.extra is not None:
            try:
                extra = self.extra()
            except Exception:  # noqa: BLE001 — scrape must never fail
                extra = None
        rows = []
        for name, value in metricslib.REGISTRY.collect_values(extra=extra):
            if value != value:  # NaN
                continue
            labels = _labels_of(name)
            if labels is None:
                continue
            labels["job"] = self.job
            labels["instance"] = self.instance
            rows.append((labels, ts_ms, value))
        return rows

    def scrape_once(self, ts_ms: int | None = None) -> int:
        if self._sink_disabled:
            return 0
        t0 = time.perf_counter()
        rows = self.collect_rows(ts_ms)
        try:
            self.sink(rows, tenant=self.tenant)
        except Exception as e:  # noqa: BLE001 — sink down ≠ scraper dead
            _ERRORS.inc()
            self._sink_fails += 1
            if "handshake failed" in str(e):
                self._saw_handshake_fail = True
            if self._saw_handshake_fail and self._sink_fails >= 3:
                self._sink_disabled = True
                logger.warnf(
                    "selfscrape: %d consecutive sink failures including an "
                    "RPC handshake rejection — the write plane is "
                    "misconfigured (2-field -storageNode spec? use "
                    "host:insertPort:selectPort); self-ingest disabled, "
                    "/metrics still serves: %s", self._sink_fails, e)
                return 0
            logger.errorf("selfscrape: ingest failed: %s", e)
            return 0
        self._sink_fails = 0
        self._saw_handshake_fail = False
        _SCRAPES.inc()
        _ROWS.inc(len(rows))
        _DURATION.update(time.perf_counter() - t0)
        return len(rows)

    # -- lifecycle ---------------------------------------------------------

    def _run(self):
        # first scrape one interval in (the reference waits too: an
        # empty registry snapshot at t=0 would just store zeros)
        while not self._stop.wait(self.interval_s):
            try:
                self.scrape_once()
            except Exception as e:  # noqa: BLE001 — keep the loop alive
                _ERRORS.inc()
                logger.errorf("selfscrape: scrape failed: %s", e)
            if self.on_tick is not None:
                try:
                    self.on_tick(fasttime.unix_ms())
                except Exception as e:  # noqa: BLE001
                    logger.errorf("selfscrape: on_tick failed: %s", e)

    def start(self):
        if self._thread is not None:
            return
        # long-lived service thread (one per process), not fan-out work
        self._thread = threading.Thread(  # vmt: disable=VMT011
            target=self._run, daemon=True, name="selfscrape")
        self._thread.start()
        logger.infof("selfscrape: every %.1fs as job=%s instance=%s",
                     self.interval_s, self.job, self.instance)

    def stop(self):
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
            self._thread = None


def maybe_start(sink, role: str, http_port: int,
                flag_value: str | float | None = None,
                extra=None, on_tick=None) -> SelfScraper | None:
    """App-side one-liner: start a scraper when configured, else None.
    ``instance`` defaults to ``<role>:<port>`` (overridable via
    ``VM_SELF_SCRAPE_INSTANCE``) so a multi-process cluster's series
    stay distinguishable."""
    interval = configured_interval(flag_value)
    if interval <= 0:
        return None
    instance = os.environ.get("VM_SELF_SCRAPE_INSTANCE",
                              f"{role}:{http_port}")
    s = SelfScraper(sink, instance=instance, interval_s=interval,
                    extra=extra, on_tick=on_tick)
    s.start()
    return s
