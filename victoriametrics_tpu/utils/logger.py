"""Leveled, rate-limited logging (capability parity with reference lib/logger).

The reference exposes Infof/Warnf/Errorf/Panicf with per-second error rate
limiting and message counters (lib/logger/logger.go:112-142).  We build on
stdlib logging and add: rate limiting per call-site, a panic helper that
raises, and counters exported to /metrics.
"""

from __future__ import annotations

import logging
import sys
import threading
import time
from collections import defaultdict

_counters = defaultdict(int)  # level -> messages logged (exported as vm_log_messages_total)
_counters_lock = threading.Lock()

_rate_state: dict[tuple[str, int], tuple[float, int]] = {}
_rate_lock = threading.Lock()

_logger = logging.getLogger("vmtpu")
if not _logger.handlers:
    _h = logging.StreamHandler(sys.stderr)
    _h.setFormatter(logging.Formatter(
        "%(asctime)s\t%(levelname)s\t%(message)s", datefmt="%Y-%m-%dT%H:%M:%S"))
    _logger.addHandler(_h)
    _logger.setLevel(logging.INFO)


def set_level(level: str) -> None:
    _logger.setLevel(getattr(logging, level.upper()))


def _count(level: str) -> None:
    with _counters_lock:
        _counters[level] += 1


def message_counters() -> dict[str, int]:
    with _counters_lock:
        return dict(_counters)


def infof(fmt: str, *args) -> None:
    _count("info")
    _logger.info(fmt, *args)


def warnf(fmt: str, *args) -> None:
    _count("warn")
    _logger.warning(fmt, *args)


def errorf(fmt: str, *args) -> None:
    _count("error")
    _logger.error(fmt, *args)


class InternalError(RuntimeError):
    """Raised by panicf — the analog of logger.Panicf 'BUG:' invariants."""


def panicf(fmt: str, *args) -> None:
    _count("panic")
    msg = fmt % args if args else fmt
    _logger.error("PANIC: %s", msg)
    raise InternalError(msg)


def throttled_warnf(key: str, interval_s: float, fmt: str, *args) -> None:
    """Log at most once per interval_s for the given key (reference:
    lib/storage/storage.go:2155 logSkippedSeries pattern)."""
    now = time.monotonic()
    with _rate_lock:
        last, suppressed = _rate_state.get((key, 0), (0.0, 0))
        if now - last < interval_s:
            _rate_state[(key, 0)] = (last, suppressed + 1)
            return
        _rate_state[(key, 0)] = (now, 0)
    if suppressed:
        warnf(fmt + " (%d similar messages suppressed)", *args, suppressed)
    else:
        warnf(fmt, *args)
