"""Storage-side query deadline budget (ROADMAP item 3's named leftover).

The select plane already clips every RPC *socket* operation to the
query's remaining budget — but a vmstorage that received the request
kept burning the dead query's FULL server-side cost (index scan, part
decode, assembly) after the caller gave up.  This module is the
server-side half: the remaining budget ships inside ``search_v1`` /
``searchColumns_v1`` requests, and the storage engine calls
:meth:`Budget.tick` every N series during index scans and
:meth:`Budget.check` once per fetch unit, aborting mid-flight with the
typed :class:`DeadlineExceededError` that crosses the RPC boundary as
itself (the vmselect surfaces it WITHOUT marking the healthy node
down).
"""

from __future__ import annotations

import threading
import time

#: index-scan granularity: budget checked every this many resolved
#: series (an abort lands within ~one check interval of expiry)
CHECK_EVERY = 256


class DeadlineExceededError(ValueError):
    """The query's deadline budget expired while the storage engine was
    still scanning/fetching; the work was aborted server-side.  Typed so
    the RPC layer ships it across the wire as a deadline (no error-log
    flood, no node-down marking) instead of a generic handler error.
    A ValueError subclass so the HTTP layer maps a LOCAL storage abort
    through the same error path as the evaluator's own
    QueryLimitError deadline check."""


class Budget:
    """Per-query abort token threaded through the storage read path.

    ``tick()`` is the cheap per-item call (one int increment; the real
    clock check fires every :data:`CHECK_EVERY` calls); ``check()`` is
    the unconditional boundary check (per fetch unit / per phase).
    ``on_abort`` runs once when the budget first trips (the
    vm_storage_deadline_aborts_total counter lives with the storage
    engine, not here)."""

    __slots__ = ("deadline", "on_abort", "_n", "_tripped", "_lock")

    def __init__(self, deadline: float, on_abort=None):
        self.deadline = deadline
        self.on_abort = on_abort
        self._n = 0
        self._tripped = False
        # fetch units call check() from concurrent pool workers: the
        # trip latch needs real mutual exclusion or one aborted query
        # counts as several in vm_storage_deadline_aborts_total
        self._lock = threading.Lock()

    def tick(self) -> None:
        self._n += 1
        if self._n % CHECK_EVERY == 0:
            self.check()

    def check(self) -> None:
        if not self.deadline or time.monotonic() < self.deadline:
            return
        with self._lock:
            first = not self._tripped
            self._tripped = True
        if first and self.on_abort is not None:
            self.on_abort()
        raise DeadlineExceededError(
            "storage-side deadline exceeded: query budget expired "
            "mid-scan; the remaining work was aborted on the vmstorage")
