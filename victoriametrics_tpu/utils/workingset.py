"""Two-generation working-set cache (reference lib/workingsetcache):
instead of wiping a full cache — a multi-million-entry ``clear()`` on
the ingest hot path costs a latency cliff AND a cold restart for every
live series — the cache rotates: on overflow the current map becomes
the *previous* generation and a fresh current map starts empty.
Lookups fall through current -> previous, promoting hits back into
current, so the working set survives rotation and only entries idle for
a whole generation are dropped.

Used by the ingest pipeline's hot caches (the raw-label TSID cache in
``storage.Storage``, the id->name/id->TSID caches in ``IndexDB``) —
each keyed lookup is a couple of dict probes under a ``make_lock`` lock
so the racetrace sanitizer sees proper happens-before edges between
concurrent striped writers.
"""

from __future__ import annotations

from ..devtools.locktrace import make_lock

__all__ = ["WorkingSetCache"]

_MISS = object()


class WorkingSetCache:
    """Bounded dict with two-generation rotation instead of clear().

    ``max_entries`` bounds the *current* generation; total resident
    entries are at most ``2 * max_entries`` across both generations
    (same bound shape as the reference's split-cache mode).
    """

    __slots__ = ("name", "max_entries", "_lock", "_cur", "_prev",
                 "rotations")

    def __init__(self, max_entries: int, name: str = "workingset"):
        if max_entries < 1:
            raise ValueError("max_entries must be positive")
        self.name = name
        self.max_entries = max_entries
        self._lock = make_lock(f"utils.workingset.{name}")
        self._cur: dict = {}
        self._prev: dict = {}
        self.rotations = 0

    def _rotate_locked(self) -> None:
        self._prev = self._cur
        self._cur = {}
        self.rotations += 1

    def get(self, key, default=None):
        with self._lock:
            v = self._cur.get(key, _MISS)
            if v is not _MISS:
                return v
            v = self._prev.get(key, _MISS)
            if v is _MISS:
                return default
            # promote: a hit in the old generation is working-set-live
            if len(self._cur) >= self.max_entries:
                self._rotate_locked()
            self._cur[key] = v
            return v

    def put(self, key, value) -> None:
        with self._lock:
            if key not in self._cur and \
                    len(self._cur) >= self.max_entries:
                self._rotate_locked()
            self._cur[key] = value

    def __contains__(self, key) -> bool:
        with self._lock:
            return key in self._cur or key in self._prev

    def __len__(self) -> int:
        with self._lock:
            if not self._prev:
                return len(self._cur)
            return len(self._cur.keys() | self._prev.keys())

    def __bool__(self) -> bool:
        with self._lock:
            return bool(self._cur) or bool(self._prev)

    def items(self) -> list:
        """Snapshot of distinct (key, value) pairs; current-generation
        values win over previous-generation ones."""
        with self._lock:
            merged = dict(self._prev)
            merged.update(self._cur)
            return list(merged.items())

    def clear(self) -> None:
        with self._lock:
            self._cur = {}
            self._prev = {}

    def filter(self, keep) -> None:
        """Drop every entry where ``keep(key, value)`` is falsy (e.g.
        purging tombstoned TSIDs after delete_series)."""
        with self._lock:
            self._cur = {k: v for k, v in self._cur.items() if keep(k, v)}
            self._prev = {k: v for k, v in self._prev.items() if keep(k, v)}
