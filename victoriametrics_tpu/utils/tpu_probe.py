"""Bounded-timeout accelerator probe.

Initializing the TPU backend IN-PROCESS is not cancellable: a hung plugin
init (e.g. a provisioned-but-unresponsive tunnel) blocks `jax.devices()`
forever and takes the whole server with it (this exact hang produced a
timed-out round-3 multichip artifact). The probe pays a subprocess to find
out whether the backend comes up, with a hard deadline; only on success do
callers initialize jax in-process (the plugin is then known-healthy, and
the subprocess's own client is gone by that point).

Used by the serving apps' `-search.tpuBackend` startup and by bench.py.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys


def probe_backend(timeout_s: float = 90.0):
    """Probe jax backend availability in a subprocess.

    Returns (platform, n_devices, error): platform is e.g. "tpu"/"cpu"
    (None when the probe failed), error is a human-readable reason on
    failure (None on success)."""
    code = (
        "import jax, json\n"
        "ds = jax.devices()\n"
        "print('PROBE:' + json.dumps("
        "{'platform': ds[0].platform, 'n': len(ds)}))\n"
    )
    try:
        r = subprocess.run([sys.executable, "-c", code],
                           capture_output=True, text=True,
                           timeout=timeout_s, env=os.environ.copy())
    except subprocess.TimeoutExpired:
        return None, 0, (f"accelerator probe timed out after {timeout_s:g}s "
                         "(hung backend init?)")
    except OSError as e:
        return None, 0, f"accelerator probe could not run: {e}"
    if r.returncode != 0:
        tail = (r.stderr or "").strip().splitlines()[-3:]
        return None, 0, ("accelerator probe failed: " +
                         (" | ".join(tail) or f"rc={r.returncode}"))
    for line in (r.stdout or "").splitlines():
        if line.startswith("PROBE:"):
            info = json.loads(line[len("PROBE:"):])
            return info["platform"], int(info["n"]), None
    return None, 0, "accelerator probe produced no result"
