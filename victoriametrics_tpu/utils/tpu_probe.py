"""Bounded-timeout accelerator probe.

Initializing the TPU backend IN-PROCESS is not cancellable: a hung plugin
init (e.g. a provisioned-but-unresponsive tunnel) blocks `jax.devices()`
forever and takes the whole server with it (this exact hang produced a
timed-out round-3 multichip artifact). The probe pays a subprocess to find
out whether the backend comes up, with a hard deadline; only on success do
callers initialize jax in-process (the plugin is then known-healthy, and
the subprocess's own client is gone by that point).

The probe subprocess arms `faulthandler.dump_traceback_later` so that when
it hangs past the deadline, the captured stderr carries periodic stack
dumps — the returned `stack` pinpoints WHERE backend init died (the
round-4 verdict's ask: prove the hang, don't guess).

`start_probe()` returns immediately with a handle so callers can overlap
the (potentially minutes-long) probe with other startup work — bench.py
overlaps it with data ingest. `probe_backend()` is the blocking wrapper.

Used by the serving apps' `-search.tpuBackend` startup and by bench.py.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

# Dump the probe's stacks every 45s while it is stuck; the LAST dump in
# stderr is what the caller reports.
_DUMP_INTERVAL_S = 45

_PROBE_CODE = """\
import faulthandler, json, sys
faulthandler.dump_traceback_later({dump}, repeat=True, file=sys.stderr)
import jax
ds = jax.devices()
faulthandler.cancel_dump_traceback_later()
print('PROBE:' + json.dumps({{'platform': ds[0].platform, 'n': len(ds)}}))
"""


class ProbeResult:
    """Outcome of an accelerator probe.

    platform: "tpu"/"cpu"/... or None on failure
    n: device count (0 on failure)
    error: human-readable failure reason, None on success
    stack: last faulthandler stack dump from a hung probe (None unless the
           probe timed out and produced one) — the where-it-died artifact
    elapsed_s: how long the probe took
    """

    __slots__ = ("platform", "n", "error", "stack", "elapsed_s")

    def __init__(self, platform, n, error, stack=None, elapsed_s=0.0):
        self.platform = platform
        self.n = n
        self.error = error
        self.stack = stack
        self.elapsed_s = elapsed_s

    def __iter__(self):  # legacy (platform, n, error) unpacking
        return iter((self.platform, self.n, self.error))


def _last_stack_dump(stderr: str):
    """Extract the last faulthandler dump from captured stderr.

    faulthandler emits blocks starting "Timeout (H:MM:SS)!"; keep the text
    from the final such marker, trimmed to a sane size."""
    if not stderr:
        return None
    idx = stderr.rfind("Timeout (")
    if idx < 0:
        return None
    return stderr[idx:idx + 4000].strip()


class ProbeHandle:
    """In-flight accelerator probe; `result()` blocks until done/deadline.

    The child's stdout/stderr go to TEMP FILES, not pipes: the caller may
    not call result() for minutes (bench overlaps the probe with ingest),
    and a chatty backend init writing >64KB into an undrained pipe would
    block mid-init — misdiagnosing a healthy device as hung."""

    def __init__(self, proc: subprocess.Popen, timeout_s: float,
                 out_f, err_f):
        self._proc = proc
        self._timeout_s = timeout_s
        self._t0 = time.monotonic()
        self._result = None
        self._out_f = out_f
        self._err_f = err_f

    def _read_files(self):
        out = err = ""
        for attr, f in (("out", self._out_f), ("err", self._err_f)):
            try:
                f.seek(0)
                data = f.read()
                f.close()
            except (OSError, ValueError):
                data = ""
            if attr == "out":
                out = data
            else:
                err = data
        return out, err

    def cancel(self) -> None:
        """Kill the probe child if still running (callers' error paths:
        a hung child must not outlive its parent holding the device)."""
        if self._result is None and self._proc.poll() is None:
            self._proc.kill()
            self._proc.wait()
            self._read_files()

    def result(self) -> ProbeResult:
        if self._result is not None:
            return self._result
        remaining = max(0.0, self._timeout_s -
                        (time.monotonic() - self._t0))
        try:
            self._proc.wait(timeout=remaining)
        except subprocess.TimeoutExpired:
            self._proc.kill()
            self._proc.wait()
            _, err = self._read_files()
            self._result = ProbeResult(
                None, 0,
                f"accelerator probe timed out after {self._timeout_s:g}s "
                "(hung backend init?)",
                stack=_last_stack_dump(err or ""),
                elapsed_s=time.monotonic() - self._t0)
            return self._result
        elapsed = time.monotonic() - self._t0
        out, err = self._read_files()
        if self._proc.returncode != 0:
            tail = (err or "").strip().splitlines()[-3:]
            self._result = ProbeResult(
                None, 0, "accelerator probe failed: " +
                (" | ".join(tail) or f"rc={self._proc.returncode}"),
                elapsed_s=elapsed)
            return self._result
        for line in (out or "").splitlines():
            if line.startswith("PROBE:"):
                info = json.loads(line[len("PROBE:"):])
                self._result = ProbeResult(info["platform"], int(info["n"]),
                                           None, elapsed_s=elapsed)
                return self._result
        self._result = ProbeResult(None, 0,
                                   "accelerator probe produced no result",
                                   elapsed_s=elapsed)
        return self._result


def start_probe(timeout_s: float = 600.0) -> ProbeHandle:
    """Launch the probe subprocess; returns immediately."""
    import tempfile
    code = _PROBE_CODE.format(dump=_DUMP_INTERVAL_S)
    try:
        out_f = tempfile.TemporaryFile(mode="w+", prefix="vmtpu-probe-out")
        err_f = tempfile.TemporaryFile(mode="w+", prefix="vmtpu-probe-err")
        proc = subprocess.Popen([sys.executable, "-c", code],
                                stdout=out_f, stderr=err_f, text=True,
                                env=os.environ.copy())
    except OSError as e:
        class _Failed:
            def result(self, _e=e):
                return ProbeResult(None, 0,
                                   f"accelerator probe could not run: {_e}")

            def cancel(self):
                pass
        return _Failed()
    return ProbeHandle(proc, timeout_s, out_f, err_f)


def probe_backend(timeout_s: float = 600.0):
    """Blocking probe. Returns (platform, n_devices, error) — platform is
    e.g. "tpu"/"cpu" (None when the probe failed), error is a
    human-readable reason on failure (None on success)."""
    return start_probe(timeout_s).result()
