"""Process-wide self-metrics registry (reference
vendor/github.com/VictoriaMetrics/metrics: Counter/FloatCounter/Gauge +
the vmrange Histogram of histogram.go, and WritePrometheus exposition).

Metrics are keyed by their FULL name including labels, exactly like the
reference library::

    REGISTRY.counter('vm_rpc_calls_total{method="search_v1"}').inc()
    REGISTRY.histogram('vm_request_duration_seconds{path="/api/v1/query"}')\
        .update(dt)

Histograms reuse the storage engine's own vmrange bucketing
(query/vmhistogram.py), so self-metrics use the same exposition the data
plane stores: ``<name>_bucket{...,vmrange="l...u"}``, ``<name>_sum``,
``<name>_count``.  ``write_prometheus()`` renders the whole registry as
parseable Prometheus text (``# TYPE`` lines, escaped label values) plus
``process_*`` gauges (RSS, open fds, threads, CPU, uptime).

One process = one registry (``REGISTRY``); tests may build private
``MetricsRegistry`` instances.
"""

from __future__ import annotations

import os
import re
import threading

from ..query import vmhistogram
from . import fasttime

_NAME_RE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:.]*(\{([a-zA-Z_][a-zA-Z0-9_]*="'
    r'([^"\\]|\\.)*",?)*\})?$')

_started_at = fasttime.unix_seconds()


def uptime_seconds() -> float:
    """Seconds since this process's registry was imported (the
    vm_app_uptime_seconds / health-report clock)."""
    return fasttime.unix_seconds() - _started_at


# -- name formatting ---------------------------------------------------------

def escape_label_value(v: str) -> str:
    """Prometheus text-format label-value escaping (backslash, quote, LF)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def format_name(base: str, labels: dict | None = None) -> str:
    """``format_name("m", {"a": "b"})`` -> ``m{a="b"}`` with values
    escaped; labels render in insertion order (callers pass stable dicts
    so identical series always produce the identical registry key)."""
    if not labels:
        return base
    inner = ",".join(f'{k}="{escape_label_value(v)}"'
                     for k, v in labels.items())
    return f"{base}{{{inner}}}"


def split_name(full: str) -> tuple[str, str]:
    """``m{a="b"}`` -> ``("m", 'a="b"')``; ``m`` -> ``("m", "")``."""
    i = full.find("{")
    if i < 0:
        return full, ""
    return full[:i], full[i + 1:full.rindex("}")]


def _join_labels(*parts: str) -> str:
    inner = ",".join(p for p in parts if p)
    return f"{{{inner}}}" if inner else ""


# -- metric kinds ------------------------------------------------------------

class Counter:
    """Monotonic integer counter."""

    type_name = "counter"
    __slots__ = ("name", "_lock", "_v")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._v = 0

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._v += n

    def get(self) -> int:
        with self._lock:
            return self._v

    def set(self, v: int) -> None:
        with self._lock:
            self._v = v

    def _samples(self):
        yield self.name, _fmt_number(self.get())


class FloatCounter(Counter):
    """Monotonic float counter (e.g. accumulated seconds)."""

    __slots__ = ()

    def __init__(self, name: str):
        super().__init__(name)
        self._v = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._v += n


class Gauge:
    """Instantaneous value: either callback-driven (read at exposition
    time) or set()/inc()/dec()-driven."""

    type_name = "gauge"
    __slots__ = ("name", "callback", "_lock", "_v")

    def __init__(self, name: str, callback=None):
        self.name = name
        self.callback = callback
        self._lock = threading.Lock()
        self._v = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._v = v

    def inc(self, n: float = 1) -> None:
        with self._lock:
            self._v += n

    def dec(self, n: float = 1) -> None:
        with self._lock:
            self._v -= n

    def get(self) -> float:
        if self.callback is not None:
            try:
                return float(self.callback())
            except Exception:  # noqa: BLE001 — exposition must never fail
                return float("nan")
        with self._lock:
            return self._v

    def _samples(self):
        yield self.name, _fmt_number(self.get())


class Histogram:
    """VictoriaMetrics-native histogram: log-spaced vmrange buckets
    (18/decade, query/vmhistogram.py) storing only non-empty buckets,
    plus _sum and _count series.  NaN and negative values are skipped,
    matching the reference (histogram.go:85)."""

    type_name = "histogram"
    __slots__ = ("name", "_lock", "_buckets", "_sum", "_count")

    def __init__(self, name: str):
        self.name = name
        self._lock = threading.Lock()
        self._buckets: dict[str, int] = {}
        self._sum = 0.0
        self._count = 0

    def update(self, v: float) -> None:
        r = vmhistogram.vmrange_for(float(v))
        if r is None:
            return
        with self._lock:
            self._buckets[r] = self._buckets.get(r, 0) + 1
            self._sum += v
            self._count += 1

    def update_duration(self, start_monotonic: float) -> None:
        import time
        self.update(time.perf_counter() - start_monotonic)

    def get_count(self) -> int:
        with self._lock:
            return self._count

    def get_sum(self) -> float:
        with self._lock:
            return self._sum

    def _samples(self):
        base, labels = split_name(self.name)
        with self._lock:
            buckets = sorted(self._buckets.items())
            total, cnt = self._sum, self._count
        if not cnt:
            return
        for rng, n in buckets:
            yield (f"{base}_bucket"
                   + _join_labels(labels, f'vmrange="{rng}"'), str(n))
        yield f"{base}_sum" + _join_labels(labels), _fmt_number(total)
        yield f"{base}_count" + _join_labels(labels), str(cnt)


def _fmt_number(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    return str(int(f)) if f.is_integer() and abs(f) < 1e15 else repr(f)


# -- registry ----------------------------------------------------------------

class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}
        self._collectors: list = []

    def _get_or_create(self, name: str, cls, **kw):
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, **kw)
            elif type(m) is not cls:
                raise ValueError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get_or_create(name, Counter)

    def float_counter(self, name: str) -> FloatCounter:
        return self._get_or_create(name, FloatCounter)

    def gauge(self, name: str, callback=None) -> Gauge:
        g = self._get_or_create(name, Gauge, callback=callback)
        if callback is not None and g.callback is None:
            g.callback = callback
        return g

    def histogram(self, name: str) -> Histogram:
        return self._get_or_create(name, Histogram)

    def unregister(self, name: str) -> None:
        with self._lock:
            self._metrics.pop(name, None)

    def register_collector(self, fn) -> None:
        """fn() -> dict of full-name -> value, rendered untyped at
        exposition time (the bridge for legacy ``.metrics()`` dicts)."""
        with self._lock:
            self._collectors.append(fn)

    def _collect(self, extra: dict | None = None,
                 include_process: bool = True):
        """The one collection pass both exposition AND the self-scrape
        plane share: yields ``(family, type, name, value_str)`` for
        every sample — registered metrics, ``register_collector``
        collectors, a one-shot ``extra`` dict, process_* gauges."""
        with self._lock:
            metrics = list(self._metrics.values())
            collectors = list(self._collectors)
        for m in metrics:
            fam = split_name(m.name)[0]
            for name, value in m._samples():
                yield fam, m.type_name, name, value
        merged: dict[str, object] = {}
        for fn in collectors:
            try:
                merged.update(fn())
            except Exception:  # noqa: BLE001 — exposition must never fail
                continue
        if extra:
            merged.update(extra)
        for name, value in merged.items():
            fam = split_name(name)[0]
            kind = "counter" if fam.endswith("_total") else "gauge"
            yield fam, kind, name, _fmt_number(value)
        if include_process:
            for name, value in _process_metrics():
                fam = split_name(name)[0]
                kind = "counter" if fam.endswith("_total") else "gauge"
                yield fam, kind, name, _fmt_number(value)

    def collect_values(self, extra: dict | None = None,
                       include_process: bool = True
                       ) -> list[tuple[str, float]]:
        """Structured snapshot for the self-scrape plane:
        ``[(full_sample_name, float_value), ...]`` from the same
        collection pass ``write_prometheus`` renders — NOT a text
        round-trip.  Unparseable collector values are skipped (the
        text path would have rendered them verbatim; the ingest path
        needs numbers)."""
        out = []
        for _fam, _kind, name, value in self._collect(
                extra, include_process):
            try:
                out.append((name, float(value)))
            except (TypeError, ValueError):
                continue
        return out

    def write_prometheus(self, extra: dict | None = None,
                         include_process: bool = True) -> str:
        """Render the registry as Prometheus text exposition.  ``extra``
        merges a one-shot dict of full-name -> value (e.g. a storage
        engine's ``.metrics()``); collectors registered via
        ``register_collector`` are read every call."""
        samples: list[tuple[str, str, str]] = []  # (family, name, value)
        types: dict[str, str] = {}
        for fam, kind, name, value in self._collect(extra, include_process):
            types.setdefault(fam, kind)
            samples.append((fam, name, value))
        samples.sort()
        out = []
        prev_fam = None
        for fam, name, value in samples:
            if fam != prev_fam:
                out.append(f"# TYPE {fam} {types.get(fam, 'gauge')}")
                prev_fam = fam
            out.append(f"{name} {value}")
        return "\n".join(out) + "\n" if out else ""


def _process_metrics():
    """process_* gauges (reference metrics.WriteProcessMetrics) + gc
    visibility (go_gc_* analog): per-generation collection counts read
    straight from the collector, so GC can be ruled in/out as a serving
    latency-variance source from /metrics alone (pause seconds come from
    the callback below — gc exposes no cumulative pause clock)."""
    import gc
    for gen, st in enumerate(gc.get_stats()):
        yield (f'vm_gc_collections_total{{gen="{gen}"}}',
               st.get("collections", 0))
        yield (f'vm_gc_collected_objects_total{{gen="{gen}"}}',
               st.get("collected", 0))
    yield "process_start_time_seconds", int(_started_at)
    yield "vm_app_uptime_seconds", round(uptime_seconds(), 3)
    # identity/info metrics (reference lib/buildinfo): constant-1 gauge
    # carrying the version labels, plus the start timestamp — the fleet
    # inventory the self-scrape plane's job=/instance= series hang off
    from . import buildinfo
    yield (f'vm_app_version{{version="{buildinfo.version()}",'
           f'short_version="{buildinfo.short_version()}"}}', 1)
    yield "vm_app_start_timestamp", int(_started_at)
    yield "process_num_threads", threading.active_count()
    try:
        t = os.times()
        yield "process_cpu_seconds_total", round(t.user + t.system, 3)
    except OSError:
        pass
    try:
        with open("/proc/self/statm") as f:
            parts = f.read().split()
        page = os.sysconf("SC_PAGE_SIZE")
        yield "process_virtual_memory_bytes", int(parts[0]) * page
        yield "process_resident_memory_bytes", int(parts[1]) * page
    except (OSError, IndexError, ValueError):
        # non-Linux: RSS via resource (kilobytes on Linux, bytes on mac)
        try:
            import resource
            import sys
            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            if sys.platform != "darwin":
                rss *= 1024
            yield "process_resident_memory_bytes", rss
        except (ImportError, OSError):
            yield "process_resident_memory_bytes", 0
    try:
        yield "process_open_fds", len(os.listdir("/proc/self/fd"))
    except OSError:
        pass


REGISTRY = MetricsRegistry()


# -- gc pause accounting ------------------------------------------------------

_GC_PAUSE = REGISTRY.float_counter("vm_gc_pause_seconds_total")
_gc_pause_t0 = [0.0]

#: (t0, dur_s, generation) observers invoked after each collection —
#: utils/flightrec appends one to land gc pauses on the flight timeline
#: without registering a SECOND gc callback that re-times the same
#: collection
gc_pause_hooks: list = []

# bound at import, NOT imported inside the callback: gc callbacks still
# fire during interpreter shutdown, when `import time` raises
# "import of time halted"
from time import perf_counter as _gc_clock  # noqa: E402


def _gc_pause_callback(phase: str, info: dict) -> None:
    # the collecting thread holds the GIL for the whole collection, so
    # start/stop pair up on one thread and a plain slot is race-free
    if phase == "start":
        _gc_pause_t0[0] = _gc_clock()
    elif phase == "stop" and _gc_pause_t0[0]:
        t0 = _gc_pause_t0[0]
        _gc_pause_t0[0] = 0.0
        dur = _gc_clock() - t0
        _GC_PAUSE.inc(dur)
        for hook in gc_pause_hooks:
            hook(t0, dur, info.get("generation", "?"))


def install_gc_metrics() -> None:
    """Accumulate gc collection pauses into vm_gc_pause_seconds_total
    (idempotent; installed at import — the counter must cover the whole
    process lifetime to be comparable with serving latency)."""
    import gc
    if _gc_pause_callback not in gc.callbacks:
        gc.callbacks.append(_gc_pause_callback)


install_gc_metrics()


def ingest_phase(phase: str) -> FloatCounter:
    """Per-phase write-path attribution counter (the ingest twin of the
    read path's ``vm_fetch_phase_seconds_total``): seconds spent in one
    stage of the ingestion pipeline.  Phases: ``resolve`` (raw key ->
    TSID), ``register`` (per-day index registration), ``append``
    (partition pending append), ``flush`` (part encode+fsync), ``merge``
    (background part merges).  Shared by storage/partition/mergeset and
    read by bench.py's per-refresh split."""
    return REGISTRY.float_counter(
        f'vm_ingest_phase_seconds_total{{phase="{phase}"}}')


# -- exposition utilities ----------------------------------------------------

def _sample_name_end(line: str) -> int:
    """Index of the first space separating the sample name (with its
    optional label set) from the value — quote-aware, so spaces inside
    label values never split the name."""
    in_q = False
    i = 0
    n = len(line)
    while i < n:
        c = line[i]
        if in_q:
            if c == "\\":
                i += 2
                continue
            if c == '"':
                in_q = False
        elif c == '"':
            in_q = True
        elif c in " \t":
            return i
        i += 1
    return -1


def splice_extra_labels(text: str, extra_labels: str) -> str:
    """Insert ``extra_labels`` (e.g. ``job="vm",instance="h:80"``) into
    every sample line of a Prometheus exposition.  Quote-aware: label
    values containing spaces or braces survive (the reference's
    addExtraLabels, vendor/.../metrics/push.go:236)."""
    if not extra_labels:
        return text
    out = []
    for line in text.splitlines():
        if not line.strip() or line.lstrip().startswith("#"):
            out.append(line)
            continue
        sp = _sample_name_end(line)
        if sp < 0:
            out.append(line)
            continue
        name, rest = line[:sp], line[sp + 1:]
        brace = name.find("{")
        if brace >= 0 and name.endswith("}"):
            inner = name[brace + 1:-1]
            name = name[:brace] + _join_labels(extra_labels, inner)
        else:
            name = name + "{" + extra_labels + "}"
        out.append(f"{name} {rest}")
    return "\n".join(out) + ("\n" if text.endswith("\n") else "")
