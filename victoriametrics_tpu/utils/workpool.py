"""Shared fetch/decode work pool + search-concurrency gate (the
single-node analog of the reference's per-CPU read parallelism:
app/vmselect/netstorage unpack workers fanning block decode across
gomaxprocs goroutines, and lib/storage's search concurrency limiter
bounding how many searches run at once).

One process owns ONE lazily-started pool (:data:`POOL`) of
``cpu_count`` daemon workers.  The hot storage read path fans
per-partition/per-part piece collection across it (zstd + native decode
release the GIL, so the workers genuinely overlap), the cluster fanout
reuses it instead of spawning fresh threads per query, and the chunked
fetch pipeline prefetches chunk *i+1* on it while chunk *i* rolls up.

Design constraints, in order:

- **Determinism of results.**  ``run(fns)`` returns results in submit
  order; callers that concatenate them get byte-identical output to the
  sequential loop.  ``VM_SEARCH_WORKERS=1`` disables the pool entirely
  (every ``run`` degenerates to an inline ``[fn() for fn in fns]``),
  restoring today's single-threaded execution exactly — the escape
  hatch the deterministic scheduler and bisection both rely on.
- **No deadlocks under nesting.**  A task may itself call ``run`` (a
  cluster fanout task fetches from a local node whose table fans parts
  across the same pool).  Waiters therefore HELP: while its batch is
  incomplete, the submitting thread drains and executes queued tasks
  instead of parking, so every ``run`` makes progress even when all
  workers are blocked in nested waits.
- **Happens-before edges the racetrace sanitizer understands.**  Tasks
  travel through a ``queue.Queue`` (put/get carry vector clocks when
  the sanitizer is on: submit *happens-before* execute), each batch's
  result slots are written and read under a ``make_lock`` lock
  (execute *happens-before* collect), and completion is signalled by
  one ``queue.Queue`` put (the final execute *happens-before* the
  waiter's wakeup).  No bare Events/Conditions anywhere on the seam.
- **Deterministic-scheduler safety.**  A thread scheduled by
  ``devtools.sched.DeterministicScheduler`` executes its batch INLINE:
  pool workers are not turnstile participants, so handing them work
  would reintroduce the wall-clock nondeterminism the scheduler exists
  to remove (and a scheduled thread parked in ``done.get()`` would
  stall the turnstile until ``step_timeout`` seizes it).

Pool sizing: ``VM_SEARCH_WORKERS`` — unset/``0`` means ``cpu_count``,
``1`` disables parallelism, ``N>1`` pins the worker count.  The env var
is re-read at every ``run``/``submit`` so tests (and the deterministic
scheduler harness) can flip modes without restarting the process.

Self-metrics (PR-2 registry): ``vm_workpool_tasks_total``,
``vm_workpool_queue_depth``, ``vm_workpool_workers``, and from the
gate ``vm_search_concurrent_{current,limit}`` plus
``vm_search_requests_{queued,rejected}_total``.
"""

from __future__ import annotations

import os
import queue
import threading
import time as _time

from ..devtools.locktrace import make_lock
from . import costacc
from . import flightrec
from . import metrics as metricslib
from . import querytracer

__all__ = ["WorkPool", "Future", "SearchGate", "TenantGate",
           "TenantQuota", "parse_tenant_quotas", "SearchLimitError",
           "MergeGate", "POOL", "SEARCH_GATE", "MERGE_GATE",
           "configured_workers", "configured_shards",
           "ingest_parallel_enabled", "serving", "serving_busy"]

_TASKS_TOTAL = metricslib.REGISTRY.counter("vm_workpool_tasks_total")

# time spent QUEUED at the SearchGate before a fetch starts (the fetch
# phase family lives in storage/storage.py; this member is owned here
# because the gate is the thing that queues) — with it the phase split
# sums to contended wall time instead of silently losing the queue wait
_QUEUE_WAIT = metricslib.REGISTRY.float_counter(
    'vm_fetch_phase_seconds_total{phase="queue_wait"}')

# whole-refresh serve sections (the HTTP cached range executor wraps each
# refresh): together with the SearchGate occupancy below this is the
# "someone is being served right now" signal the MergeGate yields to
_SERVING = metricslib.REGISTRY.gauge("vm_serving_current")
# per-thread context for the MergeGate serve-priority yield: a thread
# that is itself serving (or a pool worker holding a shared-POOL slot)
# must never sleep in the yield — see MergeGate._maybe_yield
_yield_tls = threading.local()


class _ServingSection:
    def __enter__(self):
        _SERVING.inc()
        _yield_tls.serving = getattr(_yield_tls, "serving", 0) + 1
        return self

    def __exit__(self, *exc):
        _SERVING.dec()
        _yield_tls.serving -= 1
        return False


def serving() -> _ServingSection:
    """Context manager marking an in-flight serve (query refresh); merge
    admission defers to these sections (MergeGate serve priority)."""
    return _ServingSection()


def serving_busy() -> bool:
    """True while any search or serve section is in flight (the gauges
    are process-global: every SearchGate instance shares them)."""
    return _SERVING.get() > 0 or \
        metricslib.REGISTRY.gauge("vm_search_concurrent_current").get() > 0


def configured_workers() -> int:
    """Worker count from ``VM_SEARCH_WORKERS`` (unset/0 -> cpu_count,
    1 -> parallelism disabled, N -> N)."""
    raw = os.environ.get("VM_SEARCH_WORKERS", "")
    try:
        n = int(raw)
    except ValueError:
        n = 0
    if n <= 0:
        n = os.cpu_count() or 1
    return n


def configured_shards() -> int:
    """Ingest stripe count from ``VM_INGEST_SHARDS`` (the rawRowsShards
    analog): unset/0 -> cpu_count, 1 -> the exact sequential write path,
    N -> N registration stripes."""
    raw = os.environ.get("VM_INGEST_SHARDS", "")
    try:
        n = int(raw)
    except ValueError:
        n = 0
    if n <= 0:
        n = os.cpu_count() or 1
    return n


def ingest_parallel_enabled() -> bool:
    """True when the write path may hand work to the pool:
    ``VM_INGEST_SHARDS`` > 1 AND the pool itself is enabled.
    ``VM_INGEST_SHARDS=1`` is the write path's own escape hatch; note
    that ``VM_SEARCH_WORKERS=1`` disables the SHARED pool entirely and
    therefore reverts BOTH the read and the write path to sequential —
    bisect write-path issues with VM_INGEST_SHARDS, not the pool knob."""
    return configured_shards() > 1 and POOL.parallel_enabled()


def _sched_active() -> bool:
    """True when the calling thread runs under the deterministic
    scheduler (devtools.sched) — batches then execute inline."""
    from ..devtools import racetrace
    return getattr(racetrace._tls, "sched", None) is not None


class _Batch:
    """One run()/submit() call's shared state: ordered result slots, a
    pending count, the first error, and a one-shot completion queue."""

    __slots__ = ("lock", "results", "pending", "error", "done")

    def __init__(self, n: int):
        self.lock = make_lock("utils.workpool._Batch.lock")
        self.results = [None] * n
        self.pending = n
        self.error: BaseException | None = None
        self.done: queue.Queue = queue.Queue()


class Future:
    """Handle for one submitted task; ``result()`` waits (helping the
    pool while it does) and re-raises the task's exception.  Safe for
    multiple waiters/repeat calls: the completion token is re-armed
    after each successful wait, so every ``result()`` returns."""

    __slots__ = ("_pool", "_batch")

    def __init__(self, pool: "WorkPool", batch: _Batch):
        self._pool = pool
        self._batch = batch

    def result(self):
        return self._pool._collect(self._batch)[0]


class WorkPool:
    def __init__(self, workers: int | None = None):
        # None = resolve VM_SEARCH_WORKERS at every run (the shared POOL);
        # an int pins the size (tests)
        self._cfg_workers = workers
        self._lock = make_lock("utils.workpool.WorkPool._lock")
        self._q: queue.Queue = queue.Queue()
        self._threads: list[threading.Thread] = []

    # -- sizing ------------------------------------------------------------

    def workers(self) -> int:
        return self._cfg_workers if self._cfg_workers is not None \
            else configured_workers()

    def parallel_enabled(self) -> bool:
        """True when run()/submit() would actually use worker threads."""
        return self.workers() > 1 and not _sched_active()

    def _ensure_started(self, want: int) -> None:
        with self._lock:
            while len(self._threads) < want:
                t = threading.Thread(  # vmt: disable=VMT011 — the pool itself
                    target=self._worker, daemon=True,
                    name=f"vm-workpool-{len(self._threads)}")
                self._threads.append(t)
                t.start()

    def _worker(self) -> None:
        me = threading.current_thread()
        _yield_tls.pool_worker = True
        while True:
            item = self._q.get()
            if item is None:        # shutdown sentinel (tests only)
                return
            self._exec(item)
            # converge toward a LOWERED VM_SEARCH_WORKERS: excess workers
            # retire after finishing a task (threads can't be resized in
            # place; idle excess workers retire at their next task)
            with self._lock:
                if len(self._threads) > max(self.workers(), 1) and \
                        me in self._threads:
                    self._threads.remove(me)
                    return

    def shutdown(self) -> None:
        """Stop the workers (tests; call between batches, not racing an
        in-flight run()); in production the daemon workers simply die
        with the process."""
        with self._lock:
            threads, self._threads = self._threads, []
        for _ in threads:
            self._q.put(None)
        for t in threads:
            t.join(timeout=10)

    # -- execution ---------------------------------------------------------

    def _exec(self, item) -> None:
        fn, i, batch, ctx, tracer, cost, t_enq = item
        err = None
        # cross-thread attribution: the task runs under the SUBMITTING
        # query's flight context, tracer and cost tracker, so spans and
        # cost laps created here attach to that query instead of an
        # anonymous worker (t_enq is None on the inline path — same
        # thread, context already right)
        if t_enq is not None:
            t_run = _time.perf_counter()
            prev_ctx = flightrec.set_ctx(ctx)
            prev_tr = querytracer.set_current(tracer)
            prev_cost = costacc.set_current(cost)
            # recorded AFTER set_ctx so the queue wait carries the
            # submitting query's ctx (it is part of that query's latency)
            flightrec.rec("pool:queue_wait", t_enq, t_run - t_enq)
        try:
            r = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised in _collect
            err = e
            r = None
        finally:
            if t_enq is not None:
                flightrec.rec("pool:task", t_run,
                              _time.perf_counter() - t_run)
                costacc.set_current(prev_cost)
                querytracer.set_current(prev_tr)
                flightrec.set_ctx(prev_ctx)
        with batch.lock:
            batch.results[i] = r
            if err is not None and batch.error is None:
                batch.error = err
            batch.pending -= 1
            last = batch.pending == 0
        if last:
            # exactly one put per batch: the waiter's done.get() pairs
            # with it (and carries the finisher's vector clock)
            batch.done.put(None)

    def _collect(self, batch: _Batch):
        """Wait for a batch, helping with queued work (any batch's)
        while waiting — the no-deadlock-under-nesting guarantee."""
        while True:
            with batch.lock:
                if batch.pending == 0:
                    break
            try:
                item = self._q.get_nowait()
            except queue.Empty:
                batch.done.get()
                # re-arm: Futures may be waited by several threads (or
                # twice by a helper that re-entered); each waiter must
                # find a token (its put also chains the clock edge on)
                batch.done.put(None)
                break
            if item is None:
                # a shutdown sentinel racing this waiter: hand it back to
                # the worker it was meant for and park — our batch's tasks
                # were enqueued before the sentinel, so workers drain them
                # first (FIFO)
                self._q.put(None)
                batch.done.get()
                batch.done.put(None)
                break
            self._exec(item)
        with batch.lock:
            err = batch.error
            results = list(batch.results)
        if err is not None:
            raise err
        return results

    def run(self, fns) -> list:
        """Execute every callable, returning results in submit order;
        the first raised exception is re-raised after the whole batch
        drains (no task of a failed batch is left running)."""
        fns = list(fns)
        n = len(fns)
        if n == 0:
            return []
        w = self.workers()
        if n == 1 or w <= 1 or _sched_active():
            # inline degraded mode still EXECUTES the tasks: count them,
            # so vm_workpool_tasks_total means "tasks run through the
            # pool seam" on 1-core boxes too (was 0 there, which read as
            # a dead pool on the dashboard and flaked the metric test)
            _TASKS_TOTAL.inc(n)
            return [fn() for fn in fns]
        self._ensure_started(min(w, n))
        batch = _Batch(n)
        _TASKS_TOTAL.inc(n)
        ctx = flightrec.get_ctx()
        tr = querytracer.current()
        cost = costacc.current()
        t_enq = _time.perf_counter()
        for i, fn in enumerate(fns):
            self._q.put((fn, i, batch, ctx, tr, cost, t_enq))
        return self._collect(batch)

    def submit(self, fn) -> Future:
        """Pipeline seam: run one task in the background (inline when
        the pool is disabled) and collect it later via Future.result()."""
        batch = _Batch(1)
        if self.workers() <= 1 or _sched_active():
            _TASKS_TOTAL.inc()
            self._exec((fn, 0, batch, 0, None, None, None))
            return Future(self, batch)
        self._ensure_started(1)
        _TASKS_TOTAL.inc()
        self._q.put((fn, 0, batch, flightrec.get_ctx(),
                     querytracer.current(), costacc.current(),
                     _time.perf_counter()))
        return Future(self, batch)


#: the one shared pool; sized by VM_SEARCH_WORKERS at first parallel use
POOL = WorkPool()

metricslib.REGISTRY.gauge("vm_workpool_workers",
                          callback=lambda: len(POOL._threads))
metricslib.REGISTRY.gauge("vm_workpool_queue_depth",
                          callback=POOL._q.qsize)


# -- search concurrency gate --------------------------------------------------

class SearchLimitError(RuntimeError):
    """The search could not start within the queue-wait budget.  HTTP
    layers convert this to 429 + Retry-After (the same shed-load
    contract as the ingest rate limiter's RateLimitedError)."""

    retry_after_s = 1


#: priority classes, best first; admission scans waiters by
#: (priority rank, arrival order) so "high" jumps "normal" jumps "low",
#: FIFO within a class
_PRIORITY_RANKS = {"high": 0, "normal": 1, "low": 2}


class TenantQuota:
    """One tenant's admission policy: concurrency cap, queue-time
    budget, priority class.  ``limit=0`` means "no per-tenant cap"
    (global gate only); ``queue_ms=None`` inherits the gate default."""

    __slots__ = ("limit", "queue_ms", "priority", "rank")

    def __init__(self, limit: int = 0, queue_ms: float | None = None,
                 priority: str = "normal"):
        self.limit = int(limit)
        self.queue_ms = queue_ms
        self.priority = priority
        self.rank = _PRIORITY_RANKS.get(priority, 1)


#: the no-quota default: global limit only, gate-default queue budget,
#: normal priority == exactly the pre-tenant SearchGate behavior
_DEFAULT_QUOTA = TenantQuota()


def parse_tenant_quotas(raw: str) -> dict:
    """Parse ``VM_TENANT_QUOTAS``.  Grammar::

        spec   := entry (';' entry)*
        entry  := tenant '=' limit [':' queue_ms [':' priority]]
        tenant := accountID [':' projectID] | '*'

    ``accountID`` alone means project 0; ``*`` sets the default quota
    for tenants not listed.  Unparseable entries are dropped (a typo'd
    env var must degrade to today's global behavior, not crash the
    storage engine at import).  Example::

        VM_TENANT_QUOTAS='0:0=8:5000:high;7=2:100:low;*=4'
    """
    quotas: dict = {}
    for entry in raw.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        tstr, eq, rhs = entry.partition("=")
        if not eq:
            continue
        tstr = tstr.strip()
        parts = rhs.strip().split(":")
        try:
            limit = int(parts[0])
            if limit < 0:
                # a negative cap would make the tenant permanently
                # inadmissible; drop the entry like any other typo
                continue
            queue_ms = float(parts[1]) if len(parts) > 1 and parts[1] \
                else None
            priority = parts[2] if len(parts) > 2 and parts[2] \
                else "normal"
            if priority not in _PRIORITY_RANKS:
                continue
            if tstr == "*":
                key = "*"
            elif ":" in tstr:
                a, p = tstr.split(":", 1)
                key = (int(a), int(p))
            else:
                key = (int(tstr), 0)
        except ValueError:
            continue
        quotas[key] = TenantQuota(limit, queue_ms, priority)
    return quotas


class _Waiter:
    """One queued admission request.  ``granted`` flips under the gate
    lock; the token queue additionally carries the releaser's vector
    clock to the blocked waiter (racetrace's queue put/get seam)."""

    __slots__ = ("rank", "seq", "tenant", "quota", "granted", "q")

    def __init__(self, rank: int, seq: int, tenant, quota: TenantQuota):
        self.rank = rank
        self.seq = seq
        self.tenant = tenant
        self.quota = quota
        self.granted = False
        self.q: queue.Queue = queue.Queue()


class TenantGate:
    """Per-tenant bounded admission for storage searches (the vmstorage
    ``-search.maxConcurrentRequests`` limiter analog, extended with
    multi-tenant QoS): up to ``limit`` searches run concurrently
    process-wide, and a tenant with a configured quota additionally
    never holds more than its own cap — one noisy tenant saturating its
    slots queues AGAINST ITSELF while other tenants keep being admitted
    from the remaining global capacity.  Excess callers queue for at
    most their queue-time budget and are then rejected loudly
    (:class:`SearchLimitError` → HTTP 429) instead of piling unbounded
    decode work onto a saturated host.

    Sizing: ``VM_SEARCH_CONCURRENCY`` (default ``2*cpu_count``) bounds
    the global gate; ``VM_SEARCH_MAX_QUEUE_MS`` (default 10s) is the
    default queue budget; ``VM_TENANT_QUOTAS`` (see
    :func:`parse_tenant_quotas`) adds per-tenant caps, queue budgets
    and priority classes.  The env var is re-read (and re-parsed only
    when its text changed) at every admission, so tests and operators
    flip quotas without restarting.  With ``VM_TENANT_QUOTAS`` unset
    the gate is behavior-identical to the pre-tenant SearchGate.

    Fairness: waiters are granted in (priority rank, arrival) order —
    strict priority between classes, FIFO within one — and a waiter
    blocked only by its OWN tenant quota never holds back later waiters
    of other tenants (no head-of-line blocking across tenants).

    Deterministic-scheduler safety: a thread running under
    ``devtools.sched`` spins through the (traced) gate lock instead of
    parking in a queue the turnstile cannot see, so the race-marked
    stress replays deterministically.

    Self-metrics: the global ``vm_search_*`` family (unchanged names)
    plus per-tenant ``vm_tenant_search_requests_total``,
    ``vm_tenant_search_queued_total``, ``vm_tenant_search_rejected_total``
    and ``vm_tenant_search_concurrent`` labeled ``{tenant="acc:proj"}``.
    Gate waits record ``fetch:queue_wait`` flight spans under the
    waiting query's context; rejections record a ``gate:rejected``
    flight instant so shed load shows up in captures."""

    #: bounded per-tenant metric cardinality: DISTINCT tenants beyond
    #: this fold into one shared ``tenant="other"`` label set (tenant
    #: ids come straight from the URL path — an unauthenticated client
    #: iterating ids must not grow process memory or metric output)
    _MAX_TENANT_METRICS = 1000

    def __init__(self, limit: int | None = None,
                 max_queue_ms: float | None = None,
                 quotas: dict | None = None):
        if limit is None:
            try:
                limit = int(os.environ.get("VM_SEARCH_CONCURRENCY", "0"))
            except ValueError:
                limit = 0
        if limit <= 0:
            limit = 2 * (os.cpu_count() or 1)
        if max_queue_ms is None:
            try:
                max_queue_ms = float(
                    os.environ.get("VM_SEARCH_MAX_QUEUE_MS", "10000"))
            except ValueError:
                max_queue_ms = 10000.0
        self.limit = limit
        self.max_queue_s = max_queue_ms / 1e3
        # quotas pinned at construction (tests) or re-read from
        # VM_TENANT_QUOTAS per admission (production/chaos runs)
        self._quotas_pinned = quotas
        self._quotas_env_raw: str | None = None
        self._quotas_env: dict = {}
        self._lock = make_lock("utils.workpool.TenantGate._lock")
        self._global_current = 0
        self._tenant_counts: dict = {}
        self._waiters: list[_Waiter] = []
        self._seq = 0
        metricslib.REGISTRY.gauge("vm_search_concurrent_limit").set(limit)
        self._current = metricslib.REGISTRY.gauge(
            "vm_search_concurrent_current")
        self._queued = metricslib.REGISTRY.counter(
            "vm_search_requests_queued_total")
        self._rejected = metricslib.REGISTRY.counter(
            "vm_search_requests_rejected_total")
        self._tenant_metric_memo: dict[tuple, object] = {}
        self._tenant_label_seen: set = set()

    # -- config ------------------------------------------------------------

    def _quotas(self) -> dict:
        if self._quotas_pinned is not None:
            return self._quotas_pinned
        raw = os.environ.get("VM_TENANT_QUOTAS", "")
        if raw != self._quotas_env_raw:
            self._quotas_env = parse_tenant_quotas(raw)
            self._quotas_env_raw = raw
        return self._quotas_env

    def quota_for(self, tenant) -> TenantQuota:
        q = self._quotas()
        return q.get(tenant) or q.get("*") or _DEFAULT_QUOTA

    # -- per-tenant metrics ------------------------------------------------

    def _tenant_metric(self, name: str, tenant, gauge: bool = False):
        key = (name, tenant)
        m = self._tenant_metric_memo.get(key)
        if m is not None:
            return m
        # fold decision is per DISTINCT tenant and sticky (the set only
        # grows), so inc/dec pairs always resolve to the same handle;
        # folded tenants share the (name, "other") entry and add NO
        # per-tenant memo keys — both the memo and the registry stay
        # bounded under tenant-id iteration.  GIL-benign without the
        # gate lock: a racing double-create resolves to the registry's
        # one handle.
        if tenant in self._tenant_label_seen or \
                len(self._tenant_label_seen) < self._MAX_TENANT_METRICS:
            self._tenant_label_seen.add(tenant)
            label = f"{tenant[0]}:{tenant[1]}"
        else:
            label = "other"
            key = (name, "other")
            m = self._tenant_metric_memo.get(key)
            if m is not None:
                return m
        full = metricslib.format_name(name, {"tenant": label})
        m = (metricslib.REGISTRY.gauge(full) if gauge
             else metricslib.REGISTRY.counter(full))
        self._tenant_metric_memo[key] = m
        return m

    # -- admission ---------------------------------------------------------

    def admit(self, tenant=(0, 0)) -> "_Admission":
        """Context manager admitting one search for `tenant`."""
        return _Admission(self, tenant)

    # back-compat: the gate itself is a context manager for the default
    # tenant (the pre-tenant SearchGate surface)
    def __enter__(self):
        self._acquire((0, 0))
        return self

    def __exit__(self, *exc):
        self._release((0, 0))
        return False

    def _admissible_locked(self, tenant, quota: TenantQuota) -> bool:
        if self._global_current >= self.limit:
            return False
        if quota.limit and \
                self._tenant_counts.get(tenant, 0) >= quota.limit:
            return False
        return True

    def _take_locked(self, tenant) -> None:
        self._global_current += 1
        self._tenant_counts[tenant] = \
            self._tenant_counts.get(tenant, 0) + 1

    def _grant_locked(self) -> None:
        """Hand free capacity to waiters in (priority, arrival) order.
        A waiter capped by its own tenant quota is skipped — later
        waiters of OTHER tenants still get the free global slots."""
        if not self._waiters or self._global_current >= self.limit:
            return
        for w in sorted(self._waiters, key=lambda w: (w.rank, w.seq)):
            if self._global_current >= self.limit:
                break
            if w.quota.limit and self._tenant_counts.get(
                    w.tenant, 0) >= w.quota.limit:
                continue
            self._take_locked(w.tenant)
            w.granted = True
            self._waiters.remove(w)
            # exactly one token per grant; carries the granter's clock
            w.q.put(None)

    def _acquire(self, tenant) -> None:
        quota = self.quota_for(tenant)
        self._tenant_metric("vm_tenant_search_requests_total",
                            tenant).inc()
        with self._lock:
            # fast path: empty queue + capacity (no waiter may be
            # overtaken — priority classes only reorder QUEUED requests)
            if not self._waiters and self._admissible_locked(tenant,
                                                             quota):
                self._take_locked(tenant)
                self._mark_admitted(tenant)
                return
            w = _Waiter(quota.rank, self._seq, tenant, quota)
            self._seq += 1
            self._waiters.append(w)
            # a newcomer may still be immediately grantable (e.g. the
            # queue holds only quota-capped waiters of another tenant)
            self._grant_locked()
            if w.granted:
                try:
                    w.q.get_nowait()
                except queue.Empty:
                    pass
                self._mark_admitted(tenant)
                return
        self._queued.inc()
        self._tenant_metric("vm_tenant_search_queued_total", tenant).inc()
        budget_s = (quota.queue_ms / 1e3 if quota.queue_ms is not None
                    else self.max_queue_s)
        t0 = _time.perf_counter()
        deadline = _time.monotonic() + budget_s
        admitted = self._wait(w, deadline)
        wait = _time.perf_counter() - t0
        # the previously invisible fetch phase: time QUEUED at the gate
        # before the search starts — without it the per-phase split
        # under-reports contended wall time
        _QUEUE_WAIT.inc(wait)
        flightrec.rec("fetch:queue_wait", t0, wait)
        if not admitted:
            self._rejected.inc()
            self._tenant_metric("vm_tenant_search_rejected_total",
                                tenant).inc()
            # shed load must stay attributable: an instant in the ring
            # ties the rejection into flight captures (the HTTP layer
            # additionally links it into the slow-query log)
            flightrec.instant(
                "gate:rejected",
                arg=f"{tenant[0]}:{tenant[1]} after {wait * 1e3:.0f}ms")
            per_tenant = (f" (tenant quota {quota.limit})"
                          if quota.limit else "")
            raise SearchLimitError(
                f"couldn't start the search within {budget_s:.1f}s: "
                f"{self.limit} concurrent searches are already "
                f"running{per_tenant} (raise VM_SEARCH_CONCURRENCY / "
                f"VM_TENANT_QUOTAS or reduce query load)")
        self._mark_admitted(tenant)

    def _wait(self, w: _Waiter, deadline: float) -> bool:
        """Wait for a grant until `deadline`; True = admitted.  On
        timeout the waiter deregisters itself — unless a grant raced
        the timeout, in which case the slot is kept."""
        if _sched_active():
            # deterministic-scheduler path: spin through the traced
            # lock (each acquire is a turnstile point) instead of
            # parking where the scheduler cannot see the dependency
            while True:
                with self._lock:
                    if w.granted:
                        return True
                    self._grant_locked()
                    if w.granted:
                        return True
                    if _time.monotonic() >= deadline:
                        self._waiters.remove(w)
                        return False
        while True:
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                break
            try:
                w.q.get(timeout=remaining)
                return True
            except queue.Empty:
                break
        with self._lock:
            if w.granted:
                # the grant raced our timeout: the token is already in
                # the queue — consume it and keep the slot
                try:
                    w.q.get_nowait()
                except queue.Empty:
                    pass
                return True
            self._waiters.remove(w)
        return False

    def _mark_admitted(self, tenant) -> None:
        self._current.inc()
        self._tenant_metric("vm_tenant_search_concurrent", tenant,
                            gauge=True).inc()

    def _release(self, tenant) -> None:
        with self._lock:
            self._global_current -= 1
            n = self._tenant_counts.get(tenant, 0) - 1
            if n > 0:
                self._tenant_counts[tenant] = n
            else:
                self._tenant_counts.pop(tenant, None)
            self._grant_locked()
        self._current.dec()
        self._tenant_metric("vm_tenant_search_concurrent", tenant,
                            gauge=True).dec()

    # -- introspection (tests) --------------------------------------------

    def occupancy(self) -> tuple[int, dict]:
        """(global in-flight, {tenant: in-flight}) snapshot."""
        with self._lock:
            return self._global_current, dict(self._tenant_counts)


class _Admission:
    __slots__ = ("_gate", "_tenant")

    def __init__(self, gate: TenantGate, tenant):
        self._gate = gate
        self._tenant = tenant

    def __enter__(self):
        self._gate._acquire(self._tenant)
        return self

    def __exit__(self, *exc):
        self._gate._release(self._tenant)
        return False


#: the pre-tenant name; the gate with no VM_TENANT_QUOTAS configured is
#: behavior-identical to the old global SearchGate
SearchGate = TenantGate

#: process-wide gate (one storage engine per process in production)
SEARCH_GATE = TenantGate()


# -- merge concurrency gate ---------------------------------------------------

class MergeGate:
    """Bounded admission for heavy part writes — flush encodes and
    background merges (the reference's ``mergeWorkersCount`` bound,
    lib/storage/partition.go): at most ``limit`` part writes run at
    once across data partitions AND index mergesets, so a flush storm
    cannot saturate every core with zstd/fsync while ingest and queries
    starve.

    ``VM_MERGE_WORKERS`` (default ``cpu_count``) sizes the gate; the
    gate only *bounds* concurrency — the work itself is fanned by
    ``Table.flush_to_disk``/``force_merge`` over :data:`POOL`.

    Serve priority: on entry the gate YIELDS to in-flight serving — while
    any search/serve section is active (``serving_busy``), merge
    admission defers for up to ``VM_MERGE_YIELD_MS`` (default 250; 0
    disables) and resumes as soon as serving drains.  This keeps a
    background flush/merge storm from sitting on every core exactly while
    a dashboard refresh is being served (the measured source of
    steady-state refresh-latency variance).  Bounded: merges always
    proceed after the budget, so ingest pressure cannot starve them;
    counted by ``vm_merge_gate_yields_total``.  Skipped under the
    deterministic scheduler (wall-clock waits would break replay)."""

    def __init__(self, limit: int | None = None):
        if limit is None:
            try:
                limit = int(os.environ.get("VM_MERGE_WORKERS", "0"))
            except ValueError:
                limit = 0
        if limit <= 0:
            limit = os.cpu_count() or 1
        self.limit = limit
        self._sem = threading.Semaphore(limit)
        self._pending = metricslib.Gauge("pending")
        self._active = metricslib.Gauge("active")
        self._yields = metricslib.REGISTRY.counter(
            "vm_merge_gate_yields_total")

    @property
    def yields(self) -> int:
        """Merge admissions that deferred to in-flight serving."""
        return self._yields.get()

    def _maybe_yield(self) -> None:
        # Never yield on a thread that would invert the priority it
        # exists to protect: a shared-POOL worker sleeping here holds a
        # pool slot the serve's own fetch tasks are queued behind, and a
        # serving thread that picked up a queued flush task while helping
        # the pool (WorkPool._collect) would block on its OWN serving
        # gauge for the whole budget.  The yield therefore applies only
        # on dedicated flusher/merger threads (and direct callers).
        if getattr(_yield_tls, "pool_worker", False) or \
                getattr(_yield_tls, "serving", 0):
            return
        try:
            budget_ms = float(os.environ.get("VM_MERGE_YIELD_MS", "250"))
        except ValueError:
            budget_ms = 250.0
        if budget_ms <= 0 or _sched_active() or not serving_busy():
            return
        self._yields.inc()
        t0 = _time.perf_counter()
        deadline = _time.monotonic() + budget_ms / 1e3
        while _time.monotonic() < deadline and serving_busy():
            _time.sleep(0.002)
        flightrec.rec("merge:yield", t0, _time.perf_counter() - t0)

    @property
    def pending(self) -> int:
        """Writers waiting for a merge slot."""
        return int(self._pending.get())

    @property
    def active(self) -> int:
        """Writers holding a merge slot."""
        return int(self._active.get())

    def __enter__(self):
        self._maybe_yield()
        # t0 AFTER the yield: _maybe_yield records its own merge:yield
        # span, so gate_wait covers only the slot-semaphore wait — the
        # two flight spans partition the admission delay instead of
        # double-reporting the same interval
        t0 = _time.perf_counter()
        self._pending.inc()
        try:
            self._sem.acquire()
        finally:
            self._pending.dec()
            # slot wait: the gap between a flush/merge being REQUESTED
            # (serve-priority yield already served) and a worker slot
            # freeing up
            wait = _time.perf_counter() - t0
            if wait > 0.0005:
                flightrec.rec("merge:gate_wait", t0, wait)
        self._active.inc()
        return self

    def __exit__(self, *exc):
        self._active.dec()
        self._sem.release()
        return False


#: process-wide merge gate; sized by VM_MERGE_WORKERS at import
MERGE_GATE = MergeGate()

metricslib.REGISTRY.gauge("vm_merge_pending",
                          callback=lambda: MERGE_GATE.pending)
metricslib.REGISTRY.gauge("vm_merge_active",
                          callback=lambda: MERGE_GATE.active)
