"""Prometheus TSDB block reader (+ minimal writer for tests).

Implements the on-disk TSDB block format (the reference vmctl's
prometheus mode reads these via prometheus/tsdb; format spec:
prometheus/tsdb/docs/format/{index,chunks}.md):

  block/
    meta.json
    index          magic 0xBAAAD700 v2: symbols, series (16-byte aligned,
                   label symbol-refs + chunk metas), TOC at the tail
    chunks/000001  magic 0x85BD40DD v1: uvarint len, encoding byte
                   (1 = XOR), Gorilla bitstream, crc32c

XOR chunks hold (timestamp-ms, float64) samples with delta-of-delta
timestamps (prefix codes 0 / 10+14b / 110+17b / 1110+20b / 1111+64b) and
leading/trailing-aware value XOR — decoded here with a whole-chunk int
bitreader, no per-bit Python.

read_block() yields (labels dict, ts_ms int64[], values float64[]) per
series; verify_block() walks every structure and CRC and returns a
report (the vmctl verify-block mode)."""

from __future__ import annotations

import json
import os
import struct

import numpy as np

INDEX_MAGIC = 0xBAAAD700
CHUNKS_MAGIC = 0x85BD40DD


# -- crc32 Castagnoli (TSDB uses crc32c, not zlib's IEEE) -------------------

def _make_crc32c_table():
    poly = 0x82F63B78
    tbl = np.empty(256, np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ poly if c & 1 else c >> 1
        tbl[i] = c
    return tbl


def _make_crc32c_tables8():
    """Slicing-by-8 tables: 8 bytes consumed per loop iteration (~6x a
    per-byte loop in pure Python; real blocks carry hundreds of MB of
    chunk data through verify-block)."""
    t0 = _make_crc32c_table().tolist()
    tables = [t0]
    for k in range(1, 8):
        prev = tables[k - 1]
        tables.append([t0[prev[i] & 0xFF] ^ (prev[i] >> 8)
                       for i in range(256)])
    return tables


_CRC32C_T = _make_crc32c_tables8()


def crc32c(data: bytes) -> int:
    crc = 0xFFFFFFFF
    t0, t1, t2, t3, t4, t5, t6, t7 = _CRC32C_T
    n8 = len(data) // 8 * 8
    i = 0
    while i < n8:
        crc ^= int.from_bytes(data[i:i + 4], "little")
        b4 = data[i + 4]
        b5 = data[i + 5]
        b6 = data[i + 6]
        b7 = data[i + 7]
        crc = (t7[crc & 0xFF] ^ t6[(crc >> 8) & 0xFF] ^
               t5[(crc >> 16) & 0xFF] ^ t4[(crc >> 24) & 0xFF] ^
               t3[b4] ^ t2[b5] ^ t1[b6] ^ t0[b7])
        i += 8
    for b in data[n8:]:
        crc = t0[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


# -- varints ----------------------------------------------------------------

def _uvarint(b: bytes, i: int) -> tuple[int, int]:
    shift = x = 0
    while True:
        c = b[i]
        i += 1
        x |= (c & 0x7F) << shift
        if not c & 0x80:
            return x, i
        shift += 7


def _varint(b: bytes, i: int) -> tuple[int, int]:
    u, i = _uvarint(b, i)
    return (u >> 1) ^ -(u & 1), i


def _put_uvarint(x: int) -> bytes:
    out = bytearray()
    while True:
        c = x & 0x7F
        x >>= 7
        if x:
            out.append(c | 0x80)
        else:
            out.append(c)
            return bytes(out)


def _put_varint(x: int) -> bytes:
    # zigzag encode (Go binary.PutVarint); Python's arithmetic shift
    # makes the branchless form exact for negatives too
    return _put_uvarint((x << 1) ^ (x >> 63))


# -- bit reader over the whole chunk ----------------------------------------

class _BitReader:
    """MSB-first bitstream (prometheus/tsdb bstream)."""

    __slots__ = ("val", "nbits", "pos")

    def __init__(self, data: bytes):
        self.val = int.from_bytes(data, "big")
        self.nbits = len(data) * 8
        self.pos = 0

    def bits(self, n: int) -> int:
        p = self.pos
        self.pos = p + n
        return (self.val >> (self.nbits - p - n)) & ((1 << n) - 1)

    def bit(self) -> int:
        return self.bits(1)


def decode_xor_chunk(data: bytes):
    """(ts int64[], vals float64[]) from one XOR chunk payload."""
    n = struct.unpack_from(">H", data, 0)[0]
    ts = np.empty(n, np.int64)
    vals = np.empty(n, np.float64)
    if n == 0:
        return ts, vals
    # first sample: varint t, raw 64-bit v (byte-aligned prefix)
    t0, i = _varint(data, 2)
    v0 = struct.unpack_from(">d", data, i)[0]
    i += 8
    ts[0] = t0
    vals[0] = v0
    if n == 1:
        return ts, vals
    # second sample: uvarint tDelta, then the value bitstream begins
    t_delta, i = _uvarint(data, i)
    br = _BitReader(data[i:])
    t = t0 + t_delta
    ts[1] = t
    leading = trailing = 0
    vbits = struct.unpack(">Q", struct.pack(">d", v0))[0]

    def read_value():
        nonlocal vbits, leading, trailing
        if br.bit() == 0:
            return
        if br.bit():
            leading = br.bits(5)
            mbits = br.bits(6) or 64
            trailing = 64 - leading - mbits
        mbits = 64 - leading - trailing
        vbits ^= br.bits(mbits) << trailing

    read_value()
    vals[1] = struct.unpack(">d", struct.pack(">Q", vbits))[0]
    for k in range(2, n):
        # timestamp dod prefix code
        if br.bit() == 0:
            dod = 0
        elif br.bit() == 0:
            dod = _sign_extend(br.bits(14), 14)
        elif br.bit() == 0:
            dod = _sign_extend(br.bits(17), 17)
        elif br.bit() == 0:
            dod = _sign_extend(br.bits(20), 20)
        else:
            dod = _sign_extend(br.bits(64), 64)
        t_delta += dod
        t += t_delta
        ts[k] = t
        read_value()
        vals[k] = struct.unpack(">d", struct.pack(">Q", vbits))[0]
    return ts, vals


def _sign_extend(bits: int, n: int) -> int:
    # prometheus quirk: `> (1 << (n-1))`, so -2^(n-1) is never produced
    if bits > (1 << (n - 1)):
        bits -= 1 << n
    return bits


# -- index / chunks reading -------------------------------------------------

class TSDBBlock:
    """One opened block directory.

    `verify_index=True` additionally checks the index CRCs (TOC, symbol
    table, each series entry) — the verify-block mode; plain reads skip
    them for speed."""

    def __init__(self, path: str, verify_index: bool = False):
        self.path = path
        self.verify_index = verify_index
        self.meta = {}
        mp = os.path.join(path, "meta.json")
        if os.path.exists(mp):
            self.meta = json.load(open(mp))
        self._index = open(os.path.join(path, "index"), "rb").read()
        self._segments: list[bytes] = []
        cdir = os.path.join(path, "chunks")
        for name in sorted(os.listdir(cdir)):
            self._segments.append(
                open(os.path.join(cdir, name), "rb").read())
        self._symbols: list[str] = []
        self._toc = None
        self._parse_header()

    def _parse_header(self):
        ix = self._index
        magic, ver = struct.unpack_from(">IB", ix, 0)
        if magic != INDEX_MAGIC:
            raise ValueError(f"bad index magic {magic:#x}")
        if ver != 2:
            # v1 label refs are byte offsets into the symbol section, not
            # table indexes — decoding them with v2 semantics would pair
            # labels arbitrarily; reject loudly instead
            raise ValueError(f"unsupported index version {ver} (only v2)")
        # TOC: 6 x u64 + crc32 at the tail
        if self.verify_index:
            want = struct.unpack_from(">I", ix, len(ix) - 4)[0]
            if crc32c(ix[len(ix) - 52:len(ix) - 4]) != want:
                raise ValueError("index TOC crc mismatch")
        toc = struct.unpack_from(">6Q", ix, len(ix) - 52)
        self._toc = {
            "symbols": toc[0], "series": toc[1],
            "label_indices": toc[2], "label_offset_table": toc[3],
            "postings": toc[4], "postings_offset_table": toc[5],
        }
        # symbol table: u32 len, u32 count, then uvarint-prefixed strings
        off = self._toc["symbols"]
        _len, cnt = struct.unpack_from(">II", ix, off)
        if self.verify_index:
            want = struct.unpack_from(">I", ix, off + 4 + _len)[0]
            if crc32c(ix[off + 4:off + 4 + _len]) != want:
                raise ValueError("index symbol-table crc mismatch")
        i = off + 8
        syms = []
        for _ in range(cnt):
            n, i = _uvarint(ix, i)
            syms.append(ix[i:i + n].decode("utf-8", "replace"))
            i += n
        self._symbols = syms

    def series(self):
        """Yield (labels dict, [(mint, maxt, chunk_ref), ...])."""
        ix = self._index
        pos = self._toc["series"]
        end = self._toc["label_indices"] or (len(ix) - 52)
        syms = self._symbols
        while pos < end:
            pos = (pos + 15) // 16 * 16  # entries are 16-byte aligned
            if pos >= end:
                break
            ln, i = _uvarint(ix, pos)
            if ln == 0:
                break  # zero padding: end of section
            body_end = i + ln
            if self.verify_index:
                want = struct.unpack_from(">I", ix, body_end)[0]
                if crc32c(ix[i:body_end]) != want:
                    raise ValueError(
                        f"index series entry crc mismatch at {pos}")
            nlabels, i = _uvarint(ix, i)
            labels = {}
            for _ in range(nlabels):
                kref, i = _uvarint(ix, i)
                vref, i = _uvarint(ix, i)
                labels[syms[kref]] = syms[vref]
            nchunks, i = _uvarint(ix, i)
            chunks = []
            if nchunks:
                mint, i = _varint(ix, i)
                span, i = _uvarint(ix, i)
                ref, i = _uvarint(ix, i)
                chunks.append((mint, mint + span, ref))
                prev_maxt = mint + span
                for _ in range(nchunks - 1):
                    dmint, i = _varint(ix, i)
                    span, i = _uvarint(ix, i)
                    dref, i = _varint(ix, i)
                    mint = prev_maxt + dmint
                    ref += dref
                    chunks.append((mint, mint + span, ref))
                    prev_maxt = mint + span
            yield labels, chunks
            pos = body_end + 4  # + crc32

    def read_chunk(self, ref: int, verify_crc: bool = False):
        """Decode the chunk at `ref` (= segment << 32 | offset)."""
        seg = self._segments[ref >> 32]
        off = ref & 0xFFFFFFFF
        ln, i = _uvarint(seg, off)
        enc = seg[i]
        data = seg[i + 1:i + 1 + ln]
        if verify_crc:
            want = struct.unpack_from(">I", seg, i + 1 + ln)[0]
            got = crc32c(seg[i:i + 1 + ln])
            if got != want:
                raise ValueError(
                    f"chunk crc mismatch at ref {ref:#x}")
        if enc != 1:
            raise ValueError(f"unsupported chunk encoding {enc}")
        return decode_xor_chunk(data)


def read_block(path: str, verify_crc: bool = False,
               on_unsupported=None):
    """Yield (labels dict, ts_ms int64[], values float64[]) per series.

    `on_unsupported(labels, error)` is called for series whose chunks use
    an unsupported encoding (e.g. native-histogram chunks, encoding 2/3);
    those series are SKIPPED instead of aborting a migration mid-block.
    Pass None to raise instead."""
    blk = TSDBBlock(path)
    for labels, chunks in blk.series():
        if not chunks:
            continue
        try:
            parts = [blk.read_chunk(ref, verify_crc)
                     for _, _, ref in chunks]
        except ValueError as e:
            if on_unsupported is None:
                raise
            on_unsupported(labels, e)
            continue
        ts = np.concatenate([p[0] for p in parts])
        vals = np.concatenate([p[1] for p in parts])
        yield labels, ts, vals


def verify_block(path: str) -> dict:
    """Walk every structure + CRC; returns a report dict (the reference
    vmctl verify-block mode, app/vmctl/main.go:514)."""
    report = {"path": path, "ok": True, "errors": [],
              "series": 0, "chunks": 0, "samples": 0,
              "min_ts": None, "max_ts": None}
    try:
        blk = TSDBBlock(path, verify_index=True)
    except (OSError, ValueError, KeyError, struct.error) as e:
        report["ok"] = False
        report["errors"].append(f"cannot open block: {e}")
        return report
    def _series_iter():
        # an index-crc failure aborts the series walk; record it rather
        # than crashing the report
        try:
            yield from blk.series()
        except (ValueError, IndexError, struct.error) as e:
            report["ok"] = False
            report["errors"].append(f"index: {e}")

    for labels, chunks in _series_iter():
        report["series"] += 1
        if not labels.get("__name__"):
            report["ok"] = False
            report["errors"].append(f"series without __name__: {labels}")
        prev_t = None
        for mint, maxt, ref in chunks:
            report["chunks"] += 1
            try:
                ts, vals = blk.read_chunk(ref, verify_crc=True)
            except (ValueError, IndexError, struct.error) as e:
                report["ok"] = False
                report["errors"].append(f"chunk {ref:#x}: {e}")
                continue
            report["samples"] += int(ts.size)
            if ts.size:
                if not bool((np.diff(ts) >= 0).all()):
                    report["ok"] = False
                    report["errors"].append(
                        f"chunk {ref:#x}: timestamps out of order")
                if prev_t is not None and ts[0] < prev_t:
                    report["ok"] = False
                    report["errors"].append(
                        f"chunk {ref:#x}: overlaps previous chunk")
                prev_t = int(ts[-1])
                lo, hi = int(ts[0]), int(ts[-1])
                report["min_ts"] = (lo if report["min_ts"] is None
                                    else min(report["min_ts"], lo))
                report["max_ts"] = (hi if report["max_ts"] is None
                                    else max(report["max_ts"], hi))
                if int(mint) > lo or int(maxt) < hi:
                    report["ok"] = False
                    report["errors"].append(
                        f"chunk {ref:#x}: index time range "
                        f"[{mint},{maxt}] does not cover data")
    return report


# -- minimal writer (tests / fixtures) --------------------------------------

class _BitWriter:
    __slots__ = ("buf", "acc", "nacc")

    def __init__(self):
        self.buf = bytearray()
        self.acc = 0
        self.nacc = 0

    def bits(self, v: int, n: int):
        self.acc = (self.acc << n) | (v & ((1 << n) - 1))
        self.nacc += n
        while self.nacc >= 8:
            self.nacc -= 8
            self.buf.append((self.acc >> self.nacc) & 0xFF)

    def done(self) -> bytes:
        if self.nacc:
            self.buf.append((self.acc << (8 - self.nacc)) & 0xFF)
            self.nacc = 0
        return bytes(self.buf)


def encode_xor_chunk(ts: np.ndarray, vals: np.ndarray) -> bytes:
    """Inverse of decode_xor_chunk (used to build test fixtures)."""
    n = int(ts.size)
    out = bytearray(struct.pack(">H", n))
    if n == 0:
        return bytes(out)
    out += _put_varint(int(ts[0]))
    out += struct.pack(">d", float(vals[0]))
    if n == 1:
        return bytes(out)
    t_delta = int(ts[1]) - int(ts[0])
    out += _put_uvarint(t_delta)
    bw = _BitWriter()
    leading, trailing = 0xFF, 0
    prev_bits = struct.unpack(">Q", struct.pack(">d", float(vals[0])))[0]

    def write_value(v: float):
        nonlocal prev_bits, leading, trailing
        bits = struct.unpack(">Q", struct.pack(">d", float(v)))[0]
        x = prev_bits ^ bits
        prev_bits = bits
        if x == 0:
            bw.bits(0, 1)
            return
        bw.bits(1, 1)
        lead = _clz64(x)
        trail = _ctz64(x)
        if lead > 31:
            lead = 31
        if leading != 0xFF and lead >= leading and trail >= trailing:
            bw.bits(0, 1)
            bw.bits(x >> trailing, 64 - leading - trailing)
        else:
            leading, trailing = lead, trail
            bw.bits(1, 1)
            bw.bits(lead, 5)
            mbits = 64 - lead - trail
            bw.bits(mbits & 0x3F, 6)  # 64 encodes as 0
            bw.bits(x >> trail, mbits)

    write_value(float(vals[1]))
    prev_delta = t_delta
    for k in range(2, n):
        delta = int(ts[k]) - int(ts[k - 1])
        dod = delta - prev_delta
        prev_delta = delta
        if dod == 0:
            bw.bits(0, 1)
        elif -8191 <= dod <= 8192:
            bw.bits(0b10, 2)
            bw.bits(dod & 0x3FFF, 14)
        elif -65535 <= dod <= 65536:
            bw.bits(0b110, 3)
            bw.bits(dod & 0x1FFFF, 17)
        elif -524287 <= dod <= 524288:
            bw.bits(0b1110, 4)
            bw.bits(dod & 0xFFFFF, 20)
        else:
            bw.bits(0b1111, 4)
            bw.bits(dod & ((1 << 64) - 1), 64)
        write_value(float(vals[k]))
    return bytes(out) + bw.done()


def _clz64(x: int) -> int:
    return 64 - x.bit_length()


def _ctz64(x: int) -> int:
    return (x & -x).bit_length() - 1 if x else 64


def write_block(path: str, series) -> None:
    """Write a minimal v2 TSDB block: series = [(labels dict, ts, vals)].
    Fixture-grade (no postings/label indices beyond empty sections) but
    byte-compatible with read_block/verify_block and the real format for
    the sections it emits."""
    os.makedirs(os.path.join(path, "chunks"), exist_ok=True)
    # chunks segment
    seg = bytearray(struct.pack(">IB3x", CHUNKS_MAGIC, 1))
    refs = []
    for labels, ts, vals in series:
        data = encode_xor_chunk(np.asarray(ts, np.int64),
                                np.asarray(vals, np.float64))
        body = bytes([1]) + data  # crc covers encoding + data only
        refs.append(len(seg))
        seg += _put_uvarint(len(data)) + body + \
            struct.pack(">I", crc32c(body))
    with open(os.path.join(path, "chunks", "000001"), "wb") as f:
        f.write(seg)
    # symbols
    symset = set()
    for labels, _, _ in series:
        for k, v in labels.items():
            symset.add(k)
            symset.add(v)
    syms = sorted(symset)
    sym_of = {s: i for i, s in enumerate(syms)}
    sym_body = struct.pack(">I", len(syms))
    for s in syms:
        b = s.encode()
        sym_body += _put_uvarint(len(b)) + b
    index = bytearray(struct.pack(">IB", INDEX_MAGIC, 2))
    toc_symbols = len(index)
    index += struct.pack(">I", len(sym_body)) + sym_body
    index += struct.pack(">I", crc32c(sym_body))
    # series section, 16-byte aligned entries
    toc_series = (len(index) + 15) // 16 * 16
    index += b"\x00" * (toc_series - len(index))
    min_t = None
    max_t = None
    for (labels, ts, vals), ref in zip(series, refs):
        ts = np.asarray(ts, np.int64)
        body = _put_uvarint(len(labels))
        for k in sorted(labels):
            body += _put_uvarint(sym_of[k]) + _put_uvarint(sym_of[labels[k]])
        body += _put_uvarint(1)  # one chunk per series
        mint, maxt = int(ts[0]), int(ts[-1])
        min_t = mint if min_t is None else min(min_t, mint)
        max_t = maxt if max_t is None else max(max_t, maxt)
        body += _put_varint(mint)
        body += _put_uvarint(maxt - mint)
        body += _put_uvarint(ref)
        pos = (len(index) + 15) // 16 * 16
        index += b"\x00" * (pos - len(index))
        entry = _put_uvarint(len(body)) + body
        index += entry + struct.pack(">I", crc32c(body))
    toc_label_indices = len(index)
    # TOC (empty offsets for sections we do not emit)
    toc = struct.pack(">6Q", toc_symbols, toc_series, toc_label_indices,
                      0, 0, 0)
    index += toc + struct.pack(">I", crc32c(toc))
    with open(os.path.join(path, "index"), "wb") as f:
        f.write(index)
    with open(os.path.join(path, "meta.json"), "w") as f:
        json.dump({"version": 1, "minTime": min_t, "maxTime": max_t,
                   "stats": {"numSeries": len(series)}}, f)
