"""Per-query resource cost accounting (the cost half of the cost-and-
profile observability plane; reference: per-query tracing +
``/api/v1/status/top_queries`` attribute every query's server-side cost).

One :class:`CostTracker` lives per query (``EvalConfig._cost``, shared
by every child config the way ``_samples_scanned`` is) and accumulates:

- ``samples``       — samples scanned by the evaluator (the
  ``count_samples`` / -search.maxSamplesPerQuery scope)
- ``storage_samples`` — samples scanned SERVER-SIDE on storage nodes,
  shipped back in the search RPC metadata frame (0 on single-node
  setups where the evaluator's own count is the storage count)
- ``part_bytes``    — raw column bytes handed back by the part fetch
  (timestamps + values, post-decode)
- ``rpc_bytes``     — decompressed RPC payload bytes received from
  storage nodes during the query's fan-out
- ``device_up`` / ``device_down`` — H2D/D2H bytes of the device plane
- ``rows``          — result rows (series) returned to the client
- per-bucket wall/CPU laps (``wall_ms`` / ``cpu_ms`` keyed by the
  existing phase-seam names: ``fetch:index_search``,
  ``fetch:assemble_native``, ``fetch:rollup``, ``cache:merge``, ...) —
  CPU measured on the THREAD clock (``time.thread_time``), so a lap
  says what the query burned, not what it waited for.

The tracker is reached from the storage/cache/device seams through a
thread-local "current tracker" (:func:`set_current`), installed by
``exec_query`` / the HTTP observability bracket / the vmstorage RPC
handlers and propagated to pool workers by ``utils/workpool`` the same
way the flight context and query tracer are.  No tracker installed ==
every hook is a cheap no-op.

Per-tenant aggregation: :func:`record_usage` folds a finished query's
tracker into the bounded per-tenant usage table behind
``/api/v1/status/usage`` and the ``vm_tenant_usage_*`` counters
(sticky tenant-label folding — the PR-9 TenantGate rule — so URL-
sourced tenant ids can never grow the registry unbounded).
"""

from __future__ import annotations

import threading
import time

from . import metrics as metricslib

_tls = threading.local()


class CostTracker:
    """One query's resource-cost accumulator.  Thread-safe: fan-out
    workers and the serving thread report into the same tracker."""

    __slots__ = ("_lock", "samples", "storage_samples", "part_bytes",
                 "rpc_bytes", "device_up", "device_down", "rows",
                 "wall_ms", "cpu_ms", "local_wall_ms", "remote_nodes",
                 "cost_partial")

    def __init__(self):
        self._lock = threading.Lock()
        self.samples = 0
        self.storage_samples = 0
        self.part_bytes = 0
        self.rpc_bytes = 0
        self.device_up = 0
        self.device_down = 0
        self.rows = 0
        self.wall_ms: dict[str, float] = {}
        self.cpu_ms: dict[str, float] = {}
        #: wall ms recorded by THIS process's laps only (merge_remote
        #: excluded): the denominator the eval:other/serve:other
        #: leftover buckets subtract from — remote nodes' laps accrue
        #: CONCURRENTLY and may sum past the local wall clock
        self.local_wall_ms = 0.0
        #: storage nodes that shipped a cost frame during the fan-out
        self.remote_nodes = 0
        #: True when at least one fan-out leg could NOT ship cost (an
        #: old-version node): totals are a lower bound, not wrong data
        self.cost_partial = False

    # -- scalar accumulators (GIL-cheap, lock for the read-modify-write) --

    def add_samples(self, n: int) -> None:
        with self._lock:
            self.samples += int(n)

    def add_part_bytes(self, n: int) -> None:
        with self._lock:
            self.part_bytes += int(n)

    def add_rpc_bytes(self, n: int) -> None:
        with self._lock:
            self.rpc_bytes += int(n)

    def add_device(self, up: int = 0, down: int = 0) -> None:
        with self._lock:
            self.device_up += int(up)
            self.device_down += int(down)

    def add_rows(self, n: int) -> None:
        with self._lock:
            self.rows += int(n)

    def lap(self, bucket: str, wall_s: float, cpu_s: float) -> None:
        """One timed lap of `bucket`: wall seconds plus the recording
        thread's CPU seconds (clamped to the wall lap — a stale stamp
        must never attribute another phase's CPU here)."""
        if wall_s < 0:
            wall_s = 0.0
        cpu_s = min(max(cpu_s, 0.0), wall_s if wall_s > 0 else cpu_s)
        with self._lock:
            self.wall_ms[bucket] = self.wall_ms.get(bucket, 0.0) \
                + wall_s * 1e3
            self.cpu_ms[bucket] = self.cpu_ms.get(bucket, 0.0) \
                + cpu_s * 1e3
            self.local_wall_ms += wall_s * 1e3

    # -- cross-RPC merge --------------------------------------------------

    def remote_dict(self) -> dict:
        """The wire shape shipped in the search RPC metadata frame.
        ``samples`` is THIS level's own scan count (a multilevel node's
        leaf counts live in its ``storage_samples`` and are NOT re-
        shipped — the parent would double-count them against the
        node's own merged-result count)."""
        with self._lock:
            return {"samples": self.samples,
                    "partBytes": self.part_bytes,
                    "rpcBytes": self.rpc_bytes,
                    "deviceUp": self.device_up,
                    "deviceDown": self.device_down,
                    "wallMs": {k: round(v, 3)
                               for k, v in self.wall_ms.items()},
                    "cpuMs": {k: round(v, 3)
                              for k, v in self.cpu_ms.items()}}

    def merge_remote(self, d: dict | None) -> None:
        """Fold one storage node's shipped cost frame in.  ``None``
        (an old-version node that shipped no cost) degrades to partial
        accounting instead of an error."""
        if not isinstance(d, dict):
            with self._lock:
                self.cost_partial = True
            return
        with self._lock:
            self.remote_nodes += 1
            # node-side samples land in storage_samples: the evaluator
            # counts the MERGED fan-out result into .samples itself, so
            # adding node samples there would double-count
            self.storage_samples += int(d.get("samples", 0))
            self.part_bytes += int(d.get("partBytes", 0))
            self.device_up += int(d.get("deviceUp", 0))
            self.device_down += int(d.get("deviceDown", 0))
            # a multilevel node's own rpc_bytes chain up too
            self.rpc_bytes += int(d.get("rpcBytes", 0))
            for k, v in (d.get("wallMs") or {}).items():
                self.wall_ms[k] = self.wall_ms.get(k, 0.0) + float(v)
            for k, v in (d.get("cpuMs") or {}).items():
                self.cpu_ms[k] = self.cpu_ms.get(k, 0.0) + float(v)

    # -- summaries --------------------------------------------------------

    def cpu_ms_total(self) -> float:
        with self._lock:
            return sum(self.cpu_ms.values())

    def wall_ms_total(self) -> float:
        with self._lock:
            return sum(self.wall_ms.values())

    def local_wall_ms_total(self) -> float:
        """Wall ms of this process's OWN laps (remote merges excluded) —
        the only valid baseline for leftover-bucket computation: merged
        per-node laps run concurrently and can sum past local wall."""
        with self._lock:
            return self.local_wall_ms

    def summary(self) -> dict:
        """The cost columns surfaced in top_queries/slow_queries and
        the bench artifact."""
        with self._lock:
            out = {"samplesScanned": self.samples,
                   "bytesRead": self.part_bytes,
                   "cpuMs": round(sum(self.cpu_ms.values()), 3),
                   "deviceBytes": self.device_up + self.device_down,
                   "rpcBytes": self.rpc_bytes,
                   "rowsReturned": self.rows,
                   "wallMsByPhase": {k: round(v, 3)
                                     for k, v in self.wall_ms.items()},
                   "cpuMsByPhase": {k: round(v, 3)
                                    for k, v in self.cpu_ms.items()}}
            if self.storage_samples:
                out["storageSamplesScanned"] = self.storage_samples
            if self.cost_partial:
                out["costPartial"] = True
            return out


# -- thread-local current tracker --------------------------------------------


def set_current(tracker: CostTracker | None) -> CostTracker | None:
    """Install `tracker` as this thread's cost sink; returns the
    previous one (restore it when the bracket exits).  Re-stamps the
    thread-CPU lap clock so the first lap never inherits another
    query's CPU."""
    prev = getattr(_tls, "current", None)
    _tls.current = tracker
    _tls.cpu0 = time.thread_time()
    return prev


def current() -> CostTracker | None:
    return getattr(_tls, "current", None)


def restamp() -> None:
    """Reset this thread's CPU lap stamp (call at the start of a lap
    chain, e.g. right after taking the wall t0 for the first phase)."""
    _tls.cpu0 = time.thread_time()


def lap(bucket: str, wall_s: float) -> None:
    """Account one phase lap to the current tracker: `wall_s` of wall
    time plus the thread-CPU delta since the previous lap/restamp on
    this thread.  No tracker installed == one TLS read."""
    tr = getattr(_tls, "current", None)
    now_cpu = time.thread_time()
    cpu0 = getattr(_tls, "cpu0", None)
    _tls.cpu0 = now_cpu
    if tr is None:
        return
    tr.lap(bucket, wall_s, now_cpu - cpu0 if cpu0 is not None else 0.0)


def add_samples(n: int) -> None:
    tr = getattr(_tls, "current", None)
    if tr is not None:
        tr.add_samples(n)


def add_part_bytes(n: int) -> None:
    tr = getattr(_tls, "current", None)
    if tr is not None:
        tr.add_part_bytes(n)


def add_rpc_bytes(n: int) -> None:
    tr = getattr(_tls, "current", None)
    if tr is not None:
        tr.add_rpc_bytes(n)


def add_device(up: int = 0, down: int = 0) -> None:
    tr = getattr(_tls, "current", None)
    if tr is not None:
        tr.add_device(up, down)


# -- per-tenant usage aggregation ---------------------------------------------

_USAGE_FIELDS = ("samplesScanned", "bytesRead", "cpuMs", "deviceBytes",
                 "rpcBytes", "rowsReturned", "queries")

#: vm_tenant_usage_* metric per usage field; cpuMs exports as seconds
#: (prometheus convention), everything else as raw units
_METRIC_NAMES = {
    "samplesScanned": "vm_tenant_usage_samples_scanned_total",
    "bytesRead": "vm_tenant_usage_bytes_read_total",
    "cpuMs": "vm_tenant_usage_cpu_seconds_total",
    "deviceBytes": "vm_tenant_usage_device_bytes_total",
    "rpcBytes": "vm_tenant_usage_rpc_bytes_total",
    "rowsReturned": "vm_tenant_usage_rows_returned_total",
    "queries": "vm_tenant_usage_queries_total",
}


class TenantUsage:
    """Bounded per-tenant cumulative resource usage: the table behind
    ``/api/v1/status/usage`` and the ``vm_tenant_usage_*`` counter
    family.  Tenant-label cardinality is bounded the sticky TenantGate
    way: the first ``max_tenants`` DISTINCT tenants get their own row
    and label set, everything later folds into ``other`` and adds no
    new keys — URL-sourced tenant ids cannot grow process memory."""

    def __init__(self, max_tenants: int = 1000):
        self._lock = threading.Lock()
        self._max = max_tenants
        self._rows: dict[tuple, dict] = {}
        self._metric_memo: dict[tuple, object] = {}

    def _row_key(self, tenant) -> tuple:
        if tenant in self._rows or len(self._rows) < self._max:
            return tenant
        return ("other",)

    def _metric(self, field: str, key: tuple):
        m = self._metric_memo.get((field, key))
        if m is None:
            label = "other" if key == ("other",) else \
                f"{key[0]}:{key[1]}"
            full = metricslib.format_name(_METRIC_NAMES[field],
                                          {"tenant": label})
            if field == "cpuMs":
                m = metricslib.REGISTRY.float_counter(full)
            else:
                m = metricslib.REGISTRY.counter(full)
            self._metric_memo[(field, key)] = m
        return m

    def record(self, tenant, tracker: CostTracker,
               summary: dict | None = None) -> None:
        """`summary` lets a caller that already built
        ``tracker.summary()`` (the HTTP bracket does, for the qstats/
        slowlog columns) pass it in instead of paying a second
        build+lock round trip on the serving hot path."""
        s = dict(summary) if summary is not None else tracker.summary()
        s["queries"] = 1
        with self._lock:
            key = self._row_key(tuple(tenant))
            row = self._rows.get(key)
            if row is None:
                row = self._rows[key] = {f: 0 for f in _USAGE_FIELDS}
            for f in _USAGE_FIELDS:
                v = s.get(f, 0)
                row[f] = row[f] + v
                if f == "cpuMs":
                    self._metric(f, key).inc(v / 1e3)
                elif v:
                    self._metric(f, key).inc(int(v))

    def snapshot(self, reset: bool = False) -> list[dict]:
        """Rows sorted by cumulative CPU, most expensive tenant first.
        ``reset=True`` clears the table ATOMICALLY with the read — a
        separate snapshot()+reset() pair would silently drop any usage
        recorded between the two lock acquisitions."""
        with self._lock:
            rows = [dict(v, tenant=("other" if k == ("other",)
                                    else f"{k[0]}:{k[1]}"))
                    for k, v in self._rows.items()]
            if reset:
                self._rows.clear()
        for r in rows:
            r["cpuMs"] = round(r["cpuMs"], 3)
        rows.sort(key=lambda r: -r["cpuMs"])
        return rows

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()


#: process-wide table (one per process like the metrics registry; tests
#: build private TenantUsage instances)
TENANT_USAGE = TenantUsage()


def record_usage(tenant, tracker: CostTracker | None,
                 summary: dict | None = None) -> None:
    """Fold one finished query's tracker into the per-tenant table
    (call once per query, from the serving bracket).  Pass the already-
    built ``tracker.summary()`` when the caller has one."""
    if tracker is not None:
        TENANT_USAGE.record(tenant, tracker, summary=summary)
