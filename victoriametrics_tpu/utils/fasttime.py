"""Cached coarse clock (reference lib/fasttime: 1s-resolution cached unix time).

Python's time.time() is cheap but not free on hot ingest paths; we cache the
current unix seconds, refreshed lazily with a 0.5s tolerance, plus millisecond
helpers used by storage timestamps (all timestamps in the system are unix ms,
like the reference).
"""

from __future__ import annotations

import time

_cached = (0.0, 0)  # (monotonic_at_refresh, unix_secs)


def unix_timestamp() -> int:
    global _cached
    mono = time.monotonic()
    at, secs = _cached
    if mono - at > 0.5:
        secs = int(time.time())
        _cached = (mono, secs)
    return secs


def unix_ms() -> int:
    return int(time.time() * 1000)


def unix_seconds() -> float:
    """Float unix seconds (for durations/uptime at ms resolution)."""
    return time.time()


def unix_ns() -> int:
    """Integer unix nanoseconds (uniqueness counters, snapshot-name
    seeds — anything that wants restart-monotonic entropy)."""
    return time.time_ns()
