"""Continuous sampling profiler (the profile half of the cost-and-
profile observability plane; the reference ships pprof on every app —
this is the always-on Python analog: answer "where is the CPU going
right now" WITHOUT a pre-armed capture).

A single daemon thread samples ``sys._current_frames()`` at
``VM_PROFILE_HZ`` (default 10, deliberately low: one stack walk per
thread per 100ms is invisible next to a ~100ms refresh) and folds each
thread's stack into a bounded aggregate keyed by THREAD ROLE (pool
worker, http handler, merge, ...) — a role is the thread name with its
instance counter stripped, so 8 pool workers fold into one row.

Bounded memory by construction: at most ``VM_PROFILE_MAX_STACKS``
distinct folded stacks (default 5000; later novel stacks fold into a
per-role ``(other)`` bucket and count ``dropped``), stacks truncated at
``VM_PROFILE_MAX_DEPTH`` frames.  ``VM_PROFILE_HZ=0`` disables the
profiler entirely — no thread is ever created, every surface answers
"disabled".

Renderings:

- collapsed-stack text (``role;frame;frame count`` lines — the
  flamegraph.pl / speedscope-paste format)
- speedscope JSON (``"type": "sampled"`` profiles, one per role,
  loadable at https://www.speedscope.app)

both served at ``/api/v1/status/profile`` on vmsingle, vmselect AND
vmstorage; the vmselect endpoint additionally fans ``profile_v1`` out
to its storage nodes and merges the per-node snapshots with node tags
(the quarantineReport_v1 pattern), so one URL answers for the whole
cluster.

Self-metrics: ``vm_profiler_samples_total``,
``vm_profiler_sample_seconds_total`` (time spent inside the sampler —
the overhead, measurable), ``vm_profiler_stacks`` (live aggregate
size), ``vm_profiler_dropped_stacks_total``.
"""

from __future__ import annotations

import os
import re
import sys
import threading
import time

from . import metrics as metricslib

_SAMPLES_TOTAL = metricslib.REGISTRY.counter("vm_profiler_samples_total")
_SAMPLE_SECONDS = metricslib.REGISTRY.float_counter(
    "vm_profiler_sample_seconds_total")
_DROPPED_TOTAL = metricslib.REGISTRY.counter(
    "vm_profiler_dropped_stacks_total")


def configured_hz() -> float:
    """``VM_PROFILE_HZ`` (default 10; <=0 disables), re-read per call so
    tests and operators flip it without a restart."""
    try:
        return float(os.environ.get("VM_PROFILE_HZ", "10"))
    except ValueError:
        return 10.0


def _max_stacks() -> int:
    try:
        return max(int(os.environ.get("VM_PROFILE_MAX_STACKS", "5000")), 16)
    except ValueError:
        return 5000


def _max_depth() -> int:
    try:
        return max(int(os.environ.get("VM_PROFILE_MAX_DEPTH", "64")), 4)
    except ValueError:
        return 64


_THREAD_FN_RE = re.compile(r"^Thread-\d+\s+\((.+)\)$")
_TRAILING_NUM_RE = re.compile(r"[-_]\d+$")


def thread_role(name: str) -> str:
    """Fold a thread name into its role: strip per-instance counters so
    every pool worker / HTTP handler aggregates into one row."""
    m = _THREAD_FN_RE.match(name)
    if m:
        return m.group(1)
    return _TRAILING_NUM_RE.sub("", name) or "unnamed"


def _frame_label(code) -> str:
    fn = code.co_filename
    # keep the last two path segments: enough to disambiguate, short
    # enough for folded lines
    parts = fn.replace("\\", "/").rsplit("/", 2)
    short = "/".join(parts[-2:]) if len(parts) > 1 else fn
    return f"{short}:{code.co_name}"


class SampleProfiler:
    """Folded-stack aggregator + its sampling thread."""

    def __init__(self):
        self._lock = threading.Lock()
        # (role, stack_tuple) -> count; stack root->leaf
        self._stacks: dict[tuple, int] = {}
        self._samples = 0
        self._dropped = 0
        self._started_at = 0.0
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def running(self) -> bool:
        t = self._thread
        return t is not None and t.is_alive()

    def ensure_started(self) -> bool:
        """Start the sampling thread if ``VM_PROFILE_HZ`` > 0; returns
        whether the profiler is (now) running.  HZ<=0 NEVER creates a
        thread — the documented no-op contract."""
        if configured_hz() <= 0:
            return False
        with self._lock:
            if self.running():
                return True
            # rebound only here, under _lock; _run reads the ref once at
            # thread start — the Event handed to a dying thread is never
            # reused for the next one, so a stale read cannot unstop it
            self._stop = threading.Event()  # vmt: disable=VMT015
            if not self._started_at:
                self._started_at = time.monotonic()
            # service thread by design (daemon, joined in stop());
            # the work pool is for query work, not a periodic sampler
            self._thread = threading.Thread(  # vmt: disable=VMT011
                target=self._run, name="vm-profiler", daemon=True)
            self._thread.start()
            return True

    def stop(self) -> None:
        with self._lock:
            t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            self._stop.set()
            t.join(timeout=5)

    def reset(self) -> None:
        with self._lock:
            self._stacks.clear()
            self._samples = 0
            self._dropped = 0
            self._started_at = time.monotonic() if self.running() else 0.0

    def _run(self) -> None:
        me = threading.get_ident()
        while not self._stop.is_set():
            hz = configured_hz()
            if hz <= 0:  # flipped off live: park cheaply
                if self._stop.wait(0.5):
                    return
                continue
            t0 = time.perf_counter()
            try:
                self.take_sample(skip={me})
            except Exception as e:
                # the sampler must never die; one log line per failure,
                # no re-raise

                from . import logger
                logger.errorf("profiler sample failed: %s", e)
            dt = time.perf_counter() - t0
            _SAMPLE_SECONDS.inc(dt)
            if self._stop.wait(max(1.0 / hz - dt, 0.001)):
                return

    # -- sampling ----------------------------------------------------------

    def take_sample(self, skip: set | None = None) -> int:
        """One sampling pass over every live thread; returns the number
        of thread stacks folded in (exposed for tests and for one-shot
        sampling without the background thread)."""
        depth = _max_depth()
        names = {t.ident: t.name for t in threading.enumerate()}
        n = 0
        frames = sys._current_frames()
        for tid, frame in frames.items():
            if skip and tid in skip:
                continue
            stack = []
            f = frame
            while f is not None and len(stack) < depth:
                stack.append(_frame_label(f.f_code))
                f = f.f_back
            stack.reverse()  # root -> leaf (folded-stack convention)
            role = thread_role(names.get(tid, f"tid-{tid}"))
            self._ingest(role, tuple(stack))
            n += 1
        del frames
        _SAMPLES_TOTAL.inc()
        with self._lock:
            self._samples += 1
        return n

    def _ingest(self, role: str, stack: tuple) -> None:
        """Fold one (role, stack) observation in, bounded: novel stacks
        past the cap collapse into the role's ``(other)`` bucket."""
        key = (role, stack)
        with self._lock:
            c = self._stacks.get(key)
            if c is not None:
                self._stacks[key] = c + 1
                return
            if len(self._stacks) >= _max_stacks():
                key = (role, ("(other)",))
                self._dropped += 1
                _DROPPED_TOTAL.inc()
                # the overflow bucket itself may be the one new key a
                # full table still admits (one per role, bounded by the
                # role count, not by traffic)
            self._stacks[key] = self._stacks.get(key, 0) + 1

    # -- snapshots / renderings -------------------------------------------

    def snapshot(self, node: str | None = None, reset: bool = False) -> dict:
        """The merge/wire shape: meta + the folded-stack table.  `node`
        tags the snapshot for cluster merges."""
        hz = configured_hz()
        with self._lock:
            elapsed = (time.monotonic() - self._started_at
                       if self._started_at else 0.0)
            out = {
                "node": node,
                "configuredHz": hz,
                "samples": self._samples,
                "elapsedSeconds": round(elapsed, 3),
                "approxHz": round(self._samples / elapsed, 3)
                if elapsed > 0 else 0.0,
                "droppedStacks": self._dropped,
                "stacks": [{"role": r, "stack": list(st), "count": c}
                           for (r, st), c in self._stacks.items()],
            }
            if reset:
                self._stacks.clear()
                self._samples = 0
                self._dropped = 0
                self._started_at = (time.monotonic() if self.running()
                                    else 0.0)
        return out


#: process-wide profiler (one sampling thread per process)
PROFILER = SampleProfiler()

metricslib.REGISTRY.gauge("vm_profiler_stacks",
                          callback=lambda: len(PROFILER._stacks))


def ensure_started() -> bool:
    return PROFILER.ensure_started()


# -- multi-snapshot renderings (local + fanned-out node snapshots) -----------


def _tagged_rows(snapshots: list[dict]):
    """(group_label, stack, count) rows; group = role, prefixed with the
    node tag for tagged (fanned-out) snapshots."""
    for snap in snapshots:
        node = snap.get("node")
        for row in snap.get("stacks", ()):
            group = row["role"] if not node else f"{node}/{row['role']}"
            yield group, row["stack"], int(row["count"])


def collapsed(snapshots: list[dict]) -> str:
    """Folded-stack text: ``group;frame;frame count`` per line, counts
    merged across snapshots, heaviest stack first."""
    acc: dict[tuple, int] = {}
    for group, stack, count in _tagged_rows(snapshots):
        key = (group, tuple(stack))
        acc[key] = acc.get(key, 0) + count
    lines = [";".join((g,) + st) + f" {c}"
             for (g, st), c in sorted(acc.items(),
                                      key=lambda kv: (-kv[1], kv[0]))]
    return "\n".join(lines) + ("\n" if lines else "")


def speedscope(snapshots: list[dict], name: str = "vmtpu profile") -> dict:
    """speedscope file (https://www.speedscope.app/file-format-schema):
    one ``sampled`` profile per (node/)role, weights = sample counts."""
    frame_idx: dict[str, int] = {}
    frames: list[dict] = []

    def fidx(label: str) -> int:
        i = frame_idx.get(label)
        if i is None:
            i = frame_idx[label] = len(frames)
            frames.append({"name": label})
        return i

    groups: dict[str, tuple[list, list]] = {}
    for group, stack, count in _tagged_rows(snapshots):
        samples, weights = groups.setdefault(group, ([], []))
        samples.append([fidx(f) for f in stack])
        weights.append(count)
    profiles = []
    for group in sorted(groups):
        samples, weights = groups[group]
        total = sum(weights)
        profiles.append({"type": "sampled", "name": group, "unit": "none",
                         "startValue": 0, "endValue": total,
                         "samples": samples, "weights": weights})
    return {"$schema": "https://www.speedscope.app/file-format-schema.json",
            "shared": {"frames": frames},
            "profiles": profiles,
            "name": name,
            "activeProfileIndex": 0,
            "exporter": "victoriametrics_tpu/utils/profiler"}


def handle_http(req, Response, storage=None, local_node: str | None = None):
    """The shared ``/api/v1/status/profile`` handler (vmsingle/vmselect/
    vmstorage): 503 when disabled; ``?format=collapsed`` (default) /
    ``speedscope`` / ``raw``; ``?reset=1`` clears the aggregates after
    rendering.  With a `storage` exposing ``profile_report`` (the
    vmselect ClusterStorage) the local snapshot is merged with the
    per-node fan-out, node-tagged."""
    if configured_hz() <= 0:
        return Response.error(
            "continuous profiler disabled (VM_PROFILE_HZ=0)", 503,
            "unavailable")
    PROFILER.ensure_started()
    reset = req.arg("reset") == "1"
    snaps = [PROFILER.snapshot(node=local_node, reset=reset)]
    partial = False
    if storage is not None and \
            getattr(storage, "profile_report", None) is not None:
        try:
            if getattr(storage, "reset_partial", None) is not None:
                storage.reset_partial()
            # reset propagates through profile_v1 so ?reset=1 opens a
            # fresh window on every node, not only this process
            snaps.extend(storage.profile_report(reset=reset))
            partial = bool(getattr(storage, "last_partial", False))
        except Exception as e:  # noqa: BLE001 — degraded, never a 500
            from . import logger
            logger.errorf("profile fan-out failed: %s", e)
            partial = True
    fmt = req.arg("format") or "collapsed"
    if fmt == "speedscope":
        return Response.json(speedscope(snaps))
    if fmt == "raw":
        return Response.json({"status": "success",
                              "partial": partial,
                              "data": snaps})
    return Response.text(collapsed(snaps))
