"""JWT verification (reference lib/jwt): HS256/HS384/HS512 via hmac and
RS256 via pure-integer RSASSA-PKCS1-v1_5 (no external crypto deps —
the modexp + DER parsing are ~40 lines).

verify(token, secrets=[...], public_keys=[...]) -> claims dict; raises
JWTError on bad signature/format/expiry.
"""

from __future__ import annotations

import base64
import hashlib
import hmac
import json
import time


class JWTError(ValueError):
    pass


def _b64url(data: str) -> bytes:
    pad = "=" * (-len(data) % 4)
    try:
        return base64.urlsafe_b64decode(data + pad)
    except Exception as e:
        raise JWTError(f"bad base64url segment: {e}")


_HS = {"HS256": hashlib.sha256, "HS384": hashlib.sha384,
       "HS512": hashlib.sha512}

# DigestInfo DER prefix for SHA-256 (RFC 8017 9.2)
_SHA256_PREFIX = bytes.fromhex(
    "3031300d060960864801650304020105000420")


def _parse_rsa_public_pem(pem: str) -> tuple[int, int]:
    """(n, e) from an SPKI 'PUBLIC KEY' or PKCS#1 'RSA PUBLIC KEY' PEM."""
    body = "".join(line for line in pem.strip().splitlines()
                   if not line.startswith("-----"))
    der = base64.b64decode(body)

    def read_tlv(b, i):
        tag = b[i]
        ln = b[i + 1]
        i += 2
        if ln & 0x80:
            k = ln & 0x7F
            ln = int.from_bytes(b[i:i + k], "big")
            i += k
        return tag, b[i:i + ln], i + ln

    tag, seq, _ = read_tlv(der, 0)
    if tag != 0x30:
        raise JWTError("bad DER: expected SEQUENCE")
    # SPKI: SEQUENCE { AlgorithmIdentifier, BIT STRING { PKCS#1 } }
    t1, first, j = read_tlv(seq, 0)
    if t1 == 0x30:  # AlgorithmIdentifier -> unwrap the BIT STRING
        t2, bits, _ = read_tlv(seq, j)
        if t2 != 0x03:
            raise JWTError("bad SPKI: expected BIT STRING")
        _, seq, _ = read_tlv(bits[1:], 0)  # skip unused-bits octet
        t1, first, j = read_tlv(seq, 0)
    if t1 != 0x02:
        raise JWTError("bad PKCS#1: expected INTEGER modulus")
    n = int.from_bytes(first, "big")
    t2, e_b, _ = read_tlv(seq, j)
    if t2 != 0x02:
        raise JWTError("bad PKCS#1: expected INTEGER exponent")
    return n, int.from_bytes(e_b, "big")


def _rs256_ok(signing_input: bytes, sig: bytes, pem: str) -> bool:
    n, e = _parse_rsa_public_pem(pem)
    k = (n.bit_length() + 7) // 8
    if len(sig) != k:
        return False
    m = pow(int.from_bytes(sig, "big"), e, n)
    em = m.to_bytes(k, "big")
    # EMSA-PKCS1-v1_5: 0x00 0x01 FF..FF 0x00 DigestInfo
    digest = hashlib.sha256(signing_input).digest()
    expected = b"\x00\x01" + b"\xff" * (k - 3 - len(_SHA256_PREFIX) -
                                        len(digest)) + b"\x00" + \
        _SHA256_PREFIX + digest
    return hmac.compare_digest(em, expected)


def verify(token: str, secrets: list[str] | None = None,
           public_keys: list[str] | None = None,
           now: float | None = None) -> dict:
    parts = token.split(".")
    if len(parts) != 3:
        raise JWTError("token must have three segments")
    try:
        header = json.loads(_b64url(parts[0]))
        claims = json.loads(_b64url(parts[1]))
    except (ValueError, UnicodeDecodeError) as e:
        raise JWTError(f"malformed token segments: {e}")
    if not isinstance(header, dict) or not isinstance(claims, dict):
        raise JWTError("token segments must be JSON objects")
    sig = _b64url(parts[2])
    signing_input = (parts[0] + "." + parts[1]).encode()
    alg = header.get("alg", "")
    ok = False
    if alg in _HS:
        for secret in secrets or []:
            want = hmac.new(secret.encode(), signing_input,
                            _HS[alg]).digest()
            if hmac.compare_digest(want, sig):
                ok = True
                break
    elif alg == "RS256":
        for pem in public_keys or []:
            try:
                if _rs256_ok(signing_input, sig, pem):
                    ok = True
                    break
            except JWTError:
                continue
    else:
        raise JWTError(f"unsupported alg {alg!r}")
    if not ok:
        raise JWTError("signature verification failed")
    # token exp/nbf claims are absolute wall-clock by spec
    t = time.time() if now is None else now  # vmt: disable=VMT001
    try:
        if "exp" in claims and t > float(claims["exp"]):
            raise JWTError("token expired")
        if "nbf" in claims and t < float(claims["nbf"]):
            raise JWTError("token not yet valid")
    except (TypeError, ValueError) as e:
        if isinstance(e, JWTError):
            raise
        raise JWTError(f"malformed exp/nbf claim: {e}")
    return claims
