"""Query tracing: a tree of timed spans threaded through every query layer
(reference lib/querytracer/tracer.go:16-76), activated per-request via
`trace=1` and embedded in the API response JSON for UI rendering.

A disabled tracer is a no-op singleton so hot paths pay one branch.
Device phases (TPU rollups) report their spans too, giving host+device
timing in one tree.
"""

from __future__ import annotations

import threading
import time

_enabled_globally = True

# the calling thread's active tracer: set by the serving layer around a
# query, PROPAGATED to pool workers by utils/workpool around each task —
# so a span created on a worker attaches to the submitting query's tree
# instead of silently vanishing (the PR-4/5 threading gap)
_tls = threading.local()


def set_current(tracer) -> "Tracer | _NopTracer":
    """Install `tracer` as the calling thread's active tracer; returns
    the previous one (callers restore it in a finally)."""
    prev = getattr(_tls, "current", NOP)
    _tls.current = tracer if tracer is not None else NOP
    return prev


def current() -> "Tracer | _NopTracer":
    """The calling thread's active tracer (NOP when none): worker-side
    code adds spans via ``querytracer.current().new_child(...)`` without
    threading a tracer argument through every layer."""
    return getattr(_tls, "current", NOP)


def set_deny_tracing(deny: bool):
    global _enabled_globally
    _enabled_globally = not deny


class Tracer:
    __slots__ = ("message", "start", "duration_s", "children", "_done")

    def __init__(self, fmt: str = "", *args):
        self.message = (fmt % args) if args else fmt
        self.start = time.perf_counter()
        self.duration_s = 0.0
        self.children: list[Tracer] = []
        self._done = False

    @property
    def enabled(self) -> bool:
        return True

    def new_child(self, fmt: str, *args) -> "Tracer":
        child = Tracer(fmt, *args)
        self.children.append(child)
        return child

    def printf(self, fmt: str, *args) -> None:
        child = self.new_child(fmt, *args)
        child.donef("")

    def donef(self, fmt: str = "", *args) -> None:
        if self._done:
            return
        self._done = True
        self.duration_s = time.perf_counter() - self.start
        if fmt:
            extra = (fmt % args) if args else fmt
            self.message = f"{self.message}: {extra}" if self.message else extra

    def __enter__(self) -> "Tracer":
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        # close the span even when the traced layer raises; donef is
        # idempotent, so a span already closed with a success message
        # keeps it
        if exc is not None:
            self.donef("error: %s", exc)
        else:
            self.donef("")
        return False

    @classmethod
    def from_dict(cls, d: dict) -> "Tracer":
        """Rebuild a (finished) span tree from its to_dict() form — the
        receiving half of cross-RPC trace propagation."""
        t = cls(str(d.get("message", "")))
        t.duration_s = float(d.get("duration_msec", 0.0)) / 1e3
        t._done = True
        t.children = [cls.from_dict(c) for c in d.get("children", ())]
        return t

    def add_remote(self, d: dict) -> None:
        """Graft a remote span tree (a storage node's to_dict()) under
        this span, giving one host+device+network tree per query."""
        if d:
            self.children.append(Tracer.from_dict(d))

    def to_dict(self) -> dict:
        if not self._done:
            self.donef("")
        out = {"duration_msec": round(self.duration_s * 1e3, 3),
               "message": self.message}
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out


class _NopTracer:
    __slots__ = ()

    @property
    def enabled(self) -> bool:
        return False

    def new_child(self, fmt, *args):
        return self

    def printf(self, fmt, *args):
        pass

    def donef(self, fmt="", *args):
        pass

    def __enter__(self):
        return self

    def __exit__(self, exc_type, exc, tb):
        return False

    def add_remote(self, d):
        pass

    def to_dict(self):
        return {}


NOP = _NopTracer()


def new(enabled: bool, fmt: str = "", *args):
    if enabled and _enabled_globally:
        return Tracer(fmt, *args)
    return NOP
