"""Query tracing: a tree of timed spans threaded through every query layer
(reference lib/querytracer/tracer.go:16-76), activated per-request via
`trace=1` and embedded in the API response JSON for UI rendering.

A disabled tracer is a no-op singleton so hot paths pay one branch.
Device phases (TPU rollups) report their spans too, giving host+device
timing in one tree.
"""

from __future__ import annotations

import time

_enabled_globally = True


def set_deny_tracing(deny: bool):
    global _enabled_globally
    _enabled_globally = not deny


class Tracer:
    __slots__ = ("message", "start", "duration_s", "children", "_done")

    def __init__(self, fmt: str = "", *args):
        self.message = (fmt % args) if args else fmt
        self.start = time.perf_counter()
        self.duration_s = 0.0
        self.children: list[Tracer] = []
        self._done = False

    @property
    def enabled(self) -> bool:
        return True

    def new_child(self, fmt: str, *args) -> "Tracer":
        child = Tracer(fmt, *args)
        self.children.append(child)
        return child

    def printf(self, fmt: str, *args) -> None:
        child = self.new_child(fmt, *args)
        child.donef("")

    def donef(self, fmt: str = "", *args) -> None:
        if self._done:
            return
        self._done = True
        self.duration_s = time.perf_counter() - self.start
        if fmt:
            extra = (fmt % args) if args else fmt
            self.message = f"{self.message}: {extra}" if self.message else extra

    def to_dict(self) -> dict:
        if not self._done:
            self.donef("")
        out = {"duration_msec": round(self.duration_s * 1e3, 3),
               "message": self.message}
        if self.children:
            out["children"] = [c.to_dict() for c in self.children]
        return out


class _NopTracer:
    __slots__ = ()

    @property
    def enabled(self) -> bool:
        return False

    def new_child(self, fmt, *args):
        return self

    def printf(self, fmt, *args):
        pass

    def donef(self, fmt="", *args):
        pass

    def to_dict(self):
        return {}


NOP = _NopTracer()


def new(enabled: bool, fmt: str = "", *args):
    if enabled and _enabled_globally:
        return Tracer(fmt, *args)
    return NOP
