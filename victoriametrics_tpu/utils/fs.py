"""Durable-filesystem helpers (reference lib/fs/fs.go:71,182).

The storage engine's whole crash story rests on write-to-tmp -> fsync ->
atomic rename.  The rename itself is NOT durable until the parent
directory's entry table is fsynced: a crash after ``os.rename`` but
before the directory metadata reaches disk can resurrect the old
directory listing, un-publishing a part that was already acknowledged.
:func:`fsync_dir` is that missing fsync, shared by the partition,
mergeset and snapshot paths (the MustSyncPath analog).

File checksums (crc32 of each payload file, recorded in the part's
``metadata.json`` at finalize) close the other half: a torn or
bit-flipped part is detected at open and quarantined loudly instead of
misparsing or silently vanishing from serving.
"""

from __future__ import annotations

import json
import os
import zlib

_CHUNK = 1 << 20


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-renamed entry inside it is durable
    (fs.go MustSyncPath on the parent dir).  Raises OSError on failure —
    a rename whose durability cannot be established must not be treated
    as committed."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def rename_durable(src: str, dst: str) -> None:
    """os.replace + parent-dir fsync: the atomic-publish idiom every
    finalize path uses (rename alone is atomic but not durable).  When
    src is a directory its OWN entry table is fsynced first — the files
    inside were fsynced individually, but the directory entries naming
    them were not, and a power loss could otherwise persist the rename
    while losing a child entry."""
    if os.path.isdir(src):
        fsync_dir(src)
    os.replace(src, dst)
    fsync_dir(os.path.dirname(dst) or ".")


def checksum_file(path: str) -> int:
    """crc32 of a whole file (streamed; parts can be large)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_CHUNK)
            if not chunk:
                return crc
            crc = zlib.crc32(chunk, crc)


class IntegrityError(ValueError):
    """A part file's bytes do not match the checksums recorded at
    finalize (torn write, bit rot, truncation).  Openers quarantine the
    part instead of serving — or silently dropping — corrupt data."""


def meta_crc(meta: dict) -> int:
    """Self-checksum of a metadata dict (everything except the
    ``meta_crc`` field itself, canonically serialized): catches bit
    flips inside metadata.json, which the per-file checksums it carries
    cannot cover."""
    body = {k: v for k, v in meta.items() if k != "meta_crc"}
    return zlib.crc32(json.dumps(body, sort_keys=True).encode())


def write_meta_json(path: str, meta: dict) -> None:
    """Write metadata.json with its self-crc, fsynced (callers rename
    the enclosing tmp dir afterwards)."""
    meta = dict(meta)
    meta["meta_crc"] = meta_crc(meta)
    with open(path, "w") as f:
        json.dump(meta, f)
        f.flush()
        os.fsync(f.fileno())


def verify_enabled() -> bool:
    """Checksum verification at part open (default ON; VM_VERIFY_PARTS=0
    opts out for benchmarking the raw open path)."""
    return os.environ.get("VM_VERIFY_PARTS", "1") not in ("0", "")


def load_meta_json(path: str) -> dict:
    """Read + self-verify metadata.json; raises IntegrityError when the
    recorded meta_crc does not match (bit flip inside the metadata
    itself).  Metadata written before checksums existed (no meta_crc
    field) loads unverified."""
    with open(path) as f:
        meta = json.load(f)
    rec = meta.get("meta_crc")
    if rec is not None and verify_enabled() and rec != meta_crc(meta):
        raise IntegrityError(f"{path}: metadata self-checksum mismatch")
    return meta


#: subdir (inside a partition / mergeset table dir) holding parts that
#: failed the open-time integrity check — kept for forensics/restore,
#: never served, never mistaken for a crash leftover by cleanup sweeps
QUARANTINE_DIR = "quarantine"


def quarantine_dir_entry(parent: str, name: str, err,
                         store: str, where: str) -> dict:
    """Move ``parent/name`` into ``parent/quarantine/`` (same-fs rename;
    a suffix disambiguates repeat quarantines of one name) and return
    the report entry /api/v1/status/quarantine serves."""
    from . import logger
    qdir = os.path.join(parent, QUARANTINE_DIR)
    os.makedirs(qdir, exist_ok=True)
    dst = os.path.join(qdir, name)
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = os.path.join(qdir, f"{name}.{n}")
    os.rename(os.path.join(parent, name), dst)
    try:
        fsync_dir(parent)
    except OSError:
        pass  # the move is advisory bookkeeping; never fail open on it
    logger.errorf("%s %s: QUARANTINED part %s -> %s: %s",
                  store, where, name, dst, err)
    return {"store": store, "in": where, "part": name, "path": dst,
            "error": str(err)}


def resident_quarantine_entries(parent: str, store: str,
                                where: str) -> list[dict]:
    """Report entries for parts quarantined by a PREVIOUS open (the
    quarantine dir's residents): a restart must keep serving loudly
    partial until the operator restores or deletes them.  Shared by the
    partition and mergeset openers so the report schema and operator
    guidance cannot drift between stores."""
    qdir = os.path.join(parent, QUARANTINE_DIR)
    if not os.path.isdir(qdir):
        return []
    return [{"store": store, "in": where, "part": n,
             "path": os.path.join(qdir, n),
             "error": "quarantined by a previous open; restore from a "
                      "replica/snapshot or delete the quarantine dir to "
                      "accept the loss"}
            for n in sorted(os.listdir(qdir))]


def verify_checksums(part_dir: str, meta: dict) -> None:
    """Verify every file checksum recorded in ``meta['checksums']``
    against the bytes on disk; raises IntegrityError on the first
    mismatch (missing file included).  Parts finalized before checksums
    existed carry no map and verify trivially."""
    sums = meta.get("checksums")
    if not sums or not verify_enabled():
        return
    for name, want in sums.items():
        full = os.path.join(part_dir, name)
        try:
            got = checksum_file(full)
        except OSError as e:
            # on-disk corruption is a TRUE internal error: there is no
            # typed status that makes it the client's problem, so the
            # boundary's anonymous 500/error frame is the contract
            raise IntegrityError(  # vmt: disable=VMT016
                f"{part_dir}: cannot checksum {name}: {e}") from None
        if got != want:
            raise IntegrityError(  # vmt: disable=VMT016 — corruption = 500
                f"{part_dir}: checksum mismatch on {name} "
                f"(recorded {want}, computed {got}) — torn or corrupt")
