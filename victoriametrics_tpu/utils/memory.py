"""Allowed-memory accounting (reference lib/memory/memory.go:29-72).

memory.Allowed() = allowedPercent (default 60%) of the cgroup/system RAM
limit; cache sizing throughout the storage engine derives from it.
"""

from __future__ import annotations

import os

_allowed_percent = 60.0
_allowed_bytes_override = 0


import functools


@functools.cache
def _system_memory() -> int:
    # Computed once (reference uses sync.Once): cache sizing calls this on
    # hot paths. cgroup v2 limit if present, else /proc/meminfo MemTotal.
    try:
        with open("/sys/fs/cgroup/memory.max") as f:
            v = f.read().strip()
            if v != "max":
                return int(v)
    except OSError:
        pass
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 1 << 32


def set_allowed_percent(p: float) -> None:
    global _allowed_percent
    _allowed_percent = p


def set_allowed_bytes(n: int) -> None:
    global _allowed_bytes_override
    _allowed_bytes_override = n


def allowed() -> int:
    if _allowed_bytes_override > 0:
        return _allowed_bytes_override
    return int(_system_memory() * _allowed_percent / 100.0)


def remaining() -> int:
    return max(0, _system_memory() - allowed())


def available_cpus() -> int:
    try:
        return len(os.sched_getaffinity(0))
    except AttributeError:
        return os.cpu_count() or 1
