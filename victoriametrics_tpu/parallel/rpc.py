"""Cluster RPC: length-prefixed binary frames over TCP with a versioned
handshake and negotiated zstd compression (reference lib/handshake/
handshake.go:17-160 + lib/vmselectapi/server.go framing).

Frame: u32 BE length + payload. Payload (optionally zstd): method name
(varuint len + bytes) + method-specific body. Responses: status byte
(0=ok, 1=error+message) + body. Calls are versioned through their method
names ("writeRows_v1", "search_v1", ...) for rolling-upgrade compat.
"""

from __future__ import annotations

import io
import os
import random
import socket
import socketserver
import struct
import threading
import time

import numpy as np

from ..devtools import faultinject
from ..devtools.locktrace import make_lock
from ..devtools.racetrace import traced_fields
from ..ingest.ratelimiter import RateLimitedError

try:
    from ..ops import compress as zstd
except ImportError:  # optional native dep (zstandard): the marshal layer
    zstd = None      # (Writer/Reader) stays importable; only frame I/O needs it

from ..ops.varint import marshal_varuint64, unmarshal_varuint64
from ..utils import logger
from ..utils import metrics as metricslib
from ..utils.deadline import DeadlineExceededError
from ..utils.workpool import SearchLimitError

#: wire marker for shed-load errors (TenantGate rejections): the client
#: re-raises them as SearchLimitError so a tenant-quota 429 crosses the
#: RPC boundary as ITSELF — not as a generic node failure that would
#: mark the (healthy) storage node down and go partial for every tenant
_SHED_PREFIX = "vm:shed-load: "

#: wire marker for storage-side deadline aborts: the vmstorage stopped
#: a scan/fetch because the SHIPPED budget expired — by-design behavior
#: requested by the caller, so the client re-raises a deadline error
#: with waited=False and the fan-out never marks the healthy node down
_DEADLINE_PREFIX = "vm:deadline: "

#: wire marker for ingestion rate-limit rejections: carries ONLY the
#: retry-after seconds so the client can rebuild the typed
#: RateLimitedError and the vminsert HTTP layer keeps its 429 +
#: Retry-After contract across the RPC hop
_RATELIMIT_PREFIX = "vm:rate-limited: "

#: wire marker for a multilevel child whose OWN fan-out found no live
#: storage at all: the parent re-raises ClusterUnavailableError so its
#: HTTP layer serves the promised 503, not an anonymous 500
_UNAVAIL_PREFIX = "vm:unavailable: "

#: wire marker for deny_partial rejections on a multilevel child: the
#: parent re-raises PartialResultError (capacity degradation, 503)
_PARTIAL_PREFIX = "vm:partial-denied: "


# per-(family, method) handle memo: keeps the format_name + name-regex +
# registry-lock round trip off the per-call path (method sets are tiny and
# bounded; a benign double-create under race resolves to the same handle)
_metric_memo: dict[tuple, object] = {}


def _rpc_counter(name: str, method: str):
    key = (name, method)
    m = _metric_memo.get(key)
    if m is None:
        # benign double-create: REGISTRY.counter dedups by name, so two
        # racing fills store the same object
        m = _metric_memo[key] = metricslib.REGISTRY.counter(  # vmt: disable=VMT015
            metricslib.format_name(name, {"method": method}))
    return m


def _rpc_histogram(name: str, method: str):
    key = (name, method)
    m = _metric_memo.get(key)
    if m is None:
        m = _metric_memo[key] = metricslib.REGISTRY.histogram(
            metricslib.format_name(name, {"method": method}))
    return m

HELLO_INSERT = b"vmtpu-insert.v2\n"
HELLO_SELECT = b"vmtpu-select.v2\n"
HELLO_OK = b"ok:zstd\n"

_U32 = struct.Struct(">I")
MAX_FRAME = 256 << 20


class RPCError(RuntimeError):
    pass


class RPCDeadlineError(RPCError):
    """The caller's deadline expired before the call completed.  A
    subclass of RPCError so transport layers treat it as a terminal
    call failure (never retried — there is no budget left to retry
    in).  ``waited`` is False when the budget was already exhausted
    BEFORE any I/O touched the peer: the node never misbehaved, so
    health tracking (ClusterStorage._fanout) must not mark it down for
    one over-budget query."""

    waited = True


class ClusterUnavailableError(RPCError):
    """Every storage node failed the fan-out: there is no data to serve
    at all.  HTTP layers map this to 503 (+ the first node's error)
    rather than a generic 500 — the cluster is degraded, the serving
    code is not broken.  Defined here (not cluster_api) so both error
    boundaries can map it without importing the fan-out machinery, and
    so a multilevel child's unavailability crosses the RPC hop typed
    (``_UNAVAIL_PREFIX``)."""


class PartialResultError(RuntimeError):
    """deny_partial is set and a fan-out lost node(s): the merged
    answer would be silently incomplete, so the query is refused.
    Capacity degradation, not a serving bug — boundaries map it to 503
    / a typed ``_PARTIAL_PREFIX`` frame, never an anonymous 500."""


# cross-method aggregates: the per-method vm_rpc_client_* families stay,
# these are the "is the cluster retrying/timing out AT ALL" alarms
_RETRIES_TOTAL = metricslib.REGISTRY.counter("vm_rpc_retries_total")
_DEADLINE_EXCEEDED_TOTAL = metricslib.REGISTRY.counter(
    "vm_rpc_deadline_exceeded_total")


def _retry_policy() -> tuple[int, float, float]:
    """(max reconnect retries, backoff base s, backoff cap s) —
    re-read per call so tests and operators tune live.
    ``VM_RPC_RETRIES`` (default 2), ``VM_RPC_BACKOFF_MS`` (default 20),
    ``VM_RPC_BACKOFF_MAX_MS`` (default 2000)."""
    def _num(name: str, default: float) -> float:
        try:
            return float(os.environ.get(name, "") or default)
        except ValueError:
            return default
    return (max(int(_num("VM_RPC_RETRIES", 2)), 0),
            max(_num("VM_RPC_BACKOFF_MS", 20.0), 0.0) / 1e3,
            max(_num("VM_RPC_BACKOFF_MAX_MS", 2000.0), 1.0) / 1e3)


def _acquire_cap_s() -> float:
    """Upper bound on waiting for a pooled connection when the call
    carries NO deadline (insert-path calls): a pool whose connections
    are all wedged behind a dead peer must surface as an error instead
    of hanging the caller forever.  ``VM_RPC_ACQUIRE_MAX_S`` (default
    60) — generous enough that real backpressure never trips it."""
    try:
        return float(os.environ.get("VM_RPC_ACQUIRE_MAX_S", "") or 60.0)
    except ValueError:
        return 60.0


def _read_exact(sock_file, n: int) -> bytes:
    data = sock_file.read(n)
    if data is None or len(data) != n:
        raise ConnectionError("rpc: connection closed mid-frame")
    return data


def write_frame(sock_file, payload: bytes, compress: bool = True):
    if zstd is None:
        raise RPCError("rpc frames need the 'zstandard' package")
    if compress:
        payload = zstd.compress(payload)
    sock_file.write(_U32.pack(len(payload)) + payload)
    sock_file.flush()


def read_frame(sock_file, compressed: bool = True) -> bytes:
    if zstd is None:
        raise RPCError("rpc frames need the 'zstandard' package")
    n = _U32.unpack(_read_exact(sock_file, 4))[0]
    if n > MAX_FRAME:
        raise RPCError(f"rpc frame too large: {n}")
    data = _read_exact(sock_file, n)
    return zstd.decompress(data) if compressed else data


# -- marshaling helpers ------------------------------------------------------

class Writer:
    def __init__(self):
        self.buf = bytearray()

    def bytes_(self, b: bytes):
        self.buf += marshal_varuint64(len(b))
        self.buf += b
        return self

    def str_(self, s: str):
        return self.bytes_(s.encode())

    def u64(self, x: int):
        self.buf += marshal_varuint64(x)
        return self

    def i64(self, x: int):
        self.buf += struct.pack(">q", x)
        return self

    def f64(self, x: float):
        self.buf += struct.pack(">d", x)
        return self

    def array(self, a: np.ndarray):
        raw = np.ascontiguousarray(a).tobytes()
        self.bytes_(str(a.dtype).encode())
        return self.bytes_(raw)

    def payload(self) -> bytes:
        return bytes(self.buf)


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.i = 0

    def bytes_(self) -> bytes:
        n, self.i = unmarshal_varuint64(self.data, self.i)
        out = self.data[self.i:self.i + n]
        if len(out) != n:
            raise RPCError("rpc: truncated bytes field")
        self.i += n
        return out

    def str_(self) -> str:
        return self.bytes_().decode()

    def u64(self) -> int:
        v, self.i = unmarshal_varuint64(self.data, self.i)
        return v

    def i64(self) -> int:
        v = struct.unpack_from(">q", self.data, self.i)[0]
        self.i += 8
        return v

    def f64(self) -> float:
        v = struct.unpack_from(">d", self.data, self.i)[0]
        self.i += 8
        return v

    def array(self) -> np.ndarray:
        dtype = self.bytes_().decode()
        raw = self.bytes_()
        return np.frombuffer(raw, dtype=dtype).copy()

    @property
    def remaining(self) -> int:
        return len(self.data) - self.i


# -- server ------------------------------------------------------------------

class RPCServer:
    """TCP server dispatching named methods. Handlers: fn(Reader) -> Writer
    or an iterator of Writers for streaming responses (each streamed frame is
    prefixed with status 2; final frame status 0)."""

    def __init__(self, addr: str, port: int, hello: bytes,
                 handlers: dict[str, object], max_conns: int = 64):
        self.handlers = handlers
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    got = _read_exact(self.rfile, len(hello))
                    if got != hello:
                        self.wfile.write(b"bad hello\n")
                        return
                    self.wfile.write(HELLO_OK)
                    self.wfile.flush()
                    while True:
                        try:
                            req = read_frame(self.rfile)
                        except (ConnectionError, RPCError):
                            return
                        try:
                            outer._dispatch(req, self.wfile)
                        except faultinject.ConnectionAbort:
                            # injected reset: drop the peer mid-frame,
                            # exercising the client's reconnect path
                            return
                except (BrokenPipeError, ConnectionResetError):
                    return

        class Srv(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Srv((addr, port), Handler)
        self.port = self._srv.server_address[1]
        # long-lived RPC accept loop, one per server — not fan-out work
        self._thread = threading.Thread(  # vmt: disable=VMT011
            target=self._srv.serve_forever, daemon=True)

    def start(self):
        self._thread.start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()

    def _dispatch(self, req: bytes, wfile):
        r = Reader(req)
        method = "?"
        t0 = time.perf_counter()
        try:
            method = r.str_()
            _rpc_counter("vm_rpc_server_calls_total", method).inc()
            # chaos seam: injected delays/stalls/errors/resets land here,
            # between frame parse and handler dispatch (devtools/faultinject)
            if faultinject.active():
                faultinject.fire("rpc:" + method)
            fn = self.handlers.get(method)
            if fn is None:
                raise RPCError(f"unknown rpc method {method!r}")
            out = fn(r)
            if hasattr(out, "__iter__") and not isinstance(out, Writer):
                for w in out:
                    write_frame(wfile, b"\x02" + w.payload())
                write_frame(wfile, b"\x00")
            else:
                body = out.payload() if isinstance(out, Writer) else b""
                write_frame(wfile, b"\x00" + body)
        except faultinject.ConnectionAbort:
            raise  # handled at the connection loop (drop, no response)
        except DeadlineExceededError as e:
            # the handler aborted because the query's SHIPPED budget
            # expired — by-design (vm_storage_deadline_aborts_total on
            # this node already counted it), not a handler error: no
            # error-log line, no vm_rpc_server_errors_total, and the
            # typed wire marker keeps it a deadline on the caller's side
            _rpc_counter("vm_rpc_server_deadline_total", method).inc()
            try:
                write_frame(wfile,
                            b"\x01" + (_DEADLINE_PREFIX + str(e)).encode())
            except OSError:
                pass
        except SearchLimitError as e:
            # by-design shed load, NOT a handler error: it has its own
            # accounting (vm_rpc_server_shed_total here, the gate's
            # vm_tenant_search_rejected_total on the storage side) and
            # must not flood the error log / error counter during a 429
            # storm.  The wire marker keeps the type across the hop.
            _rpc_counter("vm_rpc_server_shed_total", method).inc()
            try:
                write_frame(wfile,
                            b"\x01" + (_SHED_PREFIX + str(e)).encode())
            except OSError:
                pass
        except RateLimitedError as e:
            # ingestion backpressure, the write-plane twin of shed load:
            # only the retry-after seconds cross the wire, the client
            # rebuilds the typed error so vminsert's 429 + Retry-After
            # contract survives the hop instead of becoming a 500
            _rpc_counter("vm_rpc_server_ratelimited_total", method).inc()
            try:
                write_frame(wfile, b"\x01" + (
                    _RATELIMIT_PREFIX + str(e.retry_after_s)).encode())
            except OSError:
                pass
        except ClusterUnavailableError as e:
            # a multilevel child found no live storage: typed marker so
            # the parent's HTTP layer serves the promised 503 (before
            # the RPCError arm — it is a subclass)
            _rpc_counter("vm_rpc_server_errors_total", method).inc()
            try:
                write_frame(wfile,
                            b"\x01" + (_UNAVAIL_PREFIX + str(e)).encode())
            except OSError:
                pass
        except PartialResultError as e:
            # deny_partial refusal on a multilevel child: capacity
            # degradation the parent must surface as 503, not 500
            _rpc_counter("vm_rpc_server_errors_total", method).inc()
            try:
                write_frame(wfile,
                            b"\x01" + (_PARTIAL_PREFIX + str(e)).encode())
            except OSError:
                pass
        except RPCError as e:
            # the unmarked error frame IS the typed encoding of
            # RPCError: the client re-raises it as RPCError verbatim,
            # so the type round-trips the hop.  A separate arm (same
            # body as the anonymous one) keeps that contract explicit
            # for the VMT016 exception-escape audit.
            _rpc_counter("vm_rpc_server_errors_total", method).inc()
            logger.errorf("rpc handler error: %s", e)
            try:
                write_frame(wfile, b"\x01" + str(e).encode())
            except OSError:
                pass
        except Exception as e:  # noqa: BLE001 — rpc error boundary
            _rpc_counter("vm_rpc_server_errors_total", method).inc()
            logger.errorf("rpc handler error: %s", e)
            try:
                write_frame(wfile, b"\x01" + str(e).encode())
            except OSError:
                pass
        finally:
            _rpc_histogram("vm_rpc_server_call_duration_seconds",
                           method).update(time.perf_counter() - t0)


# -- client ------------------------------------------------------------------

@traced_fields("_sock", "_f")
class RPCClient:
    """One connection per client; callers serialize via a lock (the pool
    layer holds several clients per node)."""

    def __init__(self, host: str, port: int, hello: bytes, timeout=10.0):
        self.addr = (host, port)
        self.hello = hello
        self.timeout = timeout
        self._lock = make_lock("rpc.RPCClient._lock")
        self._sock = None
        self._f = None

    def _op_timeout(self, deadline: float) -> float:
        """Per-operation socket timeout: the configured ceiling, clipped
        to the caller's remaining budget (a query with 800ms left must
        not sit in a 10s default timeout against a hung peer)."""
        if not deadline:
            return self.timeout
        return max(min(self.timeout, deadline - time.monotonic()), 0.001)

    def _check_deadline(self, method: str, deadline: float,
                        waited: bool = True) -> None:
        if deadline and time.monotonic() >= deadline:
            _DEADLINE_EXCEEDED_TOTAL.inc()
            _rpc_counter("vm_rpc_client_deadline_exceeded_total",
                         method).inc()
            err = RPCDeadlineError(
                f"rpc {method} to {self.addr[0]}:{self.addr[1]}: "
                f"caller deadline exceeded")
            err.waited = waited
            raise err

    def _connect(self, deadline: float = 0.0):
        # connection establishment honors the caller's deadline too —
        # the constructor timeout is only the no-deadline ceiling
        sock = socket.create_connection(self.addr,
                                        timeout=self._op_timeout(deadline))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        f = sock.makefile("rwb")
        f.write(self.hello)
        f.flush()
        resp = f.read(len(HELLO_OK))
        if resp != HELLO_OK:
            raise RPCError(f"handshake failed: {resp!r}")
        self._sock, self._f = sock, f

    def close(self):
        with self._lock:
            self._close_locked()

    def _close_locked(self):
        """Close without taking the lock — for use on paths already holding
        self._lock (calling close() there self-deadlocks on the plain Lock)."""
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = self._f = None

    def call(self, method: str, w: Writer | None = None,
             deadline: float = 0.0) -> Reader:
        """Unary call."""
        frames = list(self.call_stream(method, w, deadline=deadline))
        if frames:
            return frames[0]
        return Reader(b"")

    def call_stream(self, method: str, w: Writer | None = None,
                    deadline: float = 0.0):
        """Returns an iterator of Readers, one per streamed frame.

        `deadline` is a ``time.monotonic()`` cutoff (0 = none): every
        socket operation — connect included — runs under a timeout
        derived from the REMAINING budget (capped by the constructor
        timeout), so a hung peer costs the caller at most its own
        deadline, never a fixed 10s-per-hop default.  An exhausted
        budget raises :class:`RPCDeadlineError` and counts into
        ``vm_rpc_deadline_exceeded_total``.

        Connection-level failures (peer restarted, stale kept-alive
        connection, injected resets) are retried on a fresh connection
        with bounded exponential backoff + full jitter (see
        :func:`_retry_policy`), as long as no response frame has been
        received and budget remains; each retry counts into
        ``vm_rpc_retries_total``.  A socket TIMEOUT is not retried —
        the peer is slow, not gone, and retrying would burn the rest of
        the budget re-waiting on the same stall.

        All frames are read under the lock BEFORE returning: a lazy
        generator would keep the connection lock held while the caller
        processes frames, and an abandoned generator (consumer error) would
        leave it locked until GC — a deadlock under failure. Any transport
        error also tears the connection down so a half-read stream can never
        poison the next call."""
        req = Writer().str_(method)
        if w is not None:
            req.buf += w.buf
        frames: list[Reader] = []
        _rpc_counter("vm_rpc_client_calls_total", method).inc()
        t0 = time.perf_counter()
        max_retries, backoff_base, backoff_cap = _retry_policy()
        try:
            with self._lock:
                attempt = 0
                while True:
                    # waited=False on the first pre-I/O check: a budget
                    # that was gone before we touched the peer is the
                    # QUERY's fault, not the node's
                    self._check_deadline(method, deadline,
                                         waited=attempt > 0)
                    try:
                        if self._f is None:
                            self._connect(deadline)
                        if self._sock is not None:
                            # always reset: a reused connection must not
                            # inherit the previous call's clipped timeout
                            self._sock.settimeout(
                                self._op_timeout(deadline))
                        write_frame(self._f, req.payload())
                        while True:
                            if deadline:
                                # re-check BETWEEN frames: a dripping
                                # node emitting each frame just inside
                                # the per-op timeout must still cost at
                                # most one deadline, not one timeout
                                # per streamed frame.  Tear the
                                # connection down FIRST — aborting
                                # mid-stream leaves unread frames that
                                # would poison the next (pooled) call.
                                if time.monotonic() >= deadline:
                                    self._close_locked()
                                    self._check_deadline(method,
                                                         deadline)
                                self._sock.settimeout(
                                    self._op_timeout(deadline))
                            resp = read_frame(self._f)
                            status = resp[0]
                            if status == 0:
                                if len(resp) > 1:
                                    frames.append(Reader(resp[1:]))
                                return iter(frames)
                            if status == 1:
                                # server-reported error: stream is cleanly
                                # terminated, the connection stays usable
                                msg = resp[1:].decode()
                                if msg.startswith(_SHED_PREFIX):
                                    # remote TenantGate rejection: keep
                                    # its type so the caller's 429 path
                                    # fires instead of node-down+partial
                                    raise SearchLimitError(
                                        msg[len(_SHED_PREFIX):])
                                if msg.startswith(_DEADLINE_PREFIX):
                                    # storage-side deadline abort: the
                                    # node did exactly what the shipped
                                    # budget asked — surface a typed
                                    # deadline, never mark it down
                                    _DEADLINE_EXCEEDED_TOTAL.inc()
                                    err = RPCDeadlineError(
                                        f"rpc {method} to "
                                        f"{self.addr[0]}:{self.addr[1]}: "
                                        f"{msg[len(_DEADLINE_PREFIX):]}")
                                    err.waited = False
                                    raise err
                                if msg.startswith(_RATELIMIT_PREFIX):
                                    # remote ingestion backpressure:
                                    # rebuild the typed error so the
                                    # HTTP layer's 429 + Retry-After
                                    # fires, not node-down + 500
                                    raise RateLimitedError(float(
                                        msg[len(_RATELIMIT_PREFIX):]))
                                if msg.startswith(_UNAVAIL_PREFIX):
                                    # child cluster has no live
                                    # storage: keep the 503 type
                                    raise ClusterUnavailableError(
                                        msg[len(_UNAVAIL_PREFIX):])
                                if msg.startswith(_PARTIAL_PREFIX):
                                    # child refused a partial answer:
                                    # capacity degradation, 503 type
                                    raise PartialResultError(
                                        msg[len(_PARTIAL_PREFIX):])
                                raise RPCError(msg)
                            frames.append(Reader(resp[1:]))
                    except RPCError:
                        raise
                    except TimeoutError:
                        # slow peer: tear down, surface the caller's
                        # deadline when that is what actually expired
                        self._close_locked()
                        self._check_deadline(method, deadline)
                        raise
                    except (OSError, ConnectionError):
                        self._close_locked()
                        if frames or attempt >= max_retries:
                            raise
                        attempt += 1
                        _rpc_counter("vm_rpc_client_retries_total",
                                     method).inc()
                        _RETRIES_TOTAL.inc()
                        # bounded exponential backoff with full jitter
                        delay = min(backoff_base * (2 ** (attempt - 1)),
                                    backoff_cap) * random.random()
                        if deadline:
                            remaining = deadline - time.monotonic()
                            if remaining <= 0:
                                self._check_deadline(method, deadline)
                            delay = min(delay, max(remaining, 0.0))
                        if delay > 0:
                            # the lock IS the per-connection serializer —
                            # socket ops (10s default timeout) already
                            # block under it far longer than this capped
                            # backoff, and releasing it mid-call would
                            # interleave another caller's frames onto a
                            # connection being re-dialed
                            time.sleep(delay)  # vmt: disable=VMT004 — see above
        except Exception:
            _rpc_counter("vm_rpc_client_errors_total", method).inc()
            raise
        finally:
            _rpc_histogram("vm_rpc_client_call_duration_seconds",
                           method).update(time.perf_counter() - t0)


# -- client connection pool ---------------------------------------------------

class RPCClientPool:
    """Small per-node CONNECTION pool for the select plane (the
    netstorage connPool role): concurrent queries against one storage
    node must not serialize on a single TCP connection — with one
    connection, a 300ms fetch head-of-line blocks every other query to
    that node, and the node-side TenantGate never even sees concurrent
    load to shed.

    Up to ``max_conns`` (``VM_RPC_SELECT_CONNS``, default 4) lazily
    created :class:`RPCClient` connections; callers past the cap wait
    for an idle one (bounded upstream by the HTTP concurrency gate).
    Waiting for LOCAL pool capacity is never the node's fault: a
    deadline expiring here raises ``waited=False`` so the fan-out does
    not mark the node down.  Same call/call_stream surface as
    RPCClient."""

    def __init__(self, host: str, port: int, hello: bytes,
                 timeout: float = 10.0, max_conns: int | None = None):
        if max_conns is None:
            try:
                max_conns = int(os.environ.get("VM_RPC_SELECT_CONNS",
                                               "0"))
            except ValueError:
                max_conns = 0
        if max_conns <= 0:
            max_conns = 4
        self.addr = (host, port)
        self.hello = hello
        self.timeout = timeout
        self.max_conns = max_conns
        self._lock = make_lock("rpc.RPCClientPool._lock")
        self._sem = threading.Semaphore(max_conns)
        self._idle: list[RPCClient] = []
        self._all: list[RPCClient] = []

    def _acquire(self, method: str, deadline: float) -> RPCClient:
        if deadline:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or not self._sem.acquire(
                    timeout=max(remaining, 0.001)):
                _DEADLINE_EXCEEDED_TOTAL.inc()
                _rpc_counter("vm_rpc_client_deadline_exceeded_total",
                             method).inc()
                err = RPCDeadlineError(
                    f"rpc {method} to {self.addr[0]}:{self.addr[1]}: "
                    f"deadline exceeded waiting for a pooled connection")
                err.waited = False  # local capacity, not the node
                raise err
        else:
            # deadline-free (insert-path) calls still get a bounded
            # wait: all-connections-wedged must fail loudly, not hang
            if not self._sem.acquire(timeout=_acquire_cap_s()):
                _rpc_counter("vm_rpc_client_pool_exhausted_total",
                             method).inc()
                err = RPCError(
                    f"rpc {method} to {self.addr[0]}:{self.addr[1]}: no "
                    f"pooled connection freed in {_acquire_cap_s():g}s "
                    f"(pool of {self.max_conns} wedged)")
                err.waited = False  # local capacity, not the node
                raise err
        with self._lock:
            if self._idle:
                return self._idle.pop()
            c = RPCClient(self.addr[0], self.addr[1], self.hello,
                          timeout=self.timeout)
            self._all.append(c)
            return c

    def _release(self, c: RPCClient) -> None:
        with self._lock:
            self._idle.append(c)
        self._sem.release()

    def call(self, method: str, w: Writer | None = None,
             deadline: float = 0.0) -> Reader:
        c = self._acquire(method, deadline)
        try:
            return c.call(method, w, deadline=deadline)
        finally:
            self._release(c)

    def call_stream(self, method: str, w: Writer | None = None,
                    deadline: float = 0.0):
        c = self._acquire(method, deadline)
        try:
            # RPCClient reads the whole stream before returning, so the
            # connection is quiescent by the time it goes back to idle
            return c.call_stream(method, w, deadline=deadline)
        finally:
            self._release(c)

    def close(self) -> None:
        with self._lock:
            clients, self._idle = list(self._all), []
            self._all = []
        for c in clients:
            c.close()
