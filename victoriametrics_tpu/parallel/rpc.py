"""Cluster RPC: length-prefixed binary frames over TCP with a versioned
handshake and negotiated zstd compression (reference lib/handshake/
handshake.go:17-160 + lib/vmselectapi/server.go framing).

Frame: u32 BE length + payload. Payload (optionally zstd): method name
(varuint len + bytes) + method-specific body. Responses: status byte
(0=ok, 1=error+message) + body. Calls are versioned through their method
names ("writeRows_v1", "search_v1", ...) for rolling-upgrade compat.
"""

from __future__ import annotations

import io
import socket
import socketserver
import struct
import threading
import time

import numpy as np

from ..devtools.locktrace import make_lock
from ..devtools.racetrace import traced_fields

try:
    from ..ops import compress as zstd
except ImportError:  # optional native dep (zstandard): the marshal layer
    zstd = None      # (Writer/Reader) stays importable; only frame I/O needs it

from ..ops.varint import marshal_varuint64, unmarshal_varuint64
from ..utils import logger
from ..utils import metrics as metricslib


# per-(family, method) handle memo: keeps the format_name + name-regex +
# registry-lock round trip off the per-call path (method sets are tiny and
# bounded; a benign double-create under race resolves to the same handle)
_metric_memo: dict[tuple, object] = {}


def _rpc_counter(name: str, method: str):
    key = (name, method)
    m = _metric_memo.get(key)
    if m is None:
        m = _metric_memo[key] = metricslib.REGISTRY.counter(
            metricslib.format_name(name, {"method": method}))
    return m


def _rpc_histogram(name: str, method: str):
    key = (name, method)
    m = _metric_memo.get(key)
    if m is None:
        m = _metric_memo[key] = metricslib.REGISTRY.histogram(
            metricslib.format_name(name, {"method": method}))
    return m

HELLO_INSERT = b"vmtpu-insert.v2\n"
HELLO_SELECT = b"vmtpu-select.v2\n"
HELLO_OK = b"ok:zstd\n"

_U32 = struct.Struct(">I")
MAX_FRAME = 256 << 20


class RPCError(RuntimeError):
    pass


def _read_exact(sock_file, n: int) -> bytes:
    data = sock_file.read(n)
    if data is None or len(data) != n:
        raise ConnectionError("rpc: connection closed mid-frame")
    return data


def write_frame(sock_file, payload: bytes, compress: bool = True):
    if zstd is None:
        raise RPCError("rpc frames need the 'zstandard' package")
    if compress:
        payload = zstd.compress(payload)
    sock_file.write(_U32.pack(len(payload)) + payload)
    sock_file.flush()


def read_frame(sock_file, compressed: bool = True) -> bytes:
    if zstd is None:
        raise RPCError("rpc frames need the 'zstandard' package")
    n = _U32.unpack(_read_exact(sock_file, 4))[0]
    if n > MAX_FRAME:
        raise RPCError(f"rpc frame too large: {n}")
    data = _read_exact(sock_file, n)
    return zstd.decompress(data) if compressed else data


# -- marshaling helpers ------------------------------------------------------

class Writer:
    def __init__(self):
        self.buf = bytearray()

    def bytes_(self, b: bytes):
        self.buf += marshal_varuint64(len(b))
        self.buf += b
        return self

    def str_(self, s: str):
        return self.bytes_(s.encode())

    def u64(self, x: int):
        self.buf += marshal_varuint64(x)
        return self

    def i64(self, x: int):
        self.buf += struct.pack(">q", x)
        return self

    def f64(self, x: float):
        self.buf += struct.pack(">d", x)
        return self

    def array(self, a: np.ndarray):
        raw = np.ascontiguousarray(a).tobytes()
        self.bytes_(str(a.dtype).encode())
        return self.bytes_(raw)

    def payload(self) -> bytes:
        return bytes(self.buf)


class Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.i = 0

    def bytes_(self) -> bytes:
        n, self.i = unmarshal_varuint64(self.data, self.i)
        out = self.data[self.i:self.i + n]
        if len(out) != n:
            raise RPCError("rpc: truncated bytes field")
        self.i += n
        return out

    def str_(self) -> str:
        return self.bytes_().decode()

    def u64(self) -> int:
        v, self.i = unmarshal_varuint64(self.data, self.i)
        return v

    def i64(self) -> int:
        v = struct.unpack_from(">q", self.data, self.i)[0]
        self.i += 8
        return v

    def f64(self) -> float:
        v = struct.unpack_from(">d", self.data, self.i)[0]
        self.i += 8
        return v

    def array(self) -> np.ndarray:
        dtype = self.bytes_().decode()
        raw = self.bytes_()
        return np.frombuffer(raw, dtype=dtype).copy()

    @property
    def remaining(self) -> int:
        return len(self.data) - self.i


# -- server ------------------------------------------------------------------

class RPCServer:
    """TCP server dispatching named methods. Handlers: fn(Reader) -> Writer
    or an iterator of Writers for streaming responses (each streamed frame is
    prefixed with status 2; final frame status 0)."""

    def __init__(self, addr: str, port: int, hello: bytes,
                 handlers: dict[str, object], max_conns: int = 64):
        self.handlers = handlers
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                try:
                    got = _read_exact(self.rfile, len(hello))
                    if got != hello:
                        self.wfile.write(b"bad hello\n")
                        return
                    self.wfile.write(HELLO_OK)
                    self.wfile.flush()
                    while True:
                        try:
                            req = read_frame(self.rfile)
                        except (ConnectionError, RPCError):
                            return
                        outer._dispatch(req, self.wfile)
                except (BrokenPipeError, ConnectionResetError):
                    return

        class Srv(socketserver.ThreadingTCPServer):
            daemon_threads = True
            allow_reuse_address = True

        self._srv = Srv((addr, port), Handler)
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True)

    def start(self):
        self._thread.start()

    def stop(self):
        self._srv.shutdown()
        self._srv.server_close()

    def _dispatch(self, req: bytes, wfile):
        r = Reader(req)
        method = "?"
        t0 = time.perf_counter()
        try:
            method = r.str_()
            _rpc_counter("vm_rpc_server_calls_total", method).inc()
            fn = self.handlers.get(method)
            if fn is None:
                raise RPCError(f"unknown rpc method {method!r}")
            out = fn(r)
            if hasattr(out, "__iter__") and not isinstance(out, Writer):
                for w in out:
                    write_frame(wfile, b"\x02" + w.payload())
                write_frame(wfile, b"\x00")
            else:
                body = out.payload() if isinstance(out, Writer) else b""
                write_frame(wfile, b"\x00" + body)
        except Exception as e:  # noqa: BLE001 — rpc error boundary
            _rpc_counter("vm_rpc_server_errors_total", method).inc()
            logger.errorf("rpc handler error: %s", e)
            try:
                write_frame(wfile, b"\x01" + str(e).encode())
            except OSError:
                pass
        finally:
            _rpc_histogram("vm_rpc_server_call_duration_seconds",
                           method).update(time.perf_counter() - t0)


# -- client ------------------------------------------------------------------

@traced_fields("_sock", "_f")
class RPCClient:
    """One connection per client; callers serialize via a lock (the pool
    layer holds several clients per node)."""

    def __init__(self, host: str, port: int, hello: bytes, timeout=10.0):
        self.addr = (host, port)
        self.hello = hello
        self.timeout = timeout
        self._lock = make_lock("rpc.RPCClient._lock")
        self._sock = None
        self._f = None

    def _connect(self):
        sock = socket.create_connection(self.addr, timeout=self.timeout)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        f = sock.makefile("rwb")
        f.write(self.hello)
        f.flush()
        resp = f.read(len(HELLO_OK))
        if resp != HELLO_OK:
            raise RPCError(f"handshake failed: {resp!r}")
        self._sock, self._f = sock, f

    def close(self):
        with self._lock:
            self._close_locked()

    def _close_locked(self):
        """Close without taking the lock — for use on paths already holding
        self._lock (calling close() there self-deadlocks on the plain Lock)."""
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = self._f = None

    def call(self, method: str, w: Writer | None = None) -> Reader:
        """Unary call."""
        frames = list(self.call_stream(method, w))
        if frames:
            return frames[0]
        return Reader(b"")

    def call_stream(self, method: str, w: Writer | None = None):
        """Returns an iterator of Readers, one per streamed frame.

        All frames are read under the lock BEFORE returning: a lazy
        generator would keep the connection lock held while the caller
        processes frames, and an abandoned generator (consumer error) would
        leave it locked until GC — a deadlock under failure. Any transport
        error also tears the connection down so a half-read stream can never
        poison the next call."""
        req = Writer().str_(method)
        if w is not None:
            req.buf += w.buf
        frames: list[Reader] = []
        _rpc_counter("vm_rpc_client_calls_total", method).inc()
        t0 = time.perf_counter()
        try:
            with self._lock:
                # A stale kept-alive connection (peer restarted) usually
                # fails at the FIRST read, not the write (which lands in the
                # send buffer), so retry once on a fresh connection as long
                # as no frame has been received yet.
                for attempt in (0, 1):
                    try:
                        if self._f is None:
                            self._connect()
                        write_frame(self._f, req.payload())
                        while True:
                            resp = read_frame(self._f)
                            status = resp[0]
                            if status == 0:
                                if len(resp) > 1:
                                    frames.append(Reader(resp[1:]))
                                return iter(frames)
                            if status == 1:
                                # server-reported error: stream is cleanly
                                # terminated, the connection stays usable
                                raise RPCError(resp[1:].decode())
                            frames.append(Reader(resp[1:]))
                    except RPCError:
                        raise
                    except (OSError, ConnectionError, TimeoutError):
                        self._close_locked()
                        if attempt == 1 or frames:
                            raise
                        _rpc_counter("vm_rpc_client_retries_total",
                                     method).inc()
            return iter(frames)
        except Exception:
            _rpc_counter("vm_rpc_client_errors_total", method).inc()
            raise
        finally:
            _rpc_histogram("vm_rpc_client_call_duration_seconds",
                           method).update(time.perf_counter() - t0)
